"""Round-3 follow-up TPU capture: the device-replay north-star loop + the
bf16 HLO question, on the real chip.

Run on the tunneled TPU (NO platform override), in the background, and let
it EXIT CLEANLY — SIGKILL/SIGTERM on a process that initialized the axon
backend wedges the chip lease for everyone (see .claude/skills/verify).

    cd /root/repo && nohup python tools/capture_tpu_r3.py > \
        docs/captures/northstar2_tpu.log 2>&1 &

Captures, in order (each stage isolated so one failure doesn't kill the
rest):
  1. northstar2 — the all-on-device loop (bench.py stage, the follow-up to
     the round-3 verified 499/400 env-steps/s host-replay capture);
  2. the v1 host-replay north-star loop for a same-session comparison;
  3. bf16 vs fp32 geese train step (BASELINE.md open item a); launch with
     XLA_FLAGS=--xla_dump_to=... when the HLO evidence is wanted (the
     flag parses once, at backend init).
"""

import json
import sys
import time
import traceback

sys.path.insert(0, "/root/repo")

import bench  # noqa: E402  (repo-root module)


def main() -> None:
    import jax

    out = {"platform": None, "stages": {}}
    t0 = time.time()
    devices = jax.devices()
    out["platform"] = f"{devices[0].platform}:{getattr(devices[0], 'device_kind', '?')} x{len(devices)}"
    print(f"[{time.time()-t0:.0f}s] devices: {out['platform']}", flush=True)

    args = bench._make_args(
        "HungryGeese", {"turn_based_training": False, "observation": False}
    )
    _, module, model, store = bench._fill_store(args, 12)
    from handyrl_tpu.parallel import TrainContext, make_mesh

    ctx = TrainContext(module, args, make_mesh(args["mesh"]))
    gt = {"args": args, "ctx": ctx, "module": module, "model": model,
          "store": store}
    print(f"[{time.time()-t0:.0f}s] store filled", flush=True)

    try:
        # r3 geometry pinned explicitly (the bench defaults moved to the
        # r4 sweep's tuned point); this tool reproduces the r3 rows
        ns2 = bench._device_replay_northstar_bench(
            gt, 12.0, n_lanes=256, k_steps=32, fused_steps=8,
            trains_per_rollout=2,
        )
        out["stages"]["northstar2"] = ns2
        print(f"[{time.time()-t0:.0f}s] northstar2: {ns2}", flush=True)
    except Exception:
        out["stages"]["northstar2"] = {"error": traceback.format_exc(limit=5)}
        print(out["stages"]["northstar2"]["error"], flush=True)

    try:
        ns1 = bench._concurrent_northstar_bench(gt, 12.0)
        out["stages"]["northstar_v1"] = ns1
        print(f"[{time.time()-t0:.0f}s] northstar v1: {ns1}", flush=True)
    except Exception:
        out["stages"]["northstar_v1"] = {"error": traceback.format_exc(limit=5)}
        print(out["stages"]["northstar_v1"]["error"], flush=True)

    try:
        # (an HLO dump needs XLA_FLAGS=--xla_dump_to set BEFORE launch —
        # the flag is parsed once; launch this script with it when the
        # dump is wanted)
        gt_fp32 = bench._train_bench("HungryGeese",
                                     {"turn_based_training": False,
                                      "observation": False},
                                     8.0, len(devices), reuse=gt)
        gt_bf16 = bench._train_bench(
            "HungryGeese",
            {"turn_based_training": False, "observation": False,
             "compute_dtype": "bfloat16"},
            8.0, len(devices), reuse=gt,
        )
        out["stages"]["bf16"] = {
            "fp32_updates_per_sec": gt_fp32["updates_per_sec"],
            "bf16_updates_per_sec": gt_bf16["updates_per_sec"],
        }
        print(f"[{time.time()-t0:.0f}s] bf16: {out['stages']['bf16']}", flush=True)
    except Exception:
        out["stages"]["bf16"] = {"error": traceback.format_exc(limit=5)}
        print(out["stages"]["bf16"]["error"], flush=True)

    print("RESULT " + json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
