#!/bin/bash
# Round-4 chip watcher: probe the axon lease on a loop; the moment it
# answers, bank the full capture (tools/capture_tpu_r4.py) and exit.
# The probe subprocess is timeout-killed the same way bench's own
# out-of-process probe is — it never finishes backend init on a wedged
# lease, so there is no initialized client to wedge further.
cd "$(dirname "$0")/.." || exit 1
PIDFILE=/tmp/r4_watch.pid
[ -f "$PIDFILE" ] && kill -0 "$(cat "$PIDFILE")" 2>/dev/null && { echo "watcher already running"; exit 0; }
echo $$ > "$PIDFILE"
while true; do
  if timeout 150 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
    echo "[watch $(date -u +%H:%M:%S)] chip answered; launching capture"
    python tools/capture_tpu_r4.py >> docs/captures/r4_capture.log 2>&1
    rc=$?
    echo "[watch $(date -u +%H:%M:%S)] capture finished (rc=$rc)"
    break
  fi
  echo "[watch $(date -u +%H:%M:%S)] probe hung/failed; retrying in 420s"
  sleep 420
done
rm -f "$PIDFILE"
