"""Measure the REFERENCE's generation strategy on HungryGeese, on this host.

BASELINE.md's 1,557 env-steps/s generation row is TicTacToe (tiny net,
9-step episodes); bench.py's geese_gen stage was being divided by it,
which made the host actor plane look 5x slower than the reference when it
is actually ~3.6x faster like-for-like.  This tool produces the missing
like-for-like number: the reference's generation loop shape — ONE
batch-1 torch inference per ACTIVE player per step, single process
(reference generation.py:20-93 driving ModelWrapper model.py:50-60) —
using the reference's OWN torch GeeseNet (imported from
/root/reference/handyrl/envs/kaggle/hungry_geese.py with the missing
kaggle_environments dependency stubbed; the net class itself has no
kaggle dependency), stepping the same 7x11 torus rules.

Recorded in BASELINE.md and used as bench.py's
REFERENCE_GEESE_GEN_STEPS_PER_SEC denominator.

Usage: python tools/reference_geese_gen.py [seconds]
"""

from __future__ import annotations

import sys
import time
import types

import numpy as np

sys.path.insert(0, "/root/repo")


def load_reference_geesenet():
    """Import the reference's torch GeeseNet without kaggle_environments:
    the module imports `make` at top level but only calls it inside
    Environment.__init__, which this tool never constructs."""
    sys.path.insert(0, "/root/reference")
    if "kaggle_environments" not in sys.modules:
        stub = types.ModuleType("kaggle_environments")

        def _unavailable(*_a, **_k):
            raise RuntimeError("kaggle_environments is not installed")

        stub.make = _unavailable
        sys.modules["kaggle_environments"] = stub
    import handyrl.envs.kaggle.hungry_geese as ref_hg

    return ref_hg.GeeseNet().eval()


def measure(duration: float = 10.0, seed: int = 0) -> float:
    import torch

    torch.set_num_threads(1)  # parity with the 1-core CI host

    from handyrl_tpu.envs import make_env

    np.random.seed(seed)
    env = make_env({"env": "HungryGeese"})
    net = load_reference_geesenet()

    steps = episodes = 0
    t0 = time.perf_counter()
    with torch.no_grad():
        while time.perf_counter() - t0 < duration:
            env.reset()
            while not env.terminal():
                actions = {}
                for p in env.turns():
                    obs = torch.from_numpy(env.observation(p))[None]
                    out = net(obs)
                    logits = out["policy"] if isinstance(out, dict) else out[0]
                    prob = torch.softmax(logits, -1).numpy().ravel()
                    actions[p] = int(np.random.choice(4, p=prob / prob.sum()))
                env.step(actions)
                steps += 1
            episodes += 1
    dt = time.perf_counter() - t0
    rate = steps / dt
    print(
        f"reference-style geese generation: {rate:.1f} env-steps/s "
        f"({episodes} episodes over {dt:.1f}s, torch 1-thread, batch-1/player)"
    )
    return rate


if __name__ == "__main__":
    measure(float(sys.argv[1]) if len(sys.argv) > 1 else 10.0)
