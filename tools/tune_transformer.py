"""On-chip shape sweep for the transformer MFU stage (bench.py 4d).

Times the REAL TrainContext step (Geister windows, UPGO-capable losses,
Adam) on the scaled TransformerNet across batch/window/dtype variants,
reusing one filled episode store, and prints one JSON line per variant:
updates/s, flops/update, MFU vs the chip's bf16 peak.  Used to pick the
shape the bench stage pins; run standalone whenever the lease is live:

    python tools/tune_transformer.py            # full sweep (~15 min)
    TUNE_T=6 python tools/tune_transformer.py   # shorter timed windows
    TUNE_ONLY=d1024_B64_T64_bf16,d1024_B64_T64_einsum \
        python tools/tune_transformer.py        # named variants only
"""

from __future__ import annotations

import json
import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import bench  # noqa: E402  (repo root on path)

D768 = {"d_model": 768, "n_heads": 12, "n_layers": 8, "memory_len": 32}
D1024 = {"d_model": 1024, "n_heads": 16, "n_layers": 8, "memory_len": 32}
D1024L16 = {"d_model": 1024, "n_heads": 16, "n_layers": 16, "memory_len": 32}
D1536 = {"d_model": 1536, "n_heads": 16, "n_layers": 8, "memory_len": 32}
D2048 = {"d_model": 2048, "n_heads": 16, "n_layers": 8, "memory_len": 32}
BASE = {"burn_in_steps": 2, "observation": True, "seq_attention": "flash",
        "compute_dtype": "bfloat16"}

# (name, train-arg overrides, net_args) — 2026-08-01 v5e results in the
# name comments; the bench stage pins the winner (d1024/B64/T64/bf16)
VARIANTS = [
    ("B64_T32_bf16", {**BASE, "batch_size": 64, "forward_steps": 30}, D768),    # 0.253
    ("B128_T32_bf16", {**BASE, "batch_size": 128, "forward_steps": 30}, D768),  # 0.247
    ("B64_T64_bf16", {**BASE, "batch_size": 64, "forward_steps": 62}, D768),    # 0.311
    ("B64_T32_fp32", {k: v for k, v in BASE.items() if k != "compute_dtype"}
     | {"batch_size": 64, "forward_steps": 30}, D768),                          # 0.247
    ("d1024_B64_T64_bf16", {**BASE, "batch_size": 64, "forward_steps": 62},
     D1024),                                                                    # 0.347
    # fp32 ~= bf16 at these shapes says the step is not matmul-dtype-bound;
    # candidate culprit was the flash kernel at SHORT windows (it proved
    # itself at T1024; at T64/window-32 the O(T^2) einsum is tiny and
    # XLA-fusable).  SETTLED on-chip 2026-08-02: einsum 18.6 ups / MFU 0.48
    # vs flash 13.5 / 0.347 at the pinned shape — the bench stage now pins
    # einsum and auto-mode's flash_min_t=128 rule stands
    ("d1024_B64_T64_einsum",
     {**BASE, "seq_attention": "einsum", "batch_size": 64, "forward_steps": 62},
     D1024),
    # --- beyond-0.49 sweep (2026-08-02): with attention settled on einsum
    # at T64, the remaining MFU lever is matmul size.  All einsum.
    ("d1024L16_B64_T64_einsum",
     {**BASE, "seq_attention": "einsum", "batch_size": 64, "forward_steps": 62},
     D1024L16),
    ("d1536_B64_T64_einsum",
     {**BASE, "seq_attention": "einsum", "batch_size": 64, "forward_steps": 62},
     D1536),
    ("d2048_B64_T64_einsum",
     {**BASE, "seq_attention": "einsum", "batch_size": 64, "forward_steps": 62},
     D2048),
    ("d1024_B128_T64_einsum",
     {**BASE, "seq_attention": "einsum", "batch_size": 128, "forward_steps": 62},
     D1024),
]


def _rebuild_net(reuse, net_args):
    """Swap the net family size while keeping the filled episode store
    (episodes are env-side data, independent of the net)."""
    from handyrl_tpu.envs import make_env
    from handyrl_tpu.models import InferenceModel, init_variables

    env = make_env({"env": "Geister", "net": "transformer",
                    "net_args": net_args})
    module = env.net()
    model = InferenceModel(module, init_variables(module, env))
    return {"module": module, "model": model, "store": reuse["store"]}


def main() -> None:
    duration = float(os.environ.get("TUNE_T", "8"))
    # validate the variant filter BEFORE any jax/device touch: a typo must
    # not cost a backend init (which hangs outright on a wedged lease)
    raw_only = os.environ.get("TUNE_ONLY", "").strip()
    only = {s.strip() for s in raw_only.split(",") if s.strip()} or None
    if only:
        unknown = only - {name for name, _, _ in VARIANTS}
        if unknown:
            sys.exit(f"unknown TUNE_ONLY variant(s): {sorted(unknown)}")

    import jax

    dev = jax.devices()[0]
    peak = bench._peak_flops(dev)
    print(f"# device: {dev.device_kind}, peak {peak}", file=sys.stderr)

    reuse = None
    prev_net = None
    for name, over, net_args in VARIANTS:
        if only and name not in only:
            continue
        if reuse is not None and net_args != prev_net:
            reuse = _rebuild_net(reuse, net_args)
        r = bench._train_bench(
            "Geister", over, duration, 1, fill_episodes=8,
            env_overrides={"net": "transformer", "net_args": net_args},
            reuse=reuse,
        )
        reuse = r
        prev_net = net_args
        tokens = over["batch_size"] * 2 * (over["burn_in_steps"] + over["forward_steps"])
        row = {
            "variant": name,
            "updates_per_sec": bench._sig(r["updates_per_sec"]),
            "tokens_per_sec": bench._sig(r["updates_per_sec"] * tokens, 4),
            "flops_per_step": r["flops_per_step"],
            "mfu": bench._sig(r["flops_per_step"] * r["updates_per_sec"] / peak)
            if (r["flops_per_step"] and peak) else None,
        }
        print(json.dumps(row), flush=True)


if __name__ == "__main__":
    main()
