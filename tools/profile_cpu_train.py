"""Scratch profiler for the TicTacToe train-step CPU headline (VERDICT r2 item 5).

Times one jitted sharded train step on the 1-device CPU backend the way
bench.py does, then variants, to find the 0.796x-vs-torch gap.
"""
import os
import sys
import time

import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench as B  # reuse the bench's store/batch plumbing


def main():
    import numpy as np
    from handyrl_tpu.parallel import TrainContext, make_mesh

    args = B._make_args("TicTacToe", {})
    _, module, model, store = B._fill_store(args, 48)
    mesh = make_mesh(args["mesh"])
    ctx = TrainContext(module, args, mesh)
    state = ctx.init_state(model.variables["params"])
    device_batches = [ctx.put_batch(B._sample_batch(store, args)) for _ in range(4)]

    holder = {"state": state, "i": 0}

    def seq_step():
        holder["state"], metrics = ctx.train_step(
            holder["state"], device_batches[holder["i"] % 4], 1e-5
        )
        holder["i"] += 1
        return metrics["total"]

    ups = B._timed_loop(seq_step, 8.0)
    print(f"baseline ctx.train_step: {ups:.2f} updates/s "
          f"({ups * args['batch_size'] * args['forward_steps']:.0f} env-steps/s)")

    # variant: raw bound jit call, no dispatch_serialized block
    fn = ctx._bind(holder["state"])
    lr = jax.numpy.float32(1e-5)

    def raw_step():
        holder["state"], metrics = fn(holder["state"], device_batches[holder["i"] % 4], lr)
        holder["i"] += 1
        return metrics["total"]

    ups2 = B._timed_loop(raw_step, 8.0)
    print(f"raw jit (no dispatch lock/block): {ups2:.2f} updates/s")

    # variant: fused k=8 scan path on CPU
    try:
        stacked = ctx.put_batches([B._sample_batch(store, args) for _ in range(8)])

        def fused_step():
            holder["state"], metrics = ctx.train_steps(holder["state"], stacked, 1e-5)
            return metrics["total"]

        ups3 = B._timed_loop(fused_step, 8.0) * 8
        print(f"fused k=8 scan: {ups3:.2f} updates/s")
    except Exception as e:
        print("fused failed:", e)

    # cost analysis: where do the flops go?
    flops = ctx.flops_per_step(holder["state"], device_batches[0])
    print(f"flops/step: {flops}")


if __name__ == "__main__":
    main()
