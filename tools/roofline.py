"""Roofline analysis of the game-net train steps (VERDICT r4 #5).

The bench's honest game-net MFUs are small (r4 chip capture: tictactoe
0.0154, geese 0.0356, northstar2 0.0194) and BASELINE.md asserts
"model-size artifact, not framework overhead".  This tool PROVES or
REFUTES that from the compiled programs themselves: for each stage's
exact train step it pulls XLA cost analysis (flops + bytes accessed),
computes arithmetic intensity AI = flops/bytes, and compares against the
chip's ridge point peak_flops/hbm_bw (v5e: 197e12/819e9 = 240
flops/byte).  A step with AI far below the ridge is bandwidth-bound and
its MFU CEILING is AI * bw / peak — if the measured MFU sits near that
ceiling, the small number is physics, not overhead; if far below, the
framework is leaving throughput on the table.

Run on the chip for the real fusion/layout numbers
(`python tools/roofline.py`); CPU fallback (`HANDYRL_PLATFORM=cpu`)
records its platform and is an approximation only (XLA:CPU fuses
differently).  Writes docs/captures/roofline_<stamp>.json and prints a
human summary; docs/performance.md carries the conclusions.
"""

from __future__ import annotations

import datetime
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# honor HANDYRL_PLATFORM before any jax computation (the axon
# sitecustomize pins the platform; env var alone cannot override it)
from handyrl_tpu.utils import apply_platform_override  # noqa: E402

apply_platform_override()


def _cost(ctx, state, device_batch):
    """(flops, bytes_accessed, source) from XLA cost analysis.

    The COMPILED executable's analysis is authoritative — it reflects
    post-fusion bytes, and the published methodology (performance.md's
    roofline table) is compiled-program numbers; the lowered
    (pre-optimization) analysis overcounts bytes ~2-3x and is kept only
    as a last resort for backends whose executables don't answer.
    'source' is recorded in the capture so the two are never conflated."""
    lowered = ctx._bind(state).lower(
        state, device_batch, __import__("jax").numpy.float32(1e-5)
    )
    errs = []
    for source, ca in (
        ("compiled", lambda: lowered.compile().cost_analysis()),
        ("lowered", lambda: lowered.cost_analysis()),
    ):
        try:
            got = ca()
        except Exception as exc:
            errs.append(exc)
            print(f"[roofline] {source} cost analysis failed: {exc!r}",
                  file=sys.stderr, flush=True)
            continue
        if isinstance(got, (list, tuple)):
            got = got[0] if got else None
        if got:
            return (float(got.get("flops", 0.0)),
                    float(got.get("bytes accessed", 0.0)), source)
    raise RuntimeError(
        "XLA cost analysis unavailable from both the compiled and the "
        "lowered program on this backend"
    ) from (errs[-1] if errs else None)


def stage(env_name: str, overrides: dict, measured_mfu_key: str):
    import jax

    import bench
    from handyrl_tpu.parallel import TrainContext, make_mesh
    from handyrl_tpu.parallel.train_step import (
        hbm_bandwidth_per_chip, peak_flops_per_chip,
    )

    args = bench._make_args(env_name, overrides)
    n_dev = len(jax.devices())
    if args["batch_size"] % n_dev:
        args["batch_size"] = max(n_dev, args["batch_size"] // n_dev * n_dev)
    _, module, model, store = bench._fill_store(args, 16)
    mesh = make_mesh(args["mesh"])
    ctx = TrainContext(module, args, mesh)
    state = ctx.init_state(model.variables["params"])
    host_batch = bench._sample_batch(store, args)
    db = ctx.put_batch(host_batch)
    flops, nbytes, cost_source = _cost(ctx, state, db)

    dev = jax.devices()[0]
    peak = peak_flops_per_chip(dev)
    bw = hbm_bandwidth_per_chip(dev)
    out = {
        "env": env_name,
        "batch_size": args["batch_size"],
        "forward_steps": args["forward_steps"],
        "flops_per_step": flops,
        "bytes_accessed_per_step": nbytes,
        "arithmetic_intensity": round(flops / nbytes, 3) if nbytes else None,
        "cost_source": cost_source,
        "measured_mfu_key": measured_mfu_key,
    }
    if peak and bw and nbytes:
        ridge = peak / bw
        ai = flops / nbytes
        out["ridge_flops_per_byte"] = round(ridge, 1)
        out["bandwidth_bound"] = ai < ridge
        # MFU ceiling if the step were perfectly streamed at full HBM bw
        out["mfu_ceiling_at_bw"] = round(min(1.0, ai * bw / peak), 4)
        # equivalently: the fastest possible step time is bytes/bw
        out["min_step_time_us_at_bw"] = round(nbytes / bw * 1e6, 1)

    # bytes-after-quantization column (docs/performance.md §Low-precision):
    # what the int8 fast path removes from the stage's byte traffic.  The
    # weight figure is the serving-engine residency shrink (per-channel
    # int8 codes + fp32 scales vs fp32 kernels); the obs figure is the
    # batch's observation planes at 1-byte width (the int8 obs/wire
    # plane).  The *_int8_est roofline keys are an ESTIMATE — cost
    # analysis of the fp32 program minus the byte savings — not a
    # compiled int8 program; they bound the AI shift, they don't measure
    # post-fusion layout.
    from handyrl_tpu.models.quantize import param_bytes, quantize_params

    wb_fp32 = param_bytes(model.variables["params"])
    wb_int8 = param_bytes(quantize_params(model.variables["params"]))
    obs_leaves = jax.tree.leaves(host_batch["observation"])
    ob_fp32 = sum(int(x.size) * 4 for x in obs_leaves)
    ob_int8 = sum(int(x.size) for x in obs_leaves)
    out["weight_bytes_fp32"] = wb_fp32
    out["weight_bytes_int8"] = wb_int8
    out["obs_bytes_per_step_fp32"] = ob_fp32
    out["obs_bytes_per_step_int8"] = ob_int8
    if nbytes:
        saved = (wb_fp32 - wb_int8) + (ob_fp32 - ob_int8)
        nbytes_q = max(nbytes - saved, 1.0)
        out["bytes_accessed_per_step_int8_est"] = nbytes_q
        out["arithmetic_intensity_int8_est"] = round(flops / nbytes_q, 3)
        if peak and bw:
            out["mfu_ceiling_at_bw_int8_est"] = round(
                min(1.0, flops / nbytes_q * bw / peak), 4
            )
    return out


def main() -> None:
    import jax

    dev = jax.devices()[0]
    platform = f"{dev.platform}:{getattr(dev, 'device_kind', '?')}"
    print(f"[roofline] platform {platform}", file=sys.stderr, flush=True)

    results = {
        "date_utc": datetime.datetime.now(datetime.timezone.utc).isoformat(
            timespec="seconds"
        ),
        "platform": platform,
        "note": (
            "bytes accessed / flops from XLA cost analysis of the exact "
            "bench train steps; AI vs ridge point decides bandwidth- vs "
            "compute-bound; mfu_ceiling_at_bw is the physics limit at "
            "full HBM streaming"
        ),
        "stages": [],
    }
    for env_name, over, key in (
        ("TicTacToe", {}, "tictactoe_mfu"),
        ("HungryGeese", {"turn_based_training": False, "observation": False},
         "geese_mfu"),
    ):
        print(f"[roofline] analyzing {env_name}...", file=sys.stderr, flush=True)
        results["stages"].append(stage(env_name, over, key))

    print(json.dumps(results, indent=2))
    stamp = datetime.datetime.now(datetime.timezone.utc).strftime("%Y-%m-%d_%H%M")
    dest = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "docs", "captures", f"roofline_{stamp}.json",
    )
    with open(dest, "w") as f:
        json.dump(results, f, indent=2)
    print(f"[roofline] wrote {dest}", file=sys.stderr, flush=True)


if __name__ == "__main__":
    main()
