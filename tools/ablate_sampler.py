"""Controlled sampler comparison: host EpisodeStore vs device rings
(VERDICT r4 #7, corrected design).

The first attempt compared product `--train` runs at an equal EPISODE
budget — and measured the wrong thing: on-device generation meets the
per-epoch episode budget ~100x faster than host workers, so the device
runs took ~100x fewer SGD steps (26 vs 3,195 on geese) and the curves
compared produce/consume geometry, not sampling semantics.  (Those runs
are still recorded as product context in the output.)

This harness holds EVERYTHING else equal and varies only the SAMPLER:

  shared   one streaming on-device self-play engine
           (`StreamingDeviceRollout` / `build_streaming_fn`),
           one TrainContext, one update budget, one fixed
           rollout:train cadence, one eval protocol;
  A (host) finished episodes -> host `EpisodeStore` -> the reference's
           sampling semantics: per-episode acceptance curve + recency
           bias + per-episode window draw (`runtime/replay.py`,
           reference train.py:292-316) -> make_batch -> train_step;
  B (ring) rollout records -> per-lane device rings -> uniform window
           starts over eligible steps, ring-capacity recency
           (`runtime/device_replay.py`) -> fused sample+train.

Both arms see the same number of updates AND the same generation
stream shape, so the late-mean win-rate delta IS the cost (or not) of
the device ring's two documented sampling deviations.  Writes
docs/captures/sampler_ablation_<stamp>.json; `device_replay.py`'s
docstring quotes the number.
"""

from __future__ import annotations

import datetime
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from handyrl_tpu.utils import apply_platform_override  # noqa: E402

apply_platform_override()


def _common(seed: int):
    from handyrl_tpu.config import normalize_args
    from handyrl_tpu.envs import make_env
    from handyrl_tpu.models import init_variables
    from handyrl_tpu.parallel import TrainContext, make_mesh

    cfg = normalize_args(
        {
            "env_args": {"env": "HungryGeese"},
            "train_args": {
                "turn_based_training": False,
                "observation": False,
                "burn_in_steps": 0,
                "forward_steps": 8,
                "batch_size": 32,
                "compress_steps": 4,
                "policy_target": "UPGO",
                "value_target": "TD",
                "seed": seed,
            },
        }
    )
    args = dict(cfg["train_args"])
    args["env"] = cfg["env_args"]
    env = make_env(args["env"])
    module = env.net()
    params = init_variables(module, env)["params"]
    mesh = make_mesh(args["mesh"])
    ctx = TrainContext(module, args, mesh)
    return env, module, params, mesh, ctx, args


def _eval_curve_point(evaluator, params, eval_games, key):
    from handyrl_tpu.runtime.evaluation import wp_func

    return wp_func(evaluator.evaluate(params, eval_games, key))


def run_arm(arm: str, total_updates: int, rollouts_per_update: float,
            eval_every: int, eval_games: int, n_lanes: int, seed: int) -> dict:
    """One arm: `arm` in ('host', 'ring'); a rollout dispatch advances all
    lanes k steps; `rollouts_per_update` sets the shared data cadence."""
    import random as _pyrandom

    import jax

    from handyrl_tpu.parallel.mesh import dispatch_serialized
    from handyrl_tpu.runtime.device_eval import DeviceEvaluator

    # the host arm's EpisodeStore.sample_window draws from the global
    # `random` (the product path seeds it in Learner.__init__); seed it
    # here so --seed controls BOTH arms and captures are reproducible
    _pyrandom.seed(seed)
    env, module, params, mesh, ctx, args = _common(seed)
    venv = env.vector_env()
    k_steps = 32
    state = ctx.init_state(params)
    evaluator = DeviceEvaluator(venv, module, n_lanes=32, opponent="random",
                                mesh=mesh if mesh.size > 1 else None)
    key = jax.random.PRNGKey(seed)

    if arm == "ring":
        from handyrl_tpu.runtime.device_replay import DeviceReplay
        from handyrl_tpu.runtime.device_rollout import build_streaming_fn

        fn = build_streaming_fn(venv, module, n_lanes, k_steps,
                                mesh=mesh if mesh.size > 1 else None,
                                use_observe_mask=False)
        replay = DeviceReplay(venv, module, args, mesh, n_lanes, slots=256)
        vstate = venv.init(n_lanes, jax.random.PRNGKey(seed + 1))
        hidden = module.initial_state((n_lanes, venv.num_players))

        def rollout():
            nonlocal vstate, hidden, key
            key, sub = jax.random.split(key)
            vstate, hidden, records = dispatch_serialized(
                lambda: fn(state["params"], vstate, hidden, sub)
            )
            replay.ingest(records)

        while replay.eligible_count() < args["batch_size"]:
            rollout()
        train = replay.train_fn(ctx, fused_steps=1)

        def train_once():
            nonlocal state, key
            key, sub = jax.random.split(key)
            state, m = train(state, sub, 3e-5)
            return m
    else:
        from handyrl_tpu.runtime import EpisodeStore, make_batch
        from handyrl_tpu.runtime.device_rollout import StreamingDeviceRollout

        roll = StreamingDeviceRollout(
            venv, module, args, n_lanes=n_lanes, k_steps=k_steps,
            mesh=mesh if mesh.size > 1 else None,
        )
        store = EpisodeStore(args["maximum_episodes"])
        rkey = [jax.random.PRNGKey(seed + 1)]

        def rollout():
            rkey[0], sub = jax.random.split(rkey[0])
            eps = roll.generate(state["params"], sub)
            if eps:
                store.extend(eps)

        # warm-up gate symmetric with the ring arm's (>= batch_size
        # eligible window starts): roll until the store holds at least
        # batch_size episodes (every episode contributes >= 1 window)
        while len(store) < args["batch_size"]:
            rollout()

        def _batch():
            windows = []
            while len(windows) < args["batch_size"]:
                w = store.sample_window(
                    args["forward_steps"], args["burn_in_steps"],
                    args["compress_steps"],
                )
                if w is not None:
                    windows.append(w)
            return ctx.put_batch(make_batch(windows, args))

        def train_once():
            nonlocal state
            state, m = ctx.train_step(state, _batch(), 3e-5)
            return m
    # shared cadence loop
    curve = []
    pending = 0.0
    t0 = time.perf_counter()
    m = None
    for u in range(1, total_updates + 1):
        pending += rollouts_per_update
        while pending >= 1.0:
            rollout()
            pending -= 1.0
        m = train_once()
        if u % eval_every == 0 or u == total_updates:
            key, ek = jax.random.split(key)
            wp = _eval_curve_point(evaluator, state["params"], eval_games, ek)
            curve.append({"updates": u, "win_points": round(wp, 4)})
            print(f"  [{arm}] {u}/{total_updates} updates, wp = {wp:.3f}",
                  file=sys.stderr, flush=True)
    if arm == "host":
        roll.drain()
    else:
        replay.drain()
    import numpy as np

    total = float(jax.device_get(m["total"]))
    late = [c["win_points"] for c in curve if c["updates"] >= total_updates * 2 // 3]
    return {
        "arm": arm,
        "updates": total_updates,
        "curve": curve,
        "late_mean_win_points": round(sum(late) / max(len(late), 1), 4),
        "wall_s": round(time.perf_counter() - t0, 1),
        "loss_finite": bool(np.isfinite(total)),
    }


def main() -> None:
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--updates", type=int, default=300)
    ap.add_argument("--rollouts-per-update", type=float, default=0.25)
    ap.add_argument("--eval-every", type=int, default=50)
    ap.add_argument("--eval-games", type=int, default=64)
    ap.add_argument("--lanes", type=int, default=32)
    ap.add_argument("--seed", type=int, default=0)
    a = ap.parse_args()

    out = {
        "date_utc": datetime.datetime.now(datetime.timezone.utc).isoformat(
            timespec="seconds"
        ),
        "env": "HungryGeese",
        "params": {"updates": a.updates,
                   "rollouts_per_update": a.rollouts_per_update,
                   "eval_every": a.eval_every, "eval_games": a.eval_games,
                   "lanes": a.lanes, "seed": a.seed},
        "design": (
            "one on-device generation engine, one TrainContext, equal "
            "updates and rollout cadence; only the sampler differs "
            "(host EpisodeStore acceptance/recency/per-episode windows "
            "vs device rings' uniform-step windows + capacity recency)"
        ),
        "arms": [],
    }
    for arm in ("host", "ring"):
        print(f"[sampler-ablate] arm={arm}...", file=sys.stderr, flush=True)
        out["arms"].append(
            run_arm(arm, a.updates, a.rollouts_per_update, a.eval_every,
                    a.eval_games, a.lanes, a.seed)
        )
    host, ring = out["arms"]
    out["delta_late_mean"] = round(
        ring["late_mean_win_points"] - host["late_mean_win_points"], 4
    )
    print(json.dumps(out, indent=2))
    stamp = datetime.datetime.now(datetime.timezone.utc).strftime("%Y-%m-%d_%H%M")
    dest = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                        "docs", "captures", f"sampler_ablation_{stamp}.json")
    with open(dest, "w") as f:
        json.dump(out, f, indent=2)
    print(f"[sampler-ablate] wrote {dest}", file=sys.stderr, flush=True)


if __name__ == "__main__":
    main()
