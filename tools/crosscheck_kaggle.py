"""Machine crosscheck of the standalone HungryGeese rules against the
REAL Kaggle engine (``kaggle_environments.make("hungry_geese")``) — the
ground truth the reference wraps (handyrl/envs/kaggle/hungry_geese.py:67).

The standalone rules (handyrl_tpu/envs/hungry_geese.py) were previously
self-certified by a hand-written parity doc; this drives N full games
through BOTH engines in lock-step and asserts identical deaths, growth,
goose cell-sequences, active sets, terminality and final pairwise-rank
outcomes at every step.

Randomness is handled by INJECTION, not seed-mirroring: the Kaggle
interpreter draws initial placements and food spawns from its own RNG, so
the crosscheck copies the Kaggle engine's state wholesale at reset and its
post-step food into our engine after every step (our ``_spawn_food`` is
disabled).  Everything that remains — movement, reverse-death,
self-collision, growth, hunger, cross-goose collision, rank credit — is
computed independently by both engines and compared.

Skip-gated: ``kaggle_environments`` is not installable in the build image
(zero egress); the CI onnx-extras job installs it and executes this
end-to-end (.github/workflows/tests.yaml).

Usage: python tools/crosscheck_kaggle.py [num_games]
"""

from __future__ import annotations

import random
import sys

NUM_AGENTS = 4


def _inject_state(ours, kobs) -> None:
    """Overwrite our engine's freshly-reset state with the Kaggle engine's
    initial placements (geese + food); rank credit for step 1 mirrors
    our reset()'s initial credit."""
    shared = kobs[0]["observation"]
    ours.geese = [list(g) for g in shared["geese"]]
    ours.food = list(shared["food"])
    ours.active = [True] * NUM_AGENTS
    ours.step_count = 0
    ours.last_actions = {}
    ours.prev_heads = [None] * NUM_AGENTS


def crosscheck_hungry_geese(num_games: int = 20, seed: int = 31,
                            verbose: bool = True) -> None:
    """Drive ``num_games`` random games through both engines; raises
    AssertionError on the first divergence."""
    from kaggle_environments import make

    import handyrl_tpu.envs.hungry_geese as hg

    ours = hg.Environment()
    ours._spawn_food = lambda: None  # food is injected from Kaggle's RNG
    rng = random.Random(seed)

    for g in range(num_games):
        kenv = make("hungry_geese")
        kobs = kenv.reset(num_agents=NUM_AGENTS)
        ours.reset()
        _inject_state(ours, kobs)

        steps = 0
        while True:
            kactive = {
                p for p in range(NUM_AGENTS) if kobs[p]["status"] == "ACTIVE"
            }
            assert set(ours.turns()) == kactive, (
                f"game {g} step {steps}: active sets diverge "
                f"(ours {ours.turns()}, kaggle {sorted(kactive)})"
            )
            kdone = not kactive
            assert ours.terminal() == kdone, (
                f"game {g} step {steps}: terminality diverges "
                f"(ours {ours.terminal()}, kaggle {kdone})"
            )
            if kdone:
                break

            actions = {p: rng.randrange(4) for p in kactive}
            kobs = kenv.step(
                [hg.ACTIONS[actions.get(p, 0)] for p in range(NUM_AGENTS)]
            )
            ours.step(dict(actions))
            steps += 1

            shared = kobs[0]["observation"]
            # food first: our engine consumed from the synced pre-step
            # list; Kaggle's post-step spawns become our next pre-step set
            ours.food = list(shared["food"])
            for p in range(NUM_AGENTS):
                assert list(shared["geese"][p]) == list(ours.geese[p]), (
                    f"game {g} step {steps} player {p}: goose cells diverge\n"
                    f"  kaggle {shared['geese'][p]}\n  ours   {ours.geese[p]}"
                )

        # final pairwise-rank outcome: +1/3 per beaten opponent (the rank
        # formula constants differ — ours 100*steps+len vs kaggle's — but
        # the induced ORDER must be identical)
        krewards = {
            o["observation"]["index"]: (o["reward"] or 0) for o in kobs
        }
        kout = {p: 0.0 for p in range(NUM_AGENTS)}
        for p, r in krewards.items():
            for q, rr in krewards.items():
                if p != q:
                    if r > rr:
                        kout[p] += 1 / (NUM_AGENTS - 1)
                    elif r < rr:
                        kout[p] -= 1 / (NUM_AGENTS - 1)
        oout = ours.outcome()
        for p in range(NUM_AGENTS):
            assert abs(oout[p] - kout[p]) < 1e-9, (
                f"game {g}: outcome diverges at player {p} "
                f"(ours {oout}, kaggle {kout}; rewards {krewards})"
            )
        if verbose:
            print(f"game {g}: {steps} steps identical")
    if verbose:
        print(
            f"HungryGeese: {num_games} games identical vs kaggle_environments "
            f"(deaths, growth, cells, ranks)"
        )


def main() -> None:
    num_games = int(sys.argv[1]) if len(sys.argv) > 1 else 20
    try:
        import kaggle_environments  # noqa: F401
    except ImportError:
        print("HungryGeese: SKIPPED (kaggle_environments not installed)")
        return
    crosscheck_hungry_geese(num_games)


if __name__ == "__main__":
    sys.path.insert(0, "/root/repo")
    main()
