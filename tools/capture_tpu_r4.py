"""Round-4 TPU capture: one command for every chip-gated verdict item.

The round-4 lease has been wedged for hours at a stretch, so when the
chip answers this script banks everything in one clean process:

  1. full ``python bench.py`` (subprocess, clean exit) — the same program
     the driver runs, now with per-path MFU keys; log saved;
  2. duty-cycle sweep (``tools/tune_northstar.py`` in-process) — the
     lanes x k_steps x fused x trains_per_rollout knee (VERDICT item 3);
  3. bf16 vs fp32 device-math profile (``tools/profile_bf16.py``
     in-process) with jax.profiler traces (VERDICT item 8);
  4. flash-vs-einsum on the pinned transformer shape
     (``tools/tune_transformer.py`` d1024 variants — the open
     attn-mode question, docs/ROUND4.md).

Run on the tunneled TPU (NO platform override), in the background, and
let it EXIT CLEANLY — SIGKILL/SIGTERM on a process that initialized the
axon backend wedges the chip lease for everyone (.claude/skills/verify):

    cd /root/repo && nohup python tools/capture_tpu_r4.py > \
        docs/captures/r4_capture.log 2>&1 &

Stage 1 runs bench.py as a SUBPROCESS so its own probe/watchdog contract
holds; stages 2-3 run in this process (one backend init, shared compile
cache).  Each stage is isolated: one failure doesn't kill the rest.
"""

from __future__ import annotations

import os
import subprocess
import sys
import time
import traceback

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tools"))


def _wait_gracefully(proc: "subprocess.Popen", budget: float) -> int:
    """Wait for the bench child; on budget expiry escalate SIGINT ->
    SIGTERM with grace periods instead of subprocess.run's kill-on-timeout
    — SIGKILLing a process that initialized the axon backend wedges the
    chip lease (the exact failure this tool exists to ride out).  bench's
    own probe budget + watchdog should always exit first; this is the
    backstop."""
    import signal

    try:
        return proc.wait(timeout=budget)
    except subprocess.TimeoutExpired:
        pass
    print(f"bench exceeded {budget:.0f}s (its probe budget + watchdog "
          "should have fired); sending SIGINT for a clean exit", flush=True)
    proc.send_signal(signal.SIGINT)
    try:
        return proc.wait(timeout=120)
    except subprocess.TimeoutExpired:
        print("WARNING: bench ignored SIGINT; SIGTERM — this can wedge "
              "the chip lease", flush=True)
        proc.terminate()
        return proc.wait(timeout=60)


def main() -> None:
    # ORDER MATTERS: this parent must not touch jax until the bench
    # subprocess has exited — two processes contending for the one-chip
    # axon lease is the wedge this round spent hours in.  jax is imported
    # only inside the stage mains (stage 2 onward).
    t0 = time.time()
    ts = time.strftime("%Y-%m-%d_%H%M")
    quick = bool(os.environ.get("BENCH_QUICK"))
    os.chdir(REPO)  # stages 2-3 write cwd-relative capture artifacts
    capdir = os.path.join(REPO, "docs", "captures")
    os.makedirs(capdir, exist_ok=True)

    # -- stage 1: the driver's own program, subprocess, clean exit -------
    bench_log = os.path.join(capdir, f"bench_tpu_{ts}.log")
    print(f"[{time.time()-t0:.0f}s] stage 1: python bench.py -> {bench_log}",
          flush=True)
    got_tpu = False
    try:
        with open(bench_log, "w") as f:
            proc = subprocess.Popen(
                [sys.executable, os.path.join(REPO, "bench.py")],
                stdout=f, stderr=subprocess.STDOUT, cwd=REPO,
            )
            rc = _wait_gracefully(proc, budget=3900.0)
        print(f"[{time.time()-t0:.0f}s] bench rc={rc}; tail:", flush=True)
        lines = open(bench_log).read().splitlines()
        print("\n".join(lines[-3:]), flush=True)
        import json

        for line in reversed(lines):
            if line.startswith("{"):
                got_tpu = str(json.loads(line).get("platform", "")).startswith("tpu")
                break
    except Exception:
        traceback.print_exc()

    if not (got_tpu or os.environ.get("HANDYRL_PLATFORM") == "cpu"):
        # stages 2-3 init the backend IN-PROCESS with no probe/fallback
        # layer of their own; against a wedged lease they'd hang forever
        # (observed: tune_northstar slept hours in axon init, 2026-08-01).
        # An explicit CPU override still runs them (validation smoke).
        print(
            f"[{time.time()-t0:.0f}s] bench did not reach a TPU; skipping "
            "the sweep + bf16 stages (they would hang on the wedged lease)",
            flush=True,
        )
        return

    # -- stage 2: duty-cycle sweep (VERDICT item 3) ----------------------
    print(f"[{time.time()-t0:.0f}s] stage 2: tune_northstar sweep", flush=True)
    try:
        import tune_northstar

        if quick:
            os.environ.setdefault("TUNE_QUICK", "1")
        sys.argv = ["tune_northstar.py"] + (["3"] if quick else [])
        tune_northstar.main()
    except Exception:
        traceback.print_exc()

    # -- stage 3: bf16 device-math profile (VERDICT item 8) --------------
    print(f"[{time.time()-t0:.0f}s] stage 3: bf16 profile", flush=True)
    try:
        import profile_bf16

        sys.argv = ["profile_bf16.py"] + (["2", "2"] if quick else [])
        profile_bf16.main()
    except Exception:
        traceback.print_exc()

    # -- stage 4: attn-mode comparison on the pinned transformer shape ---
    # TPU-only even under a CPU override: the d1024 shapes run the Pallas
    # kernel through the INTERPRETER on CPU — hours, not a smoke test
    if got_tpu:
        print(f"[{time.time()-t0:.0f}s] stage 4: transformer attn-mode", flush=True)
        try:
            import tune_transformer

            os.environ["TUNE_ONLY"] = "d1024_B64_T64_bf16,d1024_B64_T64_einsum"
            if quick:
                os.environ.setdefault("TUNE_T", "4")
            tune_transformer.main()
        except Exception:
            traceback.print_exc()

    print(f"[{time.time()-t0:.0f}s] capture complete", flush=True)


if __name__ == "__main__":
    main()
