"""Behavioral parity cross-check against the upstream HandyRL reference.

Plays identical random action sequences through this framework's
environments and the reference's (if mounted at /root/reference and torch
is importable), asserting legal-action sets, terminality, outcomes and
observations stay identical move for move.  Dev/judging aid only — the
committed test suite is self-contained and does not require the reference.

Usage: python tools/crosscheck_reference.py [num_games]
"""

import random
import sys

import numpy as np

sys.path.insert(0, "/root/repo")
sys.path.insert(0, "/root/reference")


def crosscheck(name, ref_module, ours_module, num_games, turn_based, compare_obs=True):
    ref = ref_module.Environment()
    ours = ours_module.Environment()
    rng = random.Random(123)
    for g in range(num_games):
        ref.reset()
        ours.reset()
        steps = 0
        while not ref.terminal():
            assert ours.terminal() == ref.terminal()
            assert set(ref.turns()) == set(ours.turns()), (g, steps)
            actions = {}
            for p in ref.turns():
                la_ref = sorted(ref.legal_actions(p))
                la_ours = sorted(ours.legal_actions(p))
                assert la_ref == la_ours, (name, g, steps, p, la_ref, la_ours)
                actions[p] = rng.choice(la_ref)
                if compare_obs:
                    o_ref = ref.observation(p)
                    o_ours = ours.observation(p)
                    if isinstance(o_ref, dict):
                        for k in o_ref:
                            np.testing.assert_allclose(o_ref[k], o_ours[k], err_msg=f"{name} obs[{k}] step {steps}")
                    else:
                        np.testing.assert_allclose(o_ref, o_ours, err_msg=f"{name} obs step {steps}")
                # string codec parity
                a = actions[p]
                assert ref.action2str(a, p) == ours.action2str(a, p)
            if turn_based:
                p = list(actions)[0]
                ref.play(actions[p], p)
                ours.play(actions[p], p)
            else:
                # simultaneous envs may draw from the global `random` inside
                # step() (e.g. ParallelTicTacToe picks whose action lands);
                # replaying the same RNG state into both keeps them lock-step
                state = random.getstate()
                ref.step(dict(actions))
                random.setstate(state)
                ours.step(dict(actions))
            steps += 1
        assert ours.terminal()
        assert ref.outcome() == ours.outcome(), (name, g, ref.outcome(), ours.outcome())
    print(f"{name}: {num_games} games identical (legal actions, obs, outcomes)")


def main():
    num_games = int(sys.argv[1]) if len(sys.argv) > 1 else 50
    import handyrl.envs.tictactoe as ref_ttt
    import handyrl_tpu.envs.tictactoe as our_ttt
    crosscheck("TicTacToe", ref_ttt, our_ttt, num_games, turn_based=True)

    import handyrl.envs.geister as ref_g
    import handyrl_tpu.envs.geister as our_g
    crosscheck("Geister", ref_g, our_g, num_games, turn_based=True)

    import handyrl.envs.parallel_tictactoe as ref_pttt
    import handyrl_tpu.envs.parallel_tictactoe as our_pttt
    random.seed(7)  # both sides draw the chooser from the global stream
    # dynamics only: our observation intentionally fixes the reference's
    # accidental everyone-gets-the-opponent-view (its turn_view check
    # compares against turn()'s sentinel return, parallel_tictactoe.py:54)
    # — documented in handyrl_tpu/envs/parallel_tictactoe.py
    crosscheck(
        "ParallelTicTacToe (dynamics)", ref_pttt, our_pttt, num_games,
        turn_based=False, compare_obs=False,
    )
    # HungryGeese's ground truth is kaggle_environments (not installable
    # here): tools/crosscheck_kaggle.py machine-checks it where the dep
    # exists (CI extras job); rule-by-rule diff: docs/hungry_geese_parity.md.
    import importlib.util

    if importlib.util.find_spec("kaggle_environments"):
        from crosscheck_kaggle import crosscheck_hungry_geese

        crosscheck_hungry_geese(num_games, verbose=False)
        print(f"HungryGeese: {num_games} games identical vs kaggle engine")
    else:
        print("HungryGeese: SKIPPED (kaggle_environments not installed)")


if __name__ == "__main__":
    main()
