"""Replay-ratio / staleness ablation for the north-star loop (VERDICT r4 #4).

The tuned northstar2 geometry re-samples each ring window ~60x
(produce/consume 0.016 at trains_per_rollout=16 on the v5e).  The soaks
passed in that regime, but nothing showed WHERE learning degrades as the
ratio grows — the most load-bearing untested assumption in the perf
story.  This tool measures it: same loop shape as the bench's northstar2
stage (streaming on-device HungryGeese self-play -> device rings ->
fused sample+train, self-play always under the latest params,
bench.py:_device_replay_northstar_bench), but run for LEARNING — a fixed
budget of UPDATES per configuration, win rate vs random evaluated every
``eval_every`` updates through DeviceEvaluator, so the curves are
win-rate-vs-updates at trains_per_rollout in {1, 4, 16, 64}.

Higher trains_per_rollout = less fresh data per update = higher
effective replay ratio/staleness.  If the 64 curve tracks the 1 curve,
the V-Trace/UPGO off-policy corrections are carrying the regime; where
it sags is the measured staleness limit, and the bench default must sit
below it.  Off-policy corrections anchor: reference train.py:230-239.

CPU mesh is fine (the ratio is a data-freshness property, not a device
property).  Writes docs/captures/replay_ratio_ablation_<stamp>.json.
"""

from __future__ import annotations

import datetime
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# honor HANDYRL_PLATFORM before any jax computation (the axon
# sitecustomize pins the platform; env var alone cannot override it)
from handyrl_tpu.utils import apply_platform_override  # noqa: E402

apply_platform_override()

RATIOS = (1, 4, 16, 64)


def run_config(trains_per_rollout: int, total_updates: int, eval_every: int,
               eval_games: int, n_lanes: int, seed: int) -> dict:
    import jax

    from handyrl_tpu.config import normalize_args
    from handyrl_tpu.envs import make_env
    from handyrl_tpu.parallel import TrainContext, make_mesh
    from handyrl_tpu.runtime.device_eval import DeviceEvaluator
    from handyrl_tpu.runtime.device_replay import DeviceReplay
    from handyrl_tpu.runtime.device_rollout import build_streaming_fn
    from handyrl_tpu.runtime.evaluation import wp_func
    from handyrl_tpu.models import init_variables
    from handyrl_tpu.parallel.mesh import dispatch_serialized

    cfg = normalize_args(
        {
            "env_args": {"env": "HungryGeese"},
            "train_args": {
                "turn_based_training": False,
                "observation": False,
                "burn_in_steps": 0,
                "forward_steps": 8,
                "batch_size": 32,
                "compress_steps": 4,
                "seed": seed,
            },
        }
    )
    args = dict(cfg["train_args"])
    args["env"] = cfg["env_args"]

    env = make_env(args["env"])
    venv = env.vector_env()
    module = env.net()
    params = init_variables(module, env)["params"]
    mesh = make_mesh(args["mesh"])

    k_steps = 32
    fn = build_streaming_fn(
        venv, module, n_lanes, k_steps,
        mesh=mesh if mesh.size > 1 else None, use_observe_mask=False,
    )
    replay = DeviceReplay(venv, module, args, mesh, n_lanes, slots=256)
    ctx = TrainContext(module, args, mesh)
    state = ctx.init_state(params)
    train = replay.train_fn(ctx, fused_steps=1)
    evaluator = DeviceEvaluator(venv, module, n_lanes=32, opponent="random",
                                mesh=mesh if mesh.size > 1 else None)

    key = jax.random.PRNGKey(seed)
    vstate = venv.init(n_lanes, jax.random.PRNGKey(seed + 1))
    hidden = module.initial_state((n_lanes, venv.num_players))

    def rollout():
        nonlocal vstate, hidden, key
        key, sub = jax.random.split(key)
        vstate, hidden, records = dispatch_serialized(
            lambda: fn(state["params"], vstate, hidden, sub)
        )
        return replay.ingest(records)

    # prefill until a batch is sampleable
    while replay.eligible_count() < args["batch_size"]:
        rollout()

    curve = []
    updates = 0
    produced_steps = 0
    t0 = time.perf_counter()
    while updates < total_updates:
        stats = rollout()
        produced_steps += int(jax.device_get(stats["game_steps"]))
        for _ in range(trains_per_rollout):
            if updates >= total_updates:
                break
            key, sub = jax.random.split(key)
            state, m = train(state, sub, 3e-5)
            updates += 1
            if updates % eval_every == 0 or updates == total_updates:
                key, ek = jax.random.split(key)
                counts = evaluator.evaluate(state["params"], eval_games, ek)
                wp = wp_func(counts)
                curve.append({"updates": updates, "win_points": round(wp, 4)})
                print(f"  [ratio {trains_per_rollout}] {updates}/"
                      f"{total_updates} updates, wp vs random = {wp:.3f}",
                      file=sys.stderr, flush=True)
    consumed = updates * args["batch_size"] * args["forward_steps"]
    total = float(jax.device_get(m["total"]))
    return {
        "trains_per_rollout": trains_per_rollout,
        "updates": updates,
        "produce_consume_ratio": round(produced_steps / consumed, 5),
        "effective_replay_ratio": round(consumed / max(produced_steps, 1), 1),
        "curve": curve,
        "final_win_points": curve[-1]["win_points"] if curve else None,
        "late_mean_win_points": round(
            sum(c["win_points"] for c in curve[-3:]) / max(len(curve[-3:]), 1), 4
        ),
        "wall_s": round(time.perf_counter() - t0, 1),
        "loss_finite": bool(__import__("numpy").isfinite(total)),
    }


def main() -> None:
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--updates", type=int, default=400)
    ap.add_argument("--eval-every", type=int, default=50)
    ap.add_argument("--eval-games", type=int, default=64)
    ap.add_argument("--lanes", type=int, default=32)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--ratios", default=",".join(map(str, RATIOS)))
    a = ap.parse_args()

    results = {
        "date_utc": datetime.datetime.now(datetime.timezone.utc).isoformat(
            timespec="seconds"
        ),
        "env": "HungryGeese (device-replay northstar loop)",
        "budget_updates_each": a.updates,
        "configs": [],
    }
    for r in (int(x) for x in a.ratios.split(",")):
        print(f"[ablate] trains_per_rollout={r}...", file=sys.stderr, flush=True)
        results["configs"].append(
            run_config(r, a.updates, a.eval_every, a.eval_games, a.lanes, a.seed)
        )

    print(json.dumps(results, indent=2))
    stamp = datetime.datetime.now(datetime.timezone.utc).strftime("%Y-%m-%d_%H%M")
    dest = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                        "docs", "captures", f"replay_ratio_ablation_{stamp}.json")
    with open(dest, "w") as f:
        json.dump(results, f, indent=2)
    print(f"[ablate] wrote {dest}", file=sys.stderr, flush=True)


if __name__ == "__main__":
    main()
