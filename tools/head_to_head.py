"""Cross-framework head-to-head: equal-budget training QUALITY comparison.

The throughput benches only imply a quality win; this tool measures it
directly (VERDICT r4 item 3).  Both frameworks train TicTacToe under the
reference's own default train_args (/root/reference/config.yaml) and an
EQUAL episode budget — identical minimum_episodes / update_episodes /
epochs, so both consume minimum + epochs*update episodes before their
identical stop condition fires (reference train.py:623-624; repo
runtime/learner.py:450) — then the two trained agents are pitted
directly through this repo's match layer with seat balancing
(runtime/evaluation.py evaluate_mp), both policies sampled at
temperature 1.0 (reference SoftAgent semantics, agent.py:110-112).

The reference's trained net plays through its own torch ModelWrapper
(model.py:33-60, numpy-in/numpy-out) wrapped in THIS repo's Agent; the
observation tensors come from this repo's TicTacToe env, which is
lock-step parity-tested against the reference env
(tools/crosscheck_reference.py), so both nets see exactly the boards
they were trained on.

Usage:
    python tools/head_to_head.py                 # all phases
    python tools/head_to_head.py --phase pit     # reuse existing runs
    python tools/head_to_head.py --epochs 25 --games 600

Writes head2head_run/{ref,ours}/ training runs (gitignored) and a
results JSON + log lines to docs/captures/.
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REFERENCE = "/root/reference"
sys.path.insert(0, REPO)

# honor HANDYRL_PLATFORM in-process for the pit phase (the axon
# sitecustomize pins jax_platforms at interpreter start; the env var
# alone cannot override it — config.update before first computation can)
from handyrl_tpu.utils import apply_platform_override  # noqa: E402

apply_platform_override()

# the reference's own default train_args (reference config.yaml), minus
# the unbounded epochs: -1 — the equal budget needs a bounded stop
COMMON_TRAIN_ARGS = {
    "turn_based_training": True,
    "observation": False,
    "gamma": 0.8,
    "forward_steps": 16,
    "burn_in_steps": 0,
    "compress_steps": 4,
    "entropy_regularization": 1.0e-1,
    "entropy_regularization_decay": 0.1,
    "update_episodes": 200,
    "batch_size": 128,
    "minimum_episodes": 400,
    "maximum_episodes": 100000,
    "num_batchers": 2,
    "eval_rate": 0.1,
    "worker": {"num_parallel": 6},
    "lambda": 0.7,
    "policy_target": "TD",
    "value_target": "TD",
    "eval": {"opponent": ["random"]},
    "seed": 0,
    "restart_epoch": 0,
}


def _write_yaml(path: str, cfg: dict) -> None:
    import yaml

    with open(path, "w") as f:
        yaml.safe_dump(cfg, f)


def _run_train(cmd, cwd, env, log_path, timeout_s: float,
               success_marker=None) -> float:
    t0 = time.perf_counter()
    with open(log_path, "w") as log:
        proc = subprocess.Popen(
            cmd, cwd=cwd, env=env, stdout=log, stderr=subprocess.STDOUT
        )
        try:
            rc = proc.wait(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            proc.terminate()
            try:
                proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                proc.kill()
            raise SystemExit(
                f"training timed out after {timeout_s:.0f}s; see {log_path}"
            )
    if rc != 0:
        # the reference aborts in teardown AFTER completing ("terminate
        # called without an active exception" from its multiprocessing
        # workers -> SIGABRT); completion is judged by the trained
        # artifact + its own success marker, not the exit code
        done_marker = success_marker and _training_completed(
            cwd, log_path, success_marker
        )
        if not done_marker:
            raise SystemExit(f"training failed rc={rc}; see {log_path}")
        print(f"[h2h] note: trainer exited rc={rc} after completing "
              f"(teardown abort); artifact + '{success_marker}' present",
              flush=True)
    return time.perf_counter() - t0


def _training_completed(run_dir: str, log_path: str, marker) -> bool:
    artifact, text = marker
    if not os.path.exists(os.path.join(run_dir, artifact)):
        return False
    with open(log_path, "r", errors="replace") as f:
        return text in f.read()


def ref_train(run_dir: str, epochs: int, timeout_s: float) -> float:
    """Train the reference (torch CPU, its own main.py --train) to
    ``epochs`` model epochs; saves models/latest.pth under run_dir."""
    os.makedirs(run_dir, exist_ok=True)
    _write_yaml(
        os.path.join(run_dir, "config.yaml"),
        {
            "env_args": {"env": "TicTacToe"},
            "train_args": {**COMMON_TRAIN_ARGS, "epochs": epochs},
            "worker_args": {"server_address": "", "num_parallel": 6},
        },
    )
    env = dict(os.environ, PYTHONPATH=REFERENCE)
    # keep torch single-threaded per process: 6 worker processes already
    # oversubscribe the 1-core host; thread fan-out makes it worse
    env.setdefault("OMP_NUM_THREADS", "1")
    return _run_train(
        [sys.executable, os.path.join(REFERENCE, "main.py"), "--train"],
        run_dir, env, os.path.join(run_dir, "train.log"), timeout_s,
        success_marker=(os.path.join("models", "latest.pth"), "finished server"),
    )


def ours_train(run_dir: str, epochs: int, timeout_s: float) -> float:
    """Train this repo (CPU-forced for like-for-like with the torch-CPU
    reference) to ``epochs`` model updates; saves models/latest.ckpt."""
    os.makedirs(run_dir, exist_ok=True)
    _write_yaml(
        os.path.join(run_dir, "config.yaml"),
        {
            "env_args": {"env": "TicTacToe"},
            "train_args": {**COMMON_TRAIN_ARGS, "epochs": epochs},
            "worker_args": {"server_address": "", "num_parallel": 6},
        },
    )
    env = dict(os.environ, HANDYRL_PLATFORM="cpu")
    return _run_train(
        [sys.executable, os.path.join(REPO, "main.py"), "--train"],
        run_dir, env, os.path.join(run_dir, "train.log"), timeout_s,
    )


def _load_ref_agent(run_dir: str, temperature: float):
    """Reference models/latest.pth -> reference torch net + ModelWrapper
    -> THIS repo's sampling Agent."""
    import torch

    sys.path.insert(0, REFERENCE)
    from handyrl.envs.tictactoe import Environment as RefEnv  # noqa: E402
    from handyrl.model import ModelWrapper  # noqa: E402

    from handyrl_tpu.agents import Agent

    net = RefEnv().net()
    path = os.path.join(run_dir, "models", "latest.pth")
    net.load_state_dict(torch.load(path))
    net.eval()
    return Agent(ModelWrapper(net), temperature=temperature, seed=1)


def _load_ours_agent(run_dir: str, temperature: float):
    from handyrl_tpu.agents import Agent
    from handyrl_tpu.envs import make_env
    from handyrl_tpu.models import InferenceModel, init_variables
    from handyrl_tpu.runtime.checkpoint import load_params

    env = make_env({"env": "TicTacToe"})
    module = env.net()
    variables = init_variables(module, env)
    params = load_params(
        os.path.join(run_dir, "models", "latest.ckpt"), variables["params"]
    )
    return Agent(
        InferenceModel(module, {"params": params}), temperature=temperature, seed=2
    )


def pit(ref_dir: str, ours_dir: str, games: int, temperature: float) -> dict:
    """Seat-balanced direct match through this repo's match layer; returns
    the result dict with win points from OUR agent's perspective.

    Results land in a league ``PayoffMatrix`` (handyrl_tpu/league) — the
    same ledger league matches and battle-server games record into — so
    this tool, the league's promotion gate, and the sampler ablation all
    report ONE win-points convention (win + draw/2 over games, wp_func)."""
    from handyrl_tpu.league.matchmaker import PayoffMatrix
    from handyrl_tpu.runtime.evaluation import evaluate_mp, wp_func

    ours = _load_ours_agent(ours_dir, temperature)
    ref = _load_ref_agent(ref_dir, temperature)
    results = evaluate_mp(
        {"env": "TicTacToe"}, {0: ours, 1: ref}, games, num_workers=2
    )
    payoff = PayoffMatrix()
    per_pattern = {}
    outcomes_total: dict = {}
    for pat, res in results.items():
        for outcome, count in res.items():
            # evaluate_mp aggregates outcomes from OUR seat's perspective;
            # replay them into the ledger pairwise (zero-sum 2p)
            payoff.record_score("ours", "ref", float(outcome), -float(outcome),
                                n=count)
            outcomes_total[outcome] = outcomes_total.get(outcome, 0) + count
        per_pattern[pat] = {
            "win_points": round(wp_func(res), 4),
            "games": sum(res.values()),
            "outcomes": {str(k): v for k, v in res.items()},
        }
    wp = payoff.win_points("ours", "ref")
    return {
        "ours_win_points": None if wp is None else round(wp, 4),
        "games": payoff.games("ours", "ref"),
        "outcomes_from_ours_perspective": {
            str(k): v for k, v in outcomes_total.items()
        },
        "per_pattern": per_pattern,
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--phase", choices=["all", "ref-train", "ours-train", "pit"],
                    default="all")
    ap.add_argument("--epochs", type=int, default=25)
    ap.add_argument("--games", type=int, default=600)
    ap.add_argument("--temperature", type=float, default=1.0)
    ap.add_argument("--train-timeout", type=float, default=3600.0)
    ap.add_argument("--run-root", default=os.path.join(REPO, "head2head_run"))
    args = ap.parse_args()

    ref_dir = os.path.join(args.run_root, "ref")
    ours_dir = os.path.join(args.run_root, "ours")
    budget = (COMMON_TRAIN_ARGS["minimum_episodes"]
              + args.epochs * COMMON_TRAIN_ARGS["update_episodes"])
    out = {
        "date_utc": datetime.datetime.now(datetime.timezone.utc).isoformat(timespec="seconds"),
        "env": "TicTacToe",
        "config": "reference defaults (reference config.yaml)",
        "epochs": args.epochs,
        "episode_budget_each": budget,
        "pit_games": args.games,
        "temperature": args.temperature,
    }

    if args.phase in ("all", "ref-train"):
        print(f"[h2h] training reference to {args.epochs} epochs "
              f"(~{budget} episodes)...", flush=True)
        out["ref_train_s"] = round(ref_train(ref_dir, args.epochs,
                                             args.train_timeout), 1)
        print(f"[h2h] reference trained in {out['ref_train_s']}s", flush=True)
    if args.phase in ("all", "ours-train"):
        print(f"[h2h] training handyrl_tpu to {args.epochs} epochs "
              f"(~{budget} episodes)...", flush=True)
        out["ours_train_s"] = round(ours_train(ours_dir, args.epochs,
                                               args.train_timeout), 1)
        print(f"[h2h] handyrl_tpu trained in {out['ours_train_s']}s", flush=True)
    if args.phase in ("all", "pit"):
        print(f"[h2h] pitting: {args.games} games, temperature "
              f"{args.temperature}, seat-balanced", flush=True)
        out["pit"] = pit(ref_dir, ours_dir, args.games, args.temperature)
        wp = out["pit"]["ours_win_points"]
        print(f"[h2h] handyrl_tpu win points vs reference: {wp:.3f} "
              f"over {out['pit']['games']} games", flush=True)

        stamp = datetime.datetime.now(datetime.timezone.utc).strftime("%Y-%m-%d_%H%M")
        dest = os.path.join(REPO, "docs", "captures",
                            f"head_to_head_{stamp}.json")
        with open(dest, "w") as f:
            json.dump(out, f, indent=2)
        print(f"[h2h] wrote {dest}", flush=True)


if __name__ == "__main__":
    main()
