"""graftlint — repo-invariant static analyzer for handyrl_tpu.

Six rules turn four PRs' worth of tribal review rules into a mechanical
gate (catalog + rationale: docs/static_analysis.md):

    HS001  no blocking host syncs in hot-loop modules
    DL002  compiled-call dispatch sites wrapped in dispatch_serialized
           with an explicit device scope
    MP003  no lock-holding mp primitives in batcher-child code paths
    RNG004 no jax PRNG key consumed twice without split
    CFG005 config knobs <-> docs/parameters.md parity, both directions
    MET006 metrics.jsonl writer/consumer key-registry parity

Run: ``python -m tools.graftlint handyrl_tpu/ --baseline``
Escape hatch: ``# graftlint: allow[RULE] reason=...``
"""

from .core import (
    Finding,
    LintConfig,
    RULE_IDS,
    apply_baseline,
    load_baseline,
    run_lint,
    write_baseline,
)

__all__ = [
    "Finding",
    "LintConfig",
    "RULE_IDS",
    "apply_baseline",
    "load_baseline",
    "run_lint",
    "write_baseline",
]
