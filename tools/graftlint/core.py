"""graftlint core: findings, pragmas, baselines, module loading, runner.

The analyzer is stdlib-only (``ast`` + ``re``) on purpose: the lint gate
runs before anything heavy imports, it can never be broken by a jax
version bump, and it lints files it does not import (no side effects).

Vocabulary shared by every rule:

* A **Finding** is one violation, anchored to a repo-relative path and a
  1-based line.  Its fingerprint is content-addressed (rule + path +
  normalized source line + occurrence index), so baselines survive
  unrelated line drift.
* A **pragma** is the in-source escape hatch::

      some_call()  # graftlint: allow[HS001] reason=epoch-end fetch

  A pragma covers its own line and the line directly below it (trailing
  same-line comment, or a comment line above the flagged statement — the
  pylint ``disable-next`` convention).  ``allow[...]`` without a
  ``reason=`` is
  itself reported (GL000): an unexplained suppression is how tribal
  rules rot.
* A **baseline** is a checked-in JSON file of grandfathered fingerprints
  (the burn-down list).  Baselined findings are reported as suppressed,
  not failures; fingerprints that no longer match anything are reported
  as stale so the baseline shrinks monotonically.
"""

from __future__ import annotations

import ast
import fnmatch
import hashlib
import json
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

RULE_IDS = ("HS001", "DL002", "MP003", "RNG004", "CFG005", "MET006")
PRAGMA_RULE = "GL000"  # malformed/unjustified pragma

_PRAGMA_RE = re.compile(
    r"#\s*graftlint:\s*allow\[([A-Za-z0-9_,\s]+)\]\s*(?:reason=(\S.*))?"
)


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str       # root-relative posix path
    line: int       # 1-based
    message: str
    fingerprint: str = ""

    def format(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"


def _norm_line(lines: Sequence[str], lineno: int) -> str:
    if 1 <= lineno <= len(lines):
        return lines[lineno - 1].strip()
    return ""


def fingerprint(rule: str, path: str, norm: str, occurrence: int) -> str:
    digest = hashlib.sha1(norm.encode("utf-8", "replace")).hexdigest()[:12]
    return f"{rule}:{path}:{digest}:{occurrence}"


class Module:
    """One parsed python file: AST + parent links + import table."""

    def __init__(self, path: Path, root: Path):
        self.path = path
        self.rel = path.relative_to(root).as_posix()
        self.source = path.read_text()
        self.lines = self.source.splitlines()
        self.tree = ast.parse(self.source, filename=str(path))
        self.parents: Dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                self.parents[child] = parent
        self.imports = _import_table(self.tree)
        self.pragmas = _parse_pragmas(self.lines)

    def ancestors(self, node: ast.AST) -> Iterable[ast.AST]:
        cur = self.parents.get(node)
        while cur is not None:
            yield cur
            cur = self.parents.get(cur)

    def enclosing_funcs(self, node: ast.AST) -> List[ast.AST]:
        """Innermost-first FunctionDef/AsyncFunctionDef ancestors."""
        return [
            a for a in self.ancestors(node)
            if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]



def _import_table(tree: ast.Module) -> Dict[str, str]:
    """alias -> dotted module/attr (relative imports keep their suffix)."""
    table: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                table[alias.asname or alias.name.split(".")[0]] = (
                    alias.name if alias.asname else alias.name.split(".")[0]
                )
                if alias.asname:
                    table[alias.asname] = alias.name
        elif isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            for alias in node.names:
                table[alias.asname or alias.name] = (
                    f"{mod}.{alias.name}" if mod else alias.name
                )
    return table


def _parse_pragmas(lines: Sequence[str]) -> Dict[int, Tuple[Set[str], Optional[str]]]:
    """lineno -> (rules allowed on that line, reason or None)."""
    out: Dict[int, Tuple[Set[str], Optional[str]]] = {}
    for i, line in enumerate(lines, start=1):
        m = _PRAGMA_RE.search(line)
        if m:
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            reason = m.group(2).strip() if m.group(2) else None
            out[i] = (rules, reason)
    return out


def dotted(node: ast.AST, imports: Dict[str, str]) -> Optional[str]:
    """Best-effort dotted name of an expression, import aliases resolved.

    ``np.asarray`` (with ``import numpy as np``) -> ``numpy.asarray``;
    ``self._fn`` -> ``self._fn``; ``holder["fn"]`` -> ``holder["fn"]``.
    """
    if isinstance(node, ast.Name):
        return imports.get(node.id, node.id)
    if isinstance(node, ast.Attribute):
        base = dotted(node.value, imports)
        return f"{base}.{node.attr}" if base else None
    if isinstance(node, ast.Subscript):
        base = dotted(node.value, imports)
        if base is None:
            return None
        sl = node.slice
        if isinstance(sl, ast.Constant) and isinstance(sl.value, str):
            return f'{base}["{sl.value}"]'
        return f"{base}[?]"
    if isinstance(node, ast.Call):
        return None
    return None


# -- baseline -----------------------------------------------------------------


def load_baseline(path: Path) -> Dict[str, Set[str]]:
    data = json.loads(path.read_text())
    if not isinstance(data, dict) or "findings" not in data:
        raise ValueError(f"{path}: not a graftlint baseline (missing 'findings')")
    return {rule: set(fps) for rule, fps in data["findings"].items()}


def write_baseline(path: Path, findings: Sequence[Finding]) -> None:
    by_rule: Dict[str, List[str]] = {}
    for f in findings:
        by_rule.setdefault(f.rule, []).append(f.fingerprint)
    payload = {
        "version": 1,
        "findings": {rule: sorted(fps) for rule, fps in sorted(by_rule.items())},
    }
    path.write_text(json.dumps(payload, indent=2) + "\n")


def apply_baseline(
    findings: Sequence[Finding], baseline: Dict[str, Set[str]]
) -> Tuple[List[Finding], List[Finding], Dict[str, Set[str]]]:
    """(new, suppressed, stale-entries-by-rule)."""
    new: List[Finding] = []
    suppressed: List[Finding] = []
    seen: Dict[str, Set[str]] = {}
    for f in findings:
        if f.fingerprint in baseline.get(f.rule, set()):
            suppressed.append(f)
            seen.setdefault(f.rule, set()).add(f.fingerprint)
        else:
            new.append(f)
    stale = {
        rule: fps - seen.get(rule, set())
        for rule, fps in baseline.items()
        if fps - seen.get(rule, set())
    }
    return new, suppressed, stale


# -- config -------------------------------------------------------------------


@dataclass
class LintConfig:
    """Repo-specific rule parameters.  Tests point these at fixture trees;
    the defaults encode THIS repo's invariants (see docs/static_analysis.md
    for the rationale behind each list)."""

    root: Path = field(default_factory=Path.cwd)

    # HS001: hot-loop modules where blocking host syncs are violations
    hs001_modules: Tuple[str, ...] = (
        "handyrl_tpu/runtime/trainer.py",
        "handyrl_tpu/runtime/learner.py",
        "handyrl_tpu/runtime/device_*.py",
        "handyrl_tpu/parallel/train_step.py",
        # the serving plane's request loop is a latency hot path: one
        # stray per-batch host sync is a p99 regression on every model
        "handyrl_tpu/serving/*.py",
        # the league plane sits inside the learner's epoch/feed loops and
        # the actors' match loop: a host sync here stalls generation
        "handyrl_tpu/league/*.py",
        # the multi-process cadence runs once per SGD step on the trainer
        # thread and the health plane's threads run beside every dispatch:
        # a stray sync here is a per-step cross-host stall
        "handyrl_tpu/parallel/distributed.py",
        "handyrl_tpu/parallel/health.py",
        # the tracer's span/record path runs INSIDE every instrumented
        # hot seam (dispatch_serialized, batch waits, cadence): a host
        # sync here would be charged to every dispatch in the repo
        "handyrl_tpu/utils/trace.py",
        # the fleet tier sits on the serving request path twice (router
        # proxy + session cache lookup/store on every stateful infer):
        # a stray host sync is a per-request latency regression
        "handyrl_tpu/fleet/*.py",
        # the low-precision fast path's dequantize runs INSIDE the jitted
        # engine apply and the ring sample/forward programs: a host sync
        # here would serialize every quantized inference and train window
        "handyrl_tpu/models/quantize.py",
        # the cross-host plane transports run on threads beside the
        # trainer's dispatch stream and inside the actor host's rollout
        # loop: every host materialization must be an annotated transport
        # boundary, not an accidental sync
        "handyrl_tpu/runtime/plane.py",
        "handyrl_tpu/runtime/actor_host.py",
        # the flywheel's harvest capture seams run INSIDE the serving
        # request path (_do_infer / _reply) and its quality tick inside
        # the watch loop: a stray host sync is a per-request regression
        "handyrl_tpu/flywheel/*.py",
    )
    # functions (bare names) that are drain/teardown/construction paths —
    # host syncs there are the POINT, not a leak
    hs001_allow_funcs: Tuple[str, ...] = (
        "__init__", "drain", "stop", "close", "teardown",
    )
    # calls that mark a loop as a dispatching hot loop (np.asarray/float
    # are only violations when their nearest enclosing loop dispatches)
    dispatch_hints: Tuple[str, ...] = (
        "dispatch_serialized", "train_step", "train_steps",
        "ingest", "ingest_counted", "generate", "evaluate", "train",
    )

    # DL002: modules whose compiled-call dispatch sites must go through
    # parallel.mesh.dispatch_serialized with an explicit device scope
    dl002_modules: Tuple[str, ...] = (
        "handyrl_tpu/runtime/trainer.py",
        "handyrl_tpu/runtime/learner.py",
        "handyrl_tpu/runtime/device_*.py",
        "handyrl_tpu/runtime/plane.py",
        # the actor host's streaming rollout dispatches onto its local
        # mesh concurrently with param polls: same lock discipline
        "handyrl_tpu/runtime/actor_host.py",
        "handyrl_tpu/runtime/shm_batch.py",
        "handyrl_tpu/parallel/train_step.py",
        # per-model serving engines share chips with each other (and, co-
        # located, with a training plane): every engine dispatch must hold
        # its explicit device scope
        "handyrl_tpu/serving/*.py",
        # league opponent engines co-reside with the training plane (and
        # each other) on the same chips — same invariant as serving
        "handyrl_tpu/league/*.py",
        # the cadence broadcasts are device programs sharing the learner
        # mesh with the train step: same lock discipline as every dispatch
        "handyrl_tpu/parallel/distributed.py",
        "handyrl_tpu/parallel/health.py",
        # the tracer must never dispatch device programs at all — any jit
        # call appearing here is a bug, and DL002 makes it lock-scoped
        "handyrl_tpu/utils/trace.py",
        # the session cache touches the device (re-pin on restore) next
        # to serving engines sharing the same chips: same lock discipline
        "handyrl_tpu/fleet/*.py",
        # quantized engines dispatch the SAME compiled apply the serving
        # batchers route through dispatch_serialized; direct dispatches in
        # the quantize module itself must hold the same lock discipline
        "handyrl_tpu/models/quantize.py",
        # the flywheel stages candidate engines onto the same chips the
        # router's serving engines occupy — any device dispatch it grows
        # must hold the same explicit scope
        "handyrl_tpu/flywheel/*.py",
    )
    dispatch_wrapper: str = "dispatch_serialized"

    # CFG005: config defaults <-> docs parity
    cfg005_config: str = "handyrl_tpu/config.py"
    cfg005_docs: str = "docs/parameters.md"
    # dict-valued defaults whose CHILDREN are the knobs (worker.entry_port);
    # every other dict-valued default (mesh, ...) is one knob
    cfg005_nested: Tuple[str, ...] = (
        "worker", "distributed", "eval", "serving", "league", "trace",
        "observability", "fleet", "flywheel",
        # second-level section: the autoscaler's knobs are documented
        # per-knob (fleet.autoscale.enabled, ...), not as one opaque dict
        "fleet.autoscale",
    )
    # documented spellings that are intentionally not defaults (aliases
    # normalized away before validation)
    cfg005_doc_aliases: Tuple[str, ...] = ("attn_mode",)

    # MET006: metrics key registry <-> writers <-> consumers
    met006_registry: str = "handyrl_tpu/utils/metrics.py"
    met006_writers: Tuple[str, ...] = (
        "handyrl_tpu/runtime/learner.py",
        "handyrl_tpu/runtime/trainer.py",
        "handyrl_tpu/serving/server.py",
        "handyrl_tpu/league/learner.py",
        "handyrl_tpu/fleet/router_tier.py",
        "handyrl_tpu/fleet/sessions.py",
        # the flywheel's stats_record feeds both the serving server's
        # periodic record and the learner's per-epoch record
        "handyrl_tpu/flywheel/harvest.py",
        "handyrl_tpu/flywheel/quality.py",
        "handyrl_tpu/flywheel/ingest.py",
    )
    # module-level *_KEYS tuples that feed metrics keys, with the prefix
    # they are written under
    met006_key_tuples: Dict[str, str] = field(default_factory=lambda: {
        "PIPE_STAT_KEYS": "pipe_",
        "PIPE_EVENT_KEYS": "pipe_",
        "SENTINEL_EVENT_KEYS": "",
        "WATCHDOG_EVENT_KEYS": "",
    })
    met006_record_names: Tuple[str, ...] = ("record", "rec", "r")
    met006_stats_attrs: Tuple[str, ...] = ("self.stats",)
    met006_consumers: Tuple[str, ...] = (
        "scripts/_logparse.py",
        "scripts/stats_plot.py",
        "scripts/loss_plot.py",
        "scripts/win_rate_plot.py",
        "tools/ablate_sampling_path.py",
    )
    met006_record_sources: Tuple[str, ...] = ("read_metrics", "parse_records")


def match_any(rel: str, patterns: Sequence[str]) -> bool:
    return any(fnmatch.fnmatch(rel, pat) for pat in patterns)


def collect_py_files(root: Path, paths: Sequence[str]) -> List[Path]:
    out: List[Path] = []
    for p in paths:
        full = (root / p) if not Path(p).is_absolute() else Path(p)
        if full.is_dir():
            out.extend(sorted(full.rglob("*.py")))
        elif full.suffix == ".py":
            out.append(full)
    # dedupe, keep order
    seen: Set[Path] = set()
    uniq = []
    for p in out:
        if p not in seen:
            seen.add(p)
            uniq.append(p)
    return uniq


def run_lint(
    config: LintConfig,
    paths: Sequence[str],
    rules: Optional[Sequence[str]] = None,
) -> List[Finding]:
    """Run the selected rules; returns findings with fingerprints filled,
    pragma-suppressed findings already removed, and GL000 findings for
    pragmas without a reason."""
    from . import rules_contract, rules_runtime

    enabled = set(rules or RULE_IDS)
    root = config.root
    files = collect_py_files(root, paths)
    modules: List[Module] = []
    for path in files:
        try:
            modules.append(Module(path, root))
        except (SyntaxError, UnicodeDecodeError) as exc:
            raise RuntimeError(f"graftlint: cannot parse {path}: {exc}") from exc

    raw: List[Finding] = []
    if enabled & {"HS001", "DL002", "MP003", "RNG004"}:
        raw.extend(rules_runtime.run(modules, config, enabled))
    if enabled & {"CFG005", "MET006"}:
        raw.extend(rules_contract.run(config, enabled))

    # pragma handling + GL000 for reasonless pragmas.  The pragma universe
    # is every file a rule can anchor a finding in: the scanned modules
    # PLUS the contract-rule targets (config/docs/registry/writers/
    # consumers) — pragmas are text-level, so non-scanned and non-python
    # files (docs/parameters.md) carry them the same way
    kept: List[Finding] = []
    line_cache: Dict[str, List[str]] = {m.rel: m.lines for m in modules}
    pragma_cache: Dict[str, Dict[int, Tuple[Set[str], Optional[str]]]] = {
        m.rel: m.pragmas for m in modules
    }
    contract_files = (
        (config.cfg005_config, config.cfg005_docs, config.met006_registry)
        + tuple(config.met006_writers)
        + tuple(config.met006_consumers)
    )
    for rel in contract_files:
        if rel in pragma_cache:
            continue
        try:
            lines = (root / rel).read_text().splitlines()
        except OSError:
            continue
        line_cache[rel] = lines
        pragma_cache[rel] = _parse_pragmas(lines)
    for f in raw:
        pragmas = pragma_cache.get(f.path, {})
        covered = False
        for pragma_line in (f.line, f.line - 1):
            entry = pragmas.get(pragma_line)
            if entry and f.rule in entry[0]:
                covered = True
                break
        if not covered:
            kept.append(f)
    for rel, pragmas in pragma_cache.items():
        for lineno, (rules_set, reason) in pragmas.items():
            if not reason:
                kept.append(Finding(
                    PRAGMA_RULE, rel, lineno,
                    f"pragma allow[{','.join(sorted(rules_set))}] has no "
                    "reason= — every suppression must say why",
                ))

    # fingerprints (content-addressed, occurrence-indexed)
    counts: Dict[Tuple[str, str, str], int] = {}
    final: List[Finding] = []
    for f in sorted(kept, key=lambda f: (f.path, f.line, f.rule)):
        lines = line_cache.get(f.path)
        if lines is None:
            try:
                lines = (root / f.path).read_text().splitlines()
            except OSError:
                lines = []
            line_cache[f.path] = lines
        norm = _norm_line(lines, f.line)
        key = (f.rule, f.path, norm)
        occ = counts.get(key, 0)
        counts[key] = occ + 1
        final.append(Finding(
            f.rule, f.path, f.line, f.message,
            fingerprint(f.rule, f.path, norm, occ),
        ))
    return final
