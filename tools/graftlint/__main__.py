"""CLI: ``python -m tools.graftlint [paths...] [options]``.

Exit status: 0 = clean (after pragmas and, when present, the baseline),
1 = findings, 2 = usage/internal error.  The default baseline
(tools/graftlint/baseline.json) is applied automatically when it exists;
``--no-baseline`` lints from zero, ``--write-baseline`` regenerates the
file from the current findings (the grandfathering step — use it once,
then burn the file down).
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from .core import (
    RULE_IDS,
    LintConfig,
    apply_baseline,
    load_baseline,
    run_lint,
    write_baseline,
)

DEFAULT_BASELINE = Path(__file__).parent / "baseline.json"


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="graftlint",
        description="repo-invariant static analyzer (rules: %s)" % ", ".join(RULE_IDS),
    )
    parser.add_argument("paths", nargs="*", default=["handyrl_tpu/"],
                        help="files/directories to scan (default: handyrl_tpu/)")
    parser.add_argument("--root", default=".", help="repo root (default: cwd)")
    parser.add_argument("--rules", default=None,
                        help="comma-separated rule subset (default: all)")
    parser.add_argument("--baseline", nargs="?", const=str(DEFAULT_BASELINE),
                        default=None, metavar="PATH",
                        help="apply a baseline file (default path when bare)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="ignore the default baseline even if it exists")
    parser.add_argument("--write-baseline", nargs="?", const=str(DEFAULT_BASELINE),
                        default=None, metavar="PATH",
                        help="write current findings as the new baseline and exit 0")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="machine-readable output")
    args = parser.parse_args(argv)

    root = Path(args.root).resolve()
    config = LintConfig(root=root)
    rules = None
    if args.rules:
        rules = [r.strip().upper() for r in args.rules.split(",") if r.strip()]
        unknown = set(rules) - set(RULE_IDS)
        if unknown:
            print(f"graftlint: unknown rules {sorted(unknown)}", file=sys.stderr)
            return 2

    try:
        findings = run_lint(config, args.paths or ["handyrl_tpu/"], rules)
    except RuntimeError as exc:
        print(f"graftlint: {exc}", file=sys.stderr)
        return 2

    if args.write_baseline is not None:
        path = Path(args.write_baseline)
        write_baseline(path, findings)
        print(f"graftlint: wrote baseline with {len(findings)} finding(s) to {path}")
        return 0

    baseline_path = None
    if args.baseline is not None:
        baseline_path = Path(args.baseline)
    elif not args.no_baseline and DEFAULT_BASELINE.exists():
        baseline_path = DEFAULT_BASELINE

    suppressed, stale = [], {}
    if baseline_path is not None and baseline_path.exists():
        try:
            baseline = load_baseline(baseline_path)
        except (ValueError, json.JSONDecodeError) as exc:
            print(f"graftlint: bad baseline {baseline_path}: {exc}", file=sys.stderr)
            return 2
        findings, suppressed, stale = apply_baseline(findings, baseline)

    if args.as_json:
        print(json.dumps({
            "findings": [f.__dict__ for f in findings],
            "suppressed": [f.__dict__ for f in suppressed],
            "stale_baseline": {k: sorted(v) for k, v in stale.items()},
        }, indent=2))
    else:
        for f in findings:
            print(f.format())
        if suppressed:
            print(f"graftlint: {len(suppressed)} finding(s) suppressed by baseline "
                  f"({baseline_path})")
        for rule, fps in sorted(stale.items()):
            print(f"graftlint: {len(fps)} stale {rule} baseline entr"
                  f"{'y' if len(fps) == 1 else 'ies'} — shrink {baseline_path}")
        if not findings:
            print("graftlint: clean")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
