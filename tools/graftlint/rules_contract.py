"""Repo-contract rules: CFG005 (config <-> docs parity) and MET006
(metrics key registry parity between writers and consumers).

Both rules parse their target files with ``ast``/text only — the linter
never imports the code it checks.

CFG005: the knobs are the keys of ``DEFAULT_TRAIN_ARGS`` /
``DEFAULT_WORKER_ARGS`` in config.py (nested sections like ``worker``
flatten to dotted keys).  Every knob must have a ``docs/parameters.md``
table row, and every documented train_args/worker_args row must name a
real knob (aliases like ``attn_mode`` are declared in the config).

MET006: ``handyrl_tpu/utils/metrics.py`` owns the metrics.jsonl key
registry (``METRIC_KEYS`` + ``METRIC_KEY_PREFIXES``) — the tolerance
contract between ``Learner._write_metrics`` writers and the
``read_metrics`` consumers (plot scripts, ablate tools).  A writer
emitting an unregistered key, or a consumer reading one, is a finding:
new keys must be registered (which is what makes every consumer's
``.get``-tolerance reviewable in one place).
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .core import Finding, LintConfig, dotted

_BACKTICK_RE = re.compile(r"`([^`]+)`")


def run(config: LintConfig, enabled: Set[str]) -> List[Finding]:
    findings: List[Finding] = []
    if "CFG005" in enabled:
        findings.extend(_cfg005(config))
    if "MET006" in enabled:
        findings.extend(_met006(config))
    return findings


# -- CFG005 -------------------------------------------------------------------


def _dict_keys(node: ast.Dict, nested: Sequence[str], prefix: str = "",
               out: Optional[Dict[str, int]] = None) -> Dict[str, int]:
    if out is None:
        out = {}
    for key_node, value in zip(node.keys, node.values):
        if not (isinstance(key_node, ast.Constant) and isinstance(key_node.value, str)):
            continue
        key = key_node.value
        full = f"{prefix}{key}"
        # a nested entry is named by its FULL dotted path, so second-level
        # sections ("fleet.autoscale") flatten too when declared; for the
        # top level full == key, which keeps the original entries working
        if isinstance(value, ast.Dict) and full in nested:
            _dict_keys(value, nested, prefix=f"{full}.", out=out)
        else:
            out[full] = key_node.lineno
    return out


def _default_knobs(path: Path, nested: Sequence[str]) -> Tuple[Dict[str, int], Dict[str, int]]:
    tree = ast.parse(path.read_text(), filename=str(path))
    train: Dict[str, int] = {}
    worker: Dict[str, int] = {}
    for node in ast.walk(tree):
        # both plain and annotated assignment (DEFAULT_TRAIN_ARGS:
        # Dict[str, Any] = {...} is an ast.AnnAssign)
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Dict):
            targets = [t for t in node.targets if isinstance(t, ast.Name)]
        elif (
            isinstance(node, ast.AnnAssign)
            and isinstance(node.value, ast.Dict)
            and isinstance(node.target, ast.Name)
        ):
            targets = [node.target]
        else:
            continue
        for target in targets:
            if target.id == "DEFAULT_TRAIN_ARGS":
                train = _dict_keys(node.value, nested)
            elif target.id == "DEFAULT_WORKER_ARGS":
                worker = _dict_keys(node.value, nested)
    return train, worker


def _doc_rows(path: Path) -> Dict[str, Dict[str, int]]:
    """section name ('train_args'/'worker_args'/...) -> {key: lineno}."""
    sections: Dict[str, Dict[str, int]] = {}
    current: Optional[str] = None
    for lineno, line in enumerate(path.read_text().splitlines(), start=1):
        if line.startswith("## "):
            current = line[3:].strip()
            sections.setdefault(current, {})
            continue
        if current is None or not line.startswith("|"):
            continue
        first_cell = line.split("|")[1].strip() if line.count("|") >= 2 else ""
        if not first_cell or set(first_cell) <= {"-", " ", ":"} or first_cell == "key":
            continue
        for token in _BACKTICK_RE.findall(first_cell):
            token = token.strip()
            if token:
                sections[current].setdefault(token, lineno)
    return sections


def _cfg005(config: LintConfig) -> Iterable[Finding]:
    cfg_path = config.root / config.cfg005_config
    docs_path = config.root / config.cfg005_docs
    if not cfg_path.exists() or not docs_path.exists():
        yield Finding("CFG005", config.cfg005_config, 1,
                      f"CFG005 targets missing: {cfg_path.name} or "
                      f"{docs_path.name} not found")
        return
    train, worker = _default_knobs(cfg_path, config.cfg005_nested)
    sections = _doc_rows(docs_path)
    doc_train = sections.get("train_args", {})
    doc_worker = sections.get("worker_args", {})
    aliases = set(config.cfg005_doc_aliases)

    for knob, lineno in sorted(train.items()):
        if knob not in doc_train:
            yield Finding("CFG005", config.cfg005_config, lineno,
                          f"train_args knob '{knob}' has no docs/parameters.md "
                          "row (document it, or delete the knob)")
    for knob, lineno in sorted(worker.items()):
        if knob not in doc_worker:
            yield Finding("CFG005", config.cfg005_config, lineno,
                          f"worker_args knob '{knob}' has no docs/parameters.md "
                          "row (document it, or delete the knob)")
    for key, lineno in sorted(doc_train.items()):
        if key not in train and key not in aliases:
            yield Finding("CFG005", config.cfg005_docs, lineno,
                          f"documented train_args row '{key}' is not a "
                          "validated knob in config.py (stale row, typo, or "
                          "an undeclared alias)")
    for key, lineno in sorted(doc_worker.items()):
        if key not in worker and key not in aliases:
            yield Finding("CFG005", config.cfg005_docs, lineno,
                          f"documented worker_args row '{key}' is not a "
                          "default in config.py (stale row or missing "
                          "default)")


# -- MET006 -------------------------------------------------------------------


def _registry(path: Path) -> Tuple[Set[str], Tuple[str, ...], bool]:
    """(exact keys, prefixes, found) from METRIC_KEYS / METRIC_KEY_PREFIXES."""
    keys: Set[str] = set()
    prefixes: Tuple[str, ...] = ()
    found = False
    tree = ast.parse(path.read_text(), filename=str(path))
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        for target in node.targets:
            if not isinstance(target, ast.Name):
                continue
            if target.id == "METRIC_KEYS":
                found = True
                value = node.value
                if isinstance(value, ast.Call):  # frozenset({...})
                    value = value.args[0] if value.args else ast.Set(elts=[])
                if isinstance(value, (ast.Set, ast.Tuple, ast.List)):
                    keys = {
                        e.value for e in value.elts
                        if isinstance(e, ast.Constant) and isinstance(e.value, str)
                    }
            elif target.id == "METRIC_KEY_PREFIXES":
                if isinstance(node.value, (ast.Tuple, ast.List)):
                    prefixes = tuple(
                        e.value for e in node.value.elts
                        if isinstance(e, ast.Constant) and isinstance(e.value, str)
                    )
    return keys, prefixes, found


def _registered(key: str, keys: Set[str], prefixes: Tuple[str, ...]) -> bool:
    return key in keys or any(key.startswith(p) for p in prefixes)


def _writer_keys(path: Path, config: LintConfig) -> Dict[str, int]:
    """Statically-visible metrics keys a writer module emits -> lineno."""
    tree = ast.parse(path.read_text(), filename=str(path))
    imports: Dict[str, str] = {}
    out: Dict[str, int] = {}
    record_names = set(config.met006_record_names)
    stats_attrs = set(config.met006_stats_attrs)

    def is_record_target(node: ast.AST) -> bool:
        if isinstance(node, ast.Name):
            return node.id in record_names
        if isinstance(node, ast.Attribute):
            return dotted(node, imports) in stats_attrs
        return False

    for node in ast.walk(tree):
        # record = {"k": ...} / record: Dict = {"k": ...} initializers
        literal_targets: List[ast.AST] = []
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Dict):
            literal_targets = list(node.targets)
        elif isinstance(node, ast.AnnAssign) and isinstance(node.value, ast.Dict):
            literal_targets = [node.target]
        for target in literal_targets:
            if isinstance(target, ast.Name) and target.id in record_names:
                for k in node.value.keys:
                    if isinstance(k, ast.Constant) and isinstance(k.value, str):
                        out.setdefault(k.value, k.lineno)
        # record["k"] = ... / self.stats["k"] = ...
        if isinstance(node, ast.Assign):
            for target in node.targets:
                if (
                    isinstance(target, ast.Subscript)
                    and is_record_target(target.value)
                ):
                    sl = target.slice
                    if isinstance(sl, ast.Constant) and isinstance(sl.value, str):
                        out.setdefault(sl.value, target.lineno)
                    elif (
                        isinstance(sl, ast.BinOp)
                        and isinstance(sl.op, ast.Add)
                        and isinstance(sl.left, ast.Constant)
                        and isinstance(sl.left.value, str)
                    ):
                        # "pipe_" + key: the literal prefix is the contract
                        out.setdefault(sl.left.value + "*", target.lineno)
                # self.stats = {literal keys}
                if (
                    is_record_target(target)
                    and isinstance(target, ast.Attribute)
                    and isinstance(node.value, ast.Dict)
                ):
                    for k in node.value.keys:
                        if isinstance(k, ast.Constant) and isinstance(k.value, str):
                            out.setdefault(k.value, k.lineno)
        # record.update(k=...) / record.setdefault("k", ...)
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            if node.func.attr == "update" and is_record_target(node.func.value):
                for kw in node.keywords:
                    if kw.arg:
                        out.setdefault(kw.arg, node.lineno)
            if (
                node.func.attr == "setdefault"
                and is_record_target(node.func.value)
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
            ):
                out.setdefault(node.args[0].value, node.lineno)
        # module-level *_KEYS tuples feeding dynamic writes
        if isinstance(node, ast.Assign) and isinstance(node.value, (ast.Tuple, ast.List)):
            for target in node.targets:
                if (
                    isinstance(target, ast.Name)
                    and target.id in config.met006_key_tuples
                ):
                    prefix = config.met006_key_tuples[target.id]
                    for e in node.value.elts:
                        if isinstance(e, ast.Constant) and isinstance(e.value, str):
                            out.setdefault(prefix + e.value, e.lineno)
    return out


def _consumer_keys(path: Path, config: LintConfig) -> Dict[str, int]:
    """Metrics keys a consumer file reads off record variables -> lineno."""
    tree = ast.parse(path.read_text(), filename=str(path))
    sources = set(config.met006_record_sources)
    tracked_lists: Set[str] = set()
    tracked: Set[str] = set(config.met006_record_names)

    def source_call(node: ast.AST) -> bool:
        if isinstance(node, ast.Call):
            fn = node.func
            name = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else ""
            )
            return name in sources
        return False

    def tracked_iter(node: ast.AST) -> bool:
        return source_call(node) or (
            isinstance(node, ast.Name) and node.id in tracked_lists
        )

    # fixed point over one or two passes: lists from sources, elements
    # from comprehensions/loops over those lists
    for _ in range(3):
        changed = False
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 and isinstance(
                node.targets[0], ast.Name
            ):
                name = node.targets[0].id
                value = node.value
                derived = source_call(value)
                if isinstance(value, ast.ListComp):
                    gen = value.generators[0]
                    if tracked_iter(gen.iter):
                        derived = True
                if derived and name not in tracked_lists:
                    tracked_lists.add(name)
                    changed = True
            if isinstance(node, (ast.comprehension,)):
                if tracked_iter(node.iter) and isinstance(node.target, ast.Name):
                    if node.target.id not in tracked:
                        tracked.add(node.target.id)
                        changed = True
            if isinstance(node, ast.For) and tracked_iter(node.iter):
                if isinstance(node.target, ast.Name) and node.target.id not in tracked:
                    tracked.add(node.target.id)
                    changed = True
        if not changed:
            break

    out: Dict[str, int] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Subscript) and isinstance(node.value, ast.Name):
            if node.value.id in tracked:
                sl = node.slice
                if isinstance(sl, ast.Constant) and isinstance(sl.value, str):
                    out.setdefault(sl.value, node.lineno)
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            if (
                node.func.attr in ("get", "setdefault")
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id in tracked
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
            ):
                out.setdefault(node.args[0].value, node.lineno)
    return out


def _met006(config: LintConfig) -> Iterable[Finding]:
    reg_path = config.root / config.met006_registry
    if not reg_path.exists():
        yield Finding("MET006", config.met006_registry, 1,
                      "metrics key registry module not found")
        return
    keys, prefixes, found = _registry(reg_path)
    if not found:
        yield Finding("MET006", config.met006_registry, 1,
                      "METRIC_KEYS registry missing — metrics.jsonl writers "
                      "and consumers have no shared key contract")
        return
    for rel in config.met006_writers:
        path = config.root / rel
        if not path.exists():
            continue
        for key, lineno in sorted(_writer_keys(path, config).items()):
            probe = key[:-1] if key.endswith("*") else key
            ok = (
                any(probe == p or probe.startswith(p) for p in prefixes)
                if key.endswith("*")
                else _registered(probe, keys, prefixes)
            )
            if not ok:
                yield Finding("MET006", rel, lineno,
                              f"metrics.jsonl key '{key}' written here is not "
                              "in utils.metrics.METRIC_KEYS — register it so "
                              "every reader's tolerance is reviewed")
    for rel in config.met006_consumers:
        path = config.root / rel
        if not path.exists():
            continue
        for key, lineno in sorted(_consumer_keys(path, config).items()):
            if not _registered(key, keys, prefixes):
                yield Finding("MET006", rel, lineno,
                              f"consumer reads metrics key '{key}' that is "
                              "not in utils.metrics.METRIC_KEYS (stale key, "
                              "typo, or an unregistered writer)")
