"""AST rules over runtime modules: HS001, DL002, MP003, RNG004.

Each rule encodes an invariant earned by a prior PR (see
docs/static_analysis.md for the catalog and the history):

* HS001 — no blocking host syncs in the hot-loop modules (PR 6 removed
  the last per-dispatch sync from the streaming path; one stray
  ``block_until_ready`` reopens the 100x pipeline gap).
* DL002 — every compiled-call dispatch site goes through
  ``parallel.mesh.dispatch_serialized`` with an explicit device scope
  (PR 3's per-device lock registry: concurrent multi-device programs
  must reach every device in one order).
* MP003 — batcher-child code paths touch no lock-holding multiprocessing
  primitives (PR 2's SIGKILL-wedge classes: a child dies holding
  whatever lock it was inside).
* RNG004 — a jax PRNG key is never consumed twice without a split
  (classic silent-correlation bug; straight-line analysis per block).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .core import Finding, LintConfig, Module, dotted, match_any

_MP_PRIMITIVES = {
    "Queue", "JoinableQueue", "SimpleQueue", "Event", "Lock", "RLock",
    "Condition", "Semaphore", "BoundedSemaphore", "Barrier", "Manager",
    "Pool",
}
_MP_BANNED_METHODS = {"is_set", "qsize", "join_thread"}


def run(modules: Sequence[Module], config: LintConfig,
        enabled: Set[str]) -> List[Finding]:
    findings: List[Finding] = []
    factories = _jit_factories(modules) if "DL002" in enabled else set()
    for mod in modules:
        if "HS001" in enabled and match_any(mod.rel, config.hs001_modules):
            findings.extend(_hs001(mod, config))
        if "DL002" in enabled and match_any(mod.rel, config.dl002_modules):
            findings.extend(_dl002(mod, config, factories))
        if "MP003" in enabled:
            findings.extend(_mp003(mod))
        if "RNG004" in enabled:
            findings.extend(_rng004(mod))
    return findings


# -- HS001: blocking host syncs in hot-loop modules ---------------------------


def _call_name(call: ast.Call, imports) -> Tuple[Optional[str], str]:
    """(resolved dotted name or None, bare attribute/function name)."""
    d = dotted(call.func, imports)
    if isinstance(call.func, ast.Attribute):
        return d, call.func.attr
    if isinstance(call.func, ast.Name):
        return d, call.func.id
    return d, ""


def _nearest_loop(mod: Module, node: ast.AST) -> Optional[ast.AST]:
    for a in mod.ancestors(node):
        if isinstance(a, (ast.For, ast.While)):
            return a
        if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return None  # loop must be in the same function body
    return None


def _loop_dispatches(loop: ast.AST, mod: Module, hints: Sequence[str]) -> bool:
    for node in ast.walk(loop):
        if isinstance(node, ast.Call):
            _, bare = _call_name(node, mod.imports)
            if bare in hints:
                return True
    return False


def _hs001(mod: Module, config: LintConfig) -> Iterable[Finding]:
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        funcs = mod.enclosing_funcs(node)
        if any(f.name in config.hs001_allow_funcs for f in funcs):
            continue
        resolved, bare = _call_name(node, mod.imports)
        # always-on primitives: these BLOCK the calling thread on device
        # execution wherever they appear
        if bare == "block_until_ready":
            yield Finding("HS001", mod.rel, node.lineno,
                          "blocking host sync: block_until_ready in a "
                          "hot-loop module (use async dispatch; drain only "
                          "in teardown paths)")
            continue
        if resolved == "jax.device_get" or (resolved or "").endswith(".device_get"):
            yield Finding("HS001", mod.rel, node.lineno,
                          "blocking host sync: jax.device_get in a hot-loop "
                          "module (fetch at epoch boundaries, not per "
                          "dispatch)")
            continue
        if bare == "item" and not node.args and not node.keywords and isinstance(
            node.func, ast.Attribute
        ):
            yield Finding("HS001", mod.rel, node.lineno,
                          "blocking host sync: .item() in a hot-loop module")
            continue
        # loop-scoped primitives: a host conversion is only a per-dispatch
        # sync when its nearest enclosing loop is a dispatching loop
        is_asarray = resolved in ("numpy.asarray", "numpy.array")
        is_float = (
            isinstance(node.func, ast.Name) and node.func.id == "float"
            and node.args and not isinstance(node.args[0], ast.Constant)
        )
        if is_asarray or is_float:
            loop = _nearest_loop(mod, node)
            if loop is not None and _loop_dispatches(loop, mod, config.dispatch_hints):
                what = "np.asarray" if is_asarray else "float()"
                yield Finding("HS001", mod.rel, node.lineno,
                              f"blocking host sync: {what} of a (possibly "
                              "device-resident) value inside a dispatching "
                              "hot loop")


# -- DL002: dispatch sites must be wrapped + explicit -------------------------


def _jit_factories(modules: Sequence[Module]) -> Set[str]:
    """Names of functions (any scanned module) that RETURN a jax.jit
    callable — assignments from their calls are jit-bound targets."""
    out: Set[str] = set()
    for mod in modules:
        for node in ast.walk(mod.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for ret in ast.walk(node):
                if (
                    isinstance(ret, ast.Return)
                    and isinstance(ret.value, ast.Call)
                    and dotted(ret.value.func, mod.imports) == "jax.jit"
                ):
                    out.add(node.name)
                    break
    return out


def _guard_nodes(mod: Module, wrapper: str) -> Set[ast.AST]:
    """Function/lambda nodes whose body executes under the dispatch
    wrapper's locks: literal lambdas/defs passed as its first argument."""
    guards: Set[ast.AST] = set()
    named: Set[str] = set()
    for node in ast.walk(mod.tree):
        if (
            isinstance(node, ast.Call)
            and _call_name(node, mod.imports)[1] == wrapper
            and node.args
        ):
            arg0 = node.args[0]
            if isinstance(arg0, ast.Lambda):
                guards.add(arg0)
            elif isinstance(arg0, ast.Name):
                named.add(arg0.id)
    if named:
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and node.name in named:
                guards.add(node)
    return guards


def _dl002(mod: Module, config: LintConfig,
           factories: Set[str]) -> Iterable[Finding]:
    wrapper = config.dispatch_wrapper
    guards = _guard_nodes(mod, wrapper)

    # jit-bound assignment targets (dotted reprs) in this module
    jit_targets: Set[str] = set()
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            callee = dotted(node.value.func, mod.imports)
            bare = _call_name(node.value, mod.imports)[1]
            if callee == "jax.jit" or bare in factories:
                for target in node.targets:
                    rep = dotted(target, mod.imports)
                    if rep:
                        jit_targets.add(rep)

    def under_guard(node: ast.AST) -> bool:
        return any(a in guards for a in mod.ancestors(node))

    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        bare = _call_name(node, mod.imports)[1]
        # check the wrapper's own call sites for an explicit device scope
        if bare == wrapper:
            in_def = any(
                isinstance(a, ast.FunctionDef) and a.name == wrapper
                for a in mod.ancestors(node)
            )
            if in_def:
                continue
            devices_given = len(node.args) >= 2 or any(
                kw.arg == "devices" for kw in node.keywords
            )
            explicit_none = (
                len(node.args) >= 2
                and isinstance(node.args[1], ast.Constant)
                and node.args[1].value is None
            )
            if not devices_given or explicit_none:
                yield Finding("DL002", mod.rel, node.lineno,
                              f"{wrapper} without an explicit device scope "
                              "(pass the mesh/devices the program touches; "
                              "None serializes with everything)")
            continue
        # direct invocation of a jit-bound callable outside the locks
        rep = dotted(node.func, mod.imports)
        if rep in jit_targets and not under_guard(node):
            yield Finding("DL002", mod.rel, node.lineno,
                          f"compiled call {rep}(...) dispatched outside "
                          f"{wrapper} — concurrent multi-device programs "
                          "need one per-device program order")
            continue
        # immediate jax.jit(...)(args) invocation
        if (
            isinstance(node.func, ast.Call)
            and dotted(node.func.func, mod.imports) == "jax.jit"
            and not under_guard(node)
        ):
            yield Finding("DL002", mod.rel, node.lineno,
                          f"jax.jit(...)(...) dispatched outside {wrapper}")


# -- MP003: mp primitives in batcher-child code paths -------------------------


def _mp003(mod: Module) -> Iterable[Finding]:
    # child roots: functions passed as target= to a *.Process(...) call
    roots: Set[str] = set()
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call) and _call_name(node, mod.imports)[1] == "Process":
            for kw in node.keywords:
                if kw.arg == "target" and isinstance(kw.value, ast.Name):
                    roots.add(kw.value.id)
    if not roots:
        return
    # same-module call-graph closure from the roots
    defs: Dict[str, ast.AST] = {
        n.name: n
        for n in ast.walk(mod.tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    }
    closure: Set[str] = set()
    frontier = [r for r in roots if r in defs]
    while frontier:
        name = frontier.pop()
        if name in closure:
            continue
        closure.add(name)
        for node in ast.walk(defs[name]):
            if isinstance(node, ast.Call):
                bare = _call_name(node, mod.imports)[1]
                if bare in defs and bare not in closure:
                    frontier.append(bare)
    for name in closure:
        for node in ast.walk(defs[name]):
            if not isinstance(node, ast.Call):
                continue
            resolved, bare = _call_name(node, mod.imports)
            if bare in _MP_PRIMITIVES and resolved and (
                resolved.startswith("multiprocessing")
                or resolved.split(".")[0] in ("mp", "multiprocessing")
                or ".multiprocessing." in f".{resolved}."
            ):
                yield Finding("MP003", mod.rel, node.lineno,
                              f"mp.{bare} constructed in batcher-child code "
                              f"path {name}() — a SIGKILL'd child dies "
                              "holding mp locks; use raw pipes / lock-free "
                              "Values (PR 2 wedge classes)")
            elif bare in _MP_BANNED_METHODS and isinstance(node.func, ast.Attribute):
                yield Finding("MP003", mod.rel, node.lineno,
                              f".{bare}() in batcher-child code path "
                              f"{name}() — lock-holding mp accessor in a "
                              "child hot loop (mp.Event.is_set takes the "
                              "shared cond lock; qsize the queue lock)")


# -- RNG004: PRNG key consumed twice without split ----------------------------


class _KeyState:
    __slots__ = ("uses",)

    def __init__(self) -> None:
        self.uses: Dict[str, int] = {}

    def copy(self) -> "_KeyState":
        s = _KeyState()
        s.uses = dict(self.uses)
        return s

    def merge_max(self, other: "_KeyState") -> None:
        for k, v in other.uses.items():
            self.uses[k] = max(self.uses.get(k, 0), v)


_KEY_SOURCES = ("jax.random.PRNGKey", "jax.random.split", "jax.random.fold_in",
                "jax.random.key")


def _terminates(body: Sequence[ast.stmt]) -> bool:
    """True when the block cannot fall through to the statement after it."""
    if not body:
        return False  # an absent else DOES fall through
    return isinstance(body[-1], (ast.Return, ast.Raise, ast.Break, ast.Continue))


def _rng004(mod: Module) -> Iterable[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            state = _KeyState()
            _rng_walk_block(node.body, state, mod, findings)
    return findings


def _is_key_source(call: ast.Call, mod: Module) -> bool:
    d = dotted(call.func, mod.imports)
    if d in _KEY_SOURCES:
        return True
    # tolerate `from jax import random` / `import jax.random as jrandom`
    return bool(d and d.split(".")[-1] in ("PRNGKey", "split", "fold_in")
                and "random" in d)


def _consume_names(node: ast.AST, state: _KeyState, mod: Module,
                   findings: List[Finding]) -> None:
    """Count key names passed as call arguments anywhere under ``node``
    (nested lambdas/defs count once — they capture, and usually run once)."""
    for sub in ast.walk(node):
        if not isinstance(sub, ast.Call):
            continue
        for arg in list(sub.args) + [kw.value for kw in sub.keywords]:
            if isinstance(arg, ast.Name) and arg.id in state.uses:
                state.uses[arg.id] += 1
                if state.uses[arg.id] == 2:
                    findings.append(Finding(
                        "RNG004", mod.rel, arg.lineno,
                        f"PRNG key '{arg.id}' consumed twice without "
                        "jax.random.split — reusing a key correlates "
                        "streams silently",
                    ))


def _rng_walk_block(body: Sequence[ast.stmt], state: _KeyState, mod: Module,
                    findings: List[Finding]) -> None:
    for stmt in body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            inner = _KeyState()
            inner_body = stmt.body
            _rng_walk_block(inner_body, inner, mod, findings)
            continue
        if isinstance(stmt, ast.Assign):
            # RHS consumption first, then LHS rebinding
            _consume_names(stmt.value, state, mod, findings)
            is_source = isinstance(stmt.value, ast.Call) and _is_key_source(
                stmt.value, mod
            )
            for target in stmt.targets:
                names = (
                    [target] if isinstance(target, ast.Name)
                    else list(target.elts) if isinstance(target, (ast.Tuple, ast.List))
                    else []
                )
                for t in names:
                    if isinstance(t, ast.Name):
                        if is_source:
                            state.uses[t.id] = 0       # fresh key binding
                        elif t.id in state.uses:
                            del state.uses[t.id]        # rebound to non-key
            continue
        if isinstance(stmt, ast.If):
            _consume_names(stmt.test, state, mod, findings)
            body_state = state.copy()
            else_state = state.copy()
            _rng_walk_block(stmt.body, body_state, mod, findings)
            _rng_walk_block(stmt.orelse, else_state, mod, findings)
            # only one branch runs: merged use count is the max, not sum —
            # and a branch that cannot fall through (return/raise/...)
            # contributes nothing to the code after the If
            state.uses = {}
            if not _terminates(stmt.body):
                state.merge_max(body_state)
            if not _terminates(stmt.orelse):
                state.merge_max(else_state)
            continue
        if isinstance(stmt, (ast.For, ast.While)):
            # single-pass body analysis: catches double use WITHIN one
            # iteration; cross-iteration reuse (no reassignment before the
            # loop repeats) is out of scope to avoid false positives on
            # guarded/continue-heavy loops
            loop_state = state.copy()
            if isinstance(stmt, ast.For):
                _consume_names(stmt.iter, loop_state, mod, findings)
            else:
                _consume_names(stmt.test, loop_state, mod, findings)
            _rng_walk_block(stmt.body, loop_state, mod, findings)
            _rng_walk_block(stmt.orelse, loop_state, mod, findings)
            state.merge_max(loop_state)
            continue
        if isinstance(stmt, (ast.Try,)):
            inner = state.copy()
            _rng_walk_block(stmt.body, inner, mod, findings)
            for handler in stmt.handlers:
                _rng_walk_block(handler.body, inner.copy(), mod, findings)
            _rng_walk_block(stmt.orelse, inner, mod, findings)
            _rng_walk_block(stmt.finalbody, inner, mod, findings)
            state.merge_max(inner)
            continue
        if isinstance(stmt, (ast.With,)):
            for item in stmt.items:
                _consume_names(item.context_expr, state, mod, findings)
            _rng_walk_block(stmt.body, state, mod, findings)
            continue
        # plain statement (Expr, Return, Aug, ...): count consumptions
        _consume_names(stmt, state, mod, findings)
