"""Shared driver for the on-chip learning soaks (tools/soak_*_tpu.py).

Phase ``train`` (real chip, single process, clean exit): Learner.run() with
a device-replay config, artifacts in ``run_dir`` (metrics.jsonl +
models/latest.ckpt), then a CPU-pinned ``eval`` subprocess whose verdict —
not just its survival — becomes the process exit code.
Phase ``eval`` (CPU-pinned): matched offline evals of the trained net and
the SAME net untrained, each vs the baseline opponent through the shared
margin-calibrated aggregation (runtime/evaluation.py:eval_vs_baseline);
exits non-zero when the outcome margin misses the bar, so a no-learning
run can never read as a clean exit.
"""

import json
import os
import subprocess
import sys


def run(argv, script_path: str, cfg: dict, run_dir: str, opponent: str,
        margin: float, wp_bar: float, num_games: int = 240) -> None:
    mode = argv[1] if len(argv) > 1 else "train"
    if mode == "train":
        _train(script_path, cfg, run_dir)
    elif mode == "eval":
        _evaluate(cfg, run_dir, opponent, margin, wp_bar, num_games)
    else:
        raise SystemExit(f"unknown mode {mode!r} (train|eval)")


def _train(script_path: str, cfg: dict, run_dir: str) -> None:
    os.makedirs(run_dir, exist_ok=True)
    os.chdir(run_dir)
    from handyrl_tpu.config import normalize_args
    from handyrl_tpu.runtime.learner import Learner

    import jax
    d = jax.devices()[0]
    print(f"platform: {d.platform}:{getattr(d, 'device_kind', '?')}", flush=True)
    Learner(normalize_args(cfg)).run()
    print("training done; launching CPU-pinned matched eval", flush=True)
    # the eval subprocess pins CPU itself; its verdict is the run's whole
    # point, so its exit code (crash OR missed margin) is ours
    rc = subprocess.run([sys.executable, script_path, "eval"],
                        check=False).returncode
    if rc != 0:
        print(f"matched eval FAILED (rc={rc})", flush=True)
    sys.exit(rc)


def _evaluate(cfg: dict, run_dir: str, opponent: str, margin: float,
              wp_bar: float, num_games: int) -> None:
    import jax
    jax.config.update("jax_platforms", "cpu")
    from handyrl_tpu.agents import Agent
    from handyrl_tpu.config import normalize_args
    from handyrl_tpu.envs import make_env
    from handyrl_tpu.models import InferenceModel, init_variables
    from handyrl_tpu.runtime.evaluation import eval_vs_baseline, load_model_agent

    args = normalize_args(cfg)
    env_args = args["env_args"]
    env = make_env(env_args)
    module = env.net()

    untrained = Agent(InferenceModel(module, init_variables(module, env)))
    trained = load_model_agent(os.path.join(run_dir, "models", "latest.ckpt"),
                               env, module)
    wp_u, out_u = eval_vs_baseline(env_args, untrained, opponent, num_games)
    print(f"untrained vs {opponent}: wp {wp_u:.3f} mean outcome {out_u:.3f}",
          flush=True)
    wp_t, out_t = eval_vs_baseline(env_args, trained, opponent, num_games)
    print(f"trained   vs {opponent}: wp {wp_t:.3f} mean outcome {out_t:.3f}",
          flush=True)
    verdict = {
        "wp_untrained": wp_u, "wp_trained": wp_t,
        "outcome_untrained": out_u, "outcome_trained": out_t,
        "margin": out_t - out_u,
        "learns": bool(out_t > out_u + margin),
        "clears_wp_bar": bool(wp_t >= wp_bar),
    }
    print("RESULT " + json.dumps(verdict), flush=True)
    sys.exit(0 if verdict["learns"] and verdict["clears_wp_bar"] else 1)
