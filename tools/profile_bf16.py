"""Close the bf16-on-conv question with an on-chip HLO profile (VERDICT r3
item 8).

History: round 2 measured bf16 geese training 2.9x SLOWER than fp32 on the
chip; round 3 measured it 1.19-1.32x FASTER — but only because tunnel RTT
dominated those captures (smaller transfers win when dispatch is the
bottleneck).  The per-op question — do the 7x11/32-channel convs
themselves run faster or slower in bf16? — was never answered.  This
times the jitted geese train step fp32 vs bf16 with DEVICE timing
decoupled from dispatch (fused lax.scan of K updates per call, so one
dispatch amortizes over K steps and the wall clock approaches pure device
time), and writes jax.profiler traces of both variants for HLO-level
inspection.

Run on the chip:  python tools/profile_bf16.py [K] [reps]
Outputs: docs/captures/bf16_profile_<ts>/ {fp32,bf16}/ trace dirs + a
printed verdict line to paste into BASELINE.md.
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    K = int(sys.argv[1]) if len(sys.argv) > 1 else 32
    reps = int(sys.argv[2]) if len(sys.argv) > 2 else 5

    import jax

    from handyrl_tpu.utils import apply_platform_override

    apply_platform_override()

    import bench

    print(f"backend: {jax.default_backend()} ({jax.devices()[0].device_kind})")
    ts = time.strftime("%Y-%m-%d_%H%M")
    outdir = f"docs/captures/bf16_profile_{ts}"

    # one shared store of episodes; both variants train the same data
    overrides = {"turn_based_training": False, "observation": False}
    base = bench._train_bench("HungryGeese", overrides, 2.0,
                              len(jax.devices()), fill_episodes=48)

    results = {}
    for name, dtype in (("fp32", None), ("bf16", "bfloat16")):
        if dtype is None:
            res = base  # fp32 IS the base config; no need to re-bench it
        else:
            res = bench._train_bench(
                "HungryGeese", dict(overrides, compute_dtype=dtype),
                2.0, len(jax.devices()), reuse=base,
            )
        ctx, args, store = res["ctx"], res["args"], res["store"]
        state = ctx.init_state(base["model"].variables["params"])
        stacked = ctx.put_batches(
            [bench._sample_batch(store, args) for _ in range(K)]
        )
        state, m = ctx.train_steps(state, stacked, 1e-5)  # compile + warm
        jax.block_until_ready(m["total"])

        times = []
        trace_dir = os.path.join(outdir, name)
        for i in range(reps):
            if i == reps - 1:  # profile only the last rep (smallest trace)
                jax.profiler.start_trace(trace_dir)
            t0 = time.perf_counter()
            state, m = ctx.train_steps(state, stacked, 1e-5)
            jax.block_until_ready(m["total"])
            times.append(time.perf_counter() - t0)
            if i == reps - 1:
                jax.profiler.stop_trace()
        per_step_ms = min(times) / K * 1000.0
        results[name] = per_step_ms
        print(f"{name}: {per_step_ms:.3f} ms/update (K={K} fused, best of "
              f"{reps}; all reps {[round(t / K * 1000, 3) for t in times]}) "
              f"trace -> {trace_dir}")

    ratio = results["fp32"] / results["bf16"]
    verdict = ("bf16 FASTER" if ratio > 1.05
               else "bf16 SLOWER" if ratio < 0.95 else "parity")
    print(
        f"VERDICT: {verdict} — fp32 {results['fp32']:.3f} ms/update vs "
        f"bf16 {results['bf16']:.3f} ms/update ({ratio:.2f}x), fused K={K} "
        f"(dispatch amortized; this is device math, not RTT)"
    )


if __name__ == "__main__":
    main()
