"""Numerical parity check of target algorithms vs the reference torch code.

Feeds identical random tensors through reference handyrl.losses.compute_target
and handyrl_tpu.ops.targets.compute_target; asserts outputs match to float32
tolerance for every algorithm / gamma / lambda / reward combination.
Dev/judging aid only (needs torch + mounted reference).
"""

import os
import sys

# this tool mixes torch and jax in one process: pin jax to CPU BEFORE any
# backend init (otherwise a site-installed accelerator backend may be dialed
# and hang) and keep both runtimes to one OpenMP thread each (oversubscribed
# OpenMP pools from the two runtimes deadlock on this machine)
os.environ.setdefault("OMP_NUM_THREADS", "1")
os.environ["JAX_PLATFORMS"] = "cpu"

import numpy as np

sys.path.insert(0, "/root/repo")
sys.path.insert(0, "/root/reference")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import torch  # noqa: E402

torch.set_num_threads(1)

from handyrl.losses import compute_target as ref_compute_target  # noqa: E402
from handyrl_tpu.ops.targets import compute_target as tpu_compute_target  # noqa: E402


def main():
    rng = np.random.default_rng(7)
    B, T, P, C = 3, 8, 2, 1
    checked = 0
    for algo in ["MC", "TD", "UPGO", "VTRACE"]:
        for gamma in [1.0, 0.8]:
            for lmb in [0.7, 1.0, 0.0]:
                for with_rewards in [True, False]:
                    values = rng.normal(size=(B, T, P, C)).astype(np.float32)
                    returns = rng.normal(size=(B, T, P, C)).astype(np.float32)
                    rewards = rng.normal(size=(B, T, P, C)).astype(np.float32) if with_rewards else None
                    rhos = rng.uniform(0, 1.5, size=(B, T, P, C)).astype(np.float32)
                    cs = rng.uniform(0, 1.5, size=(B, T, P, C)).astype(np.float32)
                    masks = (rng.uniform(size=(B, T, P, C)) > 0.3).astype(np.float32)

                    t_rew = torch.from_numpy(rewards) if rewards is not None else None
                    ref_tgt, ref_adv = ref_compute_target(
                        algo, torch.from_numpy(values), torch.from_numpy(returns), t_rew,
                        lmb, gamma, torch.from_numpy(rhos), torch.from_numpy(cs), torch.from_numpy(masks),
                    )
                    tgt, adv = tpu_compute_target(algo, values, returns, rewards, lmb, gamma, rhos, cs, masks)
                    np.testing.assert_allclose(np.asarray(tgt), ref_tgt.numpy(), rtol=2e-5, atol=1e-5,
                                               err_msg=f"{algo} g={gamma} l={lmb} rew={with_rewards} target")
                    np.testing.assert_allclose(np.asarray(adv), ref_adv.numpy(), rtol=2e-5, atol=1e-5,
                                               err_msg=f"{algo} g={gamma} l={lmb} rew={with_rewards} advantage")
                    checked += 1
    print(f"targets parity: {checked} configurations identical vs reference torch implementation")


if __name__ == "__main__":
    main()
