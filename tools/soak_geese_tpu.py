"""HungryGeese learning soak on the real chip, through device-resident replay.

The committed CPU soak (tests/test_soak.py::test_geese_device_selfplay_beats_rulebase)
is sized for a 1-core CI host: ~600 updates at lr_scale 8 over hours.  On the
chip the same loop runs at ~50 updates/s (BASELINE.md northstar2 row), so this
driver trains with a near-parity schedule (lr_scale 2) and a tens-of-thousands
update budget — the scale the reference's lr schedule (train.py:328-332,
3e-8 x data-count EMA) was designed for — in tens of minutes.

Run (background, clean exit — never kill a process holding the axon lease):

    cd /root/repo && nohup python tools/soak_geese_tpu.py train \
        > docs/captures/soak_geese_tpu.log 2>&1 &

Phase 1 (this process, TPU): Learner.run() with device_replay — self-play,
ring ingest and SGD all on device; host workers eval-only.  Artifacts land in
./soak_geese_tpu_run/ (metrics.jsonl + models/latest.ckpt).
Phase 2 (subprocess, CPU-pinned): matched 240-game evals — the trained net and
the SAME net untrained, each vs 3 greedy rule-based seats
(envs/hungry_geese.py rule_based_action) — identical margin calibration to the
committed soak: mean-outcome difference se <= 0.068, +0.12 margin.  The
verdict drives the exit code (tools/_soak_tpu_common.py).

Result 2026-07-31 (TPU v5 lite x1): wp 0.531 -> 0.733, mean outcome
-0.221 -> +0.110 — 4,944 updates / 100,500 episodes in ~17 min.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools._soak_tpu_common import run  # noqa: E402

RUN_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                       "soak_geese_tpu_run")

CFG = {
    "env_args": {"env": "HungryGeese"},
    "train_args": {
        "turn_based_training": False,
        "observation": False,
        "batch_size": 32,
        "forward_steps": 16,
        "lambda": 0.95,
        # near-parity schedule: the chip delivers the update counts the
        # reference schedule assumes, so the 8x CPU-soak boost is not needed
        "lr_scale": 2.0,
        "minimum_episodes": 500,
        "update_episodes": 500,
        "maximum_episodes": 8000,
        "epochs": 200,
        "num_batchers": 1,
        "eval_rate": 0.0,          # workers are eval-only under device_replay
        "device_rollout_games": 64,
        "device_replay": True,
        # dense per-epoch curve vs the rule-based twin — the host worker's
        # curve starved on this run's first capture (runtime/device_eval.py)
        "device_eval_games": 32,
        "fused_steps": 4,          # amortize tunnel RTT: 4 updates/dispatch
        "mesh": {"dp": 1},
        "worker": {"num_parallel": 1},
        "eval": {"opponent": ["rulebase"]},
    },
}

if __name__ == "__main__":
    run(sys.argv, os.path.abspath(__file__), CFG, RUN_DIR,
        opponent="rulebase", margin=0.12, wp_bar=0.5)
