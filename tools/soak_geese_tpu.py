"""HungryGeese learning soak on the real chip, through device-resident replay.

The committed CPU soak (tests/test_soak.py::test_geese_device_selfplay_beats_rulebase)
is sized for a 1-core CI host: ~600 updates at lr_scale 8 over hours.  On the
chip the same loop runs at ~50 updates/s (BASELINE.md northstar2 row), so this
driver trains with a near-parity schedule (lr_scale 2) and a tens-of-thousands
update budget — the scale the reference's lr schedule (train.py:328-332,
3e-8 x data-count EMA) was designed for — in tens of minutes.

Run (background, clean exit — never kill a process holding the axon lease):

    cd /root/repo && nohup python tools/soak_geese_tpu.py train \
        > docs/captures/soak_geese_tpu.log 2>&1 &

Phase 1 (this process, TPU): Learner.run() with device_replay — self-play,
ring ingest and SGD all on device; host workers eval-only.  Artifacts land in
./soak_geese_tpu_run/ (metrics.jsonl + models/latest.ckpt).
Phase 2 (subprocess, CPU-pinned): matched 240-game evals — the trained net and
the SAME net untrained, each vs 3 greedy rule-based seats
(envs/hungry_geese.py rule_based_action) — identical margin calibration to the
committed soak: mean-outcome difference se <= 0.068, +0.12 margin.
"""

import json
import os
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

RUN_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                       "soak_geese_tpu_run")

CFG = {
    "env_args": {"env": "HungryGeese"},
    "train_args": {
        "turn_based_training": False,
        "observation": False,
        "batch_size": 32,
        "forward_steps": 16,
        "lambda": 0.95,
        # near-parity schedule: the chip delivers the update counts the
        # reference schedule assumes, so the 8x CPU-soak boost is not needed
        "lr_scale": 2.0,
        "minimum_episodes": 500,
        "update_episodes": 500,
        "maximum_episodes": 8000,
        "epochs": 200,
        "num_batchers": 1,
        "eval_rate": 0.0,          # workers are eval-only under device_replay
        "device_rollout_games": 64,
        "device_replay": True,
        "fused_steps": 4,          # amortize tunnel RTT: 4 updates/dispatch
        "mesh": {"dp": 1},
        "worker": {"num_parallel": 1},
        "eval": {"opponent": ["rulebase"]},
    },
}


def train() -> None:
    os.makedirs(RUN_DIR, exist_ok=True)
    os.chdir(RUN_DIR)
    from handyrl_tpu.config import normalize_args
    from handyrl_tpu.runtime.learner import Learner

    import jax
    d = jax.devices()[0]
    print(f"platform: {d.platform}:{getattr(d, 'device_kind', '?')}", flush=True)
    Learner(normalize_args(CFG)).run()
    print("training done; launching CPU-pinned matched eval", flush=True)
    # the eval subprocess pins CPU itself (jax.config in evaluate());
    # its verdict is the run's whole point, so its failure is ours
    rc = subprocess.run([sys.executable, os.path.abspath(__file__), "eval"],
                        check=False).returncode
    if rc != 0:
        print(f"matched eval FAILED (rc={rc})", flush=True)
    sys.exit(rc)


def evaluate() -> None:
    import jax
    jax.config.update("jax_platforms", "cpu")
    from handyrl_tpu.agents import Agent
    from handyrl_tpu.config import normalize_args
    from handyrl_tpu.envs import make_env
    from handyrl_tpu.models import InferenceModel, init_variables
    from handyrl_tpu.runtime.evaluation import eval_vs_baseline, load_model_agent

    args = normalize_args(CFG)
    env_args = args["env_args"]
    env = make_env(env_args)
    module = env.net()

    def vs_rulebase(agent0, num_games=240):
        return eval_vs_baseline(env_args, agent0, "rulebase", num_games,
                                num_workers=4)

    untrained = Agent(InferenceModel(module, init_variables(module, env)))
    trained = load_model_agent(os.path.join(RUN_DIR, "models", "latest.ckpt"),
                               env, module)
    wp_u, out_u = vs_rulebase(untrained)
    print(f"untrained vs rulebase: wp {wp_u:.3f} mean outcome {out_u:.3f}", flush=True)
    wp_t, out_t = vs_rulebase(trained)
    print(f"trained   vs rulebase: wp {wp_t:.3f} mean outcome {out_t:.3f}", flush=True)
    verdict = {
        "wp_untrained": wp_u, "wp_trained": wp_t,
        "outcome_untrained": out_u, "outcome_trained": out_t,
        "margin": out_t - out_u,
        "learns": bool(out_t > out_u + 0.12), "top_half": bool(wp_t >= 0.5),
    }
    print("RESULT " + json.dumps(verdict), flush=True)


if __name__ == "__main__":
    mode = sys.argv[1] if len(sys.argv) > 1 else "train"
    {"train": train, "eval": evaluate}[mode]()
