#!/bin/bash
# Chip watcher for the flash-vs-einsum question on the pinned transformer
# shape (tools/tune_transformer.py d1024 variants): probe the axon lease
# on a loop; when it answers, bank both variants in one session (same-hour
# like-for-like) and exit.  The probe subprocess is timeout-killed before
# backend init completes on a wedged lease, so there is no initialized
# client to wedge further (same pattern as watch_and_capture.sh).
cd "$(dirname "$0")/.." || exit 1
PIDFILE=/tmp/attn_mode_watch.pid
[ -f "$PIDFILE" ] && kill -0 "$(cat "$PIDFILE")" 2>/dev/null && { echo "watcher already running"; exit 0; }
echo $$ > "$PIDFILE"
# clean up on ANY exit (incl. kill): a stale pidfile whose PID gets
# recycled would make the liveness check refuse to start a new watcher.
# Only if it is still OURS — an old instance exiting must not delete a
# live successor's pidfile.
trap '[ "$(cat "$PIDFILE" 2>/dev/null)" = "$$" ] && rm -f "$PIDFILE"' EXIT
while true; do
  if timeout 150 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
    echo "[watch $(date -u +%H:%M:%S)] chip answered; running attn-mode comparison"
    TUNE_ONLY=d1024_B64_T64_bf16,d1024_B64_T64_einsum \
      python tools/tune_transformer.py >> docs/captures/attn_mode_watch.log 2>&1
    rc=$?
    echo "[watch $(date -u +%H:%M:%S)] comparison finished (rc=$rc)"
    break
  fi
  echo "[watch $(date -u +%H:%M:%S)] probe hung/failed; retrying in 420s"
  sleep 420
done
rm -f "$PIDFILE"
