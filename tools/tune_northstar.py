"""Duty-cycle sweep for the north-star v2 loop (VERDICT r3 item 3).

Round 3 measured `northstar2_rollout_time_frac` 0.957: the chip spent 25x
more time on self-play rollouts than on SGD, so the "107k trained
steps/s" headline was mostly a rollout benchmark.  This sweeps the loop
geometry — lanes x k_steps (rollout work per call), fused_steps x
trains_per_rollout (SGD work per iteration) — through the REAL bench
stage (`bench._device_replay_northstar_bench`) and prints one row per
combo, so the knee (rollout_time_frac <= 0.5 with self-play still
outpacing or matching consumption, produce_consume_ratio >= ~0.5) can be
read off and pinned as the bench default + a BASELINE.md row.

Run ON THE CHIP (falls back to CPU with a warning — CPU ratios are not
representative, but the harness logic can be smoke-tested with
TUNE_QUICK=1).

Usage: python tools/tune_northstar.py [duration_per_combo_s]
"""

from __future__ import annotations

import itertools
import json
import os
import sys
import time

sys.path.insert(0, "/root/repo")

import bench  # noqa: E402  (repo-root import)


def main() -> None:
    import jax

    from handyrl_tpu.utils import apply_platform_override

    apply_platform_override()

    duration = float(sys.argv[1]) if len(sys.argv) > 1 else 8.0
    quick = bool(os.environ.get("TUNE_QUICK"))
    backend = jax.default_backend()
    if backend != "tpu":
        print(f"WARNING: backend is {backend}; ratios are not TPU-representative",
              file=sys.stderr)

    # geese train context once; reused across combos (same jitted step)
    bench._note("tune: building geese train context + store")
    gt = bench._train_bench(
        "HungryGeese", {"turn_based_training": False, "observation": False},
        2.0, len(jax.devices()),
        fill_episodes=12 if quick else 48,
    )

    if quick:
        combos = [(32, 16, 2, t) for t in (1, 4)]
    else:
        combos = list(itertools.product(
            (128, 256),       # n_lanes
            (16, 32),         # k_steps
            (8,),             # fused_steps
            (2, 4, 8, 16),    # trains_per_rollout
        ))
    rows = []
    for lanes, k, fused, trains in combos:
        t0 = time.perf_counter()
        try:
            r = bench._device_replay_northstar_bench(
                gt, duration, n_lanes=lanes, k_steps=k,
                fused_steps=fused, trains_per_rollout=trains,
            )
        except Exception as exc:  # keep sweeping; record the failure
            r = {"skipped": f"{type(exc).__name__}: {exc}"}
        # echo the EFFECTIVE geometry from the bench result (off-TPU the
        # stage clamps lanes/fused_steps; a knee read off requested values
        # would pin a geometry that was never measured)
        row = {"lanes": r.get("lanes", lanes), "k_steps": r.get("k_steps", k),
               "fused": r.get("fused_steps", fused),
               "trains_per_rollout": r.get("trains_per_rollout", trains),
               "wall_s": round(time.perf_counter() - t0, 1)}
        if "skipped" in r:
            row["skipped"] = r["skipped"]
        else:
            row.update(
                updates_per_sec=round(r["updates_per_sec"], 1),
                trained_steps_per_sec=round(r["trained_env_steps_per_sec"], 0),
                selfplay_steps_per_sec=round(r["selfplay_env_steps_per_sec"], 0),
                rollout_time_frac=round(r["rollout_time_frac"], 3),
                produce_consume=round(r["produce_consume_ratio"], 3)
                if r["produce_consume_ratio"] else None,
            )
        rows.append(row)
        print(json.dumps(row), flush=True)

    ok = [r for r in rows if "skipped" not in r]
    # knee: most trained steps/s among combos that keep the loop fed
    fed = [r for r in ok if r["produce_consume"] and r["produce_consume"] >= 0.5]
    if fed:
        best = max(fed, key=lambda r: r["trained_steps_per_sec"])
        print("KNEE:", json.dumps(best))
    elif ok:
        print("KNEE: none kept produce_consume >= 0.5; fastest overall:",
              json.dumps(max(ok, key=lambda r: r["trained_steps_per_sec"])))


if __name__ == "__main__":
    main()
