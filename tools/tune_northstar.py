"""Duty-cycle sweep for the north-star v2 loop (VERDICT r3 item 3) and
chip-split sweep for the v3 disaggregated planes.

Round 3 measured `northstar2_rollout_time_frac` 0.957: the chip spent 25x
more time on self-play rollouts than on SGD, so the "107k trained
steps/s" headline was mostly a rollout benchmark.  This sweeps the loop
geometry — lanes x k_steps (rollout work per call), fused_steps x
trains_per_rollout (SGD work per iteration) — through the REAL bench
stage (`bench._device_replay_northstar_bench`) and prints one row per
combo, so the knee (rollout_time_frac <= 0.5 with self-play still
outpacing or matching consumption, produce_consume_ratio >= ~0.5) can be
read off and pinned as the bench default + a BASELINE.md row.

`--split` sweeps the v3 plane instead: every actor_chips value of
`plane: split` through `bench._split_plane_northstar_bench` (plus
param_refresh_updates at the default split), so the chip allocation
where trained env-steps/s peaks with produce_consume >= 0.1 — the ratio
is a CHIP knob there, not a duty-cycle compromise — can be read off.
Needs >= 2 devices; on fewer every row reports skipped.

Run ON THE CHIP (falls back to CPU with a warning — CPU ratios are not
representative, but the harness logic can be smoke-tested with
TUNE_QUICK=1).

Usage: python tools/tune_northstar.py [--split] [duration_per_combo_s]
"""

from __future__ import annotations

import itertools
import json
import os
import sys
import time

sys.path.insert(0, "/root/repo")

import bench  # noqa: E402  (repo-root import)


def main() -> None:
    import jax

    from handyrl_tpu.utils import apply_platform_override

    apply_platform_override()

    split = "--split" in sys.argv[1:]
    argv = [a for a in sys.argv[1:] if a != "--split"]
    duration = float(argv[0]) if argv else 8.0
    quick = bool(os.environ.get("TUNE_QUICK"))
    backend = jax.default_backend()
    if backend != "tpu":
        print(f"WARNING: backend is {backend}; ratios are not TPU-representative",
              file=sys.stderr)

    # geese train context once; reused across combos (same jitted step)
    bench._note("tune: building geese train context + store")
    gt = bench._train_bench(
        "HungryGeese", {"turn_based_training": False, "observation": False},
        2.0, len(jax.devices()),
        fill_episodes=12 if quick else 48,
    )

    if split:
        _sweep_split(jax, duration, quick, gt)
        return

    if backend != "cpu":
        # the fused loop no longer host-syncs per rollout (async-dispatch
        # satellite fix), so off-CPU rollout_time_frac is the HOST enqueue
        # share, not device duty — bench main() flags the same caveat as
        # northstar2_rollout_time_frac_note; read the knee primarily off
        # produce_consume + trained_steps_per_sec there
        print("NOTE: async dispatch — rollout_time_frac is host-side "
              "enqueue share, not device duty, on this backend",
              file=sys.stderr)

    if quick:
        combos = [(32, 16, 2, t) for t in (1, 4)]
    else:
        combos = list(itertools.product(
            (128, 256),       # n_lanes
            (16, 32),         # k_steps
            (8,),             # fused_steps
            (2, 4, 8, 16),    # trains_per_rollout
        ))
    rows = []
    for lanes, k, fused, trains in combos:
        t0 = time.perf_counter()
        try:
            r = bench._device_replay_northstar_bench(
                gt, duration, n_lanes=lanes, k_steps=k,
                fused_steps=fused, trains_per_rollout=trains,
            )
        except Exception as exc:  # keep sweeping; record the failure
            r = {"skipped": f"{type(exc).__name__}: {exc}"}
        # echo the EFFECTIVE geometry from the bench result (off-TPU the
        # stage clamps lanes/fused_steps; a knee read off requested values
        # would pin a geometry that was never measured)
        row = {"lanes": r.get("lanes", lanes), "k_steps": r.get("k_steps", k),
               "fused": r.get("fused_steps", fused),
               "trains_per_rollout": r.get("trains_per_rollout", trains),
               "wall_s": round(time.perf_counter() - t0, 1)}
        if "skipped" in r:
            row["skipped"] = r["skipped"]
        else:
            row.update(
                updates_per_sec=round(r["updates_per_sec"], 1),
                trained_steps_per_sec=round(r["trained_env_steps_per_sec"], 0),
                selfplay_steps_per_sec=round(r["selfplay_env_steps_per_sec"], 0),
                rollout_time_frac=round(r["rollout_time_frac"], 3),
                produce_consume=round(r["produce_consume_ratio"], 3)
                if r["produce_consume_ratio"] else None,
            )
        rows.append(row)
        print(json.dumps(row), flush=True)

    ok = [r for r in rows if "skipped" not in r]
    # knee: most trained steps/s among combos that keep the loop fed
    fed = [r for r in ok if r["produce_consume"] and r["produce_consume"] >= 0.5]
    if fed:
        best = max(fed, key=lambda r: r["trained_steps_per_sec"])
        print("KNEE:", json.dumps(best))
    elif ok:
        print("KNEE: none kept produce_consume >= 0.5; fastest overall:",
              json.dumps(max(ok, key=lambda r: r["trained_steps_per_sec"])))


def _sweep_split(jax, duration: float, quick: bool, gt) -> None:
    """Sweep the v3 plane: actor_chips (and, at the default split, the
    param refresh cadence) through `bench._split_plane_northstar_bench`.
    One JSON row per combo; the knee is the chip split with the most
    trained env-steps/s among combos keeping produce_consume >= 0.1."""
    n = len(jax.devices())
    if n < 2:
        print(json.dumps({"skipped": f"plane sweep needs >= 2 devices, have {n}"}))
        return
    chips = [1] if quick else list(range(1, n))
    refreshes = [8] if quick else (1, 8, 32)
    combos = [(c, 8) for c in chips]
    default_split = max(1, n // 2)
    combos += [(default_split, r) for r in refreshes if r != 8]
    rows = []
    for actor_chips, refresh in combos:
        t0 = time.perf_counter()
        try:
            r = bench._split_plane_northstar_bench(
                gt, duration, actor_chips=actor_chips,
                param_refresh_updates=refresh,
            )
        except Exception as exc:  # keep sweeping; record the failure
            r = {"skipped": f"{type(exc).__name__}: {exc}"}
        row = {"actor_chips": actor_chips, "learner_chips": n - actor_chips,
               "param_refresh_updates": refresh,
               "wall_s": round(time.perf_counter() - t0, 1)}
        if "skipped" in r:
            row["skipped"] = r["skipped"]
        else:
            row.update(
                trained_steps_per_sec=round(r["trained_env_steps_per_sec"], 0),
                selfplay_steps_per_sec=round(r["selfplay_env_steps_per_sec"], 0),
                selfplay_concurrent_frac=round(r["selfplay_concurrent_frac"], 3)
                if r["selfplay_concurrent_frac"] else None,
                rollout_time_frac=round(r["rollout_time_frac"], 3),
                actor_busy_frac=round(r["actor_busy_frac"], 3),
                param_lag_mean=round(r["param_lag_mean"], 1),
                produce_consume=round(r["produce_consume_ratio"], 3)
                if r["produce_consume_ratio"] else None,
            )
        rows.append(row)
        print(json.dumps(row), flush=True)

    ok = [r for r in rows if "skipped" not in r]
    fed = [r for r in ok if r["produce_consume"] and r["produce_consume"] >= 0.1]
    if fed:
        best = max(fed, key=lambda r: r["trained_steps_per_sec"])
        print("KNEE:", json.dumps(best))
    elif ok:
        print("KNEE: none kept produce_consume >= 0.1; fastest overall:",
              json.dumps(max(ok, key=lambda r: r["trained_steps_per_sec"])))


if __name__ == "__main__":
    main()
