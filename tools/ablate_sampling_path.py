"""Quantify the device-replay sampling deviations (VERDICT r4 #7).

``runtime/device_replay.py`` deliberately deviates from the host replay's
sampling in two ways (documented in its module docstring): recency bias
comes from ring capacity instead of the reference's per-episode
acceptance curve (reference train.py:292-303), and window starts are
uniform over eligible STEPS (weighting long episodes by window count)
instead of uniform over episodes.  The soaks prove the device path
learns; this tool measures the COST of the deviation: same-budget
`--train` runs through the real product stack — host-path sampling vs
device-ring sampling — on ParallelTicTacToe and HungryGeese, comparing the
win-rate-vs-updates curves from each run's metrics.jsonl.

Both runs of a pair share every train_arg except the data path
(`device_rollout_games` + `device_replay`); equal budget = equal
`epochs` (model updates) at equal `update_episodes`.  Output:
docs/captures/sampling_path_ablation_<stamp>.json with both curves and
the late-mean delta, which device_replay.py's docstring quotes.
"""

from __future__ import annotations

import datetime
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

BASE = {
    "turn_based_training": False,
    "observation": False,
    "gamma": 0.8,
    "forward_steps": 8,
    "burn_in_steps": 0,
    "compress_steps": 4,
    "update_episodes": 100,
    "batch_size": 64,
    "minimum_episodes": 200,
    "maximum_episodes": 20000,
    "eval_rate": 0.15,
    "worker": {"num_parallel": 4},
    "lambda": 0.7,
    "policy_target": "UPGO",
    "value_target": "TD",
    "eval": {"opponent": ["random"]},
    "seed": 0,
}

# ParallelTicTacToe stands in for TicTacToe on the device side: the
# device ring needs a STREAMING vector twin (reset_done/step), and
# TicTacToe's twin is episodic — DeviceReplay rejects it at
# construction.  ParallelTicTacToe is the tictactoe-family env with the
# streaming twin + view_obs hook, so the pair isolates exactly the
# sampling-path difference the VERDICT asks about.
PAIRS = {
    "ParallelTicTacToe": {"epochs": 60},
    "HungryGeese": {"epochs": 20},
}


def run_one(env_name: str, device_path: bool, epochs: int, run_root: str,
            timeout_s: float) -> dict:
    import yaml

    tag = "device" if device_path else "host"
    run_dir = os.path.join(run_root, f"{env_name.lower()}_{tag}")
    os.makedirs(run_dir, exist_ok=True)
    train_args = {**BASE, "epochs": epochs}
    if device_path:
        train_args.update(
            {"device_rollout_games": 32, "device_replay": True,
             "device_replay_slots": 256, "device_replay_k_steps": 32,
             # device-replay runs generate nothing on the host, so the
             # win-rate books need the on-device evaluator to fill
             # metrics.jsonl win_rate records
             "device_eval_games": 64}
        )
    with open(os.path.join(run_dir, "config.yaml"), "w") as f:
        yaml.safe_dump(
            {"env_args": {"env": env_name}, "train_args": train_args,
             "worker_args": {"server_address": "", "num_parallel": 4}}, f
        )
    env = dict(os.environ, HANDYRL_PLATFORM="cpu")
    t0 = time.perf_counter()
    with open(os.path.join(run_dir, "train.log"), "w") as log:
        rc = subprocess.run(
            [sys.executable, os.path.join(REPO, "main.py"), "--train"],
            cwd=run_dir, env=env, stdout=log, stderr=subprocess.STDOUT,
            timeout=timeout_s,
        ).returncode
    if rc != 0:
        raise SystemExit(f"{env_name}/{tag} train failed rc={rc}; "
                         f"see {run_dir}/train.log")
    from handyrl_tpu.utils.metrics import read_metrics

    curve = []
    # read_metrics tolerates a truncated tail; win_rate can be an explicit
    # null on epochs with no eval results
    for rec in read_metrics(os.path.join(run_dir, "metrics.jsonl")):
        wr = (rec.get("win_rate") or {}).get("total")
        if wr is not None:
            curve.append({"epoch": rec["epoch"], "win_rate": round(wr, 4)})
    late = [c["win_rate"] for c in curve if c["epoch"] >= epochs * 2 // 3]
    return {
        "path": tag,
        "epochs": epochs,
        "wall_s": round(time.perf_counter() - t0, 1),
        "curve": curve,
        "late_mean_win_rate": round(sum(late) / max(len(late), 1), 4),
    }


def main() -> None:
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--envs", default="ParallelTicTacToe,HungryGeese")
    ap.add_argument("--train-timeout", type=float, default=5400.0)
    ap.add_argument("--run-root",
                    default=os.path.join(REPO, "sampling_ablation_run"))
    a = ap.parse_args()

    out = {
        "date_utc": datetime.datetime.now(datetime.timezone.utc).isoformat(
            timespec="seconds"
        ),
        "pairs": [],
    }
    for env_name in a.envs.split(","):
        epochs = PAIRS[env_name]["epochs"]
        pair = {"env": env_name}
        for device_path in (False, True):
            tag = "device" if device_path else "host"
            print(f"[ablate-sampling] {env_name} {tag} path, "
                  f"{epochs} epochs...", file=sys.stderr, flush=True)
            pair[tag] = run_one(env_name, device_path, epochs, a.run_root,
                                a.train_timeout)
            print(f"[ablate-sampling]   late-mean win rate "
                  f"{pair[tag]['late_mean_win_rate']}", file=sys.stderr,
                  flush=True)
        pair["delta_late_mean"] = round(
            pair["device"]["late_mean_win_rate"]
            - pair["host"]["late_mean_win_rate"], 4
        )
        out["pairs"].append(pair)

    print(json.dumps(out, indent=2))
    stamp = datetime.datetime.now(datetime.timezone.utc).strftime("%Y-%m-%d_%H%M")
    dest = os.path.join(REPO, "docs", "captures",
                        f"sampling_path_ablation_{stamp}.json")
    with open(dest, "w") as f:
        json.dump(out, f, indent=2)
    print(f"[ablate-sampling] wrote {dest}", file=sys.stderr, flush=True)


if __name__ == "__main__":
    main()
