"""Geister learning soak on the real chip through the turn-based/recurrent
device-resident replay (runtime/device_replay.py turn mode).

The committed CPU soak (tests/test_soak.py::test_geister_drc_beats_random)
drives the HOST actor path (thread workers, host replay) and is sized for a
1-core CI host.  This driver is the chip-side complement: GeisterNet's DRC
ConvLSTM trained ONLY by streaming device self-play — records ingested into
device rings, burn-in windows sampled and stepped on device (UPGO targets,
burn-in 4) — then verified with a matched offline eval, trained vs the SAME
net untrained, each over seat-balanced games vs random.

Run (background, clean exit — never kill a process holding the axon lease):

    cd /root/repo && nohup python tools/soak_geister_tpu.py train \
        > docs/captures/soak_geister_tpu.log 2>&1 &

Margin: Geister outcomes are {-1, 0, +1} (win/draw/loss, geister.py
outcome); per-game std <= 1, so each 240-game mean outcome has
se <= 0.065 and the matched difference se <= 0.092 — a +0.20 margin keeps
the no-learning false-pass rate under ~2%.  The verdict drives the exit
code (tools/_soak_tpu_common.py).

Result 2026-07-31 (TPU v5 lite x1): wp 0.519 -> 0.694, mean outcome
+0.037 -> +0.388 — 15,740 DRC updates / 45,300 episodes in ~10 min.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools._soak_tpu_common import run  # noqa: E402

RUN_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                       "soak_geister_tpu_run")

CFG = {
    "env_args": {"env": "Geister"},
    "train_args": {
        "turn_based_training": True,
        "observation": True,
        "batch_size": 16,
        "forward_steps": 8,
        "burn_in_steps": 4,
        "policy_target": "UPGO",
        "value_target": "UPGO",
        # near-parity schedule: the chip delivers tens of thousands of
        # updates, so the CPU soak's 16x boost is not needed; 1e-2 entropy
        # bonus for the same reason as the committed soak (1e-1 pins a
        # self-play run at the uniform policy)
        "lr_scale": 2.0,
        "entropy_regularization": 1.0e-2,
        "minimum_episodes": 300,
        "update_episodes": 300,
        "maximum_episodes": 8000,
        "epochs": 150,
        "num_batchers": 1,
        "eval_rate": 0.0,          # workers are eval-only under device_replay
        "device_rollout_games": 64,
        "device_replay": True,
        "device_replay_slots": 512,   # > max episode length 202 + window
        "device_replay_k_steps": 32,
        # dense per-epoch curve vs device random (Geister has no rule-based
        # device twin); the host worker's curve starved on the first capture
        "device_eval_games": 32,
        "fused_steps": 4,
        "mesh": {"dp": 1},
        "worker": {"num_parallel": 1},
        "eval": {"opponent": ["random"]},
    },
}

if __name__ == "__main__":
    run(sys.argv, os.path.abspath(__file__), CFG, RUN_DIR,
        opponent="random", margin=0.20, wp_bar=0.55)
