"""perfgate: the perf-regression CI gate over bench snapshots (ROADMAP #6).

Judges a ``bench_snapshot.json`` against a banked capture (``BENCH_*.json``)
the way graftlint judges invariants: mechanically, with an explicit
sensitivity class per metric and a content-addressed baseline for
burn-down.  The class system encodes BASELINE.md's measured lesson — the
round-5 capture moved ABSOLUTE single-dispatch rates 0.6x on identical
code (tunnel RTT that day), while same-session internal ratios stayed
put — so:

* **hard** class: ratio-of-internal-baseline metrics (``*_frac``,
  ``*_ratio``, ``*_coverage``, ``speedup``, ``*_dropped``) and
  categorical pins (``*_target_met``, ``*_mode``, ``*_attn``).  These
  compare two measurements from the SAME session, so RTT/lease variance
  divides out; a move past ``--hard-tol`` is a code regression and FAILS
  the gate.
* **soft** class: absolute throughput/latency (``*_per_sec``, ``*_qps``,
  ``*_mfu``, ``*_ms``, ``*_vs_*``).  Session variance is real here; only
  a move past ``--soft-tol`` (default 2x) is even reported as a
  regression, and soft regressions never fail the gate on their own.
* **info**: everything else (counts, run lengths, shapes) — reported,
  never gated.

A banked hard/exact metric MISSING from the current snapshot also fails
in enforcing mode (a crashed stage's numbers simply vanish — the exact
regression class a perf gate exists to catch); ``--allow-missing`` is
the explicit escape for a deliberate ``BENCH_STAGES`` subset.

Cross-platform comparisons (a CPU smoke vs a TPU capture) are forced to
ADVISORY: the report still prints, the exit code stays 0.  ``--advisory``
forces the same for same-platform runs — the CI mode until BENCH_r06 is
banked (docs/observability.md documents the flip to enforcing).

Baseline burn-down (graftlint discipline): ``--baseline FILE`` suppresses
grandfathered regression fingerprints and reports stale entries;
``--write-baseline`` banks the current regressions.  Fingerprints are
content-addressed (metric + class + direction), immune to report-order
drift.

Usage::

    python -m tools.perfgate bench_snapshot.json --against BENCH_r05.json
    python -m tools.perfgate bench_snapshot.json --against BENCH_r05.json \
        --advisory --baseline tools/PERFGATE_BASELINE.json

Exit codes: 0 = clean (or advisory), 1 = hard-class regression
(enforcing mode), 2 = usage / unreadable snapshot.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import sys
from typing import Any, Dict, List, Optional, Tuple

HARD_SUFFIXES = ("_frac", "_ratio", "_coverage", "speedup", "_dropped")
SOFT_SUFFIXES = ("_per_sec", "_qps", "_mfu", "_ms")
EXACT_SUFFIXES = ("_target_met", "_mode", "_attn")
# numeric metrics where SMALLER is better (everything else: bigger)
LOWER_BETTER_MARKERS = (
    "input_wait_frac", "rollout_time_frac", "shed_rate", "deadline_miss",
    "_dropped", "_p50_ms", "_p99_ms", "warm_ms", "_ttfr_ms",
)


def classify(key: str, value: Any) -> Tuple[str, int]:
    """(class, direction) for one metric: class in hard/soft/exact/info,
    direction +1 bigger-is-better / -1 smaller-is-better (0 for exact)."""
    if isinstance(value, bool):
        return "exact", 0
    if isinstance(value, str):
        return ("exact", 0) if key.endswith(EXACT_SUFFIXES) else ("info", 0)
    if not isinstance(value, (int, float)) or value is None:
        return "info", 0
    direction = -1 if any(m in key for m in LOWER_BETTER_MARKERS) else 1
    if key.endswith(HARD_SUFFIXES):
        return "hard", direction
    if key.endswith(SOFT_SUFFIXES) or "_vs_" in key or key.endswith("_vs_baseline"):
        return "soft", direction
    return "info", direction


def fingerprint(key: str, cls: str, direction: int) -> str:
    digest = hashlib.sha1(f"{key}:{cls}:{direction}".encode()).hexdigest()[:12]
    return f"PERF:{key}:{digest}"


# -- snapshot loading ---------------------------------------------------------


def _flatten(record: Dict[str, Any]) -> Tuple[Dict[str, Any], Optional[str]]:
    """bench snapshot record -> ({metric: value}, platform)."""
    out: Dict[str, Any] = {}
    if record.get("metric") and record.get("value") is not None:
        out[str(record["metric"])] = record["value"]
    for key, value in (record.get("extra") or {}).items():
        if isinstance(value, dict):
            for k2, v2 in value.items():
                out[f"{key}_{k2}"] = v2
        elif isinstance(value, list):
            continue  # stages_skipped etc. — not metrics
        else:
            out[key] = value
    return out, record.get("platform")


def load_snapshot(path: str) -> Tuple[Dict[str, Any], Optional[str]]:
    """Load metrics from a bench_snapshot.json, a banked ``BENCH_*.json``
    capture ({n, cmd, rc, tail}: the newest parseable snapshot line in the
    tail wins), or a plain flat {metric: value} dict (tests)."""
    with open(path) as f:
        data = json.load(f)
    if not isinstance(data, dict):
        raise ValueError(f"{path}: not a JSON object")
    if "tail" in data and "cmd" in data:
        tail = str(data.get("tail") or "")
        for line in reversed(tail.splitlines()):
            idx = line.find('{"metric"')
            if idx >= 0:
                try:
                    return _flatten(json.loads(line[idx:]))
                except ValueError:
                    pass
            # the tail window often starts MID-record (it is the last N
            # bytes of stdout, and one snapshot line is the whole record):
            # recover the intact suffix — the "extra" object carries every
            # stage metric, and platform rides a scalar field before it
            idx = line.find('"extra": {')
            if idx >= 0:
                try:
                    extra, _ = json.JSONDecoder().raw_decode(
                        line[idx + len('"extra": '):]
                    )
                except ValueError:
                    continue
                import re

                m = re.search(r'"platform":\s*"([^"]*)"', line)
                return _flatten({
                    "extra": extra,
                    "platform": m.group(1) if m else None,
                })
        raise ValueError(
            f"{path}: banked capture holds no parseable snapshot line "
            "(the bench emits one full JSON record per stage)"
        )
    if "metric" in data or "extra" in data:
        return _flatten(data)
    platform = data.pop("platform", None)
    return data, platform


# -- judgment -----------------------------------------------------------------


class Verdict:
    __slots__ = ("key", "cls", "direction", "base", "cur", "status", "note")

    def __init__(self, key, cls, direction, base, cur, status, note=""):
        self.key, self.cls, self.direction = key, cls, direction
        self.base, self.cur, self.status, self.note = base, cur, status, note

    @property
    def fingerprint(self) -> str:
        return fingerprint(self.key, self.cls, self.direction)

    def format(self) -> str:
        tag = {"hard": "HARD", "soft": "soft", "exact": "PIN ",
               "info": "info"}[self.cls]
        return f"  {tag}  {self.key}: {self.base!r} -> {self.cur!r} {self.note}"


def judge(baseline: Dict[str, Any], current: Dict[str, Any],
          hard_tol: float, soft_tol: float) -> List[Verdict]:
    """Compare every baseline metric against the current snapshot."""
    verdicts: List[Verdict] = []
    for key in sorted(baseline):
        base = baseline[key]
        cls, direction = classify(key, base)
        if key not in current:
            verdicts.append(Verdict(key, cls, direction, base, None, "missing",
                                    "(not in current snapshot)"))
            continue
        cur = current[key]
        if cls == "info":
            verdicts.append(Verdict(key, cls, direction, base, cur, "info"))
            continue
        if cls == "exact":
            if isinstance(base, bool):
                # True -> False is the regression; False -> True is progress
                bad = bool(base) and not bool(cur)
            else:
                bad = base != cur
            verdicts.append(Verdict(
                key, cls, direction, base, cur,
                "regressed" if bad else "ok",
                "(pinned value moved)" if bad else "",
            ))
            continue
        try:
            base_f, cur_f = float(base), float(cur)
        except (TypeError, ValueError):
            verdicts.append(Verdict(key, cls, direction, base, cur, "info",
                                    "(non-numeric)"))
            continue
        tol = hard_tol if cls == "hard" else soft_tol
        if base_f == 0.0:
            # no ratio exists: a lower-is-better zero (dropped requests)
            # regressing to nonzero is real; a higher-is-better zero is
            # uninformative
            if direction < 0 and cur_f > 0:
                verdicts.append(Verdict(key, cls, direction, base, cur,
                                        "regressed", "(was 0)"))
            else:
                verdicts.append(Verdict(key, cls, direction, base, cur, "ok"))
            continue
        ratio = cur_f / base_f
        if direction > 0:
            regressed, improved = ratio < 1.0 - tol, ratio > 1.0 + tol
        else:
            regressed, improved = ratio > 1.0 + tol, ratio < 1.0 - tol
        status = "regressed" if regressed else "improved" if improved else "ok"
        verdicts.append(Verdict(key, cls, direction, base, cur, status,
                                f"({ratio:.2f}x, tol {tol:.2f})"))
    return verdicts


# -- baseline (graftlint-style burn-down) -------------------------------------


def load_baseline(path: str) -> set:
    with open(path) as f:
        data = json.load(f)
    if not isinstance(data, dict) or "findings" not in data:
        raise ValueError(f"{path}: not a perfgate baseline (missing 'findings')")
    return {fp for fps in data["findings"].values() for fp in fps}


def write_baseline(path: str, regressions: List[Verdict]) -> None:
    payload = {
        "version": 1,
        "findings": {"PERFGATE": sorted(v.fingerprint for v in regressions)},
    }
    with open(path, "w") as f:
        json.dump(payload, f, indent=2)
        f.write("\n")


# -- CLI ----------------------------------------------------------------------


def run(current_path: str, against_path: str, advisory: bool = False,
        hard_tol: float = 0.10, soft_tol: float = 0.50,
        baseline_path: Optional[str] = None, write_baseline_path: Optional[str] = None,
        force_platform: bool = False, allow_missing: bool = False,
        out=sys.stdout) -> int:
    try:
        current, cur_platform = load_snapshot(current_path)
        banked, base_platform = load_snapshot(against_path)
    except (OSError, ValueError) as exc:
        print(f"perfgate: cannot load snapshots: {exc}", file=sys.stderr)
        return 2
    platform_mismatch = (
        cur_platform and base_platform and cur_platform != base_platform
    )
    if platform_mismatch and not force_platform:
        advisory = True
    verdicts = judge(banked, current, hard_tol, soft_tol)

    suppressed: List[Verdict] = []
    stale: set = set()
    if baseline_path:
        try:
            grandfathered = load_baseline(baseline_path)
        except (OSError, ValueError) as exc:
            print(f"perfgate: bad baseline: {exc}", file=sys.stderr)
            return 2
        kept = []
        seen = set()
        for v in verdicts:
            if v.status == "regressed" and v.fingerprint in grandfathered:
                suppressed.append(v)
                seen.add(v.fingerprint)
            else:
                kept.append(v)
        stale = grandfathered - seen
        verdicts = kept

    regressions = [v for v in verdicts if v.status == "regressed"]
    hard = [v for v in regressions if v.cls in ("hard", "exact")]
    soft = [v for v in regressions if v.cls == "soft"]
    improved = [v for v in verdicts if v.status == "improved"]
    missing = [v for v in verdicts if v.status == "missing"]
    # a stage that crashes or stops emitting numbers is the regression
    # class this gate exists to catch — its banked hard/exact metrics
    # simply VANISH from the current snapshot, so in enforcing mode a
    # missing hard-class metric fails like a regressed one (stage subsets
    # pass --allow-missing explicitly)
    missing_hard = [
        v for v in missing
        if v.cls in ("hard", "exact") and not allow_missing
    ]

    print(
        f"perfgate: {current_path} ({cur_platform or '?'}) judged against "
        f"{against_path} ({base_platform or '?'})"
        + (" [ADVISORY: platform mismatch]" if platform_mismatch else
           " [ADVISORY]" if advisory else ""),
        file=out,
    )
    for v in regressions:
        print(v.format() + "  REGRESSED", file=out)
    for v in improved:
        print(v.format() + "  improved", file=out)
    if missing_hard and not advisory:
        for v in missing_hard:
            print(f"  MISS  {v.key} ({v.cls}): banked but absent from the "
                  "current snapshot — a vanished stage fails the gate "
                  "(pass --allow-missing for a deliberate stage subset)",
                  file=out)
    if missing:
        print(f"  ({len(missing)} banked metric(s) absent from the current "
              "snapshot — stage subset or skipped stages)", file=out)
    for v in suppressed:
        print(v.format() + "  suppressed (baselined — burn down)", file=out)
    for fp in sorted(stale):
        print(f"  stale baseline entry {fp} (matches nothing — delete it)",
              file=out)
    print(
        f"perfgate: {len(hard)} hard / {len(soft)} soft regression(s), "
        f"{len(improved)} improved, {len(missing)} missing, "
        f"{len(suppressed)} suppressed",
        file=out,
    )

    if write_baseline_path:
        write_baseline(write_baseline_path, regressions)
        print(f"perfgate: wrote baseline {write_baseline_path} "
              f"({len(regressions)} fingerprint(s))", file=out)

    if (hard or missing_hard) and not advisory:
        print(
            "perfgate: FAIL ("
            + ("hard-class regression" if hard else "hard-class metric missing")
            + ")",
            file=out,
        )
        return 1
    print("perfgate: " + ("ADVISORY" if advisory and (hard or soft) else "PASS"),
          file=out)
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.perfgate",
        description="perf-regression gate over bench snapshots",
    )
    ap.add_argument("current", help="bench_snapshot.json (or banked capture)")
    ap.add_argument("--against", required=True,
                    help="banked capture to judge against (BENCH_*.json)")
    ap.add_argument("--advisory", action="store_true",
                    help="report but never fail (CI mode until the next "
                    "same-platform capture is banked)")
    ap.add_argument("--hard-tol", type=float, default=0.10,
                    help="hard-class relative tolerance (default 0.10)")
    ap.add_argument("--soft-tol", type=float, default=0.50,
                    help="soft-class relative tolerance (default 0.50)")
    ap.add_argument("--baseline", default=None,
                    help="grandfathered-regression baseline JSON (burn-down)")
    ap.add_argument("--write-baseline", default=None,
                    help="bank the current regressions as the baseline")
    ap.add_argument("--force-platform", action="store_true",
                    help="gate even across differing platform strings")
    ap.add_argument("--allow-missing", action="store_true",
                    help="deliberate stage subset: banked hard-class "
                    "metrics absent from the current snapshot do not fail")
    args = ap.parse_args(argv)
    return run(
        args.current, args.against, advisory=args.advisory,
        hard_tol=args.hard_tol, soft_tol=args.soft_tol,
        baseline_path=args.baseline, write_baseline_path=args.write_baseline,
        force_platform=args.force_platform, allow_missing=args.allow_missing,
    )


if __name__ == "__main__":
    sys.exit(main())
