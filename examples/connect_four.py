"""Connect Four — a complete custom environment outside the built-in
registry, loaded by dotted path (docs/custom_environment.md):

    env_args:
      env: 'examples.connect_four'

Demonstrates the user extension contract end-to-end: the 17-method game
interface (reference environment.py:41-145), delta-sync for network
battle mode, a rule-based opponent, and a bespoke net hookup — everything
a framework user writes for their own game.

Run a random self-play smoke loop (like the built-in envs):

    python -m examples.connect_four
"""

from __future__ import annotations

import random
from typing import Dict, List

import numpy as np

from handyrl_tpu.envs.base import BaseEnvironment

ROWS, COLS = 6, 7
CONNECT = 4


class Environment(BaseEnvironment):
    """Two-player gravity-drop four-in-a-row on a 6x7 board."""

    def __init__(self, args=None):
        super().__init__(args)
        self.reset()

    # -- core state ---------------------------------------------------------

    def reset(self, args=None):
        self.board = np.zeros((ROWS, COLS), np.int8)  # 0 empty, 1 / -1 stones
        self.color = 1
        self.win_color = 0
        self.moves: List[int] = []
        return None

    def play(self, action, player=None):
        col = int(action)
        row = int(np.count_nonzero(self.board[:, col] == 0)) - 1
        self.board[row, col] = self.color
        self.moves.append(col)
        if self._wins(row, col):
            self.win_color = self.color
        self.color = -self.color
        return None

    def _wins(self, row: int, col: int) -> bool:
        c = self.board[row, col]
        for dr, dc in ((0, 1), (1, 0), (1, 1), (1, -1)):
            run = 1
            for sgn in (1, -1):
                r, q = row + sgn * dr, col + sgn * dc
                while 0 <= r < ROWS and 0 <= q < COLS and self.board[r, q] == c:
                    run += 1
                    r += sgn * dr
                    q += sgn * dc
            if run >= CONNECT:
                return True
        return False

    def terminal(self) -> bool:
        return self.win_color != 0 or len(self.moves) == ROWS * COLS

    def outcome(self) -> Dict[int, float]:
        if self.win_color == 0:
            return {0: 0.0, 1: 0.0}
        winner = 0 if self.win_color == 1 else 1
        return {winner: 1.0, 1 - winner: -1.0}

    # -- interface ----------------------------------------------------------

    def players(self) -> List[int]:
        return [0, 1]

    def turn(self) -> int:
        return 0 if self.color == 1 else 1

    def legal_actions(self, player=None) -> List[int]:
        return [c for c in range(COLS) if self.board[0, c] == 0]

    def action2str(self, action, player=None) -> str:
        return str(int(action) + 1)

    def str2action(self, s, player=None) -> int:
        return int(s) - 1

    def observation(self, player=None):
        """(3, 6, 7) planes: own stones, opponent stones, side-to-move.

        ``player=None`` means the turn player's view (framework
        convention, e.g. envs/tictactoe.py)."""
        if player is None:
            player = self.turn()
        mine = 1 if player == 0 else -1
        return np.stack(
            [
                (self.board == mine).astype(np.float32),
                (self.board == -mine).astype(np.float32),
                np.full((ROWS, COLS), float(self.color == mine), np.float32),
            ]
        )

    def rule_based_action(self, player=None, key=None) -> int:
        """Win in one if possible, else block, else random."""
        legal = self.legal_actions()
        for want in (self.color, -self.color):
            for col in legal:
                row = int(np.count_nonzero(self.board[:, col] == 0)) - 1
                self.board[row, col] = want
                won = self._wins(row, col)
                self.board[row, col] = 0
                if won:
                    return col
        return random.choice(legal)

    # -- network battle mode (delta sync) ------------------------------------

    def diff_info(self, player=None):
        return self.moves[-1] if self.moves else None

    def update(self, info, reset: bool):
        if reset:
            self.reset()
        if info is not None:
            self.play(info)

    # -- model hookup ---------------------------------------------------------

    def action_size(self) -> int:
        return COLS

    def default_net(self):
        from handyrl_tpu.models import SimpleConvNet

        return SimpleConvNet(filters=48, blocks=4, num_actions=COLS)

    def __str__(self) -> str:
        rows = ["".join(".XO"[v] for v in row) for row in self.board]
        return "\n".join(rows)


if __name__ == "__main__":
    env = Environment()
    for _ in range(3):
        env.reset()
        while not env.terminal():
            env.play(random.choice(env.legal_actions()))
        print(env)
        print(env.outcome())
