"""Connect Four — a complete custom environment, registered in the env
zoo as ``env: ConnectFour`` (envs/__init__.py) and also loadable by
dotted path (docs/custom_environment.md):

    env_args:
      env: 'ConnectFour'            # or 'examples.connect_four'

Demonstrates the user extension contract end-to-end: the 17-method game
interface (reference environment.py:41-145), delta-sync for network
battle mode, a rule-based opponent, a bespoke net hookup, AND the
**twin-less device path**: instead of a hand-written ``vector_*`` twin,
the game rules are written ONCE as pure single-game numpy functions
(``ConnectFourRules``) and ``envs/autovec.py`` lifts them into the
batched jnp vector env that unlocks fully on-device self-play
(``device_rollout_games``) and league training.  Step-parity of the lift
is asserted by tests/test_autovec.py, and rules parity with the host env
by tests/test_device_rollout.py.

Run a random self-play smoke loop (like the built-in envs):

    python -m examples.connect_four
"""

from __future__ import annotations

import random
from typing import Dict, List

import numpy as np

from handyrl_tpu.envs.base import BaseEnvironment

ROWS, COLS = 6, 7
CONNECT = 4


class Environment(BaseEnvironment):
    """Two-player gravity-drop four-in-a-row on a 6x7 board."""

    def __init__(self, args=None):
        super().__init__(args)
        self.reset()

    # -- core state ---------------------------------------------------------

    def reset(self, args=None):
        self.board = np.zeros((ROWS, COLS), np.int8)  # 0 empty, 1 / -1 stones
        self.color = 1
        self.win_color = 0
        self.moves: List[int] = []
        return None

    def play(self, action, player=None):
        col = int(action)
        row = int(np.count_nonzero(self.board[:, col] == 0)) - 1
        self.board[row, col] = self.color
        self.moves.append(col)
        if self._wins(row, col):
            self.win_color = self.color
        self.color = -self.color
        return None

    def _wins(self, row: int, col: int) -> bool:
        c = self.board[row, col]
        for dr, dc in ((0, 1), (1, 0), (1, 1), (1, -1)):
            run = 1
            for sgn in (1, -1):
                r, q = row + sgn * dr, col + sgn * dc
                while 0 <= r < ROWS and 0 <= q < COLS and self.board[r, q] == c:
                    run += 1
                    r += sgn * dr
                    q += sgn * dc
            if run >= CONNECT:
                return True
        return False

    def terminal(self) -> bool:
        return self.win_color != 0 or len(self.moves) == ROWS * COLS

    def outcome(self) -> Dict[int, float]:
        if self.win_color == 0:
            return {0: 0.0, 1: 0.0}
        winner = 0 if self.win_color == 1 else 1
        return {winner: 1.0, 1 - winner: -1.0}

    # -- interface ----------------------------------------------------------

    def players(self) -> List[int]:
        return [0, 1]

    def turn(self) -> int:
        return 0 if self.color == 1 else 1

    def legal_actions(self, player=None) -> List[int]:
        return [c for c in range(COLS) if self.board[0, c] == 0]

    def action2str(self, action, player=None) -> str:
        return str(int(action) + 1)

    def str2action(self, s, player=None) -> int:
        return int(s) - 1

    def observation(self, player=None):
        """(3, 6, 7) planes: own stones, opponent stones, side-to-move.

        ``player=None`` means the turn player's view (framework
        convention, e.g. envs/tictactoe.py)."""
        if player is None:
            player = self.turn()
        mine = 1 if player == 0 else -1
        return np.stack(
            [
                (self.board == mine).astype(np.float32),
                (self.board == -mine).astype(np.float32),
                np.full((ROWS, COLS), float(self.color == mine), np.float32),
            ]
        )

    def rule_based_action(self, player=None, key=None) -> int:
        """Win in one if possible, else block, else random."""
        legal = self.legal_actions()
        for want in (self.color, -self.color):
            for col in legal:
                row = int(np.count_nonzero(self.board[:, col] == 0)) - 1
                self.board[row, col] = want
                won = self._wins(row, col)
                self.board[row, col] = 0
                if won:
                    return col
        return random.choice(legal)

    # -- network battle mode (delta sync) ------------------------------------

    def diff_info(self, player=None):
        return self.moves[-1] if self.moves else None

    def update(self, info, reset: bool):
        if reset:
            self.reset()
        if info is not None:
            self.play(info)

    # -- model hookup ---------------------------------------------------------

    def action_size(self) -> int:
        return COLS

    def default_net(self):
        from handyrl_tpu.models import SimpleConvNet

        return SimpleConvNet(filters=48, blocks=4, num_actions=COLS)

    @staticmethod
    def vector_env():
        """Device twin for on-device self-play (``device_rollout_games``)
        — autovectorized from ``ConnectFourRules``, no hand-written
        ``vector_connect_four`` (envs/autovec.py; lifts are memoized)."""
        from handyrl_tpu.envs.autovec import autovectorize

        return autovectorize(ConnectFourRules)

    def __str__(self) -> str:
        rows = ["".join(".XO"[v] for v in row) for row in self.board]
        return "\n".join(rows)


class ConnectFourRules:
    """Pure single-game numpy rules — the autovec source of truth.

    Same rules as ``Environment`` (pinned by tests), written to the
    autovec liftability contract (envs/autovec.py): pure functions,
    out-of-place array updates, no value-dependent python control flow,
    fixed shapes/dtypes.  Strict turn alternation makes the step index a
    static python int, so turn math is ordinary python.

    State (one game): ``board`` (6, 7) int8 (0 empty / +1 first player /
    -1 second), ``winner`` () int8 (0 none / +-1).
    """

    num_actions = COLS
    max_steps = ROWS * COLS
    num_players = 2

    @staticmethod
    def _color(step: int) -> int:
        return 1 if step % 2 == 0 else -1

    @staticmethod
    def init():
        return {
            "board": np.zeros((ROWS, COLS), np.int8),
            "winner": np.zeros((), np.int8),
        }

    @staticmethod
    def observation(state, step: int):
        """(3, 6, 7) turn-player planes, identical to the host
        ``observation()`` at acting time: own stones, opponent stones,
        side-to-move (always mine when acting)."""
        me = ConnectFourRules._color(step)
        board = state["board"]
        return np.stack(
            [
                (board == me).astype(np.float32),
                (board == -me).astype(np.float32),
                np.ones((ROWS, COLS), np.float32),
            ]
        )

    @staticmethod
    def legal_mask(state):
        """(7,) bool — columns whose top cell is empty."""
        return state["board"][0, :] == 0

    @staticmethod
    def terminal(state, step: int):
        return (state["winner"] != 0) | (step >= ROWS * COLS)

    @staticmethod
    def _connects(stones):
        """Any 4-in-a-row in a (6, 7) bool plane, as sums of four shifted
        slices per direction (static shapes, no loops)."""
        s = stones.astype(np.int8)
        h = s[:, :-3] + s[:, 1:-2] + s[:, 2:-1] + s[:, 3:]
        v = s[:-3, :] + s[1:-2, :] + s[2:-1, :] + s[3:, :]
        d = s[:-3, :-3] + s[1:-2, 1:-2] + s[2:-1, 2:-1] + s[3:, 3:]
        u = s[3:, :-3] + s[2:-1, 1:-2] + s[1:-2, 2:-1] + s[:-3, 3:]
        return (
            (h == CONNECT).any()
            | (v == CONNECT).any()
            | (d == CONNECT).any()
            | (u == CONNECT).any()
        )

    @staticmethod
    def apply(state, action, step: int):
        """Gravity-drop ``action`` for the step's color.  Called on live
        games only (the autovec totality wrapper discards its output for
        finished lanes); a full column — illegal, excluded by legal_mask
        — gives row -1, which the equality masks below match NOWHERE, so
        the drop is a safe no-op (do NOT rewrite this as integer indexing
        ``board[row, action]``: -1 would then really wrap to the bottom
        row on the host-numpy execution path)."""
        me = ConnectFourRules._color(step)
        board = state["board"]
        empties = (board == 0).sum(axis=0)                    # (7,)
        row = empties[action] - 1
        cell = (np.arange(ROWS)[:, None] == row) & (
            np.arange(COLS)[None, :] == action
        )
        board = np.where(cell, np.int8(me), board)
        won = ConnectFourRules._connects(board == me)
        winner = np.where(won, np.int8(me), state["winner"]).astype(np.int8)
        return {"board": board, "winner": winner}

    @staticmethod
    def outcome(state):
        """(2,) float32 per-player scores, host ``outcome()`` order."""
        w = state["winner"].astype(np.float32)
        return np.stack([w, -w])


if __name__ == "__main__":
    env = Environment()
    for _ in range(3):
        env.reset()
        while not env.terminal():
            env.play(random.choice(env.legal_actions()))
        print(env)
        print(env.outcome())
