"""Connect Four — a complete custom environment outside the built-in
registry, loaded by dotted path (docs/custom_environment.md):

    env_args:
      env: 'examples.connect_four'

Demonstrates the user extension contract end-to-end: the 17-method game
interface (reference environment.py:41-145), delta-sync for network
battle mode, a rule-based opponent, a bespoke net hookup, AND a device
twin (``VectorConnectFour`` below) — the worked example of writing the
batched pure-jnp rules that unlock fully on-device self-play
(``device_rollout_games``) for a custom game.  Lock-step rules parity
with the host env is asserted by tests/test_device_rollout.py.

Run a random self-play smoke loop (like the built-in envs):

    python -m examples.connect_four
"""

from __future__ import annotations

import random
from typing import Dict, List

import numpy as np

from handyrl_tpu.envs.base import BaseEnvironment

ROWS, COLS = 6, 7
CONNECT = 4


class Environment(BaseEnvironment):
    """Two-player gravity-drop four-in-a-row on a 6x7 board."""

    def __init__(self, args=None):
        super().__init__(args)
        self.reset()

    # -- core state ---------------------------------------------------------

    def reset(self, args=None):
        self.board = np.zeros((ROWS, COLS), np.int8)  # 0 empty, 1 / -1 stones
        self.color = 1
        self.win_color = 0
        self.moves: List[int] = []
        return None

    def play(self, action, player=None):
        col = int(action)
        row = int(np.count_nonzero(self.board[:, col] == 0)) - 1
        self.board[row, col] = self.color
        self.moves.append(col)
        if self._wins(row, col):
            self.win_color = self.color
        self.color = -self.color
        return None

    def _wins(self, row: int, col: int) -> bool:
        c = self.board[row, col]
        for dr, dc in ((0, 1), (1, 0), (1, 1), (1, -1)):
            run = 1
            for sgn in (1, -1):
                r, q = row + sgn * dr, col + sgn * dc
                while 0 <= r < ROWS and 0 <= q < COLS and self.board[r, q] == c:
                    run += 1
                    r += sgn * dr
                    q += sgn * dc
            if run >= CONNECT:
                return True
        return False

    def terminal(self) -> bool:
        return self.win_color != 0 or len(self.moves) == ROWS * COLS

    def outcome(self) -> Dict[int, float]:
        if self.win_color == 0:
            return {0: 0.0, 1: 0.0}
        winner = 0 if self.win_color == 1 else 1
        return {winner: 1.0, 1 - winner: -1.0}

    # -- interface ----------------------------------------------------------

    def players(self) -> List[int]:
        return [0, 1]

    def turn(self) -> int:
        return 0 if self.color == 1 else 1

    def legal_actions(self, player=None) -> List[int]:
        return [c for c in range(COLS) if self.board[0, c] == 0]

    def action2str(self, action, player=None) -> str:
        return str(int(action) + 1)

    def str2action(self, s, player=None) -> int:
        return int(s) - 1

    def observation(self, player=None):
        """(3, 6, 7) planes: own stones, opponent stones, side-to-move.

        ``player=None`` means the turn player's view (framework
        convention, e.g. envs/tictactoe.py)."""
        if player is None:
            player = self.turn()
        mine = 1 if player == 0 else -1
        return np.stack(
            [
                (self.board == mine).astype(np.float32),
                (self.board == -mine).astype(np.float32),
                np.full((ROWS, COLS), float(self.color == mine), np.float32),
            ]
        )

    def rule_based_action(self, player=None, key=None) -> int:
        """Win in one if possible, else block, else random."""
        legal = self.legal_actions()
        for want in (self.color, -self.color):
            for col in legal:
                row = int(np.count_nonzero(self.board[:, col] == 0)) - 1
                self.board[row, col] = want
                won = self._wins(row, col)
                self.board[row, col] = 0
                if won:
                    return col
        return random.choice(legal)

    # -- network battle mode (delta sync) ------------------------------------

    def diff_info(self, player=None):
        return self.moves[-1] if self.moves else None

    def update(self, info, reset: bool):
        if reset:
            self.reset()
        if info is not None:
            self.play(info)

    # -- model hookup ---------------------------------------------------------

    def action_size(self) -> int:
        return COLS

    def default_net(self):
        from handyrl_tpu.models import SimpleConvNet

        return SimpleConvNet(filters=48, blocks=4, num_actions=COLS)

    @staticmethod
    def vector_env():
        """Device twin for on-device self-play (`device_rollout_games`)."""
        return VectorConnectFour

    def __str__(self) -> str:
        rows = ["".join(".XO"[v] for v in row) for row in self.board]
        return "\n".join(rows)


class VectorConnectFour:
    """Batched pure-jnp Connect Four — the device twin of ``Environment``.

    The worked example of the VectorTicTacToe-style episodic contract
    (handyrl_tpu/envs/vector_tictactoe.py): strict turn alternation lets
    the step index be a static Python int, every transition is a total
    function (finished games pass through unchanged), and the win test is
    branch-free shifted-slice sums instead of the host env's scan loops.
    ``runtime/device_rollout.make_device_rollout`` picks the episodic
    driver automatically (no streaming ``record`` hook).

    State (per game, batch-leading):
        cells  (B, 6, 7) int8   0 empty / +1 first player / -1 second
        winner (B,)      int8   0 none / +-1
    """

    num_actions = COLS
    max_steps = ROWS * COLS
    num_players = 2

    @staticmethod
    def init(n_games: int):
        import jax.numpy as jnp

        return {
            "cells": jnp.zeros((n_games, ROWS, COLS), jnp.int8),
            "winner": jnp.zeros((n_games,), jnp.int8),
        }

    @staticmethod
    def color(step: int) -> int:
        return 1 if step % 2 == 0 else -1

    @staticmethod
    def turn_player(step: int) -> int:
        return step % 2

    @staticmethod
    def observation(state, step: int):
        """(B, 3, 6, 7) turn-player planes, identical to the host
        ``observation()``: own stones, opponent stones, side-to-move."""
        import jax.numpy as jnp

        me = VectorConnectFour.color(step)
        cells = state["cells"]
        B = cells.shape[0]
        return jnp.stack(
            [
                (cells == me).astype(jnp.float32),
                (cells == -me).astype(jnp.float32),
                jnp.ones((B, ROWS, COLS), jnp.float32),  # acting => my move
            ],
            axis=1,
        )

    @staticmethod
    def legal_mask(state):
        """(B, 7) bool — columns whose top cell is empty."""
        return state["cells"][:, 0, :] == 0

    @staticmethod
    def terminal(state, step: int):
        return (state["winner"] != 0) | (step >= VectorConnectFour.max_steps)

    @staticmethod
    def _connects(stones):
        """(B,) bool — any 4-in-a-row in the (B, 6, 7) bool plane, as sums
        of four shifted slices per direction (static shapes, no loops)."""
        s = stones.astype("int8")
        h = s[:, :, :-3] + s[:, :, 1:-2] + s[:, :, 2:-1] + s[:, :, 3:]
        v = s[:, :-3, :] + s[:, 1:-2, :] + s[:, 2:-1, :] + s[:, 3:, :]
        d = s[:, :-3, :-3] + s[:, 1:-2, 1:-2] + s[:, 2:-1, 2:-1] + s[:, 3:, 3:]
        u = s[:, 3:, :-3] + s[:, 2:-1, 1:-2] + s[:, 1:-2, 2:-1] + s[:, :-3, 3:]
        return (
            (h == CONNECT).any(axis=(1, 2))
            | (v == CONNECT).any(axis=(1, 2))
            | (d == CONNECT).any(axis=(1, 2))
            | (u == CONNECT).any(axis=(1, 2))
        )

    @staticmethod
    def apply(state, actions, step: int):
        """Gravity-drop ``actions`` (B,) for the step's color in every
        live game; finished games pass through unchanged."""
        import jax
        import jax.numpy as jnp

        me = VectorConnectFour.color(step)
        cells, winner = state["cells"], state["winner"]
        live = ~VectorConnectFour.terminal(state, step)

        # landing row = (empties in the chosen column) - 1; a full column
        # (illegal, excluded by legal_mask) gives -1, which one_hot maps
        # to an all-zero row mask — a safe no-op, keeping apply total
        empties = (cells == 0).sum(axis=1)                       # (B, 7)
        row = jnp.take_along_axis(empties, actions[:, None].astype(jnp.int32), 1)[:, 0] - 1
        cell = (
            jax.nn.one_hot(row, ROWS, dtype=jnp.int8)[:, :, None]
            * jax.nn.one_hot(actions, COLS, dtype=jnp.int8)[:, None, :]
        ) * live[:, None, None].astype(jnp.int8)                 # (B, 6, 7)
        cells = jnp.where(cell > 0, jnp.int8(me), cells)

        won = VectorConnectFour._connects(cells == me) & live
        winner = jnp.where(won, jnp.int8(me), winner)
        return {"cells": cells, "winner": winner}

    @staticmethod
    def outcome(state):
        """(B, 2) float32 per-player scores, host ``outcome()`` order."""
        import jax.numpy as jnp

        w = state["winner"].astype(jnp.float32)
        return jnp.stack([w, -w], axis=1)


if __name__ == "__main__":
    env = Environment()
    for _ in range(3):
        env.reset()
        while not env.terminal():
            env.play(random.choice(env.legal_actions()))
        print(env)
        print(env.outcome())
