"""CLI entry point — mode dispatch parity with reference main.py:8-38.

Modes:
    --train / -t             standalone training (learner + local actors)
    --train-server / -ts     learner serving remote TCP workers
    --worker / -w            worker machine connecting to a train server
    --serve / -s             standalone inference serving plane
                             (continuous batching + hot-swap; docs/serving.md;
                             SIGTERM drains sessions to the fleet and exits 75)
    --fleet / -f             fleet front-end: session-affinity router over
                             the replicas in fleet.replicas (docs/serving.md);
                             fleet.autoscale.enabled spawns/retires local
                             replica processes against the shed-rate SLO
    --edge [ARTIFACT]        CPU edge replica serving a frozen export
                             artifact (fleet capability tag: edge)
    --league / -l            population-based league training (PFSP
                             matchmaking + promotion gate; docs/league.md)
    --eval / -e              MODEL_PATH NUM_GAMES NUM_PROCESS
    --eval-server / -es      network battle server
    --eval-client / -ec      network battle client
"""

import os
import sys

import yaml

# Platform override BEFORE any backend initializes (shared helper; see
# handyrl_tpu/utils/platform.py for why JAX_PLATFORMS alone is not enough).
from handyrl_tpu.utils import apply_platform_override

apply_platform_override()

from handyrl_tpu.config import normalize_args


def load_args(path: str = "config.yaml"):
    with open(path) as f:
        return normalize_args(yaml.safe_load(f) or {})


if __name__ == "__main__":
    try:
        args = load_args()
    except FileNotFoundError:
        args = None
    print(sys.argv)

    if len(sys.argv) < 2:
        print("Please set mode of HandyRL-TPU.")
        sys.exit(1)

    mode = sys.argv[1]

    if mode in ("--train", "-t", "--train-server", "-ts"):
        dist = args["train_args"].get("distributed") or {}
        if dist.get("role") == "actor":
            # dedicated actor host (docs/performance.md §Pod-slice
            # topology): deliberately OUTSIDE jax.distributed — it talks
            # to the learner tier over the plane gateway only, so losing
            # it can never wedge the learner collective
            from handyrl_tpu.runtime.actor_host import actor_host_main

            actor_host_main(args)
        else:
            from handyrl_tpu.parallel import init_distributed

            init_distributed(dist)
            if mode in ("--train", "-t"):
                from handyrl_tpu.runtime.learner import train_main

                train_main(args)
            else:
                from handyrl_tpu.runtime.learner import train_server_main

                train_server_main(args)
    elif mode in ("--worker", "-w"):
        from handyrl_tpu.runtime.server import worker_main

        worker_main(args, sys.argv)
    elif mode in ("--serve", "-s"):
        from handyrl_tpu.serving import serve_main

        serve_main(args)
    elif mode in ("--fleet", "-f"):
        from handyrl_tpu.fleet import fleet_main

        fleet_main(args)
    elif mode == "--edge":
        from handyrl_tpu.fleet import edge_main

        if len(sys.argv) > 2:
            args["edge_model"] = sys.argv[2]
        edge_main(args)
    elif mode in ("--league", "-l"):
        from handyrl_tpu.league import league_main
        from handyrl_tpu.parallel import init_distributed

        init_distributed(args["train_args"].get("distributed"))
        league_main(args)
    elif mode in ("--eval", "-e"):
        from handyrl_tpu.runtime.evaluation import eval_main

        eval_main(args, sys.argv[2:])
    elif mode in ("--eval-server", "-es"):
        from handyrl_tpu.runtime.battle import eval_server_main

        eval_server_main(args, sys.argv[2:])
    elif mode in ("--eval-client", "-ec"):
        from handyrl_tpu.runtime.battle import eval_client_main

        eval_client_main(args, sys.argv[2:])
    else:
        print("Unknown mode %s" % mode)
        sys.exit(1)
