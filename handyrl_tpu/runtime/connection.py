"""Host-level transport for the distributed actor plane.

Capability parity with reference handyrl/connection.py: length-prefixed
framing (connection.py:20-69), ``send_recv`` RPC (14-17), socket helpers
(72-114), and the ``QueueCommunicator`` async hub (176-224).  Differences:

* Frames carry the pickle-free codec (runtime/codec.py), not pickle.
* This layer only moves *actor-plane* traffic (job args, episodes, eval
  results, param blobs).  The gradient/param plane inside the learner is
  XLA collectives over ICI/DCN (parallel/train_step.py) and never touches
  these sockets — the two planes the reference conflates are split by
  design (SURVEY.md §2.5).
* Fault tolerance (docs/fault_tolerance.md): frame send/recv take
  optional deadlines (a WAN blackhole must surface as TimeoutError, not
  an eternal block), and the hub gives each peer its OWN bounded send
  queue + sender thread, so one stalled peer's TCP backpressure can never
  wedge delivery to every other peer.
"""

from __future__ import annotations

import io
import queue
import select
import socket
import struct
import threading
import time
from typing import Any, Dict, Iterator, List, Optional, Tuple

from . import codec

_HEADER = struct.Struct("!I")

_UNSET = object()  # "use the connection default" sentinel for timeouts


def _wait_io(sock, for_write: bool, deadline: float) -> None:
    """Block until the socket (or raw fd) is ready for the given direction
    or the deadline passes (raises socket.timeout).

    Readiness-polling instead of ``settimeout``: the socket's timeout is
    SHARED state, and one connection is legitimately used by an
    independent sender and receiver thread at once (QueueCommunicator) —
    a sender calling settimeout(None) between the receiver's
    settimeout(30) and its recv syscall would silently strip the
    receiver's dead-peer deadline.  poll/select mutate nothing.  Also the
    readiness-wait primitive for non-socket fds (the shm pipeline's ready
    pipe) — accept an int fd directly.
    """
    remaining = deadline - time.monotonic()
    if remaining > 0:
        try:
            fd = sock if isinstance(sock, int) else sock.fileno()
            if fd < 0:
                raise OSError("socket closed")
            if hasattr(select, "poll"):  # no FD_SETSIZE cap (select does)
                poller = select.poll()
                poller.register(fd, select.POLLOUT if for_write else select.POLLIN)
                if poller.poll(remaining * 1000.0):
                    return
            else:  # pragma: no cover - non-poll platforms
                rw = ([], [sock]) if for_write else ([sock], [])
                if any(select.select(*rw, [], remaining)[:2]):
                    return
        except ValueError:
            raise OSError("socket closed")
    raise socket.timeout(
        f"{'send' if for_write else 'recv'} deadline exceeded"
    )


class FramedConnection:
    """u32-length-prefixed codec frames over a stream socket.

    ``timeout`` (constructor default, overridable per call) bounds the
    SILENCE on each send/recv — how long the transfer may stall without a
    byte of progress, not how long the whole frame may take (a large
    params blob on a slow link is alive as long as bytes flow).  On
    expiry the call raises ``TimeoutError`` (socket.timeout) and the
    stream must be considered dead — a deadline can fire mid-frame,
    leaving the framing desynchronized, so the only safe recovery is to
    close and re-establish the connection.  The underlying socket stays in
    blocking mode; deadlines are enforced by readiness polling, so the
    sender's and receiver's deadlines never interfere (see ``_wait_io``).
    """

    def __init__(self, conn: socket.socket, timeout: Optional[float] = None):
        self.conn = conn
        self.default_timeout = timeout
        self._send_lock = threading.Lock()
        self._recv_lock = threading.Lock()
        # frame-payload byte tallies (headers included), updated under the
        # respective direction's lock: the fleet bench reads these to
        # measure wire bytes/request — session routing's whole claim
        self.bytes_sent = 0
        self.bytes_received = 0

    def fileno(self) -> int:
        return self.conn.fileno()

    def close(self) -> None:
        try:
            # shutdown, not just close: close() of the fd does NOT wake a
            # thread blocked inside a send/recv syscall on this socket
            # (it would stay wedged forever, stranding e.g. a hub sender
            # thread mid-sendall); shutdown() forces those syscalls to
            # return so teardown actually tears down
            self.conn.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self.conn.close()
        except OSError:
            pass

    def _gap(self, timeout) -> Optional[float]:
        t = self.default_timeout if timeout is _UNSET else timeout
        return None if t is None else float(t)

    def _recv_exact(
        self, n: int, gap: Optional[float], hard_deadline: Optional[float] = None
    ) -> bytes:
        buf = io.BytesIO()
        while buf.tell() < n:
            if gap is not None or hard_deadline is not None:
                # the gap deadline restarts on every chunk: it bounds
                # SILENCE, not total frame time — a multi-hundred-MB params
                # blob trickling over a slow WAN is alive by construction
                # (progress is the liveness proof) and must never be cut
                # off mid-transfer by a whole-frame budget.  hard_deadline
                # is the opposite mode, for tiny control frames (entry
                # handshake): an ABSOLUTE budget a byte-trickler cannot
                # keep alive by dribbling one byte per gap
                if gap is None:
                    deadline = hard_deadline
                elif hard_deadline is None:
                    deadline = time.monotonic() + gap
                else:
                    deadline = min(time.monotonic() + gap, hard_deadline)
                _wait_io(self.conn, False, deadline)
            chunk = self.conn.recv(n - buf.tell())
            if not chunk:
                raise ConnectionResetError("connection closed mid-frame")
            buf.write(chunk)
        return buf.getvalue()

    def recv(self, timeout=_UNSET, hard: bool = False) -> Any:
        """``hard`` turns ``timeout`` into an absolute whole-frame budget
        instead of a stall bound — see ``_recv_exact``."""
        with self._recv_lock:
            gap = self._gap(timeout)
            hard_deadline = None
            if hard and gap is not None:
                hard_deadline, gap = time.monotonic() + gap, None
            (length,) = _HEADER.unpack(self._recv_exact(4, gap, hard_deadline))
            payload = self._recv_exact(length, gap, hard_deadline) if length else b""
            self.bytes_received += 4 + length
        return codec.loads(payload)

    def send(self, obj: Any, timeout=_UNSET, hard: bool = False) -> None:
        payload = codec.dumps(obj)
        data = _HEADER.pack(len(payload)) + payload
        with self._send_lock:
            self._send_bytes(data, self._gap(timeout), hard)

    def try_send(self, obj: Any, timeout=_UNSET) -> bool:
        """``send`` iff no other frame is in flight on this connection;
        returns False (without blocking) otherwise.

        The liveness-ping use case: a frame already being sent proves the
        link alive better than a queued ping would, and a ping thread
        blocking behind a multi-minute trickling upload would starve its
        OTHER duties (pinging the sibling connections)."""
        payload = codec.dumps(obj)
        if not self._send_lock.acquire(blocking=False):
            return False
        try:
            self._send_bytes(_HEADER.pack(len(payload)) + payload, self._gap(timeout))
        finally:
            self._send_lock.release()
        return True

    def _send_bytes(self, data: bytes, gap: Optional[float], hard: bool = False) -> None:
        """Write one frame; caller holds the send lock."""
        self.bytes_sent += len(data)
        if gap is None:
            self.conn.sendall(data)
            return
        hard_deadline = time.monotonic() + gap if hard else None
        view = memoryview(data)
        while view:
            # writable after poll => send() accepts >= 1 byte without
            # blocking (send_lock serializes writers on this socket);
            # like recv, the gap bounds stall time, not frame time —
            # unless ``hard``, the absolute-budget mode for control frames
            # whose peer could drip-READ to keep the gap alive
            _wait_io(
                self.conn, True,
                hard_deadline if hard else time.monotonic() + gap,
            )
            view = view[self.conn.send(view):]


def send_recv(conn: FramedConnection, sdata: Any, timeout=_UNSET) -> Any:
    conn.send(sdata, timeout=timeout)
    return conn.recv(timeout=timeout)


def open_socket_connection(port: int, reuse: bool = True) -> socket.socket:
    sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1 if reuse else 0)
    sock.bind(("", int(port)))
    return sock


def accept_socket_connections(
    port: Optional[int] = None,
    timeout: Optional[float] = None,
    maxsize: Optional[int] = None,
    sock: Optional[socket.socket] = None,
) -> Iterator[Optional[FramedConnection]]:
    """Yield accepted FramedConnections (None on timeout) until closed.

    ``maxsize`` bounds the total accept count when given; the default is
    unbounded — long-lived servers (elastic worker fleets, battle servers)
    must never silently stop accepting.
    """
    if sock is None:
        sock = open_socket_connection(port)
    sock.listen(1024)
    sock.settimeout(timeout)
    count = 0
    while maxsize is None or count < maxsize:
        try:
            conn, _ = sock.accept()
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            conn.settimeout(None)  # accept() propagates the listener timeout
            yield FramedConnection(conn)
            count += 1
        except socket.timeout:
            yield None
        except OSError:
            return


def connect_socket_connection(
    host: str, port: int, timeout: float = 32.0, retry_seconds: float = 0.0
) -> FramedConnection:
    """Connect, optionally retrying for ``retry_seconds`` (peer still booting)."""
    import time

    deadline = time.monotonic() + retry_seconds
    while True:
        try:
            sock = socket.create_connection((host, int(port)), timeout=timeout)
            break
        except OSError:
            if time.monotonic() >= deadline:
                raise
            time.sleep(0.5)
    sock.settimeout(None)
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    return FramedConnection(sock)


class QueueCommunicator:
    """Async fan-in hub over many connections (connection.py:176-224).

    A daemon receiver thread per connection funnels frames into
    ``input_queue``; a daemon SENDER thread per connection drains that
    peer's own bounded send queue.  Per-peer send queues are the fault
    boundary: a peer that stops reading fills its TCP window, then its
    queue, and is disconnected — every other peer keeps flowing (the
    previous single shared send loop let one wedged ``sendall`` starve
    all peers).  ``recv_timeout`` bounds each peer's frame gap; a peer
    silent for longer (no traffic, no heartbeat) is presumed dead and
    dropped, so half-open TCP connections cannot pin receiver threads or
    the connection count forever.
    """

    def __init__(
        self,
        conns: Optional[List[FramedConnection]] = None,
        recv_timeout: Optional[float] = None,
        send_queue_size: int = 64,
    ):
        self.input_queue: "queue.Queue[Tuple[FramedConnection, Any]]" = queue.Queue(maxsize=256)
        self.conns: Dict[FramedConnection, "queue.Queue"] = {}
        self.recv_timeout = recv_timeout
        self.send_queue_size = send_queue_size
        self._lock = threading.Lock()
        self.shutdown_flag = False
        for conn in conns or []:
            self.add_connection(conn)

    def connection_count(self) -> int:
        with self._lock:
            return len(self.conns)

    def connections(self) -> List[FramedConnection]:
        with self._lock:
            return list(self.conns)

    def recv(self, timeout: Optional[float] = None) -> Tuple[FramedConnection, Any]:
        return self.input_queue.get(timeout=timeout)

    def send(self, conn: FramedConnection, send_data: Any, droppable: bool = False) -> None:
        with self._lock:
            send_q = self.conns.get(conn)
        if send_q is None:
            return  # peer already gone; its jobs were reclaimed on disconnect
        try:
            send_q.put_nowait(send_data)
        except queue.Full:
            if droppable:
                # e.g. a liveness ping queued behind a long in-flight blob
                # transfer: the peer is demonstrably alive (bytes flowing),
                # so drop the PING, not the peer — disconnecting here would
                # re-impose the whole-frame time budget the frame layer
                # deliberately avoids
                return
            # TCP window AND the queue are full: the peer stopped reading
            # long ago — tear it down rather than buffer without bound
            print("peer send queue overflow, dropping connection")
            self.disconnect(conn)

    def shutdown(self) -> None:
        self.shutdown_flag = True
        for conn in self.connections():
            self.disconnect(conn)

    def add_connection(self, conn: FramedConnection) -> None:
        send_q: "queue.Queue" = queue.Queue(maxsize=self.send_queue_size)
        with self._lock:
            self.conns[conn] = send_q
        # one receiver thread per connection: blocking recv() needs no
        # select() dance and each frame lands on input_queue in order
        threading.Thread(target=self._recv_loop, args=(conn,), daemon=True).start()
        threading.Thread(target=self._send_loop, args=(conn, send_q), daemon=True).start()

    def disconnect(self, conn: FramedConnection) -> None:
        with self._lock:
            send_q = self.conns.pop(conn, None)
        conn.close()
        if send_q is not None:
            try:
                send_q.put_nowait(_UNSET)  # wake the sender thread to exit
            except queue.Full:
                pass  # sender will notice the closed socket on its next send
            self.on_disconnect(conn)

    def on_disconnect(self, conn: FramedConnection) -> None:
        """Hook: called once per peer actually removed (subclasses reclaim
        the peer's in-flight jobs here).  Runs on whichever thread noticed
        the failure; keep it non-blocking."""

    def _recv_loop(self, conn: FramedConnection) -> None:
        while not self.shutdown_flag:
            try:
                data = conn.recv(timeout=self.recv_timeout)
            except socket.timeout:
                # silent past the deadline: presumed dead (live peers
                # heartbeat well inside recv_timeout)
                self.disconnect(conn)
                return
            except (ConnectionResetError, BrokenPipeError, EOFError, OSError, codec.CodecError):
                self.disconnect(conn)
                return
            with self._lock:
                if conn not in self.conns:
                    return
            self.input_queue.put((conn, data))

    def _send_loop(self, conn: FramedConnection, send_q: "queue.Queue") -> None:
        while True:
            data = send_q.get()
            if data is _UNSET:
                return  # disconnected while idle
            with self._lock:
                if conn not in self.conns:
                    return
            try:
                conn.send(data)
            except (socket.timeout, ConnectionResetError, BrokenPipeError, OSError):
                self.disconnect(conn)
                return
            except Exception as exc:
                # e.g. CodecError on an unencodable reply: drop that peer —
                # only ITS sender thread dies, every other peer keeps flowing
                print("send failed, dropping connection:", exc)
                self.disconnect(conn)
                return
