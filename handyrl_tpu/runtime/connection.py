"""Host-level transport for the distributed actor plane.

Capability parity with reference handyrl/connection.py: length-prefixed
framing (connection.py:20-69), ``send_recv`` RPC (14-17), socket helpers
(72-114), and the ``QueueCommunicator`` async hub (176-224).  Differences:

* Frames carry the pickle-free codec (runtime/codec.py), not pickle.
* This layer only moves *actor-plane* traffic (job args, episodes, eval
  results, param blobs).  The gradient/param plane inside the learner is
  XLA collectives over ICI/DCN (parallel/train_step.py) and never touches
  these sockets — the two planes the reference conflates are split by
  design (SURVEY.md §2.5).
"""

from __future__ import annotations

import io
import queue
import socket
import struct
import threading
from typing import Any, Dict, Iterator, List, Optional, Tuple

from . import codec

_HEADER = struct.Struct("!I")


class FramedConnection:
    """u32-length-prefixed codec frames over a stream socket."""

    def __init__(self, conn: socket.socket):
        self.conn = conn
        self._send_lock = threading.Lock()
        self._recv_lock = threading.Lock()

    def fileno(self) -> int:
        return self.conn.fileno()

    def close(self) -> None:
        try:
            self.conn.close()
        except OSError:
            pass

    def _recv_exact(self, n: int) -> bytes:
        buf = io.BytesIO()
        while buf.tell() < n:
            chunk = self.conn.recv(n - buf.tell())
            if not chunk:
                raise ConnectionResetError("connection closed mid-frame")
            buf.write(chunk)
        return buf.getvalue()

    def recv(self) -> Any:
        with self._recv_lock:
            (length,) = _HEADER.unpack(self._recv_exact(4))
            payload = self._recv_exact(length) if length else b""
        return codec.loads(payload)

    def send(self, obj: Any) -> None:
        payload = codec.dumps(obj)
        with self._send_lock:
            self.conn.sendall(_HEADER.pack(len(payload)) + payload)


def send_recv(conn: FramedConnection, sdata: Any) -> Any:
    conn.send(sdata)
    return conn.recv()


def open_socket_connection(port: int, reuse: bool = True) -> socket.socket:
    sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1 if reuse else 0)
    sock.bind(("", int(port)))
    return sock


def accept_socket_connections(
    port: Optional[int] = None,
    timeout: Optional[float] = None,
    maxsize: Optional[int] = None,
    sock: Optional[socket.socket] = None,
) -> Iterator[Optional[FramedConnection]]:
    """Yield accepted FramedConnections (None on timeout) until closed.

    ``maxsize`` bounds the total accept count when given; the default is
    unbounded — long-lived servers (elastic worker fleets, battle servers)
    must never silently stop accepting.
    """
    if sock is None:
        sock = open_socket_connection(port)
    sock.listen(1024)
    sock.settimeout(timeout)
    count = 0
    while maxsize is None or count < maxsize:
        try:
            conn, _ = sock.accept()
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            yield FramedConnection(conn)
            count += 1
        except socket.timeout:
            yield None
        except OSError:
            return


def connect_socket_connection(
    host: str, port: int, timeout: float = 32.0, retry_seconds: float = 0.0
) -> FramedConnection:
    """Connect, optionally retrying for ``retry_seconds`` (peer still booting)."""
    import time

    deadline = time.monotonic() + retry_seconds
    while True:
        try:
            sock = socket.create_connection((host, int(port)), timeout=timeout)
            break
        except OSError:
            if time.monotonic() >= deadline:
                raise
            time.sleep(0.5)
    sock.settimeout(None)
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    return FramedConnection(sock)


class QueueCommunicator:
    """Async fan-in hub over many connections (connection.py:176-224).

    Daemon send/recv threads multiplex the registered connections through
    bounded queues; connections are dropped silently on reset/EOF, matching
    the reference's join-only elasticity (workers may come and go, the
    server never tracks them individually).
    """

    def __init__(self, conns: Optional[List[FramedConnection]] = None):
        self.input_queue: "queue.Queue[Tuple[FramedConnection, Any]]" = queue.Queue(maxsize=256)
        self.output_queue: "queue.Queue[Tuple[FramedConnection, Any]]" = queue.Queue(maxsize=256)
        self.conns: Dict[FramedConnection, threading.Thread] = {}
        self._lock = threading.Lock()
        self.shutdown_flag = False
        for conn in conns or []:
            self.add_connection(conn)
        self._send_thread = threading.Thread(target=self._send_loop, daemon=True)
        self._send_thread.start()

    def connection_count(self) -> int:
        with self._lock:
            return len(self.conns)

    def recv(self, timeout: Optional[float] = None) -> Tuple[FramedConnection, Any]:
        return self.input_queue.get(timeout=timeout)

    def send(self, conn: FramedConnection, send_data: Any) -> None:
        self.output_queue.put((conn, send_data))

    def shutdown(self) -> None:
        self.shutdown_flag = True
        with self._lock:
            conns = list(self.conns)
        for conn in conns:
            self.disconnect(conn)

    def add_connection(self, conn: FramedConnection) -> None:
        # one receiver thread per connection: blocking recv() needs no
        # select() dance and each frame lands on input_queue in order
        t = threading.Thread(target=self._recv_loop, args=(conn,), daemon=True)
        with self._lock:
            self.conns[conn] = t
        t.start()

    def disconnect(self, conn: FramedConnection) -> None:
        with self._lock:
            self.conns.pop(conn, None)
        conn.close()

    def _recv_loop(self, conn: FramedConnection) -> None:
        while not self.shutdown_flag:
            try:
                data = conn.recv()
            except (ConnectionResetError, BrokenPipeError, EOFError, OSError, codec.CodecError):
                self.disconnect(conn)
                return
            with self._lock:
                if conn not in self.conns:
                    return
            self.input_queue.put((conn, data))

    def _send_loop(self) -> None:
        while True:
            conn, data = self.output_queue.get()
            try:
                conn.send(data)
            except (ConnectionResetError, BrokenPipeError, OSError):
                self.disconnect(conn)
            except Exception as exc:
                # e.g. CodecError on an unencodable reply: drop that peer but
                # never kill the hub's only send thread (all peers would hang)
                print("send failed, dropping connection:", exc)
                self.disconnect(conn)
