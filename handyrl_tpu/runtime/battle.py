"""Network battle mode: agents on different machines play one game.

Capability parity with reference handyrl/evaluation.py: the server owns
the master env and drives ``exec_network_match`` over per-player socket
proxies (``NetworkAgent``, evaluation.py:66-80); each client owns a
replica env synchronised purely through ``diff_info``/``update`` deltas
and a local agent (``NetworkAgentClient``, evaluation.py:32-63); entry
points mirror ``eval_server_main``/``eval_client_main``
(evaluation.py:407-436).  Default port 9876 (evaluation.py:15).

The wire carries only the pickle-free codec frames (runtime/codec.py) —
env deltas must therefore be codec-encodable (str/bytes/numbers/pytrees),
which all bundled envs satisfy.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from ..envs import make_env, prepare_env
from .connection import (
    FramedConnection,
    accept_socket_connections,
    connect_socket_connection,
    send_recv,
)
from .evaluation import build_agent, exec_network_match, load_model_agent, wp_func

BATTLE_PORT = 9876


class PeerSevered(RuntimeError):
    """A remote peer's connection died mid-match; carries the seat so the
    match can be scored as a forfeit instead of silently killing the
    match thread."""

    def __init__(self, player):
        super().__init__(f"peer for player {player} severed mid-match")
        self.player = player


def forfeit_outcome(players, severed_player):
    """Outcome dict for a severed-peer forfeit: the severed seat scores
    -1, every surviving seat +1.  The payoff ledger refines this pairwise
    (survivors beat the forfeiter; survivor-vs-survivor pairs are NOT
    recorded — see PayoffMatrix.record_forfeit)."""
    return {
        p: (-1.0 if p == severed_player else 1.0) for p in players
    }


def exec_recorded_match(env, network_agents, names=None, payoff=None,
                        game_args=None):
    """``exec_network_match`` + the outcome accounting the league payoff
    matrix consumes: finished games record pairwise (draws as half-wins,
    multi-player placements decomposed by score), a severed peer records
    a forfeit.  Returns ``(outcome, severed_player)`` — outcome is the
    forfeit dict when a peer died, or None on an env-level error (which
    records NOTHING: a broken game carries no information about relative
    strength).

    ``names`` maps seats to ledger member names (defaults to
    ``seat{p}``); ``payoff`` is any PayoffMatrix-shaped ledger (None =
    play without books).
    """
    names = names or {p: f"seat{p}" for p in env.players()}
    try:
        outcome = exec_network_match(env, network_agents, game_args=game_args)
    except PeerSevered as exc:
        if env.terminal():
            # the game FINISHED and the peer died during the outcome-
            # notification round (a client exiting right after its last
            # move): the master env holds the real result — booking a
            # forfeit here would record a loss for an actual winner
            outcome = env.outcome()
            if payoff is not None:
                payoff.record_outcome(names, outcome)
            return outcome, None
        if payoff is not None:
            payoff.record_forfeit(names, exc.player)
        return forfeit_outcome(env.players(), exc.player), exc.player
    if outcome is not None and payoff is not None:
        payoff.record_outcome(names, outcome)
    return outcome, None


class NetworkAgentClient:
    """Client-side command loop: local agent + replica env (evaluation.py:32-63)."""

    def __init__(self, agent, env, conn: FramedConnection):
        self.agent = agent
        self.env = env
        self.conn = conn

    def run(self) -> None:
        while True:
            try:
                command, args = self.conn.recv()
            except (ConnectionResetError, EOFError, OSError):
                break
            if command == "quit":
                break
            elif command == "outcome":
                print("outcome = %f" % args)
                self.conn.send(None)
            elif hasattr(self.agent, command):
                if command == "action":
                    player = args
                    ret = self.agent.action(self.env, player)
                    ret = self.env.action2str(ret, player)
                else:  # reset / observe
                    ret = getattr(self.agent, command)(self.env, args)
                    if ret is not None:
                        ret = [float(x) for x in np.reshape(np.asarray(ret), (-1,))]
                self.conn.send(ret)
            elif command == "update":
                info, reset = args
                self.env.update(info, reset)
                self.conn.send(None)
            else:
                self.conn.send(None)


class NetworkAgent:
    """Server-side RPC proxy for a remote client (evaluation.py:66-80).

    Every RPC converts a dead/stalled connection into ``PeerSevered``
    carrying this proxy's seat, so ``exec_recorded_match`` can score the
    match as a forfeit for the right player instead of the exception
    killing the match thread anonymously."""

    def __init__(self, conn: FramedConnection, player=None):
        self.conn = conn
        self.player = player

    def _rpc(self, payload):
        try:
            return send_recv(self.conn, payload)
        except (OSError, EOFError, ConnectionResetError, TimeoutError) as exc:
            raise PeerSevered(self.player) from exc

    def update(self, data, reset: bool):
        return self._rpc(("update", (data, reset)))

    def outcome(self, outcome):
        return self._rpc(("outcome", float(outcome)))

    def action(self, player: int):
        return self._rpc(("action", player))

    def observe(self, player: int):
        return self._rpc(("observe", player))


def network_match_acception(n_games: int, env_args: Dict[str, Any], num_agents: int, port: int):
    """Yield a group of num_agents client conns per game (evaluation.py:264-284).

    Groups are yielded as soon as they fill so matches start while later
    clients are still joining — clients that play game after game can
    reconnect between yields without deadlocking the accept loop.
    """
    from .connection import open_socket_connection

    waiting_conns: List[FramedConnection] = []
    games = 0
    sock = open_socket_connection(port)
    try:
        for conn in accept_socket_connections(sock=sock):
            if conn is None:
                continue
            conn.send(env_args)  # every client learns the env on join
            waiting_conns.append(conn)
            if len(waiting_conns) == num_agents:
                group, waiting_conns = waiting_conns, []
                yield group
                games += 1
            if games >= n_games:
                return
    finally:
        # refuse further joins and release stranded half-group clients, so
        # clients see 'server is gone' instead of blocking in recv forever
        sock.close()
        for conn in waiting_conns:
            conn.close()


def eval_server_main(args: Dict[str, Any], argv: List[str], port: Optional[int] = None) -> None:
    """`main.py --eval-server [NUM_GAMES]` (evaluation.py:407-421)."""
    import threading

    env_args = args["env_args"]
    prepare_env(env_args)
    master_env = make_env(env_args)
    num_games = int(argv[0]) if argv else 100
    port = port or int(args["train_args"].get("battle_port", BATTLE_PORT))

    print("network match server mode")
    from ..league.matchmaker import PayoffMatrix

    total: Dict[Any, int] = {}
    # the session ledger: one PayoffMatrix (the league's bookkeeping) per
    # serve session, seats named by join order — network matches and
    # league matches share one accounting of draws/placements/forfeits
    payoff = PayoffMatrix()
    lock = threading.Lock()
    threads: List[threading.Thread] = []

    def run_match(game: int, conns: List[FramedConnection]) -> None:
        env = make_env(env_args)
        agents = {
            p: NetworkAgent(conn, p) for p, conn in zip(env.players(), conns)
        }
        names = {p: f"seat{p}" for p in env.players()}
        outcome, severed = exec_recorded_match(env, agents, names, _Locked(payoff, lock))
        if severed is not None:
            print("game %d: seat %s severed — forfeit, outcome = %s"
                  % (game, severed, outcome))
        if outcome is not None:
            o = outcome[env.players()[0]]
            with lock:
                total[o] = total.get(o, 0) + 1
            if severed is None:
                print("game %d: outcome = %s" % (game, outcome))
        for conn in conns:
            try:
                conn.send(("quit", None))
            except OSError:
                pass
            conn.close()

    groups = network_match_acception(num_games, env_args, len(master_env.players()), port)
    for game, conns in enumerate(groups):
        t = threading.Thread(target=run_match, args=(game, conns))
        t.start()
        threads.append(t)
    for t in threads:
        t.join()
    print("total = %.3f (%d)" % (wp_func(total), sum(total.values())))
    seats = [f"seat{p}" for p in master_env.players()]
    wp0 = payoff.aggregate_win_points(seats[0], seats[1:])
    if wp0 is not None:
        print(
            "payoff: %s wp vs field = %.3f over %d match(es), %d forfeit(s)"
            % (seats[0], wp0, payoff.matches, payoff.forfeits)
        )


class _Locked:
    """Serialize one ledger's record_* calls across match threads."""

    def __init__(self, payoff, lock):
        self._payoff = payoff
        self._lock = lock

    def record_outcome(self, names, outcome):
        with self._lock:
            self._payoff.record_outcome(names, outcome)

    def record_forfeit(self, names, severed_seat):
        with self._lock:
            self._payoff.record_forfeit(names, severed_seat)


def eval_client_main(args: Dict[str, Any], argv: List[str], port: Optional[int] = None) -> None:
    """`main.py --eval-client AGENT [HOST] [N_GAMES]` (evaluation.py:424-436)."""
    print("network match client mode")
    host = argv[1] if len(argv) >= 2 else "localhost"
    port = port or int(args["train_args"].get("battle_port", BATTLE_PORT))
    max_games = None
    if len(argv) >= 3:
        max_games = 1 if argv[2] == "once" else int(argv[2])
    games_played = 0
    connected_once = False
    while True:
        try:
            # retry while the server boots; after first contact, a refused
            # connect means the server finished its games and went away
            conn = connect_socket_connection(
                host, port, retry_seconds=0.0 if connected_once else 60.0
            )
            connected_once = True
        except OSError:
            print("server is gone")
            return
        try:
            env_args = conn.recv()
        except (OSError, ConnectionResetError, EOFError):
            conn.close()
            print("server is gone")
            return

        prepare_env(env_args)
        env = make_env(env_args)
        agent = build_agent(argv[0] if argv else "random", env)
        if agent is None:
            agent = load_model_agent(argv[0], env)
        NetworkAgentClient(agent, env, conn).run()
        conn.close()
        games_played += 1
        if max_games is not None and games_played >= max_games:
            return
