"""Dedicated actor host: on-device self-play feeding a remote learner.

Pod-slice rung 2 (docs/performance.md §Pod-slice topology).  A process
launched with ``distributed.role: actor`` runs ONLY the data plane: the
streaming device rollout over all of its local devices, shipping each
(K, B, ...) record batch to the learner's plane gateway over DCN and
polling versioned params back (runtime/plane.py — the health plane's TCP
framing with byte-counted npz payloads).

Deliberately OUTSIDE ``jax.distributed``: an actor host never joins the
learner collective, so losing one can never wedge a cross-host train step
— the learner's gateway logs the disconnect, bumps
``dist_actor_host_losses``, and the surviving producers absorb the game
quota (the degradable direction of docs/fault_tolerance.md's matrix).
The reverse is loud: a dead gateway socket means the learner tier is
gone, and this process announces the fault and exits 75 (EX_TEMPFAIL) so
a supervisor relaunches it once the learner is back — the params it
would generate against are unowned until then.

Rate coupling is structural: one record batch is in flight per host (the
ship is a blocking request/reply), so a slow learner back-pressures the
rollout loop without a budget protocol.
"""

from __future__ import annotations

import signal
import sys
import threading
import time
from typing import Any, Dict

from ..envs import make_env, prepare_env
from ..models import init_variables
from ..utils import trace
from ..utils.retry import retry_call

# same convention as the learner's drain path (runtime/learner.py)
EXIT_RESUMABLE = 75


def actor_host_main(args: Dict[str, Any]) -> None:
    """Entry point for ``--train`` with ``distributed.role: actor``."""
    import jax

    from ..parallel.mesh import dispatch_serialized, make_mesh
    from .device_rollout import build_streaming_fn
    from .plane import PlaneClient

    train_args = dict(args["train_args"])
    train_args["env"] = args["env_args"]
    dist = dict(train_args.get("distributed") or {})
    seed = int(train_args["seed"])
    rank = int(dist.get("process_id") or 0)

    if trace.configure(train_args.get("trace"), rank=1000 + rank):
        print(f"trace: spans -> {trace.current_path()} (actor host {rank})")

    prepare_env(args["env_args"])
    env = make_env(args["env_args"])
    module = env.net()
    vector_env = getattr(env, "vector_env", None)
    if vector_env is None:
        raise ValueError(
            f"distributed.role: actor needs a vector env; "
            f"{args['env_args'].get('env')} exposes no vector_env()"
        )
    venv = vector_env()
    if not hasattr(venv, "record"):
        raise ValueError(
            "distributed.role: actor needs a STREAMING vector env "
            "(record/reset_done/step hooks); "
            f"{getattr(venv, '__name__', type(venv).__name__)} lacks them"
        )
    # match the learner tier's PER-PROCESS lane count: the gateway ingests
    # into rings built for device_rollout_games / num_processes lanes
    # (config.py validated the divisibility), and a mismatched record
    # batch width must fail loudly at the gateway, not silently reshape
    games = int(train_args["device_rollout_games"]) // max(
        1, int(dist.get("num_processes") or 1)
    )
    mesh = make_mesh({"dp": -1}, jax.local_devices())
    if games % mesh.size:
        raise ValueError(
            f"device_rollout_games {games} not divisible by this actor "
            f"host's {mesh.size} local devices (lanes shard over them)"
        )
    stream_fn = build_streaming_fn(
        venv, module, games,
        int(train_args["device_replay_k_steps"]),
        mesh=mesh if mesh.size > 1 else None,
        use_observe_mask=bool(train_args["observation"]),
    )
    # identical seed -> identical init params on every process: rollouts
    # are on-policy-ish from step 0, before the first param poll lands
    params = init_variables(module, env, seed)["params"]

    client = PlaneClient(dist)
    version = client.connect(
        retry_for=float(dist.get("initialization_timeout") or 300.0)
    )
    print(
        f"actor host {rank}: connected to plane gateway "
        f"(param version {version}); {games} lanes on {mesh.size} devices"
    )

    stop = threading.Event()

    def _reconnect(i, exc):
        # one flaky syscall (EINTR, a reset mid-frame) must not cost an
        # exit 75: drop the wedged connection, dial a fresh one, and let
        # retry_call re-issue the SAME request.  A reconnect that itself
        # fails propagates — that IS the gateway being gone, and the
        # outer handler's announce_fault + exit 75 keeps its meaning
        nonlocal client
        print(
            f"[handyrl_tpu] actor host {rank}: transient plane fault "
            f"({exc}); reconnect attempt {i + 1}",
            file=sys.stderr,
        )
        try:
            client.close()
        except Exception:
            pass
        client = PlaneClient(dist)
        client.connect(retry_for=30.0)

    def _stop_signal(signum, frame):
        print(
            f"[handyrl_tpu] actor host {rank}: signal {signum} — draining",
            file=sys.stderr,
        )
        stop.set()

    signal.signal(signal.SIGTERM, _stop_signal)
    signal.signal(signal.SIGINT, _stop_signal)

    # rank-decorrelated rollout stream, offset past the learner ranks'
    # seed + 1009*rank family so a co-hosted learner never shares a key
    key = jax.random.PRNGKey(seed + 0x5EED + 0xAC706 + 1009 * rank)
    key, k0 = jax.random.split(key)
    vstate = venv.init(games, k0)
    hidden = module.initial_state((games, venv.num_players))
    dispatches = 0
    try:
        while not stop.is_set():
            key, sub = jax.random.split(key)
            vstate, hidden, records = dispatch_serialized(
                lambda: stream_fn(params, vstate, hidden, sub), mesh
            )
            # graftlint: allow[HS001] reason=the record batch leaves this machine over DCN — host materialization is the transport's input, one D2H per k_steps block
            host_records = jax.device_get(records)
            gateway_version = retry_call(
                lambda: client.ship_records(host_records),
                attempts=3, base_delay=0.1, on_retry=_reconnect,
            )
            if gateway_version is None:
                break  # clean stop from the gateway
            dispatches += 1
            if gateway_version > client.param_version:
                got = retry_call(
                    lambda: client.poll_params(),
                    attempts=3, base_delay=0.1, on_retry=_reconnect,
                )
                if got is None:
                    break
                new_version, fresh = got
                if fresh is not None:
                    params = fresh
                    print(
                        f"actor host {rank}: params -> version {new_version}"
                    )
    except (ConnectionError, OSError) as e:
        from ..parallel.health import announce_fault

        announce_fault(
            f"plane gateway lost after {dispatches} dispatches: {e}",
            "learner_loss",
            EXIT_RESUMABLE,
        )
        client.close()
        sys.exit(EXIT_RESUMABLE)
    finally:
        # await the in-flight async dispatch; exiting the process with an
        # XLA execution still running aborts it (see
        # StreamingDeviceRollout.drain)
        try:
            # graftlint: allow[HS001] reason=teardown drain: the loop has exited; awaiting the last in-flight rollout is the point (aborting a live XLA execute at interpreter exit crashes)
            jax.block_until_ready(vstate)
        except Exception:
            pass
    client.close()
    print(f"actor host {rank}: finished ({dispatches} dispatches)")
