"""GIL-free batch-assembly plane: batcher PROCESSES + shared-memory ring.

The threaded BatchPipeline (runtime/trainer.py) keeps every make_batch on
the learner process's GIL, where it contends with the inference engine,
the worker threads and jax dispatch — measured at 3 updates/s against 376
for the direct path on HungryGeese (BENCH_r05.json).  This module moves
assembly off the GIL entirely, the IMPALA/HandyRL decoupled-batcher
design point (reference train.py:271-401 forks num_batchers processes):

    parent                                children (num_batchers processes)
    ------                                ---------------------------------
    EpisodeStore ──codec blobs──▶ feed_q ─▶ replica EpisodeStore
                                            sample local_batch windows
    free_q ◀──────────── slot indices ◀──── fill_batch into shm slot views
    ready_q ◀─ (slot, stage timings) ◀────┘
    device-put thread: slot views ─▶ ctx.put_batch ─▶ device queue

Zero-copy by construction: batches have fixed (B, T, P, ...) shapes
(runtime/batch.py), so each ring slot is a preallocated columnar layout in
one ``multiprocessing.shared_memory`` segment.  Children write into numpy
views over their mapping; the parent wraps the SAME bytes as views and
hands them to ``TrainContext.put_batch`` — no pickling and no host-side
memcpy anywhere on the consumer path.  A slot is recycled only after
``jax.block_until_ready`` on the device transfer, so an in-flight H2D DMA
can never read a half-overwritten slot.

Episodes travel to the children once, as wire-codec bytes (never pickle,
matching the trust model of runtime/codec.py), and each child maintains
its own recency-biased replica store — per-batch sampling then costs the
parent nothing.  Every stage is timed (sample / assemble / free-slot wait
/ ready wait / device put / device-queue depth) and surfaced through
``stats()`` into metrics.jsonl and bench.py.
"""

from __future__ import annotations

import atexit
import multiprocessing as mp
import os
import queue as thqueue
import sys
import threading
import time
import traceback
from collections import deque
from multiprocessing import shared_memory
from typing import Any, Dict, List, Optional

import numpy as np

from . import codec
from .batch import fill_batch, make_batch
from .replay import EpisodeStore
from .trainer import PIPE_STAT_KEYS

_ALIGN = 64  # cache-line-align every leaf inside a slot


def slot_spec(template: Dict[str, Any]):
    """(nested spec, slot_bytes) for one batch.

    The spec mirrors the batch dict structure with ndarray leaves replaced
    by ``("leaf", shape, dtype_str, offset)``; containers are plain
    dict/list/tuple nodes, so the whole spec is picklable for spawn-start
    children and rebuilds identically on both sides of the fork (dict keys
    are laid out sorted, matching jax's pytree flattening order)."""
    offset = 0

    def walk(node):
        nonlocal offset
        if isinstance(node, np.ndarray):
            here = offset
            offset += (node.nbytes + _ALIGN - 1) // _ALIGN * _ALIGN
            return ("leaf", tuple(node.shape), node.dtype.str, here)
        if isinstance(node, dict):
            return ("dict", {k: walk(node[k]) for k in sorted(node)})
        if isinstance(node, (list, tuple)):
            return ("seq", isinstance(node, tuple), [walk(x) for x in node])
        raise TypeError(f"batch leaf {type(node).__name__} is not shm-mappable")

    spec = walk(template)
    return spec, max(offset, _ALIGN)


def slot_views(spec, buf, base: int):
    """Rebuild the batch dict as numpy views into ``buf`` at ``base``."""
    kind = spec[0]
    if kind == "leaf":
        _, shape, dtype_str, off = spec
        return np.ndarray(shape, dtype=np.dtype(dtype_str), buffer=buf, offset=base + off)
    if kind == "dict":
        return {k: slot_views(v, buf, base) for k, v in spec[1].items()}
    _, is_tuple, items = spec
    seq = [slot_views(s, buf, base) for s in items]
    return tuple(seq) if is_tuple else seq


def _drain_feed(feed_q, store: EpisodeStore) -> None:
    while True:
        try:
            blob = feed_q.get_nowait()
        except thqueue.Empty:
            return
        try:
            store.extend([codec.loads(blob)])
        except Exception:
            traceback.print_exc()


def _batcher_main(shm_name, spec, slot_bytes, args, local_batch, seed,
                  feed_q, free_q, ready_q, stop) -> None:
    """Child entry point: replica store -> sample -> fill shm slot.

    Runs under fork (Linux default) or spawn; everything it needs arrives
    through its arguments, and fork-inherited module state that could
    carry a held lock is re-created first.  Never touches jax arrays or
    the device — pure numpy + zlib + codec, i.e. C code that releases the
    GIL it no longer shares with the learner anyway."""
    import random

    from . import replay

    replay.reset_block_cache()
    random.seed((int(seed) & 0xFFFFFFFF) * 1_000_003 + os.getpid())
    views_by_slot: Dict[int, Dict[str, Any]] = {}
    shm = None
    try:
        # NOTE: attaching registers the segment with the resource tracker a
        # second time, but fork/spawn children share the parent's tracker
        # process, so the name is a set entry — the parent's close() path
        # unlinks and unregisters exactly once and nothing leaks
        shm = shared_memory.SharedMemory(name=shm_name)
        store = EpisodeStore(int(args["maximum_episodes"]))
        fs = args["forward_steps"]
        bs = args["burn_in_steps"]
        cs = args["compress_steps"]
        while not stop.is_set():
            _drain_feed(feed_q, store)
            t0 = time.perf_counter()
            windows: List[Dict[str, Any]] = []
            while len(windows) < local_batch:
                if stop.is_set():
                    return
                w = store.sample_window(fs, bs, cs)
                if w is None:
                    _drain_feed(feed_q, store)
                    time.sleep(0.05)
                    continue
                windows.append(w)
            t_sample = time.perf_counter() - t0

            t0 = time.perf_counter()
            slot = None
            while slot is None:
                try:
                    slot = free_q.get(timeout=0.2)
                except thqueue.Empty:
                    if stop.is_set():
                        return
                    _drain_feed(feed_q, store)
            t_free = time.perf_counter() - t0

            out = views_by_slot.get(slot)
            if out is None:
                out = views_by_slot[slot] = slot_views(spec, shm.buf, slot * slot_bytes)
            t0 = time.perf_counter()
            fill_batch(windows, args, out)
            ready_q.put((slot, t_sample, time.perf_counter() - t0, t_free))
    except Exception:
        traceback.print_exc()
        try:
            ready_q.put(("error", traceback.format_exc(limit=5)))
        except Exception:
            pass
    finally:
        views_by_slot.clear()
        if shm is not None:
            try:
                import gc

                gc.collect()  # numpy views pin shm.buf; drop them first
                shm.close()
            except BufferError:
                pass  # process exit unmaps regardless


class ShmBatchPipeline:
    """Process batchers writing into a shared-memory slot ring.

    Drop-in for trainer.BatchPipeline: same constructor signature, same
    ``start()``/``batch()`` surface, plus ``stop()`` (join children +
    unlink the segment) and ``stats()`` (per-stage cumulative timings).
    """

    mode = "shm"

    def __init__(self, args: Dict[str, Any], store: EpisodeStore, ctx,
                 stop_event: Optional[threading.Event] = None):
        self.args = args
        self.store = store
        self.ctx = ctx
        self.stop_event = stop_event or threading.Event()
        from ..parallel import local_batch_size

        self._local_batch = local_batch_size(args["batch_size"])
        self._fused = max(1, args.get("fused_steps", 1))
        # the fused device-put drains `fused` ready slots before freeing
        # any; fewer than fused+1 slots would deadlock the ring
        self._n_slots = max(int(args.get("shm_slots", 6)), self._fused + 2, 2)
        self._device_queue: thqueue.Queue = thqueue.Queue(
            maxsize=args.get("prefetch_batches", 2)
        )
        # fork shares the already-warm parent image (children need numpy +
        # this package, not a fresh interpreter); spawn is the portable
        # fallback and everything passed to the child is picklable
        method = "fork" if "fork" in mp.get_all_start_methods() else "spawn"
        self._mp = mp.get_context(method)
        self._procs: List[Any] = []
        self._feed_qs: List[Any] = []
        self._slot_views = None
        self._shm: Optional[shared_memory.SharedMemory] = None
        self._mp_stop = None
        self._started = False
        self._closed = False
        self._fallback = None
        self._lock = threading.Lock()
        self._stats: Dict[str, float] = {k: 0.0 for k in PIPE_STAT_KEYS}
        self._stats.update(batches=0.0, device_queue_depth_sum=0.0, gets=0.0)
        self._pending: deque = deque()
        self._pending_cv = threading.Condition()

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        if self._started:
            return
        self._started = True
        try:
            self._start_impl()
        except Exception:
            traceback.print_exc()
            print(
                "[handyrl_tpu] shared-memory batch pipeline failed to start "
                "(above); falling back to threaded batchers "
                "(batch_pipeline: thread)",
                file=sys.stderr,
            )
            self.close()
            from .trainer import BatchPipeline

            self._fallback = BatchPipeline(self.args, self.store, self.ctx, self.stop_event)
            self._fallback.start()

    def _sample_template_windows(self):
        windows = []
        while len(windows) < self._local_batch:
            if self.stop_event.is_set():
                return None
            w = self.store.sample_window(
                self.args["forward_steps"],
                self.args["burn_in_steps"],
                self.args["compress_steps"],
            )
            if w is None:
                time.sleep(0.2)
                continue
            windows.append(w)
        return windows

    def _start_impl(self) -> None:
        windows = self._sample_template_windows()
        if windows is None:
            return  # shutting down before any episode arrived
        # one reference batch pins the slot layout (fixed shapes) AND
        # anchors the parity contract: children produce bit-identical
        # bytes for the same windows (tests/test_shm_pipeline.py)
        template = make_batch(windows, self.args)
        self._spec, self._slot_bytes = slot_spec(template)
        self._shm = shared_memory.SharedMemory(
            create=True, size=self._slot_bytes * self._n_slots
        )
        atexit.register(self._unlink_quiet)
        self._free_q = self._mp.Queue()
        for i in range(self._n_slots):
            self._free_q.put(i)
        self._ready_q = self._mp.Queue()
        self._mp_stop = self._mp.Event()
        self._slot_views = [
            slot_views(self._spec, self._shm.buf, i * self._slot_bytes)
            for i in range(self._n_slots)
        ]
        self._spawn_children()

    def _spawn_children(self) -> None:
        # subscribe BEFORE snapshotting: an episode landing in between is
        # delivered twice (snapshot + listener) rather than lost — a
        # duplicate in a replica store only nudges sampling weights, a
        # missing one is a hole in the children's data forever
        self.store.subscribe(self._on_episodes)
        snapshot = [codec.dumps(ep) for ep in self.store.snapshot()]
        for i in range(max(1, int(self.args["num_batchers"]))):
            feed_q = self._mp.Queue()
            for blob in snapshot:
                feed_q.put(blob)
            self._feed_qs.append(feed_q)
            proc = self._mp.Process(
                target=_batcher_main,
                args=(self._shm.name, self._spec, self._slot_bytes, self.args,
                      self._local_batch, int(self.args.get("seed", 0)) + i,
                      feed_q, self._free_q, self._ready_q, self._mp_stop),
                daemon=True,
            )
            import warnings

            with warnings.catch_warnings():
                # jax warns that fork + its internal threads can deadlock;
                # these children never call into jax/XLA (pure numpy +
                # zlib + codec, and replay.reset_block_cache() re-creates
                # the one inherited lock they touch), so the general
                # warning does not apply to this fork
                warnings.filterwarnings(
                    "ignore", message="os.fork", category=RuntimeWarning
                )
                proc.start()
            self._procs.append(proc)
        threading.Thread(target=self._feeder_loop, daemon=True).start()
        threading.Thread(target=self._device_put_loop, daemon=True).start()

    def _on_episodes(self, episodes: List[Dict[str, Any]]) -> None:
        # store.extend runs on the learner's server thread — only queue a
        # reference here; the feeder thread pays for encoding
        with self._pending_cv:
            self._pending.extend(episodes)
            self._pending_cv.notify()

    def _feeder_loop(self) -> None:
        try:
            while not self.stop_event.is_set():
                with self._pending_cv:
                    if not self._pending:
                        self._pending_cv.wait(timeout=0.3)
                    batch = list(self._pending)
                    self._pending.clear()
                for episode in batch:
                    blob = codec.dumps(episode)
                    for feed_q in self._feed_qs:
                        feed_q.put(blob)
        except Exception:
            traceback.print_exc()

    # -- consumer side -------------------------------------------------------

    def _ready_get(self):
        t0 = time.perf_counter()
        while not self.stop_event.is_set():
            try:
                item = self._ready_q.get(timeout=0.3)
            except thqueue.Empty:
                continue
            if item and item[0] == "error":
                # a dead silent pipeline deadlocks the trainer — fail loudly
                print(
                    "[handyrl_tpu] batcher process died:\n" + str(item[1]),
                    file=sys.stderr,
                )
                self.stop_event.set()
                return None
            with self._lock:
                self._stats["ready_wait_s"] += time.perf_counter() - t0
            return item
        return None

    def _device_put_loop(self) -> None:
        import jax

        try:
            while not self.stop_event.is_set():
                group, slots = [], []
                while len(group) < self._fused:
                    item = self._ready_get()
                    if item is None:
                        return
                    slot, t_sample, t_assemble, t_free = item
                    with self._lock:
                        self._stats["sample_s"] += t_sample
                        self._stats["assemble_s"] += t_assemble
                        self._stats["free_wait_s"] += t_free
                    group.append(self._slot_views[slot])
                    slots.append(slot)
                t0 = time.perf_counter()
                if self._fused > 1:
                    device_batch = self.ctx.put_batches(group)
                else:
                    device_batch = self.ctx.put_batch(group[0])
                with self._lock:
                    self._stats["put_s"] += time.perf_counter() - t0
                    self._stats["batches"] += len(group)
                # hand the (possibly still-transferring) batch to the
                # trainer FIRST — its async train-step dispatch overlaps
                # the rest of the H2D copy...
                queued = self._put_device(device_batch)
                # ...but the slots recycle only after the transfer has
                # finished reading them: an in-flight DMA must never see a
                # half-overwritten slot
                t0 = time.perf_counter()
                jax.block_until_ready(device_batch)
                with self._lock:
                    self._stats["put_s"] += time.perf_counter() - t0
                for slot in slots:
                    self._free_q.put(slot)
                if not queued:
                    return
        except Exception:
            traceback.print_exc()
            self.stop_event.set()
        finally:
            self.close()

    def _put_device(self, item) -> bool:
        while not self.stop_event.is_set():
            try:
                self._device_queue.put(item, timeout=0.3)
                return True
            except thqueue.Full:
                continue
        return False

    def batch(self):
        """Next device batch, or None when shutting down."""
        if self._fallback is not None:
            return self._fallback.batch()
        with self._lock:
            self._stats["device_queue_depth_sum"] += self._device_queue.qsize()
            self._stats["gets"] += 1
        while not self.stop_event.is_set():
            try:
                return self._device_queue.get(timeout=0.3)
            except thqueue.Empty:
                continue
        return None

    # -- teardown / introspection -------------------------------------------

    def stop(self) -> None:
        self.stop_event.set()
        if self._fallback is not None:
            return
        self.close()

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
        try:
            # a dead pipeline must stop mirroring the episode stream (its
            # feeder thread is gone; the pending deque would only grow)
            self.store.unsubscribe(self._on_episodes)
        except Exception:
            pass
        if self._mp_stop is not None:
            self._mp_stop.set()
        for proc in self._procs:
            proc.join(timeout=5.0)
        for proc in self._procs:
            if proc.is_alive():
                proc.terminate()
                proc.join(timeout=2.0)
        for q in self._feed_qs + [getattr(self, "_free_q", None),
                                  getattr(self, "_ready_q", None)]:
            if q is None:
                continue
            try:
                q.cancel_join_thread()
                q.close()
            except Exception:
                pass
        self._slot_views = None
        if self._shm is not None:
            import gc

            gc.collect()  # release numpy views of shm.buf before unmapping
            try:
                self._shm.close()
            except BufferError:
                pass
            self._unlink_quiet()
        # the atexit safety net is only for pipelines that never reached
        # close(); keeping it would pin this instance (ctx/store/spec) for
        # process lifetime — bench runs build several pipelines per process
        try:
            atexit.unregister(self._unlink_quiet)
        except Exception:
            pass

    def _unlink_quiet(self) -> None:
        shm = self._shm
        if shm is None:
            return
        try:
            shm.unlink()
        except FileNotFoundError:
            pass
        except Exception:
            pass

    def stats(self) -> Dict[str, Any]:
        if self._fallback is not None:
            return self._fallback.stats()
        with self._lock:
            out = dict(self._stats)
        out["mode"] = self.mode
        return out
