"""GIL-free batch-assembly plane: batcher PROCESSES + shared-memory ring.

The threaded BatchPipeline (runtime/trainer.py) keeps every make_batch on
the learner process's GIL, where it contends with the inference engine,
the worker threads and jax dispatch — measured at 3 updates/s against 376
for the direct path on HungryGeese (BENCH_r05.json).  This module moves
assembly off the GIL entirely, the IMPALA/HandyRL decoupled-batcher
design point (reference train.py:271-401 forks num_batchers processes):

    parent                                children (num_batchers processes)
    ------                                ---------------------------------
    EpisodeStore ──codec blobs──▶ feed_q ─▶ replica EpisodeStore
                                            sample local_batch windows
    free_q[i] ────────── slot indices ────▶ fill_batch into shm slot views
    ready pipe ◀─ fixed-size records ◀────┘
    device-put thread: slot views ─▶ ctx.put_batch ─▶ device queue

    Both slot channels are designed to survive a SIGKILL'd child, which
    dies holding whatever lock it was inside:

    * Free slots travel through PER-CHILD ``mp.Queue``s (the parent deals
      recycled slots round-robin), not one shared queue — ``Queue.get``
      holds its reader lock for the whole blocking wait, so a kill almost
      always catches the victim INSIDE the lock; per-child queues mean a
      dead child can only poison itself.
    * Ready messages travel over a raw ``os.pipe`` as fixed-size structs
      (far below PIPE_BUF, so every write is kernel-atomic and LOCK-FREE).
      An ``mp.Queue`` here would wedge the survivors a different way: each
      writer's queue-feeder thread takes a shared write lock per message,
      and a kill mid-write leaves that lock dead — the survivors' feeders
      then buffer forever and nothing reaches the parent (observed as
      qsize growing while poll() stays empty).  A killed pipe writer, by
      contrast, leaves a whole record or nothing.

Zero-copy by construction: batches have fixed (B, T, P, ...) shapes
(runtime/batch.py), so each ring slot is a preallocated columnar layout in
one ``multiprocessing.shared_memory`` segment.  Children write into numpy
views over their mapping; the parent wraps the SAME bytes as views and
hands them to ``TrainContext.put_batch`` — no pickling and no host-side
memcpy anywhere on the consumer path.  A slot is recycled only after
``jax.block_until_ready`` on the device transfer, so an in-flight H2D DMA
can never read a half-overwritten slot.

Episodes travel to the children once, as wire-codec bytes (never pickle,
matching the trust model of runtime/codec.py), and each child maintains
its own recency-biased replica store — per-batch sampling then costs the
parent nothing.  Every stage is timed (sample / assemble / free-slot wait
/ ready wait / device put / device-queue depth) and surfaced through
``stats()`` into metrics.jsonl and bench.py.

Supervision (docs/fault_tolerance.md): the parent watches its children.
An OOM-killed / SIGKILL'd batcher process no longer starves the trainer
silently — the consumer loop notices the dead child, reclaims every ring
slot dealt to it (the parent stamps a shared ownership array BEFORE each
deal, so no slot is ever unattributed; a per-slot generation counter
makes any in-flight ready message for a reclaimed slot self-invalidating,
so a slot can never circulate twice), redistributes those slots to the
survivors, respawns the child up to ``batcher_max_restarts`` times, and
past that — or if the ring stays wedged for ``batcher_stall_timeout``
after a death (the narrow remaining window: a SIGKILL inside the shared
ready queue's write lock) — degrades loudly to the threaded pipeline.
Deaths, restarts and the fallback flip are counted in ``stats()`` and
land in metrics.jsonl as ``pipe_batcher_*`` events.
"""

from __future__ import annotations

import atexit
import multiprocessing as mp
import os
import queue as thqueue
import struct
import sys
import threading
import time
import traceback
from collections import deque
from multiprocessing import shared_memory
from typing import Any, Dict, List, Optional

import numpy as np

from ..utils.trace import trace_event
from . import codec
from .batch import fill_batch, make_batch
from .connection import _wait_io
from .replay import EpisodeStore
from .trainer import PIPE_EVENT_KEYS, PIPE_STAT_KEYS

_ALIGN = 64  # cache-line-align every leaf inside a slot

# one ready message: slot (-1 = "this child hit an exception and is
# exiting"), slot generation, sample/assemble/free-wait timings.  36 bytes,
# far under PIPE_BUF (>= 512 by POSIX, 4096 on Linux): os.write of a whole
# record is atomic, so records from concurrent children never interleave
# and a SIGKILL'd writer can never leave a torn record in the pipe
_READY_REC = struct.Struct("=iQddd")


def slot_spec(template: Dict[str, Any]):
    """(nested spec, slot_bytes) for one batch.

    The spec mirrors the batch dict structure with ndarray leaves replaced
    by ``("leaf", shape, dtype_str, offset)``; containers are plain
    dict/list/tuple nodes, so the whole spec is picklable for spawn-start
    children and rebuilds identically on both sides of the fork (dict keys
    are laid out sorted, matching jax's pytree flattening order)."""
    offset = 0

    def walk(node):
        nonlocal offset
        if isinstance(node, np.ndarray):
            here = offset
            offset += (node.nbytes + _ALIGN - 1) // _ALIGN * _ALIGN
            return ("leaf", tuple(node.shape), node.dtype.str, here)
        if isinstance(node, dict):
            return ("dict", {k: walk(node[k]) for k in sorted(node)})
        if isinstance(node, (list, tuple)):
            return ("seq", isinstance(node, tuple), [walk(x) for x in node])
        raise TypeError(f"batch leaf {type(node).__name__} is not shm-mappable")

    spec = walk(template)
    return spec, max(offset, _ALIGN)


def slot_views(spec, buf, base: int):
    """Rebuild the batch dict as numpy views into ``buf`` at ``base``."""
    kind = spec[0]
    if kind == "leaf":
        _, shape, dtype_str, off = spec
        return np.ndarray(shape, dtype=np.dtype(dtype_str), buffer=buf, offset=base + off)
    if kind == "dict":
        return {k: slot_views(v, buf, base) for k, v in spec[1].items()}
    _, is_tuple, items = spec
    seq = [slot_views(s, buf, base) for s in items]
    return tuple(seq) if is_tuple else seq


def _drain_feed(feed_q, store: EpisodeStore) -> None:
    while True:
        try:
            blob = feed_q.get_nowait()
        except thqueue.Empty:
            return
        try:
            store.extend([codec.loads(blob)])
        except Exception:
            traceback.print_exc()


def _batcher_main(shm_name, spec, slot_bytes, args, local_batch, seed,
                  feed_q, free_q, ready_w, stop, slot_gen) -> None:
    """Child entry point: replica store -> sample -> fill shm slot.

    Runs under fork (Linux default) or spawn; everything it needs arrives
    through its arguments, and fork-inherited module state that could
    carry a held lock is re-created first.  Never touches jax arrays or
    the device — pure numpy + zlib + codec, i.e. C code that releases the
    GIL it no longer shares with the learner anyway.

    Crash-safety protocol: ``free_q`` is this child's PRIVATE free-slot
    queue — the parent stamped ``owner[slot]`` before dealing each index
    into it, so every slot this process holds (queued or in hand) is
    attributed and reclaimable if it dies, and a kill inside the queue's
    reader lock wedges nobody else.  The child snapshots
    ``slot_gen[slot]`` at claim time and sends it with the ready message;
    reclamation bumps the generation, invalidating any message still in
    flight so a reclaimed slot can never circulate twice."""
    import random
    import signal

    from . import replay

    # fork copies the learner's SIGTERM/SIGINT drain handlers into this
    # process, where they only flip flags on a dead copy of the learner —
    # a terminate() from the parent would be swallowed and the child
    # would survive its own teardown.  Restore the default disposition
    # so this process stays killable.
    for _sig in (signal.SIGTERM, signal.SIGINT):
        try:
            signal.signal(_sig, signal.SIG_DFL)
        except (ValueError, OSError):
            pass

    replay.reset_block_cache()
    random.seed((int(seed) & 0xFFFFFFFF) * 1_000_003 + os.getpid())
    views_by_slot: Dict[int, Dict[str, Any]] = {}
    shm = None
    try:
        # NOTE: attaching registers the segment with the resource tracker a
        # second time, but fork/spawn children share the parent's tracker
        # process, so the name is a set entry — the parent's close() path
        # unlinks and unregisters exactly once and nothing leaks
        shm = shared_memory.SharedMemory(name=shm_name)
        store = EpisodeStore(int(args["maximum_episodes"]))
        fs = args["forward_steps"]
        bs = args["burn_in_steps"]
        cs = args["compress_steps"]
        while not stop.value:
            _drain_feed(feed_q, store)
            t0 = time.perf_counter()
            windows: List[Dict[str, Any]] = []
            while len(windows) < local_batch:
                if stop.value:
                    return
                w = store.sample_window(fs, bs, cs)
                if w is None:
                    _drain_feed(feed_q, store)
                    time.sleep(0.05)
                    continue
                windows.append(w)
            t_sample = time.perf_counter() - t0

            t0 = time.perf_counter()
            slot = None
            while slot is None:
                try:
                    slot = free_q.get(timeout=0.2)
                except thqueue.Empty:
                    if stop.value:
                        return
                    _drain_feed(feed_q, store)
            gen = slot_gen[slot]
            t_free = time.perf_counter() - t0

            out = views_by_slot.get(slot)
            if out is None:
                out = views_by_slot[slot] = slot_views(spec, shm.buf, slot * slot_bytes)
            t0 = time.perf_counter()
            fill_batch(windows, args, out)
            os.write(ready_w, _READY_REC.pack(
                slot, gen, t_sample, time.perf_counter() - t0, t_free
            ))
    except Exception:
        traceback.print_exc()  # full detail to stderr; the record below
        # just tells the parent this child is exiting abnormally
        try:
            os.write(ready_w, _READY_REC.pack(-1, 0, 0.0, 0.0, 0.0))
        except Exception:
            pass
    finally:
        views_by_slot.clear()
        if shm is not None:
            try:
                import gc

                gc.collect()  # numpy views pin shm.buf; drop them first
                shm.close()
            except BufferError:
                pass  # process exit unmaps regardless


class ShmBatchPipeline:
    """Process batchers writing into a shared-memory slot ring.

    Drop-in for trainer.BatchPipeline: same constructor signature, same
    ``start()``/``batch()`` surface, plus ``stop()`` (join children +
    unlink the segment) and ``stats()`` (per-stage cumulative timings +
    supervision event counters).
    """

    mode = "shm"

    def __init__(self, args: Dict[str, Any], store: EpisodeStore, ctx,
                 stop_event: Optional[threading.Event] = None):
        self.args = args
        self.store = store
        self.ctx = ctx
        self.stop_event = stop_event or threading.Event()
        from ..parallel import local_batch_size

        self._local_batch = local_batch_size(args["batch_size"])
        self._fused = max(1, args.get("fused_steps", 1))
        # the consumer double-buffers H2D transfers (one group transferring
        # while the next is drained from the ring), so up to TWO fused
        # groups' slots can be pinned in flight at once; fewer than
        # 2*fused + 1 free-able slots would stall the children exactly when
        # the overlap is supposed to keep them filling.  The clamp lives in
        # config.effective_shm_slots — validate_args checks num_batchers
        # against the same number
        from ..config import effective_shm_slots

        self._n_slots = effective_shm_slots(dict(args, fused_steps=self._fused))
        self._device_queue: thqueue.Queue = thqueue.Queue(
            maxsize=args.get("prefetch_batches", 2)
        )
        # fork shares the already-warm parent image (children need numpy +
        # this package, not a fresh interpreter); spawn is the portable
        # fallback and everything passed to the child is picklable
        method = "fork" if "fork" in mp.get_all_start_methods() else "spawn"
        self._mp = mp.get_context(method)
        self._procs: List[Any] = []
        self._feed_qs: List[Any] = []
        self._slot_views = None
        self._shm: Optional[shared_memory.SharedMemory] = None
        self._mp_stop = None
        self._started = False
        self._closed = False
        self._fallback = None
        self._lock = threading.Lock()
        self._stats: Dict[str, float] = {k: 0.0 for k in PIPE_STAT_KEYS}
        self._stats.update({k: 0.0 for k in PIPE_EVENT_KEYS})
        self._stats.update(batches=0.0, device_queue_depth_sum=0.0, gets=0.0)
        self._pending: deque = deque()
        self._pending_cv = threading.Condition()
        # supervision state (consumer-thread only, except the counters)
        self._max_restarts = int(args.get("batcher_max_restarts", 3))
        self._stall_timeout = float(args.get("batcher_stall_timeout", 60.0))
        self._restarts = 0
        self._had_death = False
        self._last_child_check = 0.0
        self._last_death = 0.0

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        if self._started:
            return
        self._started = True
        try:
            self._start_impl()
        except Exception:
            traceback.print_exc()
            print(
                "[handyrl_tpu] shared-memory batch pipeline failed to start "
                "(above); falling back to threaded batchers "
                "(batch_pipeline: thread)",
                file=sys.stderr,
            )
            self.close()
            from .trainer import BatchPipeline

            self._fallback = BatchPipeline(self.args, self.store, self.ctx, self.stop_event)
            self._fallback.start()

    def _sample_template_windows(self):
        windows = []
        while len(windows) < self._local_batch:
            if self.stop_event.is_set():
                return None
            w = self.store.sample_window(
                self.args["forward_steps"],
                self.args["burn_in_steps"],
                self.args["compress_steps"],
            )
            if w is None:
                time.sleep(0.2)
                continue
            windows.append(w)
        return windows

    def _start_impl(self) -> None:
        windows = self._sample_template_windows()
        if windows is None:
            return  # shutting down before any episode arrived
        # one reference batch pins the slot layout (fixed shapes) AND
        # anchors the parity contract: children produce bit-identical
        # bytes for the same windows (tests/test_shm_pipeline.py)
        template = make_batch(windows, self.args)
        self._spec, self._slot_bytes = slot_spec(template)
        self._shm = shared_memory.SharedMemory(
            create=True, size=self._slot_bytes * self._n_slots
        )
        atexit.register(self._unlink_quiet)
        if "fork" not in mp.get_all_start_methods():
            # the ready pipe rides fork fd inheritance; platforms without
            # fork take the (loud) threaded fallback via start()'s handler
            raise RuntimeError(
                "shm batch pipeline requires the fork start method "
                "(ready-pipe fds are fork-inherited)"
            )
        self._ready_r, self._ready_w = os.pipe()
        self._ready_buf = b""
        # lock-FREE stop flag, not mp.Event: Event.is_set() takes the
        # event's shared condition lock, and children poll the flag in
        # their hottest loop — a SIGKILL landing inside that lock would
        # wedge every surviving child forever.  A raw shared int has no
        # lock to die holding.
        self._mp_stop = self._mp.Value("i", 0, lock=False)
        # slot ownership + generation (see _batcher_main docstring for the
        # crash-safety protocol); both are lock-free because the PARENT is
        # the only writer: owner[slot] is stamped before each deal and
        # cleared on receipt, slot_gen[slot] bumps only while the slot is
        # in the parent's domain
        self._owner = self._mp.Array("i", self._n_slots, lock=False)
        self._slot_gen = self._mp.Array("L", self._n_slots, lock=False)
        for i in range(self._n_slots):
            self._owner[i] = -1
        self._deal_rr = 0
        self._orphan_slots: List[int] = []
        self._slot_views = [
            slot_views(self._spec, self._shm.buf, i * self._slot_bytes)
            for i in range(self._n_slots)
        ]
        self._spawn_children()

    def _spawn_children(self) -> None:
        # subscribe BEFORE snapshotting: an episode landing in between is
        # delivered twice (snapshot + listener) rather than lost — a
        # duplicate in a replica store only nudges sampling weights, a
        # missing one is a hole in the children's data forever
        self.store.subscribe(self._on_episodes)
        snapshot = [codec.dumps(ep) for ep in self.store.snapshot()]
        n = max(1, int(self.args["num_batchers"]))
        self._procs = [None] * n
        self._feed_qs = [None] * n
        self._free_qs = [None] * n
        for i in range(n):
            self._spawn_child(i, snapshot)
        for slot in range(self._n_slots):
            self._deal_slot(slot)
        threading.Thread(target=self._feeder_loop, daemon=True).start()
        self._consumer_thread = threading.Thread(
            target=self._device_put_loop, daemon=True
        )
        self._consumer_thread.start()

    def _spawn_child(self, i: int, snapshot: Optional[List[bytes]] = None) -> None:
        """(Re)start batcher child ``i`` with a fresh replica feed from the
        parent's authoritative store."""
        feed_q = self._mp.Queue()
        # publish BEFORE snapshotting — the respawn path runs with the
        # feeder live, and an episode arriving between the snapshot and
        # the publication would go to the dead child's orphaned queue: a
        # permanent hole in the replica.  This order can deliver such an
        # episode twice (live feed + snapshot), which replica stores
        # tolerate by design (same reasoning as subscribe-before-snapshot
        # in _spawn_children)
        self._feed_qs[i] = feed_q
        if snapshot is None:
            snapshot = [codec.dumps(ep) for ep in self.store.snapshot()]
        for blob in snapshot:
            feed_q.put(blob)
        free_q = self._mp.Queue()
        self._free_qs[i] = free_q
        proc = self._mp.Process(
            target=_batcher_main,
            args=(self._shm.name, self._spec, self._slot_bytes, self.args,
                  self._local_batch,
                  int(self.args.get("seed", 0)) + i + 7919 * self._restarts,
                  feed_q, free_q, self._ready_w, self._mp_stop,
                  self._slot_gen),
            daemon=True,
        )
        import warnings

        with warnings.catch_warnings():
            # jax warns that fork + its internal threads can deadlock;
            # these children never call into jax/XLA (pure numpy +
            # zlib + codec, and replay.reset_block_cache() re-creates
            # the one inherited lock they touch), so the general
            # warning does not apply to this fork
            warnings.filterwarnings(
                "ignore", message="os.fork", category=RuntimeWarning
            )
            proc.start()
        self._procs[i] = proc

    def _on_episodes(self, episodes: List[Dict[str, Any]]) -> None:
        # store.extend runs on the learner's server thread — only queue a
        # reference here; the feeder thread pays for encoding
        with self._pending_cv:
            self._pending.extend(episodes)
            self._pending_cv.notify()

    def _feeder_loop(self) -> None:
        try:
            while not self.stop_event.is_set():
                with self._pending_cv:
                    if not self._pending:
                        self._pending_cv.wait(timeout=0.3)
                    batch = list(self._pending)
                    self._pending.clear()
                for episode in batch:
                    blob = codec.dumps(episode)
                    for feed_q in tuple(self._feed_qs):
                        if feed_q is None:
                            continue
                        try:
                            feed_q.put(blob)
                        except Exception:
                            pass  # queue of a child being replaced; its
                            # successor reseeds from the store snapshot
        except Exception:
            traceback.print_exc()

    # -- slot dealing --------------------------------------------------------

    def _deal_slot(self, slot: int) -> None:
        """Hand a free slot to a live child's private queue (round-robin),
        stamping ownership FIRST so the slot is attributed at every
        instant it is outside the parent's hands — a child killed at any
        point can have all its slots reclaimed."""
        if self._closed or self.stop_event.is_set():
            # teardown: close() may already have closed the free queues
            # under the consumer thread retiring its in-flight slots —
            # nothing will consume the slot again, parking it is enough
            self._orphan_slots.append(slot)
            return
        n = len(self._procs)
        for off in range(n):
            i = (self._deal_rr + off) % n
            if self._procs[i] is not None:
                self._deal_rr = (i + 1) % n
                self._owner[slot] = i
                try:
                    self._free_qs[i].put(slot)
                except (ValueError, OSError):  # closed under our feet
                    self._orphan_slots.append(slot)
                return
        # every child is currently dead (between death and respawn, or
        # headed for degradation): park the slot; respawn re-deals it
        self._orphan_slots.append(slot)

    # -- supervision ---------------------------------------------------------

    def _check_children(self) -> None:
        """Reap dead batcher children: reclaim their ring slots, respawn
        within budget, degrade to the thread pipeline past it.  Runs on
        the consumer thread only (throttled)."""
        # never respawn during teardown: children exiting 0 after
        # close() set mp_stop are a NORMAL stop, and a child forked here
        # races close()'s procs snapshot — it would be neither joined nor
        # terminated, and the interpreter's multiprocessing atexit join
        # then hangs the learner's exit on it
        if self.stop_event.is_set() or self._closed:
            return
        now = time.monotonic()
        if now - self._last_child_check < 0.25 or self._fallback is not None:
            return
        self._last_child_check = now
        for i, proc in enumerate(self._procs):
            if proc is None or proc.is_alive():
                continue
            exitcode = proc.exitcode
            self._procs[i] = None
            self._had_death = True
            self._last_death = now
            with self._lock:
                self._stats["batcher_deaths"] += 1
            # reclaim every slot dealt to the dead child — queued in its
            # private free queue or claimed in its hands, all are stamped
            # with its index.  Bump the generation FIRST: any ready
            # message the dead child managed to send is now stale and will
            # be discarded, so a slot can never circulate twice.  The dead
            # child's queue is abandoned unread (its reader lock may have
            # died with it); the slots are re-dealt to the survivors.
            reclaimed = []
            for slot in range(self._n_slots):
                if self._owner[slot] == i:
                    self._owner[slot] = -1
                    self._slot_gen[slot] += 1
                    reclaimed.append(slot)
            # retire BOTH of the dead child's queues.  cancel_join_thread
            # is the critical call: the feed queue's internal feeder
            # thread can be blocked forever on a full unread pipe, and
            # multiprocessing's exit finalizer would otherwise join it —
            # hanging learner shutdown after any batcher death
            for old_q in (self._free_qs[i], self._feed_qs[i]):
                if old_q is not None:
                    try:
                        old_q.cancel_join_thread()
                        old_q.close()
                    except Exception:
                        pass
            self._free_qs[i] = None
            self._feed_qs[i] = None
            print(
                f"[handyrl_tpu] batcher process {i} died (exitcode {exitcode}); "
                f"reclaimed ring slots {reclaimed}",
                file=sys.stderr,
            )
            for slot in reclaimed:
                self._deal_slot(slot)  # survivors keep the ring flowing NOW
            if self._restarts >= self._max_restarts:
                self._degrade(
                    f"restart budget exhausted ({self._max_restarts})"
                )
                return
            self._restarts += 1
            with self._lock:
                self._stats["batcher_restarts"] += 1
            try:
                self._spawn_child(i)
                print(
                    f"[handyrl_tpu] batcher process {i} respawned "
                    f"(restart {self._restarts}/{self._max_restarts})",
                    file=sys.stderr,
                )
            except Exception:
                traceback.print_exc()
                self._degrade("batcher respawn failed")
                return
            for slot in self._orphan_slots:
                self._deal_slot(slot)
            self._orphan_slots = []

    def _degrade(self, reason: str) -> None:
        """Swap in the threaded pipeline.  Loud: a degraded assembly plane
        changes the learner's throughput profile and must be visible in
        logs AND metrics (``pipe_batcher_fallback`` flips to 1, the
        ``pipeline`` mode field flips to 'thread')."""
        print(
            f"[handyrl_tpu] shm batch pipeline degrading to threaded "
            f"batchers: {reason}",
            file=sys.stderr,
        )
        from .trainer import BatchPipeline

        fallback = BatchPipeline(self.args, self.store, self.ctx, self.stop_event)
        with self._lock:
            # carry ALL cumulative counters across the mode flip — the
            # trainer diffs stage timings per epoch, so a fresh-zeroed
            # fallback would make the degradation epoch's pipe_* records
            # go negative; the event counts must survive too
            fallback._stats.update(self._stats)
            fallback._stats["batcher_fallback"] = 1.0
        fallback.start()
        self._fallback = fallback

    # -- consumer side -------------------------------------------------------

    def _ready_next_record(self):
        """Next whole record from the ready pipe, or None after ~0.3s of
        nothing.  Writes are atomic (<= PIPE_BUF) so only READS can split
        a record — the carry buffer handles that."""
        if len(self._ready_buf) < _READY_REC.size:
            try:
                _wait_io(self._ready_r, False, time.monotonic() + 0.3)
            except TimeoutError:  # covers socket.timeout (py>=3.10 alias)
                return None
            chunk = os.read(self._ready_r, 4096)
            if not chunk:
                return None  # all writers closed (teardown)
            self._ready_buf += chunk
        if len(self._ready_buf) < _READY_REC.size:
            return None
        record = _READY_REC.unpack(self._ready_buf[: _READY_REC.size])
        self._ready_buf = self._ready_buf[_READY_REC.size:]
        return record

    def _ready_get(self):
        t0 = time.perf_counter()
        t_enter = time.monotonic()
        while not self.stop_event.is_set():
            self._check_children()
            if self._fallback is not None:
                return None
            item = self._ready_next_record()
            if item is None:
                # no shared-lock wedge mode is known to remain, but keep a
                # last-resort watchdog: after a death, zero ready traffic
                # for this long means give up on the shm plane.  The clock
                # baselines on THIS call's entry (and the death, if later):
                # time the consumer spent elsewhere — device-queue
                # backpressure, a minutes-long first jit compile — must not
                # count as ring stall, or a death coinciding with an epoch
                # boundary would spuriously and permanently degrade
                if (
                    self._had_death
                    and time.monotonic() - max(t_enter, self._last_death)
                    > self._stall_timeout
                ):
                    self._degrade(
                        f"ring stalled > {self._stall_timeout:.0f}s after a "
                        "batcher death"
                    )
                    return None
                continue
            slot, gen, t_sample, t_assemble, t_free = item
            if slot < 0:
                # the child printed its traceback and is exiting;
                # supervision reaps it (respawn or degrade) — a one-off
                # fill failure must not take down the whole training run
                print(
                    "[handyrl_tpu] a batcher process failed (traceback on "
                    "its stderr) and will be reaped",
                    file=sys.stderr,
                )
                continue
            if gen != self._slot_gen[slot]:
                continue  # stale: produced by a child that died; the slot
                # was already reclaimed and may be refilling right now
            self._owner[slot] = -1
            self._had_death = False  # ring proved itself post-death: disarm
            wait = time.perf_counter() - t0
            with self._lock:
                self._stats["ready_wait_s"] += wait
            trace_event("pipe.ready_wait", wait, plane="pipeline", mode="shm")
            return slot, t_sample, t_assemble, t_free
        return None

    def _device_put_loop(self) -> None:
        import jax

        # Transfers IN FLIGHT: a group's slots recycle only after ITS
        # transfer completes (an in-flight DMA must never see a
        # half-overwritten slot), but the consumer no longer parks the
        # whole ring on that completion.  The old synchronous
        # block_until_ready here was what serialized the multi-batcher
        # plane: every child funnelled through one consumer that spent the
        # H2D time neither draining ready records nor recycling slots, so
        # past one child the extra fills just queued behind it.  Depth 2
        # (one group transferring while the next is drained + dispatched)
        # is the classic double buffer; _n_slots is clamped to 2*fused + 2
        # so the ring always has a dealable slot with two groups pinned.
        inflight: deque = deque()

        def retire_oldest() -> None:
            device_batch, done_slots = inflight.popleft()
            t0 = time.perf_counter()
            jax.block_until_ready(device_batch)
            with self._lock:
                self._stats["put_s"] += time.perf_counter() - t0
            for slot in done_slots:
                self._slot_gen[slot] += 1
                self._deal_slot(slot)

        try:
            while not self.stop_event.is_set():
                group, slots = [], []
                while len(group) < self._fused:
                    item = self._ready_get()
                    if item is None:
                        # shutdown OR degradation: recycle this partial
                        # group's slots so close() finds a consistent ring
                        for slot in slots:
                            self._slot_gen[slot] += 1
                            self._deal_slot(slot)
                        return
                    slot, t_sample, t_assemble, t_free = item
                    with self._lock:
                        self._stats["sample_s"] += t_sample
                        self._stats["assemble_s"] += t_assemble
                        self._stats["free_wait_s"] += t_free
                    group.append(self._slot_views[slot])
                    slots.append(slot)
                t0 = time.perf_counter()
                if self._fused > 1:
                    device_batch = self.ctx.put_batches(group)
                else:
                    device_batch = self.ctx.put_batch(group[0])
                with self._lock:
                    self._stats["put_s"] += time.perf_counter() - t0
                    self._stats["batches"] += len(group)
                # hand the (possibly still-transferring) batch to the
                # trainer FIRST — its async train-step dispatch overlaps
                # the rest of the H2D copy
                queued = self._put_device(device_batch)
                inflight.append((device_batch, slots))
                while len(inflight) > 1:
                    retire_oldest()
                if not queued:
                    return
        except Exception:
            traceback.print_exc()
            self.stop_event.set()
        finally:
            # settle every outstanding transfer (recycling its slots) so
            # close() — and a degradation's thread fallback — find a
            # consistent ring
            try:
                while inflight:
                    retire_oldest()
            except Exception:
                pass
            # degradation keeps the learner alive on the thread pipeline;
            # the shm plane itself still tears down completely
            self.close()

    def _put_device(self, item) -> bool:
        while not self.stop_event.is_set():
            try:
                self._device_queue.put(item, timeout=0.3)
                return True
            except thqueue.Full:
                # a full device queue parks the consumer thread HERE, not
                # in _ready_get — keep supervising or a child death would
                # go unnoticed until the trainer drains a batch
                self._check_children()
                if self._fallback is not None:
                    return False  # degraded: nobody drains this queue now
                continue
        return False

    def batch(self):
        """Next device batch, or None when shutting down."""
        if self._fallback is not None:
            return self._fallback.batch()
        with self._lock:
            self._stats["device_queue_depth_sum"] += self._device_queue.qsize()
            self._stats["gets"] += 1
        while not self.stop_event.is_set():
            if self._fallback is not None:
                # degraded mid-wait: the device queue will never fill again
                return self._fallback.batch()
            try:
                return self._device_queue.get(timeout=0.3)
            except thqueue.Empty:
                continue
        return None

    # -- teardown / introspection -------------------------------------------

    def stop(self) -> None:
        self.stop_event.set()
        self.close()

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
        try:
            # a dead pipeline must stop mirroring the episode stream (its
            # feeder thread is gone; the pending deque would only grow) —
            # the fallback BatchPipeline samples the store directly
            self.store.unsubscribe(self._on_episodes)
        except Exception:
            pass
        if self._mp_stop is not None:
            self._mp_stop.value = 1
        procs = [p for p in self._procs if p is not None]
        for proc in procs:
            proc.join(timeout=5.0)
        for proc in procs:
            if proc.is_alive():
                proc.terminate()
                proc.join(timeout=2.0)
        for q in (
            [q for q in self._feed_qs if q is not None]
            + [q for q in getattr(self, "_free_qs", []) if q is not None]
        ):
            try:
                q.cancel_join_thread()
                q.close()
            except Exception:
                pass
        # the consumer thread polls/reads the ready fds: join it (unless
        # close() IS running on it, via _device_put_loop's finally) before
        # closing them — a reused fd number would otherwise let os.read
        # consume bytes from an unrelated descriptor
        consumer = getattr(self, "_consumer_thread", None)
        if consumer is not None and consumer is not threading.current_thread():
            consumer.join(timeout=5.0)
        for fd in (getattr(self, "_ready_r", None), getattr(self, "_ready_w", None)):
            if fd is not None:
                try:
                    os.close(fd)
                except OSError:
                    pass
        self._ready_r = self._ready_w = None
        self._slot_views = None
        if self._shm is not None:
            import gc

            gc.collect()  # release numpy views of shm.buf before unmapping
            try:
                self._shm.close()
            except BufferError:
                pass
            self._unlink_quiet()
        # the atexit safety net is only for pipelines that never reached
        # close(); keeping it would pin this instance (ctx/store/spec) for
        # process lifetime — bench runs build several pipelines per process
        try:
            atexit.unregister(self._unlink_quiet)
        except Exception:
            pass

    def _unlink_quiet(self) -> None:
        shm = self._shm
        if shm is None:
            return
        try:
            shm.unlink()
        except FileNotFoundError:
            pass
        except Exception:
            pass

    def stats(self) -> Dict[str, Any]:
        if self._fallback is not None:
            return self._fallback.stats()
        with self._lock:
            out = dict(self._stats)
        out["mode"] = self.mode
        return out
