/* C accelerator for the pickle-free wire codec (runtime/codec.py).
 *
 * Byte-for-byte the same format as the pure-Python encoder — one tag byte
 * per value, big-endian fixed-width lengths, raw C-contiguous array
 * buffers.  The win is the per-small-object overhead (struct.pack, list
 * appends, Python recursion), which dominates episode blocks: arrays were
 * already memcpy-bound.  numpy is driven through cached Python callables
 * (ascontiguousarray / frombuffer / dtype), so this file needs no numpy
 * C-API and is insensitive to its ABI.
 *
 * The module is compiled on first import by runtime/_codec_build.py with
 * plain cc -O2 -shared; codec.py falls back to the Python implementation
 * whenever the build or import fails.  codec.init(CodecError, numpy) must
 * be called before use (codec.py does).
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdint.h>
#include <string.h>

/* shared with the pure-Python encoder (codec.py _MAX_DEPTH): both
 * implementations must accept and reject the same nesting, or a frame
 * encoded on an accelerated host would kill decode on a fallback host */
#define MAX_DEPTH 500

static PyObject *CodecError;       /* class from codec.py */
static PyObject *np_ndarray;       /* numpy.ndarray */
static PyObject *np_scalar_types;  /* (np.bool_, np.integer, np.floating) */
static PyObject *np_ascontiguous;  /* numpy.ascontiguousarray */
static PyObject *np_frombuffer;    /* numpy.frombuffer */
static PyObject *np_dtype;         /* numpy.dtype */

/* ---------------- growing output buffer ---------------- */

typedef struct {
    char *buf;
    Py_ssize_t len, cap;
} Out;

static int out_ensure(Out *o, Py_ssize_t extra) {
    if (o->len + extra <= o->cap) return 0;
    Py_ssize_t cap = o->cap ? o->cap : 256;
    while (cap < o->len + extra) cap *= 2;
    char *nb = PyMem_Realloc(o->buf, cap);
    if (!nb) { PyErr_NoMemory(); return -1; }
    o->buf = nb;
    o->cap = cap;
    return 0;
}

static int out_raw(Out *o, const void *p, Py_ssize_t n) {
    if (out_ensure(o, n) < 0) return -1;
    memcpy(o->buf + o->len, p, n);
    o->len += n;
    return 0;
}

static int out_byte(Out *o, char c) { return out_raw(o, &c, 1); }

static int out_u32(Out *o, uint32_t v) {
    unsigned char b[4] = {(unsigned char)(v >> 24), (unsigned char)(v >> 16),
                          (unsigned char)(v >> 8), (unsigned char)v};
    return out_raw(o, b, 4);
}

static int out_u64be(Out *o, uint64_t v) {
    unsigned char b[8];
    for (int i = 0; i < 8; i++) b[i] = (unsigned char)(v >> (56 - 8 * i));
    return out_raw(o, b, 8);
}

/* ---------------- encode ---------------- */

static int enc(PyObject *obj, Out *o, int depth);

static int enc_len_u32(Out *o, Py_ssize_t n) {
    if (n < 0 || n > 0xFFFFFFFFLL) {
        PyErr_Format(CodecError, "length %zd out of u32 range", n);
        return -1;
    }
    return out_u32(o, (uint32_t)n);
}

static int enc_ndarray(PyObject *obj, Out *o) {
    PyObject *dtype = PyObject_GetAttrString(obj, "dtype");
    if (!dtype) return -1;
    PyObject *hasobj = PyObject_GetAttrString(dtype, "hasobject");
    Py_DECREF(dtype);
    if (!hasobj) return -1;
    int is_obj = PyObject_IsTrue(hasobj);
    Py_DECREF(hasobj);
    if (is_obj < 0) return -1;
    if (is_obj) {
        PyErr_SetString(CodecError, "object-dtype arrays are not wire-encodable");
        return -1;
    }
    /* shape BEFORE ascontiguousarray (which promotes 0-d to 1-d) */
    PyObject *shape = PyObject_GetAttrString(obj, "shape");
    if (!shape) return -1;
    PyObject *arr = PyObject_CallFunctionObjArgs(np_ascontiguous, obj, NULL);
    if (!arr) { Py_DECREF(shape); return -1; }
    PyObject *adt = PyObject_GetAttrString(arr, "dtype");
    PyObject *dts = adt ? PyObject_GetAttrString(adt, "str") : NULL;
    Py_XDECREF(adt);
    PyObject *dtb = dts ? PyUnicode_AsASCIIString(dts) : NULL;
    Py_XDECREF(dts);
    PyObject *raw = dtb ? PyObject_CallMethod(arr, "tobytes", NULL) : NULL;
    Py_DECREF(arr);
    int rc = -1;
    if (raw) {
        Py_ssize_t ndim = PyTuple_GET_SIZE(shape);
        if (out_byte(o, 'a') == 0 &&
            enc_len_u32(o, PyBytes_GET_SIZE(dtb)) == 0 &&
            out_raw(o, PyBytes_AS_STRING(dtb), PyBytes_GET_SIZE(dtb)) == 0 &&
            enc_len_u32(o, ndim) == 0) {
            rc = 0;
            for (Py_ssize_t i = 0; i < ndim && rc == 0; i++) {
                Py_ssize_t d = PyLong_AsSsize_t(PyTuple_GET_ITEM(shape, i));
                if (d == -1 && PyErr_Occurred()) rc = -1;
                else rc = enc_len_u32(o, d);
            }
            if (rc == 0 &&
                (enc_len_u32(o, PyBytes_GET_SIZE(raw)) < 0 ||
                 out_raw(o, PyBytes_AS_STRING(raw), PyBytes_GET_SIZE(raw)) < 0))
                rc = -1;
        }
    }
    Py_DECREF(shape);
    Py_XDECREF(dtb);
    Py_XDECREF(raw);
    return rc;
}

static int enc(PyObject *obj, Out *o, int depth) {
    if (depth > MAX_DEPTH) {
        PyErr_SetString(CodecError, "nesting too deep");
        return -1;
    }
    if (obj == Py_None) return out_byte(o, 'N');
    if (obj == Py_True) return out_byte(o, 'T');
    if (obj == Py_False) return out_byte(o, 'F');
    if (PyLong_Check(obj)) {
        int overflow = 0;
        long long v = PyLong_AsLongLongAndOverflow(obj, &overflow);
        if (overflow || (v == -1 && PyErr_Occurred())) {
            PyErr_Clear();
            PyErr_Format(CodecError, "int out of i64 range: %R", obj);
            return -1;
        }
        if (out_byte(o, 'i') < 0) return -1;
        return out_u64be(o, (uint64_t)(int64_t)v);
    }
    if (PyFloat_Check(obj)) {
        double d = PyFloat_AS_DOUBLE(obj);
        uint64_t bits;
        memcpy(&bits, &d, 8);
        if (out_byte(o, 'f') < 0) return -1;
        return out_u64be(o, bits);
    }
    if (PyUnicode_Check(obj)) {
        Py_ssize_t n;
        const char *s = PyUnicode_AsUTF8AndSize(obj, &n);
        if (!s) return -1;
        if (out_byte(o, 's') < 0 || enc_len_u32(o, n) < 0) return -1;
        return out_raw(o, s, n);
    }
    if (PyBytes_Check(obj)) {
        if (out_byte(o, 'b') < 0 || enc_len_u32(o, PyBytes_GET_SIZE(obj)) < 0)
            return -1;
        return out_raw(o, PyBytes_AS_STRING(obj), PyBytes_GET_SIZE(obj));
    }
    if (PyByteArray_Check(obj) || PyMemoryView_Check(obj)) {
        PyObject *b = PyBytes_FromObject(obj);
        if (!b) return -1;
        int rc = (out_byte(o, 'b') == 0 &&
                  enc_len_u32(o, PyBytes_GET_SIZE(b)) == 0 &&
                  out_raw(o, PyBytes_AS_STRING(b), PyBytes_GET_SIZE(b)) == 0)
                     ? 0 : -1;
        Py_DECREF(b);
        return rc;
    }
    int is_arr = PyObject_IsInstance(obj, np_ndarray);
    if (is_arr < 0) return -1;
    if (is_arr) return enc_ndarray(obj, o);
    int is_sc = PyObject_IsInstance(obj, np_scalar_types);
    if (is_sc < 0) return -1;
    if (is_sc) {
        PyObject *item = PyObject_CallMethod(obj, "item", NULL);
        if (!item) return -1;
        int rc = enc(item, o, depth + 1);
        Py_DECREF(item);
        return rc;
    }
    if (PyList_Check(obj)) {
        Py_ssize_t n = PyList_GET_SIZE(obj);
        if (out_byte(o, 'l') < 0 || enc_len_u32(o, n) < 0) return -1;
        for (Py_ssize_t i = 0; i < n; i++) {
            /* enc() can call back into Python (numpy, .item()), which can
               release the GIL or run GC; a concurrent mutation of the
               list would leave a borrowed pointer dangling — hold a
               strong ref across the recursive call.  Bounds re-checked:
               a shrink during a callback must not read past the end. */
            if (i >= PyList_GET_SIZE(obj)) {
                PyErr_SetString(CodecError, "list mutated during encode");
                return -1;
            }
            PyObject *item = PyList_GET_ITEM(obj, i);
            Py_INCREF(item);
            int rc = enc(item, o, depth + 1);
            Py_DECREF(item);
            if (rc < 0) return -1;
        }
        return 0;
    }
    if (PyTuple_Check(obj)) {
        Py_ssize_t n = PyTuple_GET_SIZE(obj);
        if (out_byte(o, 't') < 0 || enc_len_u32(o, n) < 0) return -1;
        for (Py_ssize_t i = 0; i < n; i++)
            if (enc(PyTuple_GET_ITEM(obj, i), o, depth + 1) < 0) return -1;
        return 0;
    }
    if (PyDict_Check(obj)) {
        /* snapshot items (strong refs) before encoding: PyDict_Next's
           cursor is invalidated by concurrent mutation during Python
           callbacks — the snapshot turns that into consistent output
           (like Python's items()) instead of undefined behavior */
        PyObject *items = PyDict_Items(obj);
        if (!items) return -1;
        Py_ssize_t n = PyList_GET_SIZE(items);
        if (out_byte(o, 'd') < 0 || enc_len_u32(o, n) < 0) {
            Py_DECREF(items);
            return -1;
        }
        for (Py_ssize_t i = 0; i < n; i++) {
            PyObject *kv = PyList_GET_ITEM(items, i);
            if (enc(PyTuple_GET_ITEM(kv, 0), o, depth + 1) < 0 ||
                enc(PyTuple_GET_ITEM(kv, 1), o, depth + 1) < 0) {
                Py_DECREF(items);
                return -1;
            }
        }
        Py_DECREF(items);
        return 0;
    }
    PyErr_Format(CodecError, "type %s is not wire-encodable",
                 Py_TYPE(obj)->tp_name);
    return -1;
}

static PyObject *c_dumps(PyObject *self, PyObject *obj) {
    Out o = {NULL, 0, 0};
    if (enc(obj, &o, 0) < 0) {
        PyMem_Free(o.buf);
        return NULL;
    }
    PyObject *res = PyBytes_FromStringAndSize(o.buf, o.len);
    PyMem_Free(o.buf);
    return res;
}

/* ---------------- decode ---------------- */

typedef struct {
    const unsigned char *p;
    Py_ssize_t len, pos;
} In;

static int in_take(In *r, Py_ssize_t n, const unsigned char **out) {
    if (r->pos + n > r->len) {
        PyErr_SetString(CodecError, "truncated message");
        return -1;
    }
    *out = r->p + r->pos;
    r->pos += n;
    return 0;
}

static int in_u32(In *r, uint32_t *v) {
    const unsigned char *b;
    if (in_take(r, 4, &b) < 0) return -1;
    *v = ((uint32_t)b[0] << 24) | ((uint32_t)b[1] << 16) |
         ((uint32_t)b[2] << 8) | (uint32_t)b[3];
    return 0;
}

static uint64_t rd_u64be(const unsigned char *b) {
    uint64_t v = 0;
    for (int i = 0; i < 8; i++) v = (v << 8) | b[i];
    return v;
}

static PyObject *dec(In *r, int depth) {
    if (depth > MAX_DEPTH) {
        PyErr_SetString(CodecError, "nesting too deep");
        return NULL;
    }
    const unsigned char *b;
    if (in_take(r, 1, &b) < 0) return NULL;
    switch (*b) {
    case 'N': Py_RETURN_NONE;
    case 'T': Py_RETURN_TRUE;
    case 'F': Py_RETURN_FALSE;
    case 'i': {
        if (in_take(r, 8, &b) < 0) return NULL;
        return PyLong_FromLongLong((long long)(int64_t)rd_u64be(b));
    }
    case 'f': {
        if (in_take(r, 8, &b) < 0) return NULL;
        uint64_t bits = rd_u64be(b);
        double d;
        memcpy(&d, &bits, 8);
        return PyFloat_FromDouble(d);
    }
    case 's': {
        uint32_t n;
        if (in_u32(r, &n) < 0 || in_take(r, n, &b) < 0) return NULL;
        return PyUnicode_DecodeUTF8((const char *)b, n, NULL);
    }
    case 'b': {
        uint32_t n;
        if (in_u32(r, &n) < 0 || in_take(r, n, &b) < 0) return NULL;
        return PyBytes_FromStringAndSize((const char *)b, n);
    }
    case 'a': {
        uint32_t dtn, ndim, rawn;
        const unsigned char *dtb;
        if (in_u32(r, &dtn) < 0 || in_take(r, dtn, &dtb) < 0) return NULL;
        PyObject *dts = PyUnicode_DecodeASCII((const char *)dtb, dtn, NULL);
        if (!dts) return NULL;
        PyObject *dtype = PyObject_CallFunctionObjArgs(np_dtype, dts, NULL);
        Py_DECREF(dts);
        if (!dtype) return NULL;
        if (in_u32(r, &ndim) < 0) { Py_DECREF(dtype); return NULL; }
        if (ndim > 64) {  /* numpy caps at 64 dims; a hostile header must not
                             allocate an absurd tuple */
            Py_DECREF(dtype);
            PyErr_SetString(CodecError, "array rank out of range");
            return NULL;
        }
        PyObject *shape = PyTuple_New(ndim);
        if (!shape) { Py_DECREF(dtype); return NULL; }
        for (uint32_t i = 0; i < ndim; i++) {
            uint32_t d;
            if (in_u32(r, &d) < 0) { Py_DECREF(dtype); Py_DECREF(shape); return NULL; }
            PyObject *di = PyLong_FromUnsignedLong(d);
            if (!di) { Py_DECREF(dtype); Py_DECREF(shape); return NULL; }
            PyTuple_SET_ITEM(shape, i, di);
        }
        if (in_u32(r, &rawn) < 0 || in_take(r, rawn, &b) < 0) {
            Py_DECREF(dtype); Py_DECREF(shape); return NULL;
        }
        PyObject *mem = PyMemoryView_FromMemory((char *)b, rawn, PyBUF_READ);
        PyObject *flat = mem
            ? PyObject_CallFunctionObjArgs(np_frombuffer, mem, dtype, NULL)
            : NULL;
        Py_XDECREF(mem);
        Py_DECREF(dtype);
        /* "(O)" forces a 1-tuple: a bare "O" would SPREAD the shape tuple
           into positional args (reshape() with 0 args for 0-d arrays) */
        PyObject *shaped = flat ? PyObject_CallMethod(flat, "reshape", "(O)", shape) : NULL;
        Py_XDECREF(flat);
        Py_DECREF(shape);
        PyObject *copied = shaped ? PyObject_CallMethod(shaped, "copy", NULL) : NULL;
        Py_XDECREF(shaped);
        return copied;  /* copy detaches from the input buffer's memory */
    }
    case 'l': {
        uint32_t n;
        if (in_u32(r, &n) < 0) return NULL;
        PyObject *lst = PyList_New(0);
        if (!lst) return NULL;
        for (uint32_t i = 0; i < n; i++) {
            PyObject *item = dec(r, depth + 1);
            if (!item || PyList_Append(lst, item) < 0) {
                Py_XDECREF(item); Py_DECREF(lst); return NULL;
            }
            Py_DECREF(item);
        }
        return lst;
    }
    case 't': {
        uint32_t n;
        if (in_u32(r, &n) < 0) return NULL;
        /* build as list first: a hostile count must not preallocate */
        PyObject *lst = PyList_New(0);
        if (!lst) return NULL;
        for (uint32_t i = 0; i < n; i++) {
            PyObject *item = dec(r, depth + 1);
            if (!item || PyList_Append(lst, item) < 0) {
                Py_XDECREF(item); Py_DECREF(lst); return NULL;
            }
            Py_DECREF(item);
        }
        PyObject *tup = PyList_AsTuple(lst);
        Py_DECREF(lst);
        return tup;
    }
    case 'd': {
        uint32_t n;
        if (in_u32(r, &n) < 0) return NULL;
        PyObject *dct = PyDict_New();
        if (!dct) return NULL;
        for (uint32_t i = 0; i < n; i++) {
            PyObject *key = dec(r, depth + 1);
            PyObject *val = key ? dec(r, depth + 1) : NULL;
            if (!val || PyDict_SetItem(dct, key, val) < 0) {
                Py_XDECREF(key); Py_XDECREF(val); Py_DECREF(dct); return NULL;
            }
            Py_DECREF(key);
            Py_DECREF(val);
        }
        return dct;
    }
    default:
        PyErr_Format(CodecError, "unknown tag %c", *b);
        return NULL;
    }
}

static PyObject *c_loads(PyObject *self, PyObject *arg) {
    PyObject *buf = PyBytes_Check(arg) ? Py_NewRef(arg) : PyBytes_FromObject(arg);
    if (!buf) return NULL;
    In r = {(const unsigned char *)PyBytes_AS_STRING(buf),
            PyBytes_GET_SIZE(buf), 0};
    PyObject *obj = dec(&r, 0);
    if (obj && r.pos != r.len) {
        Py_DECREF(obj);
        obj = NULL;
        PyErr_SetString(CodecError, "trailing bytes after message");
    }
    if (!obj && !PyErr_ExceptionMatches(CodecError)
        && PyErr_ExceptionMatches(PyExc_Exception)) {
        /* mirror codec.loads exactly: any non-CodecError EXCEPTION
           (np.dtype on junk, reshape size mismatch, utf-8 errors,
           unhashable keys) becomes CodecError so connection loops drop
           the peer instead of dying — but KeyboardInterrupt/SystemExit
           (BaseException) propagate, same as the Python implementation */
        PyObject *t, *v, *tb;
        PyErr_Fetch(&t, &v, &tb);
        PyErr_NormalizeException(&t, &v, &tb);
        PyErr_Format(CodecError, "malformed frame: %s: %S",
                     t ? ((PyTypeObject *)t)->tp_name : "Error",
                     v ? v : Py_None);
        Py_XDECREF(t); Py_XDECREF(v); Py_XDECREF(tb);
    }
    Py_DECREF(buf);
    return obj;
}

/* ---------------- columnar batch fill ----------------
 *
 * Hot path of runtime/batch.py make_batch: each sampled window writes its
 * per-key arrays into a (B, T, ...) output at [b, lo:lo+rows].  For a
 * C-contiguous destination that region is one contiguous byte range, so
 * the whole fancy-indexed numpy assignment (ufunc dispatch, broadcasting
 * machinery, per-call allocation) collapses to a bounds-checked memcpy —
 * fill_column does a whole per-key column (all windows) in one call, and
 * fill_rows broadcasts the value-frozen-at-outcome row.  Python-side
 * (batch.py) pre-checks dtype equality and falls back to numpy on any
 * mismatch; these functions still validate shapes, bounds and itemsize
 * so a buggy caller gets ValueError, never memory corruption.  Buffer
 * protocol only — no numpy C-API, same as the codec.
 */

static Py_ssize_t row_bytes_of(const Py_buffer *b, int from) {
    Py_ssize_t n = b->itemsize;
    for (int i = from; i < b->ndim; i++) n *= b->shape[i];
    return n;
}

static int fmt_equal(const char *a, const char *b) {
    /* NULL format means "B" (unsigned bytes) per the buffer protocol */
    if (!a) a = "B";
    if (!b) b = "B";
    return strcmp(a, b) == 0;
}

static PyObject *c_fill_rows(PyObject *self, PyObject *args) {
    /* broadcast one row (shape == dst.shape[2:]) into dst[b, lo:hi] —
     * the "value frozen at the outcome past episode end" write */
    PyObject *dsto, *rowo;
    Py_ssize_t b, lo, hi;
    if (!PyArg_ParseTuple(args, "OnnnO", &dsto, &b, &lo, &hi, &rowo)) return NULL;
    Py_buffer db, sb;
    if (PyObject_GetBuffer(dsto, &db,
                           PyBUF_WRITABLE | PyBUF_C_CONTIGUOUS | PyBUF_FORMAT) < 0)
        return NULL;
    if (PyObject_GetBuffer(rowo, &sb, PyBUF_C_CONTIGUOUS | PyBUF_FORMAT) < 0) {
        PyBuffer_Release(&db);
        return NULL;
    }
    int ok = db.ndim >= 2 && sb.ndim == db.ndim - 2 &&
             db.itemsize == sb.itemsize && fmt_equal(db.format, sb.format);
    for (int i = 0; ok && i < sb.ndim; i++) ok = sb.shape[i] == db.shape[i + 2];
    ok = ok && b >= 0 && b < db.shape[0] && lo >= 0 && hi >= lo && hi <= db.shape[1];
    if (!ok) {
        PyBuffer_Release(&db);
        PyBuffer_Release(&sb);
        PyErr_SetString(PyExc_ValueError,
                        "fill_rows: dst/row shape, dtype or bounds mismatch");
        return NULL;
    }
    Py_ssize_t rb = row_bytes_of(&db, 2);
    char *p = (char *)db.buf + (size_t)(b * db.shape[1] + lo) * (size_t)rb;
    Py_BEGIN_ALLOW_THREADS
    for (Py_ssize_t r = lo; r < hi; r++, p += rb)
        memcpy(p, sb.buf, (size_t)rb);
    Py_END_ALLOW_THREADS
    PyBuffer_Release(&db);
    PyBuffer_Release(&sb);
    Py_RETURN_NONE;
}

static PyObject *c_fill_column(PyObject *self, PyObject *args) {
    /* fill_column(dst, los, srcs): dst[b, los[b]:los[b]+len(srcs[b])] =
     * srcs[b] for every b — the whole per-key column of a batch in ONE
     * call.  Acquiring the destination buffer once and looping the
     * windows in C is what beats numpy here: per-item buffer-protocol
     * acquisitions cost more on large columns than the fancy-index
     * assignment they replace.  Two phases: validate + acquire every
     * source with the GIL held (shape, bounds, itemsize AND format — a
     * same-width different dtype must raise, never be bit-reinterpreted),
     * then run all memcpys with the GIL RELEASED, so multi-megabyte
     * column copies never stall the learner's other threads. */
    PyObject *dsto, *los, *srcs;
    if (!PyArg_ParseTuple(args, "OOO", &dsto, &los, &srcs)) return NULL;
    Py_buffer db;
    if (PyObject_GetBuffer(dsto, &db,
                           PyBUF_WRITABLE | PyBUF_C_CONTIGUOUS | PyBUF_FORMAT) < 0)
        return NULL;
    PyObject *lof = PySequence_Fast(los, "fill_column: los not a sequence");
    PyObject *srf = lof ? PySequence_Fast(srcs, "fill_column: srcs not a sequence") : NULL;
    if (!srf) {
        Py_XDECREF(lof);
        PyBuffer_Release(&db);
        return NULL;
    }
    Py_ssize_t n = PySequence_Fast_GET_SIZE(lof);
    int ok = db.ndim >= 2 && n == PySequence_Fast_GET_SIZE(srf) && n <= db.shape[0];
    Py_buffer *sbs = NULL;
    Py_ssize_t *offs = NULL;
    Py_ssize_t acquired = 0;
    if (ok && n > 0) {
        sbs = PyMem_Malloc((size_t)n * sizeof(Py_buffer));
        offs = PyMem_Malloc((size_t)n * sizeof(Py_ssize_t));
        if (!sbs || !offs) {
            PyMem_Free(sbs);
            PyMem_Free(offs);
            Py_DECREF(lof);
            Py_DECREF(srf);
            PyBuffer_Release(&db);
            return PyErr_NoMemory();
        }
    }
    Py_ssize_t rb = row_bytes_of(&db, 2);
    for (Py_ssize_t b = 0; ok && b < n; b++) {
        Py_ssize_t lo = PyLong_AsSsize_t(PySequence_Fast_GET_ITEM(lof, b));
        if (lo == -1 && PyErr_Occurred()) { ok = 0; break; }
        Py_buffer *sb = &sbs[b];
        if (PyObject_GetBuffer(PySequence_Fast_GET_ITEM(srf, b), sb,
                               PyBUF_C_CONTIGUOUS | PyBUF_FORMAT) < 0) { ok = 0; break; }
        acquired = b + 1;
        int good = sb->ndim == db.ndim - 1 && sb->itemsize == db.itemsize &&
                   fmt_equal(db.format, sb->format);
        for (int i = 1; good && i < sb->ndim; i++)
            good = sb->shape[i] == db.shape[i + 1];
        good = good && lo >= 0 && sb->shape[0] <= db.shape[1] - lo;
        offs[b] = (b * db.shape[1] + lo) * rb;
        ok = good;
    }
    if (ok && n > 0 && rb > 0) {
        Py_BEGIN_ALLOW_THREADS
        for (Py_ssize_t b = 0; b < n; b++)
            if (sbs[b].len > 0)
                memcpy((char *)db.buf + (size_t)offs[b], sbs[b].buf,
                       (size_t)sbs[b].len);
        Py_END_ALLOW_THREADS
    }
    for (Py_ssize_t b = 0; b < acquired; b++)
        PyBuffer_Release(&sbs[b]);
    PyMem_Free(sbs);
    PyMem_Free(offs);
    Py_DECREF(lof);
    Py_DECREF(srf);
    PyBuffer_Release(&db);
    if (!ok) {
        if (!PyErr_Occurred())
            PyErr_SetString(PyExc_ValueError,
                            "fill_column: dst/src shape, dtype or bounds mismatch");
        return NULL;
    }
    Py_RETURN_NONE;
}

/* ---------------- module ---------------- */

static PyObject *c_init(PyObject *self, PyObject *args) {
    PyObject *err, *np;
    if (!PyArg_ParseTuple(args, "OO", &err, &np)) return NULL;
    Py_XDECREF(CodecError);
    CodecError = Py_NewRef(err);
#define GRAB(dst, name)                                   \
    do {                                                  \
        Py_XDECREF(dst);                                  \
        dst = PyObject_GetAttrString(np, name);           \
        if (!dst) return NULL;                            \
    } while (0)
    GRAB(np_ndarray, "ndarray");
    GRAB(np_ascontiguous, "ascontiguousarray");
    GRAB(np_frombuffer, "frombuffer");
    GRAB(np_dtype, "dtype");
#undef GRAB
    PyObject *b = PyObject_GetAttrString(np, "bool_");
    PyObject *i = PyObject_GetAttrString(np, "integer");
    PyObject *f = PyObject_GetAttrString(np, "floating");
    if (!b || !i || !f) { Py_XDECREF(b); Py_XDECREF(i); Py_XDECREF(f); return NULL; }
    Py_XDECREF(np_scalar_types);
    np_scalar_types = PyTuple_Pack(3, b, i, f);
    Py_DECREF(b); Py_DECREF(i); Py_DECREF(f);
    if (!np_scalar_types) return NULL;
    Py_RETURN_NONE;
}

static PyMethodDef methods[] = {
    {"init", c_init, METH_VARARGS,
     "init(CodecError, numpy) — bind the error class and numpy callables"},
    {"dumps", c_dumps, METH_O, "encode to wire bytes"},
    {"loads", c_loads, METH_O, "decode wire bytes"},
    {"fill_rows", c_fill_rows, METH_VARARGS,
     "fill_rows(dst, b, lo, hi, row) — broadcast row into dst[b, lo:hi]"},
    {"fill_column", c_fill_column, METH_VARARGS,
     "fill_column(dst, los, srcs) — dst[b, los[b]:...] = srcs[b] for every b"},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_codec_accel",
    "C accelerator for handyrl_tpu.runtime.codec", -1, methods,
};

PyMODINIT_FUNC PyInit__codec_accel(void) { return PyModule_Create(&moduledef); }
