"""Device-resident replay: self-play records -> training batches, all on device.

The streaming self-play path (runtime/device_rollout.py) still round-trips
every episode device -> host (episode assembly, EpisodeStore) -> device
(make_batch + a ~43 MB observation upload per HungryGeese update).  The
round-3 TPU capture measured that loop at 499 trained + 400 self-play
env-steps/s on one chip — bounded entirely by those transfers, not by
compute.  This module removes the host from the data path:

    build_streaming_fn records (K, B, ...)        [device, 1 dispatch]
      -> ingest() into per-lane step RING BUFFERS [device, 1 dispatch]
      -> sample() windows + assemble the train batch + SGD step(s)
                                                  [device, 1 dispatch]

The only host traffic left is scalar counters and the dispatches
themselves.  The reference has no analogue — its replay is host pickles
(train.py:271-319) because its actors are host processes; a device ring is
the design point TPU self-play makes natural.

Ring invariants (what makes exact episode bookkeeping cheap):

* Every lane writes exactly one record per game step (finished lanes
  auto-reset, so there are no gaps): the write head is ONE scalar ``g``
  (global step count) and slot ``s`` of every lane holds global step
  ``gs(s) = g-1 - ((g-1-s) mod S)``.
* Slots are therefore overwritten oldest-first, and training windows only
  ever read FORWARD (younger slots) — so invalidating just the slot being
  overwritten is exact: a still-valid window start can never reach an
  overwritten step, and a long episode simply loses its oldest window
  starts one by one.
* Episode ids ARE global start steps (``ep_start_g``), unique per lane,
  so finalizing an episode (write ``ep_end_g``, set ``valid``) is one
  masked compare per step; outcome/length/progress all derive from the
  two id rings, no outcome broadcast needed (the final record's
  ``outcome`` field is gathered from the end slot at sample time).

Sampling parity with the host path (replay.py:110-140 + batch.py):
window starts are uniform over the legal ``train_start`` range
``[0, max(0, steps - forward_steps)]`` of every finished episode still
fully resident; one target player uniform per window
(``turn_based_training: false`` semantics, batch.py:62-67); padding past
the episode end reproduces make_batch exactly (prob 1, action-mask all
illegal, value frozen at the outcome, progress 1, episode_mask 0) —
pinned key-by-key against make_batch by tests/test_device_replay.py.
Two deliberate deviations, both MEASURED (round 5): recency bias is the
ring's finite capacity (oldest data falls out) instead of the reference's
per-episode acceptance curve (train.py:292-303), and window starts are
uniform over eligible STEPS, which weights episodes by the number of
windows they contain rather than uniformly.  Controlled comparison
(tools/ablate_sampler.py: one generation engine, one TrainContext, equal
updates and rollout cadence, seeded end-to-end, only the sampler swapped
— host EpisodeStore semantics vs these rings — HungryGeese, 300 updates,
2 seeds): late-mean win points vs random, ring − host = **−0.037 and
−0.017** (mean −0.027; host arm's own seed spread 0.016).  A small,
consistently-signed cost of ~0.02-0.04 win points at this budget —
the price of uniform-step windows + capacity recency, known and bounded
(docs/captures/sampler_ablation_2026-08-02_{0739,0756}.json).

Two window modes (checked at construction, dispatched by
``turn_based_training``):

* ``ff`` (``turn_based_training: false``) — simultaneous-move vector envs
  with a ``view_obs`` device view, feed-forward nets, ``burn_in_steps: 0``,
  one target player per window — the north-star HungryGeese configuration
  (``_sample_batch``).
* ``turn`` (``turn_based_training: true`` + ``observation: true``) — any
  vector env with a ``view_obs_all`` device view, all players kept per
  window (make_batch target_players = all), recurrent nets included:
  burn-in rows are real earlier steps of the same episode and hidden
  warms from zeros over them in the train step, so no hidden ring is
  needed — the Geister DRC flagship configuration (``_sample_batch_turn``).
"""

from __future__ import annotations

from collections import deque
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from ..utils import tree_map

ILLEGAL = 1e32

# record fields consumed positionally by the ring (everything else the
# streaming fn emits is an env compact-obs field, stored as-is)
_CONTROL = ("done",)


def _lane_sharding(mesh, tree):
    """Lane-leading arrays shard over 'dp'; scalars replicate."""

    def shard(x):
        if getattr(x, "ndim", 0) >= 1:
            return NamedSharding(mesh, PartitionSpec("dp"))
        return NamedSharding(mesh, PartitionSpec())

    return tree_map(shard, tree)


class DeviceReplay:
    """Per-lane device ring buffers + jitted ingest / sample-and-train.

    ``slots`` is the ring length in steps per lane.  It does NOT need to
    exceed the env's max episode length: an episode longer than the ring
    keeps its most recent ``slots`` steps sampleable (older window starts
    fall out exactly as if overwritten), because invalidation is by
    episode id and windows only ever read forward (younger slots).
    """

    def __init__(self, venv, module, args: Dict[str, Any], mesh,
                 n_lanes: int, slots: int = 1024):
        name = getattr(venv, "__name__", type(venv).__name__)
        if not hasattr(venv, "record"):
            raise ValueError(
                f"device_replay needs a vector env with compact-record "
                f"streaming hooks; {name} lacks them"
            )
        if args.get("turn_based_training", True):
            # all-player windows (make_batch target_players = all): the
            # recurrent/turn-based flagship path (Geister DRC).  Burn-in
            # warms hidden from zeros exactly like the host train step, so
            # no hidden ring is needed; window rows before the episode
            # start reproduce make_batch's pre-window padding.
            self.mode = "turn"
            if not args.get("observation", False):
                raise ValueError(
                    "device_replay with turn_based_training: true requires "
                    "observation: true (both players' views recorded; the "
                    "turn-player-gather batch layout keeps the host path)"
                )
            if not hasattr(venv, "view_obs_all"):
                raise ValueError(
                    f"device_replay (turn-based) needs {name}.view_obs_all "
                    "(device-side all-player observation reconstruction)"
                )
            min_slots = args.get("burn_in_steps", 0) + args["forward_steps"]
            if slots <= min_slots:
                raise ValueError(
                    f"device_replay_slots must exceed burn_in_steps + "
                    f"forward_steps = {min_slots}"
                )
        else:
            # single-target-player feed-forward windows (the north-star
            # HungryGeese configuration)
            self.mode = "ff"
            if not getattr(venv, "simultaneous", False):
                raise ValueError(
                    "device_replay with turn_based_training: false needs a "
                    f"simultaneous-move vector env; {name} is turn-based"
                )
            if not hasattr(venv, "view_obs"):
                raise ValueError(
                    f"device_replay needs {name}.view_obs (device-side "
                    "single-player observation reconstruction)"
                )
            if module.initial_state((1, 1)) is not None:
                raise ValueError(
                    "recurrent nets need whole-window hidden warmup — use "
                    "turn_based_training: true (all-player windows) or the "
                    "host path"
                )
            if args.get("burn_in_steps", 0) != 0:
                raise ValueError(
                    "device_replay with turn_based_training: false requires "
                    "burn_in_steps: 0"
                )
        dp = mesh.shape.get("dp", 1)
        if n_lanes % dp:
            raise ValueError(f"n_lanes {n_lanes} not divisible by dp axis {dp}")
        self.venv = venv
        self.module = module
        self.args = args
        self.mesh = mesh
        self.n_lanes = n_lanes
        self.slots = slots
        self.rings = None        # built lazily from the first record batch
        self._ingest = None
        self._pending = None     # last dispatched stats (drain target)
        self._train_fns: Dict[int, Any] = {}
        self._sample_fns: Dict[int, Any] = {}
        self._sample_debug = None
        self.counters = {
            "episodes": 0, "game_steps": 0, "player_steps": 0,
            "outcome_sum": 0.0, "outcome_sq_sum": 0.0,
        }
        # deferred-stats FIFO (ingest_counted(defer=True)): device scalar
        # handles whose host fetch is postponed one dispatch so it overlaps
        # the ingest's execution instead of synchronizing on it
        self._stats_fifo: deque = deque()

    # -- ring construction --------------------------------------------------

    def _init_rings(self, rec_spec: Dict[str, Any]):
        """Allocate rings matching one step's record layout (``rec_spec``
        leaves are per-step (B, ...), the K axis already dropped)."""
        B, S = self.n_lanes, self.slots

        def ring(leaf):
            return jnp.zeros((B, S) + leaf.shape[1:], leaf.dtype)

        rings = {
            "rec": {
                k: ring(v) for k, v in rec_spec.items() if k not in _CONTROL
            },
            "ep_start_g": jnp.full((B, S), -1, jnp.int32),
            "ep_end_g": jnp.full((B, S), -1, jnp.int32),
            "valid": jnp.zeros((B, S), bool),
            "cur_start_g": jnp.zeros((B,), jnp.int32),
            "g": jnp.zeros((), jnp.int32),
        }
        sharding = _lane_sharding(self.mesh, rings)
        from ..parallel.mesh import dispatch_serialized

        # the first-ingest layout put is a multi-device program dispatched
        # from the rollout thread — lock it like every other dispatch (the
        # trainer cannot be stepping yet with an empty ring, but a split
        # plane's learner mesh may be busy with other programs)
        put = jax.jit(lambda t: t, out_shardings=sharding)
        return dispatch_serialized(lambda: put(rings), self.mesh), sharding

    # -- ingest -------------------------------------------------------------

    def _build_ingest(self, rec_sharding):
        B, S = self.n_lanes, self.slots

        def write_step(rings, rec_t):
            g = rings["g"]
            pos = g % S
            # (1) write the record; invalidating ONLY the overwritten slot
            # is exact: slots are overwritten oldest-first and windows read
            # forward (younger slots), so a still-valid start slot can
            # never reach an overwritten step — an episode losing its
            # oldest slots just loses those window starts
            rec = {
                k: rings["rec"][k].at[:, pos].set(v)
                for k, v in rec_t.items()
                if k not in _CONTROL
            }
            ep_start_g = rings["ep_start_g"].at[:, pos].set(rings["cur_start_g"])
            ep_end_g = rings["ep_end_g"].at[:, pos].set(-1)
            valid = rings["valid"].at[:, pos].set(False)
            # (2) finished lanes: finalize every slot of the current episode
            done = rec_t["done"]                                     # (B,)
            # episode ids (global start steps) are unique per lane forever,
            # so this compare can never hit a stale slot of another episode
            mine = ep_start_g == rings["cur_start_g"][:, None]       # (B, S)
            fin = done[:, None] & mine
            ep_end_g = jnp.where(fin, g, ep_end_g)
            valid = valid | fin
            cur_start_g = jnp.where(done, g + 1, rings["cur_start_g"])
            return {
                "rec": rec,
                "ep_start_g": ep_start_g,
                "ep_end_g": ep_end_g,
                "valid": valid,
                "cur_start_g": cur_start_g,
                "g": g + 1,
            }

        def ingest(rings, records):
            def body(rings, rec_t):
                return write_step(rings, rec_t), None

            rings, _ = jax.lax.scan(body, rings, records)
            # counters for host bookkeeping (epoch cadence, gen stats):
            done = records["done"]                                    # (K, B)
            active = records["active"]                                # (K, B, P)
            n_done = done.sum(dtype=jnp.int32)
            # mean self-play outcome over finished episodes, per player
            # (zero-sum envs hover at 0 — reported for parity with
            # feed_episodes' generation stats)
            out_sum = (records["outcome"] * done[..., None]).sum(axis=(0, 1))
            stats = {
                "episodes": n_done,
                "game_steps": (active.sum(axis=2) > 0).sum(dtype=jnp.int32),
                "player_steps": active.sum(dtype=jnp.int32),
                "outcome_sum": out_sum,
                "outcome_sq_sum": (records["outcome"] ** 2 * done[..., None]).sum(),
            }
            return rings, stats

        ring_shard = _lane_sharding(self.mesh, self.rings)
        rep = NamedSharding(self.mesh, PartitionSpec())
        stats_shard = {
            "episodes": rep, "game_steps": rep, "player_steps": rep,
            "outcome_sum": rep, "outcome_sq_sum": rep,
        }
        return jax.jit(
            ingest,
            donate_argnums=(0,),
            in_shardings=(ring_shard, rec_sharding),
            out_shardings=(ring_shard, stats_shard),
        )

    def ingest(self, records) -> Dict[str, Any]:
        """Fold a (K, B, ...) record batch (one streaming-fn call) into the
        rings.  Returns device-scalar stats (fetch lazily/rarely).

        The ring swap happens INSIDE the dispatch locks: ingest donates
        the old ring buffers the moment it dispatches, so a concurrent
        train dispatch must never read ``self.rings`` between the two —
        both paths read/replace it under this mesh's per-device dispatch
        locks (train_fn reads it inside its locked lambda the same way).
        The contract is PER PLANE: ingest and train both run on this
        replay's mesh, so a split-plane actor mesh's rollout dispatches
        never contend with it."""
        if self.rings is None:
            spec = tree_map(lambda x: jax.ShapeDtypeStruct(x.shape[1:], x.dtype), records)
            self.rings, _ = self._init_rings(spec)
        if self._ingest is None:
            self._rec_sharding = tree_map(
                lambda x: NamedSharding(self.mesh, PartitionSpec(None, "dp")), records
            )
            self._ingest = self._build_ingest(self._rec_sharding)
        if jax.process_count() > 1:
            # multi-process jit refuses numpy args under partitioned
            # shardings even on a fully-addressable process-local mesh —
            # place host-born records (the episode-stage flush path)
            # explicitly; device-born rollout records pass through
            records = tree_map(
                lambda x, s: x if isinstance(x, jax.Array) else jax.device_put(x, s),
                records, self._rec_sharding,
            )
        from ..parallel.mesh import dispatch_serialized

        def _run():
            rings, stats = self._ingest(self.rings, records)
            self.rings = rings
            self._pending = stats
            return stats

        return dispatch_serialized(_run, self.mesh)

    def _account(self, dev_stats) -> Dict[str, Any]:
        """Host-fetch one ingest's stats and fold them into the cumulative
        counters (blocks until that ingest has executed)."""
        # graftlint: allow[HS001] reason=THE deferred-fetch point: callers defer this one dispatch behind the next enqueue (ingest_counted defer=True), so it overlaps execution instead of serializing the rollout thread
        stats = tree_map(np.asarray, jax.device_get(dev_stats))
        self.counters["episodes"] += int(stats["episodes"])
        self.counters["game_steps"] += int(stats["game_steps"])
        self.counters["player_steps"] += int(stats["player_steps"])
        self.counters["outcome_sum"] += float(stats["outcome_sum"].sum())
        self.counters["outcome_sq_sum"] += float(stats["outcome_sq_sum"])
        return stats

    def ingest_counted(self, records, defer: bool = False):
        """ingest + host fetch of the stats, accumulated into
        ``self.counters`` — the learner-integration path, which needs
        episode counts for epoch cadence anyway.

        ``defer=False`` fetches synchronously (one blocking scalar fetch
        per rollout-sized call — fine for prefill loops and tests).
        ``defer=True`` removes that last host round-trip from the hot
        path: the fetch of dispatch N happens only after dispatch N+1 has
        been enqueued, so it overlaps ingest N+1's execution instead of
        serializing the rollout thread on every ingest.  Returns the
        PREVIOUS dispatch's stats (None on the first call); callers drain
        the tail with ``flush_counted``.  Counter totals are identical
        either way (pinned by tests/test_device_replay.py)."""
        dev = self.ingest(records)
        if not defer:
            return self._account(dev)
        self._stats_fifo.append(dev)
        if len(self._stats_fifo) < 2:
            return None
        return self._account(self._stats_fifo.popleft())

    def flush_counted(self) -> Optional[Dict[str, float]]:
        """Fetch-and-account every deferred ingest still in flight; returns
        their aggregate (None when nothing was pending) so the caller can
        report the tail's episode counts."""
        agg: Optional[Dict[str, float]] = None
        while self._stats_fifo:
            stats = self._account(self._stats_fifo.popleft())
            if agg is None:
                agg = {
                    "episodes": 0, "game_steps": 0, "player_steps": 0,
                    "outcome_sum": 0.0, "outcome_sq_sum": 0.0,
                }
            agg["episodes"] += int(stats["episodes"])
            agg["game_steps"] += int(stats["game_steps"])
            agg["player_steps"] += int(stats["player_steps"])
            agg["outcome_sum"] += float(stats["outcome_sum"].sum())
            agg["outcome_sq_sum"] += float(stats["outcome_sq_sum"])
        return agg

    def drain(self) -> None:
        """Block on the last in-flight ingest (see StreamingDeviceRollout
        .drain: exiting the process mid-execution aborts XLA)."""
        if self._pending is not None:
            jax.block_until_ready(self._pending)

    def eligible_count(self) -> int:
        """Number of sampleable window starts (host sync — call before the
        first train step, or sparingly from a consumer waiting on warmup,
        not per step).  Reads the rings under this mesh's dispatch locks:
        a concurrent ingest donates the old ring buffers, and an eager
        read racing that swap would touch deleted arrays."""
        if self.rings is None:
            return 0
        from ..parallel.mesh import dispatch_serialized

        def _count():
            return _eligibility(
                self.rings, self.args["forward_steps"],
                self.args.get("burn_in_steps", 0),
            ).sum()

        # graftlint: allow[HS001] reason=documented host sync: warmup gate only, called before the first train step / sparingly, never per step
        return int(jax.device_get(dispatch_serialized(_count, self.mesh)))

    # -- sample + train -----------------------------------------------------

    def _sample(self, rings, key, batch_size: int):
        fn = _sample_batch_turn if self.mode == "turn" else _sample_batch
        return fn(rings, key, batch_size, self.venv, self.args,
                  self._sample_debug)

    def sample(self, key, batch_size: int, with_info: bool = False):
        """Eager one-off sampling (tests / inspection).  The production
        path fuses _sample into train_fn's single dispatch instead."""
        info = [] if with_info else None
        self._sample_debug = info
        try:
            batch = self._sample(self.rings, key, batch_size)
        finally:
            self._sample_debug = None
        if with_info:
            return batch, tree_map(np.asarray, info[0])
        return batch

    def sample_host(self, key, batch_size: int):
        """Sample ``batch_size`` windows and materialize them on HOST.

        The multi-process path: each process samples its LOCAL rings for
        its shard of the global batch, and the host rows re-enter the
        device world through ``TrainContext.put_batch`` — jax's
        ``make_array_from_process_local_data`` seam — so the collective
        train step sees one global batch assembled from per-host episode
        populations.  The fused ``train_fn`` cannot be used there: it
        would fuse a process-LOCAL gather into the cross-host collective
        program, and the rings live on different meshes per process.
        Jitted per batch size; rings read under the dispatch locks like
        every other ring consumer (a concurrent ingest donates the old
        buffers)."""
        if batch_size not in self._sample_fns:
            rep = NamedSharding(self.mesh, PartitionSpec())

            def fn(rings, key):
                return self._sample(rings, key, batch_size)

            holder = {}

            def bound(key):
                if "fn" not in holder:
                    ring_shard = _lane_sharding(self.mesh, self.rings)
                    holder["fn"] = jax.jit(
                        fn, in_shardings=(ring_shard, rep), out_shardings=rep
                    )
                from ..parallel.mesh import dispatch_serialized

                # self.rings is read INSIDE the locked lambda — see ingest
                return dispatch_serialized(
                    lambda: holder["fn"](self.rings, key), self.mesh
                )

            self._sample_fns[batch_size] = bound
        batch = self._sample_fns[batch_size](key)
        # graftlint: allow[HS001] reason=the point of this path IS host materialization: local shard rows cross to the collective mesh via make_array_from_process_local_data, which takes host buffers
        return tree_map(np.asarray, jax.device_get(batch))

    def train_fn(self, ctx, fused_steps: int = 1):
        """Jitted ``fn(state, key, lr) -> (state, metrics)`` running
        ``fused_steps`` sample+SGD updates from the CURRENT rings in ONE
        dispatch (metrics summed, matching TrainContext.train_steps).  The
        state layout is pinned on both sides like TrainContext._bind; the
        rings are read under this mesh's dispatch locks (see ingest) so a
        concurrent ingest can never hand the train step donated buffers."""
        if fused_steps in self._train_fns:
            return self._train_fns[fused_steps]
        from ..parallel.mesh import param_shardings

        B = self.args["batch_size"]
        step_fn = ctx._step_fn

        def one(state, rings, key, lr):
            batch = self._sample(rings, key, B)
            return step_fn(state, batch, lr)

        def fn(state, rings, key, lr):
            if fused_steps == 1:
                return one(state, rings, key, lr)

            def body(state, k):
                return one(state, rings, k, lr)

            state, metrics = jax.lax.scan(
                body, state, jax.random.split(key, fused_steps),
                unroll=jax.default_backend() == "cpu" and self.mesh.size == 1,
            )
            return state, jax.tree.map(lambda m: m.sum(axis=0), metrics)

        # state shardings are bound at first call (shapes unknown here)
        holder = {}

        def bound(state, key, lr):
            if "fn" not in holder:
                ss = param_shardings(self.mesh, state)
                ring_shard = _lane_sharding(self.mesh, self.rings)
                rep = NamedSharding(self.mesh, PartitionSpec())
                holder["fn"] = jax.jit(
                    fn,
                    donate_argnums=(0,),
                    in_shardings=(ss, ring_shard, rep, rep),
                    out_shardings=(ss, rep),
                )
            from ..parallel.mesh import dispatch_serialized

            # self.rings is read INSIDE the locked lambda — see ingest
            return dispatch_serialized(
                lambda: holder["fn"](state, self.rings, key, jnp.float32(lr)),
                self.mesh,
            )

        def flops_per_update(state) -> float:
            """Analytic FLOPs of ONE SGD update of this program (trace-only,
            nothing executes): jaxpr_flops over the fused body / fused_steps.
            Sampling/assembly are gathers, not FLOPs, so this equals the
            plain train step's count — used for MFU in Trainer.stats."""
            from ..parallel.train_step import jaxpr_flops

            jaxpr = jax.make_jaxpr(fn)(
                state, self.rings, jax.random.PRNGKey(0), jnp.float32(1e-5)
            )
            return jaxpr_flops(jaxpr.jaxpr) / fused_steps

        bound.flops_per_update = flops_per_update
        self._train_fns[fused_steps] = bound
        return bound


def _slot_gsteps(g, S: int):
    """Global step held by each slot: the latest write < g congruent to the
    slot index mod S (meaningful only where valid — guarded by callers)."""
    s = jnp.arange(S, dtype=jnp.int32)
    return g - 1 - ((g - 1 - s) % S)


def _eligibility(rings, forward_steps: int, burn_in_steps: int = 0):
    """(B, S) bool — slots that are legal window STARTS: part of a finished
    resident episode, with in-episode index inside the host sampler's
    ``train_start`` range [0, max(0, steps - forward_steps)]
    (replay.py:124).  With burn-in the window also reads BACKWARD
    min(burn_in, idx_in_ep) real steps, so those older slots must still be
    resident (>= the oldest global step the ring holds) — the one case the
    forward-only invalidation argument does not cover."""
    S = rings["valid"].shape[1]
    gs = _slot_gsteps(rings["g"], S)[None, :]              # (1, S)
    idx_in_ep = gs - rings["ep_start_g"]                   # (B, S)
    ep_len = rings["ep_end_g"] - rings["ep_start_g"] + 1
    max_start = jnp.maximum(0, ep_len - forward_steps)
    ok = rings["valid"] & (idx_in_ep <= max_start)
    if burn_in_steps:
        lookback = jnp.minimum(burn_in_steps, idx_in_ep)
        ok = ok & (gs - lookback >= rings["g"] - S)
    return ok


# per-step arrays the samplers consume positionally; everything else in the
# record is an env compact-obs field handed to the obs reconstruction hook.
# "reward"/"ret" are OPTIONAL: streaming rollouts derive a constant
# step_reward in closed form (_step_returns) and never record them, while
# host-born episodes (DeviceEpisodeStage) carry the generator's explicit
# per-step columns in the ring
_RECORD_FIELDS = ("active", "observing", "legal", "action", "prob", "value",
                  "outcome", "reward", "ret")


def _draw_windows(rings, key, batch_size: int, forward_steps: int,
                  burn_in: int) -> Dict[str, Any]:
    """Shared window geometry for both sampling modes: draw eligible
    train_starts uniformly, derive per-row in-episode indices / liveness
    over the (burn_in + forward) window, and gather the per-step record
    arrays.  Rows with ``i_t < 0`` are burn-in underflow (before the
    episode start); rows with ``post`` are past the episode end."""
    S = rings["valid"].shape[1]
    T = burn_in + forward_steps

    ok = _eligibility(rings, forward_steps, burn_in)
    logits = jnp.where(ok.reshape(-1), 0.0, -jnp.inf)
    flat = jax.random.categorical(key, logits, shape=(batch_size,))
    lane = (flat // S).astype(jnp.int32)                   # (N,)
    slot = (flat % S).astype(jnp.int32)                    # train_start slot

    gs0 = _slot_gsteps(rings["g"], S)[slot]                # (N,) train_start g
    ep_start = rings["ep_start_g"][lane, slot]
    ep_end = rings["ep_end_g"][lane, slot]
    idx0 = gs0 - ep_start                                  # in-episode index

    j = jnp.arange(T, dtype=jnp.int32)                     # (T,)
    i_t = idx0[:, None] - burn_in + j[None, :]             # (N, T) in-ep index
    gstep = ep_start[:, None] + i_t                        # (N, T) global step
    live_b = (i_t >= 0) & (gstep <= ep_end[:, None])       # (N, T)
    wslots = (slot[:, None] - burn_in + j[None, :]) % S    # (N, T)

    def gather(x):                                         # (B, S, ...) -> (N, T, ...)
        return x[lane[:, None], wslots]

    rec = rings["rec"]
    # final outcome lives in the episode's END slot record (younger than
    # train_start, so resident whenever train_start's valid flag survives)
    end_slot = (slot + (ep_end - gs0)) % S
    out = {
        "lane": lane, "slot": slot, "i_t": i_t, "gstep": gstep,
        "ep_end": ep_end,
        "ep_len": (ep_end - ep_start + 1).astype(jnp.float32),
        "live_b": live_b, "live": live_b.astype(jnp.float32),
        "post": gstep > ep_end[:, None],
        "active": gather(rec["active"]).astype(jnp.float32),
        "observing": gather(rec["observing"]).astype(jnp.float32),
        "prob": gather(rec["prob"]),
        "value": gather(rec["value"]),
        "action": gather(rec["action"]),
        "legal": gather(rec["legal"]),
        "outcome": rec["outcome"][lane, end_slot],         # (N, P)
        "compact": {
            k: gather(v) for k, v in rec.items() if k not in _RECORD_FIELDS
        },
    }
    # explicit per-step reward/return columns (host-born episodes); the
    # streaming path derives them in closed form instead (_step_returns)
    for k in ("reward", "ret"):
        if k in rec:
            out[k] = gather(rec[k])
    return out


def _step_returns(venv, gamma: float, w: Dict[str, Any]):
    """Constant per-step reward and its discounted return-to-go on live
    rows (_streaming_episode's reverse accumulation in closed form)."""
    step_reward = float(getattr(venv, "step_reward", 0.0))
    if not step_reward:
        zeros = jnp.zeros(w["live"].shape, jnp.float32)
        return zeros, zeros
    n_t = (w["ep_end"][:, None] - w["gstep"] + 1).astype(jnp.float32)
    if gamma == 1.0:
        ret = step_reward * n_t
    else:
        ret = step_reward * (1 - gamma ** n_t) / (1 - gamma)
    return w["live"] * step_reward, w["live"] * ret


def _sample_batch(rings, key, batch_size: int, venv, args: Dict[str, Any],
                  debug: Optional[list] = None) -> Dict[str, Any]:
    """Assemble a (batch_size, T, 1, ...) training batch from the rings —
    the device twin of replay.sample_window + batch.make_batch for the
    simultaneous / feed-forward / single-target-player configuration."""
    P = venv.num_players
    k_start, k_player = jax.random.split(key)
    w = _draw_windows(rings, k_start, batch_size, args["forward_steps"], 0)
    player = jax.random.randint(k_player, (batch_size,), 0, P)
    if debug is not None:
        debug.append({"lane": w["lane"], "slot": w["slot"], "player": player})
    live_b, live = w["live_b"], w["live"]

    def pick_player(x):                                    # (N, T, P, ...) -> (N, T)
        idx = player.reshape(-1, 1, 1)
        idx = jnp.broadcast_to(idx, (batch_size, x.shape[1], 1))
        idx = idx.reshape(idx.shape + (1,) * (x.ndim - 3))
        return jnp.take_along_axis(x, idx, axis=2)[:, :, 0]

    act_p = pick_player(w["active"])                       # (N, T)
    obs_p = pick_player(w["observing"])
    prob_p = pick_player(w["prob"])
    value_p = pick_player(w["value"])
    action_p = pick_player(w["action"])
    legal_p = pick_player(w["legal"])                      # (N, T, A)
    outcome_p = jnp.take_along_axis(w["outcome"], player[:, None], axis=1)[:, 0]

    tmask = live * act_p                                   # (N, T)
    omask = live * obs_p

    # leaves (N, T, ...): single array for the vector envs, a pytree for
    # host-born episodes whose obs is structured (DeviceEpisodeStage)
    planes = venv.view_obs(w["compact"], player)
    obs = tree_map(
        lambda x: (
            x * omask.reshape(omask.shape + (1,) * (x.ndim - 2))
        )[:, :, None],                                     # (N, T, 1, ...)
        planes,
    )

    amask = jnp.where(
        legal_p & (tmask[..., None] > 0), 0.0, ILLEGAL
    ).astype(jnp.float32)[:, :, None]                      # (N, T, 1, A)

    if "reward" in w:   # explicit per-step columns (host-born episodes)
        reward = pick_player(w["reward"]) * live
        ret = pick_player(w["ret"]) * live
    else:
        reward, ret = _step_returns(venv, args["gamma"], w)

    progress = jnp.where(
        live_b, w["i_t"].astype(jnp.float32) / w["ep_len"][:, None], 1.0
    )

    exp = lambda x: x[:, :, None, None]                    # (N, T) -> (N, T, 1, 1)
    return {
        "observation": obs,
        "selected_prob": exp(jnp.where(tmask > 0, prob_p, 1.0)),
        "value": exp(jnp.where(live_b, value_p * obs_p, outcome_p[:, None])),
        "action": exp(jnp.where(tmask > 0, action_p, 0).astype(jnp.int32)),
        "outcome": outcome_p[:, None, None, None],
        "reward": exp(reward),
        "return": exp(ret),
        "episode_mask": exp(live),
        "turn_mask": exp(tmask),
        "observation_mask": exp(omask),
        "action_mask": amask,
        "progress": progress[:, :, None],
    }


def _sample_batch_turn(rings, key, batch_size: int, venv, args: Dict[str, Any],
                       debug: Optional[list] = None) -> Dict[str, Any]:
    """All-player window assembly — the device twin of sample_window +
    make_batch for ``turn_based_training: true`` with ``observation: true``
    (batch.py:62-93, target_players = all): actor- and target-side arrays
    both keep every player, windows span burn_in + forward_steps rows with
    the host's three padding regions (zeros/fills before the episode
    start, live data inside, outcome-frozen fills past the end).  Burn-in
    rows are REAL earlier steps of the same episode (start = max(0,
    train_start - burn_in), replay.py:125) — hidden warms from zeros over
    them under stop_gradient in the train step, so no hidden ring is
    stored."""
    burn_in = args.get("burn_in_steps", 0)
    T = burn_in + args["forward_steps"]
    P = venv.num_players

    w = _draw_windows(rings, key, batch_size, args["forward_steps"], burn_in)
    if debug is not None:
        debug.append({"lane": w["lane"], "slot": w["slot"],
                      "player": jnp.full((batch_size,), -1, jnp.int32)})
    live_b, live, outcome = w["live_b"], w["live"], w["outcome"]

    act = live[..., None] * w["active"]                    # (N, T, P)
    obsv = live[..., None] * w["observing"]

    planes = venv.view_obs_all(w["compact"])               # leaves (N, T, P, ...)
    obs = tree_map(
        lambda x: x * obsv.reshape(obsv.shape + (1,) * (x.ndim - 3)), planes
    )

    amask = jnp.where(
        w["legal"] & (act[..., None] > 0), 0.0, ILLEGAL
    ).astype(jnp.float32)                                  # (N, T, P, A)

    per_p = lambda x: jnp.broadcast_to(x[:, :, None, None], (batch_size, T, P, 1))
    if "reward" in w:   # explicit per-step columns (host-born episodes)
        reward_col = (w["reward"] * live[..., None])[..., None]  # (N, T, P, 1)
        ret_col = (w["ret"] * live[..., None])[..., None]
    else:
        reward, ret = _step_returns(venv, args["gamma"], w)
        reward_col, ret_col = per_p(reward), per_p(ret)

    # value: live rows carry the recorded estimate (x observing), rows past
    # the end freeze at the outcome, burn-in underflow rows are 0
    value_b = jnp.where(
        live_b[..., None], w["value"] * obsv,
        jnp.where(w["post"][..., None], outcome[:, None, :], 0.0),
    )

    progress = jnp.where(
        live_b, w["i_t"].astype(jnp.float32) / w["ep_len"][:, None], 1.0
    )

    return {
        "observation": obs,
        "selected_prob": jnp.where(act > 0, w["prob"], 1.0)[..., None],
        "value": value_b[..., None],
        "action": jnp.where(act > 0, w["action"], 0).astype(jnp.int32)[..., None],
        "outcome": outcome[:, None, :, None],
        "reward": reward_col,
        "return": ret_col,
        "episode_mask": live[:, :, None, None],
        "turn_mask": act[..., None],
        "observation_mask": obsv[..., None],
        "action_mask": amask,
        "progress": progress[:, :, None],
    }


# -- host-born episodes: wire blobs -> device rings ---------------------------


class EpisodeObsView:
    """venv-like shim for host-born episodes staged into device rings.

    The streaming path reconstructs observations on device from an env's
    COMPACT record fields (``venv.view_obs``); host-born episodes already
    carry their full observation planes, so those live in the ring
    verbatim (pytree leaves flattened under ``obs<i>`` keys) and
    "reconstruction" is a per-player gather.  ``simultaneous``/ff mode
    here means make_batch's non-turn-based layout — one uniform target
    player per window — which is defined for ANY env's episodes, so the
    flag is unconditionally true.  ``step_reward`` is unused: the ring
    carries the generator's explicit per-step reward/return columns.
    """

    simultaneous = True
    step_reward = 0.0

    # DeviceReplay's constructor only probes for the streaming-hook's
    # presence; the stage drives ingest with pre-built record chunks
    record = None

    def __init__(self, num_players: int, obs_treedef, n_obs_leaves: int,
                 obs_spec=None):
        self.num_players = num_players
        self._treedef = obs_treedef
        self._n = n_obs_leaves
        # obs_int8: per-leaf (scale, zero_point) the episode's obs planes
        # were quantized under (rides in the episode dict as
        # obs_scale/obs_zero); None = obs stored at native dtype
        self._spec = obs_spec

    def _tree(self, compact: Dict[str, Any]):
        tree = jax.tree.unflatten(
            self._treedef, [compact[f"obs{i}"] for i in range(self._n)]
        )
        if self._spec is not None:
            # dequantize-on-device: runs INSIDE the jitted sample/assemble
            # programs (XLA fuses convert+mul into the gather consumers),
            # so the ring stays int8-resident and the host never touches
            # float obs on this path
            from ..models.quantize import dequantize_obs_tree

            tree = dequantize_obs_tree(tree, self._spec)
        return tree

    def view_obs(self, compact: Dict[str, Any], player):
        def pick(x):                         # (N, T, P, ...) -> (N, T, ...)
            idx = player.reshape((-1, 1, 1) + (1,) * (x.ndim - 3))
            idx = jnp.broadcast_to(idx, x.shape[:2] + (1,) + x.shape[3:])
            return jnp.take_along_axis(x, idx, axis=2)[:, :, 0]

        return tree_map(pick, self._tree(compact))

    def view_obs_all(self, compact: Dict[str, Any]):
        return self._tree(compact)           # leaves (N, T, P, ...)


class DeviceEpisodeStage:
    """Host-born episodes uploaded ONCE into DeviceReplay ring buffers.

    The host-fed pipeline re-uploads every sampled observation window per
    update (~43 MB/update on HungryGeese — BENCH_r05's 3 vs 376 updates/s
    gap); this stage removes the host from the per-update path for
    episodes that are BORN on the host (worker actors, remote workers):

        episode (decoded dict, or the wire-codec bytes EpisodeStore
        mirrors to batcher children)
          -> per-step record columns, queued per lane  [host, once]
          -> fixed-size (chunk, lanes) ingest calls    [one H2D per chunk]
          -> DeviceReplay rings: windows sampled + assembled ON DEVICE by
             the same programs the streaming path uses (parity pinned
             key-by-key against make_batch by tests/test_device_stage.py)

    Lane discipline: the ring invariant is that every lane advances one
    slot per global step, so episodes queue per lane (shortest queue
    first — greedy balancing) and a chunk flushes only when EVERY lane
    has ``chunk_steps`` queued.  An episode's steps therefore occupy a
    contiguous lane-local span whose indices EQUAL the ring's global
    steps, which is what makes window bookkeeping exact.  Keep
    ``n_lanes * chunk_steps`` well below ``minimum_episodes`` x the
    typical episode length, or the first flush (and the trainer's first
    batch) waits on generation.
    """

    def __init__(self, module, args: Dict[str, Any], mesh, n_lanes: int = 8,
                 slots: int = 1024, chunk_steps: int = 64,
                 track_episodes: bool = False):
        # mirror DeviceReplay's ARG-side mode checks here, eagerly: the
        # replay itself is built lazily from the first episode (it needs
        # the player count and obs structure), which happens on a feeder
        # thread — too late for make_pipeline's loud fallback
        if args.get("turn_based_training", True):
            if not args.get("observation", False):
                raise ValueError(
                    "batch_pipeline: device with turn_based_training: true "
                    "requires observation: true (all-player windows; the "
                    "turn-player-gather batch layout keeps the host path)"
                )
            min_slots = args.get("burn_in_steps", 0) + args["forward_steps"]
            if slots <= min_slots:
                raise ValueError(
                    f"device_stage_slots must exceed burn_in_steps + "
                    f"forward_steps = {min_slots}"
                )
        else:
            if module.initial_state((1, 1)) is not None:
                raise ValueError(
                    "batch_pipeline: device with a recurrent net needs "
                    "turn_based_training: true (whole-window hidden warmup)"
                )
            if args.get("burn_in_steps", 0) != 0:
                raise ValueError(
                    "batch_pipeline: device with turn_based_training: false "
                    "requires burn_in_steps: 0"
                )
        dp = mesh.shape.get("dp", 1)
        if n_lanes % dp:
            rounded = max(dp, (n_lanes + dp - 1) // dp * dp)
            import sys

            print(
                f"[handyrl_tpu] device_stage_lanes {n_lanes} rounded to "
                f"{rounded} (lanes shard over the mesh's dp axis of {dp})",
                file=sys.stderr,
            )
            n_lanes = rounded
        self.module = module
        self.args = args
        self.mesh = mesh
        self.n_lanes = n_lanes
        self.slots = slots
        self.chunk_steps = int(chunk_steps)
        self.replay: Optional[DeviceReplay] = None
        self._view: Optional[EpisodeObsView] = None
        # per-lane FIFO of [rec_dict, offset] with (T, ...) numpy leaves
        self._queues: List[List[list]] = [[] for _ in range(n_lanes)]
        self._qlen = [0] * n_lanes     # pending (unflushed) steps
        self._qtotal = [0] * n_lanes   # steps EVER enqueued = ring g of the
        #                                lane's next step once flushed
        self.episodes_staged = 0
        self.steps_staged = 0
        self.chunks_flushed = 0
        # (g0, g1, episode) spans per lane — test/debug bookkeeping only
        # (unbounded over a long run), enabled by track_episodes
        self.spans: Optional[List[list]] = (
            [[] for _ in range(n_lanes)] if track_episodes else None
        )

    # -- episode intake ------------------------------------------------------

    def add_blob(self, blob: bytes) -> None:
        """Stage one episode from its wire-codec bytes — the exact frames
        ``EpisodeStore`` mirrors to shm batcher children."""
        from . import codec

        self.add_episode(codec.loads(blob))

    def add_episode(self, episode: Dict[str, Any]) -> None:
        """Decode one columnar episode into per-step record arrays and
        queue it on the shortest lane."""
        from .batch import _concat_columns
        from .replay import decompress_block

        cols = _concat_columns(
            [decompress_block(b) for b in episode["blocks"]]
        )
        T = int(episode["steps"])
        P = cols["prob"].shape[1]
        outcome = np.asarray(
            [episode["outcome"][p] for p in episode["players"]], np.float32
        )
        done = np.zeros((T,), bool)
        done[-1] = True
        rec = {
            "active": cols["tmask"].astype(np.float32),
            "observing": cols["omask"].astype(np.float32),
            "legal": cols["amask"] == 0.0,
            "action": cols["action"].astype(np.int32),
            "prob": cols["prob"].astype(np.float32),
            "value": cols["value"].astype(np.float32),
            "reward": cols["reward"].astype(np.float32),
            "ret": cols["ret"].astype(np.float32),
            "outcome": np.broadcast_to(outcome, (T, P)).copy(),
            "done": done,
        }
        obs_leaves, treedef = jax.tree.flatten(cols["obs"])
        for i, leaf in enumerate(obs_leaves):
            rec[f"obs{i}"] = np.asarray(leaf)
        if self.replay is None:
            spec = None
            if episode.get("obs_scale") is not None:
                # the quantization spec travels WITH the episode
                # (generation.py _finalize) — no env re-derivation here
                spec = list(zip(
                    np.asarray(episode["obs_scale"], np.float32).tolist(),
                    np.asarray(episode["obs_zero"], np.float32).tolist(),
                ))
            self._view = EpisodeObsView(P, treedef, len(obs_leaves), obs_spec=spec)
            self.replay = DeviceReplay(
                self._view, self.module, self.args, self.mesh,
                self.n_lanes, slots=self.slots,
            )
        lane = min(range(self.n_lanes), key=lambda i: self._qlen[i])
        if self.spans is not None:
            self.spans[lane].append(
                (self._qtotal[lane], self._qtotal[lane] + T - 1, episode)
            )
        self._queues[lane].append([rec, 0])
        self._qlen[lane] += T
        self._qtotal[lane] += T
        self.episodes_staged += 1
        self.steps_staged += T

    # -- chunk assembly + flush ----------------------------------------------

    def _take(self, lane: int, k: int) -> Dict[str, np.ndarray]:
        """Pop ``k`` steps off a lane's queue (possibly spanning episode
        boundaries) as one concatenated record dict with (k, ...) leaves."""
        q = self._queues[lane]
        parts: List[Dict[str, np.ndarray]] = []
        left = k
        while left > 0:
            rec, off = q[0]
            T = rec["done"].shape[0]
            take = min(left, T - off)
            parts.append({key: val[off:off + take] for key, val in rec.items()})
            if off + take == T:
                q.pop(0)
            else:
                q[0][1] = off + take
            left -= take
        self._qlen[lane] -= k
        if len(parts) == 1:
            return parts[0]
        return {
            key: np.concatenate([p[key] for p in parts]) for key in parts[0]
        }

    def ready(self) -> bool:
        """True when every lane has a full chunk queued."""
        return self.replay is not None and min(self._qlen) >= self.chunk_steps

    def flush(self) -> int:
        """Fold every complete (chunk, lanes) block into the rings; returns
        the number of chunks ingested.  Stats fetches are deferred
        (ingest_counted defer=True) so consecutive chunks overlap."""
        n = 0
        K = self.chunk_steps
        while self.ready():
            chunks = [self._take(lane, K) for lane in range(self.n_lanes)]
            records = {
                key: np.stack([c[key] for c in chunks], axis=1)  # (K, B, ...)
                for key in chunks[0]
            }
            self.replay.ingest_counted(records, defer=True)
            self.chunks_flushed += 1
            n += 1
        return n

    def eligible(self) -> int:
        """Sampleable window starts currently resident (host sync)."""
        if self.replay is None:
            return 0
        return self.replay.eligible_count()

    def drain(self) -> None:
        """Settle deferred stats and block on the last in-flight ingest."""
        if self.replay is not None:
            self.replay.flush_counted()
            self.replay.drain()
