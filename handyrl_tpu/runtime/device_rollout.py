"""Fully on-device self-play: env stepping + inference + sampling in ONE jit.

The thread-actor plane (runtime/worker.py + inference_engine.py) keeps the
reference's architecture — host envs, device model — and pays one host
round-trip per step wave. For envs that also exist as pure jnp transition
functions (envs/vector_tictactoe.py), this module removes the host from
the loop entirely: a ``lax.scan`` steps B games for max_steps, sampling
actions on device via Gumbel-max over legal-masked logits, and the ONLY
host work left is converting finished games into the standard columnar
episode format for the replay store. This is the actor-plane design point
the reference's process tree (worker.py:110-189) cannot express — per-step
throughput scales with the device batch, not with host round-trips.

Behavior parity with the host Generator (runtime/generation.py):
temperature-1 softmax sampling over legal-masked logits, recorded
behavior prob / action mask / critic value per turn player, discounted
returns (zero for reward-free games), identical columnar block schema —
pinned by tests/test_device_rollout.py, which replays every device game
through the host env.
"""

from __future__ import annotations

from typing import Any, Dict, List

import jax
import jax.numpy as jnp
import numpy as np

from .replay import compress_block

ILLEGAL = 1e32


def build_selfplay_fn(venv, module, n_games: int):
    """Compile-once device self-play for a VectorTicTacToe-style env.

    Returns ``fn(params, rng_key) -> columns`` (jitted), where columns are
    time-major device arrays over the full max_steps horizon:
        obs    (T, B, ...)  turn player's observation
        prob   (T, B)       behavior probability of the selected action
        action (T, B) int32
        amask  (T, B, A)    0 legal / 1e32 illegal at selection time
        value  (T, B)       critic output at acting time
        alive  (T, B)       1.0 while the game was still running
        outcome (B, P)      final per-player scores
    """

    def fn(params, key):
        keys = jax.random.split(key, venv.max_steps)

        # strict alternation lets the step index be a Python int: unroll
        # over max_steps (9 for TicTacToe) so observation/turn math is
        # static per step while the games stay batched on device
        cols = {"obs": [], "prob": [], "action": [], "amask": [], "value": [], "alive": []}
        state = venv.init(n_games)
        for t in range(venv.max_steps):
            alive = ~venv.terminal(state, t)
            obs = venv.observation(state, t)
            out = module.apply({"params": params}, obs, None)
            logits = out["policy"].astype(jnp.float32)
            amask = jnp.where(venv.legal_mask(state), 0.0, ILLEGAL)
            masked = logits - amask
            # Gumbel-max == sampling from softmax(masked) (generation.py
            # samples softmax at temperature 1)
            g = jax.random.gumbel(keys[t], masked.shape)
            action = jnp.argmax(masked + g, axis=-1)
            probs = jax.nn.softmax(masked, axis=-1)
            prob = jnp.take_along_axis(probs, action[:, None], axis=-1)[:, 0]

            cols["obs"].append(obs)
            cols["prob"].append(prob)
            cols["action"].append(action.astype(jnp.int32))
            cols["amask"].append(amask)
            cols["value"].append(out["value"][:, 0] if out.get("value") is not None else jnp.zeros_like(prob))
            cols["alive"].append(alive.astype(jnp.float32))
            state = venv.apply(state, action, t)

        stacked = {k: jnp.stack(v) for k, v in cols.items()}
        stacked["outcome"] = venv.outcome(state)
        return stacked

    return jax.jit(fn)


def columns_to_episodes(host_cols: Dict[str, Any], venv, args: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Device rollout columns -> standard columnar episodes (the schema of
    Generator._finalize, runtime/generation.py) ready for EpisodeStore."""
    P = venv.num_players
    A = venv.num_actions
    alive = np.asarray(host_cols["alive"])               # (T, B)
    lengths = alive.sum(axis=0).astype(np.int32)         # (B,)
    outcome = np.asarray(host_cols["outcome"])           # (B, P)
    obs = np.asarray(host_cols["obs"])                   # (T, B, ...)
    prob = np.asarray(host_cols["prob"])
    action = np.asarray(host_cols["action"])
    amask = np.asarray(host_cols["amask"])
    value = np.asarray(host_cols["value"])

    block_len = args["compress_steps"]
    players = list(range(P))
    episodes = []
    for b in range(obs.shape[1]):
        T = int(lengths[b])
        if T == 0:
            continue
        blocks = []
        for lo in range(0, T, block_len):
            hi = min(lo + block_len, T)
            t = hi - lo
            ts = np.arange(lo, hi)
            tp = ts % P                                   # turn player per step
            cols = {
                "prob": np.ones((t, P), np.float32),
                "action": np.zeros((t, P), np.int32),
                "amask": np.full((t, P, A), ILLEGAL, np.float32),
                "value": np.zeros((t, P), np.float32),
                "reward": np.zeros((t, P), np.float32),
                "ret": np.zeros((t, P), np.float32),
                "tmask": np.zeros((t, P), np.float32),
                "omask": np.zeros((t, P), np.float32),
                "turn": tp.astype(np.int32),
            }
            rows = np.arange(t)
            cols["prob"][rows, tp] = prob[ts, b]
            cols["action"][rows, tp] = action[ts, b]
            cols["amask"][rows, tp] = amask[ts, b]
            cols["value"][rows, tp] = value[ts, b]
            cols["tmask"][rows, tp] = 1.0
            cols["omask"][rows, tp] = 1.0
            obs_block = np.zeros((t, P) + obs.shape[2:], np.float32)
            obs_block[rows, tp] = obs[ts, b]
            cols["obs"] = obs_block
            blocks.append(compress_block(cols))
        episodes.append(
            {
                "args": {"player": players, "model_id": {p: -1 for p in players}},
                "steps": T,
                "players": players,
                "outcome": {p: float(outcome[b, p]) for p in players},
                "blocks": blocks,
            }
        )
    return episodes


class DeviceRollout:
    """Compile-once wrapper: generate whole batches of finished episodes
    with a single device call each."""

    def __init__(self, venv, module, args: Dict[str, Any], n_games: int = 256):
        self.venv = venv
        self.args = args
        self.n_games = n_games
        self._fn = build_selfplay_fn(venv, module, n_games)

    def generate(self, params, key) -> List[Dict[str, Any]]:
        cols = self._fn(params, key)
        return columns_to_episodes(jax.device_get(cols), self.venv, self.args)
