"""Fully on-device self-play: env stepping + inference + sampling in ONE jit.

The thread-actor plane (runtime/worker.py + inference_engine.py) keeps the
reference's architecture — host envs, device model — and pays one host
round-trip per step wave. For envs that also exist as pure jnp transition
functions (envs/vector_tictactoe.py), this module removes the host from
the loop entirely: a ``lax.scan`` steps B games for max_steps, sampling
actions on device via Gumbel-max over legal-masked logits, and the ONLY
host work left is converting finished games into the standard columnar
episode format for the replay store. This is the actor-plane design point
the reference's process tree (worker.py:110-189) cannot express — per-step
throughput scales with the device batch, not with host round-trips.

Behavior parity with the host Generator (runtime/generation.py):
temperature-1 softmax sampling over legal-masked logits, recorded
behavior prob / action mask / critic value per turn player, discounted
returns (zero for reward-free games), identical columnar block schema —
pinned by tests/test_device_rollout.py, which replays every device game
through the host env.
"""

from __future__ import annotations

from typing import Any, Dict, List

import jax
import jax.numpy as jnp
import numpy as np

from ..utils import tree_map
from .replay import compress_block

ILLEGAL = 1e32


def build_selfplay_fn(venv, module, n_games: int):
    """Compile-once device self-play for a VectorTicTacToe-style env.

    Returns ``fn(params, rng_key) -> columns`` (jitted), where columns are
    time-major device arrays over the full max_steps horizon:
        obs    (T, B, ...)  turn player's observation
        prob   (T, B)       behavior probability of the selected action
        action (T, B) int32
        amask  (T, B, A)    0 legal / 1e32 illegal at selection time
        value  (T, B)       critic output at acting time
        alive  (T, B)       1.0 while the game was still running
        outcome (B, P)      final per-player scores
    """

    def fn(params, key):
        keys = jax.random.split(key, venv.max_steps)

        # strict alternation lets the step index be a Python int: unroll
        # over max_steps (9 for TicTacToe) so observation/turn math is
        # static per step while the games stay batched on device
        cols = {"obs": [], "prob": [], "action": [], "amask": [], "value": [], "alive": []}
        state = venv.init(n_games)
        for t in range(venv.max_steps):
            alive = ~venv.terminal(state, t)
            obs = venv.observation(state, t)
            out = module.apply({"params": params}, obs, None)
            logits = out["policy"].astype(jnp.float32)
            amask = jnp.where(venv.legal_mask(state), 0.0, ILLEGAL)
            masked = logits - amask
            # Gumbel-max == sampling from softmax(masked) (generation.py
            # samples softmax at temperature 1)
            g = jax.random.gumbel(keys[t], masked.shape)
            action = jnp.argmax(masked + g, axis=-1)
            probs = jax.nn.softmax(masked, axis=-1)
            prob = jnp.take_along_axis(probs, action[:, None], axis=-1)[:, 0]

            cols["obs"].append(obs)
            cols["prob"].append(prob)
            cols["action"].append(action.astype(jnp.int32))
            cols["amask"].append(amask)
            cols["value"].append(out["value"][:, 0] if out.get("value") is not None else jnp.zeros_like(prob))
            cols["alive"].append(alive.astype(jnp.float32))
            state = venv.apply(state, action, t)

        stacked = {k: jnp.stack(v) for k, v in cols.items()}
        stacked["outcome"] = venv.outcome(state)
        return stacked

    return jax.jit(fn)


def columns_to_episodes(host_cols: Dict[str, Any], venv, args: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Device rollout columns -> standard columnar episodes (the schema of
    Generator._finalize, runtime/generation.py) ready for EpisodeStore."""
    P = venv.num_players
    A = venv.num_actions
    alive = np.asarray(host_cols["alive"])               # (T, B)
    lengths = alive.sum(axis=0).astype(np.int32)         # (B,)
    outcome = np.asarray(host_cols["outcome"])           # (B, P)
    obs = np.asarray(host_cols["obs"])                   # (T, B, ...)
    prob = np.asarray(host_cols["prob"])
    action = np.asarray(host_cols["action"])
    amask = np.asarray(host_cols["amask"])
    value = np.asarray(host_cols["value"])

    block_len = args["compress_steps"]
    players = list(range(P))
    episodes = []
    for b in range(obs.shape[1]):
        T = int(lengths[b])
        if T == 0:
            continue
        blocks = []
        for lo in range(0, T, block_len):
            hi = min(lo + block_len, T)
            t = hi - lo
            ts = np.arange(lo, hi)
            tp = ts % P                                   # turn player per step
            cols = {
                "prob": np.ones((t, P), np.float32),
                "action": np.zeros((t, P), np.int32),
                "amask": np.full((t, P, A), ILLEGAL, np.float32),
                "value": np.zeros((t, P), np.float32),
                "reward": np.zeros((t, P), np.float32),
                "ret": np.zeros((t, P), np.float32),
                "tmask": np.zeros((t, P), np.float32),
                "omask": np.zeros((t, P), np.float32),
                "turn": tp.astype(np.int32),
            }
            rows = np.arange(t)
            cols["prob"][rows, tp] = prob[ts, b]
            cols["action"][rows, tp] = action[ts, b]
            cols["amask"][rows, tp] = amask[ts, b]
            cols["value"][rows, tp] = value[ts, b]
            cols["tmask"][rows, tp] = 1.0
            cols["omask"][rows, tp] = 1.0
            obs_block = np.zeros((t, P) + obs.shape[2:], np.float32)
            obs_block[rows, tp] = obs[ts, b]
            cols["obs"] = obs_block
            blocks.append(compress_block(cols))
        episodes.append(
            {
                "args": {"player": players, "model_id": {p: -1 for p in players}},
                "steps": T,
                "players": players,
                "outcome": {p: float(outcome[b, p]) for p in players},
                "blocks": blocks,
            }
        )
    return episodes


class DeviceRollout:
    """Compile-once wrapper: generate whole batches of finished episodes
    with a single device call each."""

    def __init__(self, venv, module, args: Dict[str, Any], n_games: int = 256):
        self.venv = venv
        self.args = args
        self.n_games = n_games
        self._fn = build_selfplay_fn(venv, module, n_games)

    def generate(self, params, key) -> List[Dict[str, Any]]:
        from ..parallel.mesh import dispatch_serialized

        # the episodic program is unsharded (it commits to the default
        # device), but the rollout thread dispatches it CONCURRENTLY with
        # sharded train steps whose device set includes that device — the
        # enqueue needs the same per-device program order as every other
        # dispatch site (the device scope is exactly the one device)
        cols = dispatch_serialized(
            lambda: self._fn(params, key), jax.devices()[:1]
        )
        # whole-horizon episodic fetch: this driver's contract IS one
        # host round-trip per batch of finished games
        # graftlint: allow[HS001] reason=episodic driver fetches one whole-horizon batch per call by design
        return columns_to_episodes(jax.device_get(cols), self.venv, self.args)


# ---------------------------------------------------------------------------
# Streaming rollout for simultaneous-move envs (VectorHungryGeese)
# ---------------------------------------------------------------------------


def build_streaming_fn(venv, module, n_lanes: int, k_steps: int, mesh=None,
                       use_observe_mask: bool = True):
    """Compile-once streaming self-play step for a simultaneous-move vector
    env (``venv.simultaneous``): ``fn(params, state, key) -> (state, record)``
    scans ``k_steps`` game steps over ``n_lanes`` persistent lanes,
    auto-resetting finished lanes at each iteration start so no device work
    is wasted on dead games.  Episodes are stitched across calls by
    StreamingDeviceRollout from the COMPACT per-step record (occupancy +
    heads + food, not full observation planes) — ~40x less HBM->host
    traffic than shipping the 17-plane observations, which the host
    reconstructs with pure numpy scatter ops.

    With ``mesh``, lanes shard over the mesh's 'dp' axis (params
    replicated): one SPMD program steps n_lanes games across all devices,
    the self-play analogue of the data-parallel train step.

    Works for simultaneous-move envs (every active player acts, e.g.
    VectorHungryGeese) and strict-alternation envs (``state['active']``
    one-hots the turn player, e.g. VectorGeister); recurrent modules
    (DRC ConvLSTM) carry per-(lane, player) hidden state across steps,
    zeroed on lane reset and committed where the player observed —
    matching the host generator's per-player hidden handling."""

    P = venv.num_players

    def fn(params, state, hidden, key):
        def body(carry, key_t):
            state, hidden = carry
            kr, ka, kf = jax.random.split(key_t, 3)
            reset = state["done"]
            state = venv.reset_done(state, kr)
            if hidden is not None:
                # fresh games start from zero hidden (host: init_hidden)
                hidden = tree_map(
                    lambda h: h * ~reset.reshape((-1,) + (1,) * (h.ndim - 1)),
                    hidden,
                )
            active = state["active"]                     # (B, P) acting mask
            # observe_mask (observer views for non-acting players) applies
            # only under ``observation: true`` — with it false the host
            # generator records turn players only, and the device path must
            # emit the same omask semantics into the shared replay store
            observing = (
                venv.observe_mask(state)
                if use_observe_mask and hasattr(venv, "observe_mask")
                else active
            )
            obs = venv.observation(state)                # leaves (B, P, ...)
            B = active.shape[0]
            flat = tree_map(lambda x: x.reshape((B * P,) + x.shape[2:]), obs)
            h_flat = (
                None
                if hidden is None
                else tree_map(lambda h: h.reshape((B * P,) + h.shape[2:]), hidden)
            )
            out = module.apply({"params": params}, flat, h_flat)
            if hidden is not None:
                new_hidden = tree_map(
                    lambda h: h.reshape((B, P) + h.shape[1:]), out["hidden"]
                )
                # commit where observed, keep elsewhere (train_step.py:146)
                hidden = jax.tree.map(
                    lambda h, nh: jnp.where(
                        observing.reshape((B, P) + (1,) * (h.ndim - 2)), nh, h
                    ),
                    hidden,
                    new_hidden,
                )
            logits = out["policy"].astype(jnp.float32).reshape(B, P, -1)
            legal = venv.legal_mask_all(state)           # (B, P, A) bool
            masked = jnp.where(legal, logits, logits - ILLEGAL)
            # Gumbel-max == softmax sampling at temperature 1 (generation.py)
            g = jax.random.gumbel(ka, masked.shape)
            action = jnp.argmax(masked + g, axis=-1).astype(jnp.int32)
            probs = jax.nn.softmax(masked, axis=-1)
            prob = jnp.take_along_axis(probs, action[..., None], axis=-1)[..., 0]
            value = (
                out["value"].reshape(B, P)
                if out.get("value") is not None
                else jnp.zeros_like(prob)
            )
            record = {
                "active": active,
                "observing": observing,
                "legal": legal,
                "action": action.astype(jnp.int32),
                "prob": prob,
                "value": value,
            }
            record.update(venv.record(state))   # env's compact obs fields
            state = venv.step(state, action, kf)
            record["done"] = state["done"]   # reset_done cleared stale flags
            record["outcome"] = venv.outcome_scores(state)  # final where done
            return (state, hidden), record

        # Stays a genuine loop on every backend: unrolling k_steps bodies
        # here multiplies compile time by k (measured: minutes per shape on
        # the 1-core CPU host) for a path whose CPU throughput is a
        # fallback, not a target — unlike the RNN TRAIN scan, which is
        # unrolled on single-device CPU (see parallel/train_step.py).
        (state, hidden), records = jax.lax.scan(
            body, (state, hidden), jax.random.split(key, k_steps)
        )
        return state, hidden, records

    if mesh is None:
        return jax.jit(fn, donate_argnums=(1, 2))
    from jax.sharding import NamedSharding, PartitionSpec

    lanes = NamedSharding(mesh, PartitionSpec("dp"))            # state: (B, ...)
    rec = NamedSharding(mesh, PartitionSpec(None, "dp"))        # record: (K, B, ...)
    rep = NamedSharding(mesh, PartitionSpec())
    return jax.jit(
        fn,
        donate_argnums=(1, 2),
        in_shardings=(rep, lanes, lanes, rep),
        out_shardings=(lanes, lanes, rec),
    )


def _streaming_episode(venv, steps: List[tuple], done_rec, done_k: int, lane: int,
                       args: Dict[str, Any]) -> Dict[str, Any]:
    """Assemble one finished lane into the standard columnar episode.

    ``steps`` is the lane's buffered [(record, k_start, k_end)] span
    history (possibly spanning several device calls); observations are
    rebuilt host-side from the env's compact record fields
    (``venv.episode_obs``) — pinned against the host env's observation()
    by tests/test_device_rollout.py."""
    P = venv.num_players
    T = sum(k1 - k0 for _, k0, k1 in steps)
    b = lane

    def gather(name, dtype=None):
        out = np.concatenate(
            [np.asarray(rec[name][k0:k1, b]) for rec, k0, k1 in steps]
        )
        return out if dtype is None else out.astype(dtype)

    action = gather("action", np.int32)    # (T, P)
    prob = gather("prob", np.float32)
    value = gather("value", np.float32)
    active = gather("active", np.float32)  # (T, P) 0/1 — acted this step
    observing = gather("observing", np.float32)      # (T, P) 0/1
    legal = gather("legal")                # (T, P, A) bool
    compact = {
        name: gather(name)
        for name in steps[0][0]
        if name not in ("active", "observing", "legal", "action",
                        "prob", "value", "done", "outcome")
    }
    obs = venv.episode_obs(compact, observing)       # (T, P, ...)

    final = np.asarray(done_rec["outcome"][done_k][b], np.float32)
    players = list(range(P))
    outcome = {p: float(final[p]) for p in players}

    # per-step reward (constant-per-step envs, e.g. Geister's -0.01) and
    # its discounted return-to-go (generation.py:78-82, 101-103 — rewards
    # accrue to every player each step)
    step_reward = float(getattr(venv, "step_reward", 0.0))
    reward = np.full((T, P), step_reward, np.float32)
    ret = np.zeros((T, P), np.float32)
    if step_reward:
        acc = np.zeros(P, np.float32)
        for t in range(T - 1, -1, -1):
            acc = reward[t] + args["gamma"] * acc
            ret[t] = acc

    block_len = args["compress_steps"]
    blocks = []
    for lo in range(0, T, block_len):
        hi = min(lo + block_len, T)
        act = active[lo:hi]
        obsv = observing[lo:hi]
        amask = np.where(
            legal[lo:hi] & (act[..., None] > 0), 0.0, ILLEGAL
        ).astype(np.float32)
        cols = {
            "obs": tree_map(lambda x: x[lo:hi], obs),
            "prob": np.where(act > 0, prob[lo:hi], 1.0).astype(np.float32),
            "action": (action[lo:hi] * (act > 0)).astype(np.int32),
            "amask": amask,
            "value": (value[lo:hi] * obsv).astype(np.float32),
            "reward": reward[lo:hi],
            "ret": ret[lo:hi],
            "tmask": act.astype(np.float32),
            "omask": obsv.astype(np.float32),
            "turn": np.argmax(act, axis=1).astype(np.int32),
        }
        blocks.append(compress_block(cols))

    return {
        "args": {"player": players, "model_id": {p: -1 for p in players}},
        "steps": T,
        "players": players,
        "outcome": outcome,
        "blocks": blocks,
    }


def make_device_rollout(venv, module, args: Dict[str, Any], n_games: int, mesh=None):
    """Pick the rollout driver for a vector env: persistent streaming
    lanes for envs exposing the streaming hooks (VectorHungryGeese,
    VectorParallelTicTacToe, VectorGeister) — lanes sharded over the
    mesh's 'dp' axis when a mesh is given — else episodic whole-horizon
    calls (VectorTicTacToe's 9-ply games)."""
    if hasattr(venv, "record"):
        return StreamingDeviceRollout(venv, module, args, n_lanes=n_games, mesh=mesh)
    if module.initial_state((1,)) is not None:
        # build_selfplay_fn steps with hidden=None (fresh state every ply):
        # a stateful policy self-plays MEMORYLESSLY on this driver.  The
        # recorded behavior probs are still the true behavior policy, so
        # training stays sound (off-policy corrections), but the data is
        # not what host actors (which carry hidden) would generate — say so
        import sys

        print(
            "[handyrl_tpu] episodic device rollout steps a stateful model "
            "(RNN/KV-cache) with a fresh hidden state every ply — self-play "
            "is memoryless on this driver; for memory-faithful device "
            "self-play give the env a streaming vector twin (record/"
            "reset_done/step hooks), or use host actors",
            file=sys.stderr,
        )
    return DeviceRollout(venv, module, args, n_games)


class StreamingDeviceRollout:
    """Persistent-lane self-play for simultaneous-move vector envs.

    Each ``generate`` call advances every lane ``k_steps`` game steps in
    ONE device call and returns the episodes that finished; in-progress
    games carry over (their lanes keep stepping next call).  Lanes reset
    the moment their game ends, so device utilization is independent of
    episode length — the design point behind the HungryGeese north star.

    Params may change between calls (the learner publishes new epochs);
    in-flight games finish under the newest params and are credited to the
    model_id the caller stamps at flush time — the same staleness the
    IMPALA off-policy corrections (ops/losses.py) already absorb.
    """

    def __init__(self, venv, module, args: Dict[str, Any], n_lanes: int = 256,
                 k_steps: int = 32, mesh=None):
        if mesh is not None:
            dp = mesh.shape.get("dp", 1)
            if n_lanes % dp:
                raise ValueError(f"n_lanes {n_lanes} not divisible by dp axis {dp}")
        self.venv = venv
        self.args = args
        self.n_lanes = n_lanes
        self.k_steps = k_steps
        self.module = module
        # mesh (or None): the device set the dispatch locks cover — a
        # split-plane actor mesh dispatches concurrently with the learner
        # plane; mesh-less rollouts keep the conservative all-device locks
        self.mesh = mesh
        self._fn = build_streaming_fn(
            venv, module, n_lanes, k_steps, mesh,
            use_observe_mask=bool(args.get("observation", False)),
        )
        self._state = None
        self._hidden = None
        self._pending = None         # in-flight device record (one-call pipeline)
        self._partial: List[List[tuple]] = [[] for _ in range(n_lanes)]
        self.game_steps = 0          # lifetime game-steps (>=1 player acting)
        self.player_steps = 0        # lifetime per-player acting steps

    def generate(self, params, key) -> List[Dict[str, Any]]:
        """Advance all lanes k_steps and return episodes finished one call
        ago: the device computes block N while the host transfers and
        assembles block N-1 (jax dispatch is async; only the device_get
        synchronizes), so host-side episode assembly is hidden behind
        device compute instead of serializing with it."""
        import jax as _jax

        if self._state is None:
            key, k0 = _jax.random.split(key)
            self._state = self.venv.init(self.n_lanes, k0)
            self._hidden = self.module.initial_state(
                (self.n_lanes, self.venv.num_players)
            )
        from ..parallel.mesh import dispatch_serialized

        # consistent cross-device program order vs concurrent programs on
        # an overlapping device set (and serialization with them on the
        # CPU backend) — the dispatch is async on TPU, so execution still
        # overlaps the assembly below; on a split-plane actor mesh the
        # locks cover only the actor devices, so the learner plane's train
        # dispatches proceed concurrently
        self._state, self._hidden, record = dispatch_serialized(
            lambda: self._fn(params, self._state, self._hidden, key),
            self.mesh,
        )
        record, self._pending = self._pending, record
        if record is None:
            return []
        # graftlint: allow[HS001] reason=one-call-pipelined fetch: block N-1's transfer overlaps block N's device compute (the dispatch above is async)
        record = _jax.device_get(record)

        active = record["active"]                    # (K, B, P)
        self.game_steps += int((active.sum(axis=2) > 0).sum())
        self.player_steps += int(active.sum())

        # span bookkeeping: one (record, k0, k1) entry per lane per call in
        # the common case — not one append per lane per STEP, which at
        # 512 lanes x 32 steps costs ~16k interpreter appends on the very
        # host thread the compute/assembly overlap is keeping light
        episodes = []
        done = record["done"]                        # (K, B)
        lane_has_done = done.any(axis=0)
        K = self.k_steps
        for b in range(self.n_lanes):
            if not lane_has_done[b]:
                self._partial[b].append((record, 0, K))
                continue
            seg = 0
            for kd in np.flatnonzero(done[:, b]):
                kd = int(kd)
                self._partial[b].append((record, seg, kd + 1))
                episodes.append(
                    _streaming_episode(
                        self.venv, self._partial[b], record, kd, b, self.args
                    )
                )
                self._partial[b] = []
                seg = kd + 1        # the lane resets at kd + 1 (next episode)
            if seg < K:
                self._partial[b].append((record, seg, K))
        return episodes

    def drain(self) -> None:
        """Block on the in-flight device block.  MUST be called before the
        owning process exits: tearing down the runtime while an async
        dispatch is still executing cancels XLA's worker threads mid-thunk
        and aborts the process (observed as 'FATAL: exception not
        rethrown' at interpreter exit)."""
        import jax as _jax

        if self._pending is not None:
            _jax.block_until_ready(self._pending)
