"""Fully on-device self-play: env stepping + inference + sampling in ONE jit.

The thread-actor plane (runtime/worker.py + inference_engine.py) keeps the
reference's architecture — host envs, device model — and pays one host
round-trip per step wave. For envs that also exist as pure jnp transition
functions (envs/vector_tictactoe.py), this module removes the host from
the loop entirely: a ``lax.scan`` steps B games for max_steps, sampling
actions on device via Gumbel-max over legal-masked logits, and the ONLY
host work left is converting finished games into the standard columnar
episode format for the replay store. This is the actor-plane design point
the reference's process tree (worker.py:110-189) cannot express — per-step
throughput scales with the device batch, not with host round-trips.

Behavior parity with the host Generator (runtime/generation.py):
temperature-1 softmax sampling over legal-masked logits, recorded
behavior prob / action mask / critic value per turn player, discounted
returns (zero for reward-free games), identical columnar block schema —
pinned by tests/test_device_rollout.py, which replays every device game
through the host env.
"""

from __future__ import annotations

from typing import Any, Dict, List

import jax
import jax.numpy as jnp
import numpy as np

from .replay import compress_block

ILLEGAL = 1e32


def build_selfplay_fn(venv, module, n_games: int):
    """Compile-once device self-play for a VectorTicTacToe-style env.

    Returns ``fn(params, rng_key) -> columns`` (jitted), where columns are
    time-major device arrays over the full max_steps horizon:
        obs    (T, B, ...)  turn player's observation
        prob   (T, B)       behavior probability of the selected action
        action (T, B) int32
        amask  (T, B, A)    0 legal / 1e32 illegal at selection time
        value  (T, B)       critic output at acting time
        alive  (T, B)       1.0 while the game was still running
        outcome (B, P)      final per-player scores
    """

    def fn(params, key):
        keys = jax.random.split(key, venv.max_steps)

        # strict alternation lets the step index be a Python int: unroll
        # over max_steps (9 for TicTacToe) so observation/turn math is
        # static per step while the games stay batched on device
        cols = {"obs": [], "prob": [], "action": [], "amask": [], "value": [], "alive": []}
        state = venv.init(n_games)
        for t in range(venv.max_steps):
            alive = ~venv.terminal(state, t)
            obs = venv.observation(state, t)
            out = module.apply({"params": params}, obs, None)
            logits = out["policy"].astype(jnp.float32)
            amask = jnp.where(venv.legal_mask(state), 0.0, ILLEGAL)
            masked = logits - amask
            # Gumbel-max == sampling from softmax(masked) (generation.py
            # samples softmax at temperature 1)
            g = jax.random.gumbel(keys[t], masked.shape)
            action = jnp.argmax(masked + g, axis=-1)
            probs = jax.nn.softmax(masked, axis=-1)
            prob = jnp.take_along_axis(probs, action[:, None], axis=-1)[:, 0]

            cols["obs"].append(obs)
            cols["prob"].append(prob)
            cols["action"].append(action.astype(jnp.int32))
            cols["amask"].append(amask)
            cols["value"].append(out["value"][:, 0] if out.get("value") is not None else jnp.zeros_like(prob))
            cols["alive"].append(alive.astype(jnp.float32))
            state = venv.apply(state, action, t)

        stacked = {k: jnp.stack(v) for k, v in cols.items()}
        stacked["outcome"] = venv.outcome(state)
        return stacked

    return jax.jit(fn)


def columns_to_episodes(host_cols: Dict[str, Any], venv, args: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Device rollout columns -> standard columnar episodes (the schema of
    Generator._finalize, runtime/generation.py) ready for EpisodeStore."""
    P = venv.num_players
    A = venv.num_actions
    alive = np.asarray(host_cols["alive"])               # (T, B)
    lengths = alive.sum(axis=0).astype(np.int32)         # (B,)
    outcome = np.asarray(host_cols["outcome"])           # (B, P)
    obs = np.asarray(host_cols["obs"])                   # (T, B, ...)
    prob = np.asarray(host_cols["prob"])
    action = np.asarray(host_cols["action"])
    amask = np.asarray(host_cols["amask"])
    value = np.asarray(host_cols["value"])

    block_len = args["compress_steps"]
    players = list(range(P))
    episodes = []
    for b in range(obs.shape[1]):
        T = int(lengths[b])
        if T == 0:
            continue
        blocks = []
        for lo in range(0, T, block_len):
            hi = min(lo + block_len, T)
            t = hi - lo
            ts = np.arange(lo, hi)
            tp = ts % P                                   # turn player per step
            cols = {
                "prob": np.ones((t, P), np.float32),
                "action": np.zeros((t, P), np.int32),
                "amask": np.full((t, P, A), ILLEGAL, np.float32),
                "value": np.zeros((t, P), np.float32),
                "reward": np.zeros((t, P), np.float32),
                "ret": np.zeros((t, P), np.float32),
                "tmask": np.zeros((t, P), np.float32),
                "omask": np.zeros((t, P), np.float32),
                "turn": tp.astype(np.int32),
            }
            rows = np.arange(t)
            cols["prob"][rows, tp] = prob[ts, b]
            cols["action"][rows, tp] = action[ts, b]
            cols["amask"][rows, tp] = amask[ts, b]
            cols["value"][rows, tp] = value[ts, b]
            cols["tmask"][rows, tp] = 1.0
            cols["omask"][rows, tp] = 1.0
            obs_block = np.zeros((t, P) + obs.shape[2:], np.float32)
            obs_block[rows, tp] = obs[ts, b]
            cols["obs"] = obs_block
            blocks.append(compress_block(cols))
        episodes.append(
            {
                "args": {"player": players, "model_id": {p: -1 for p in players}},
                "steps": T,
                "players": players,
                "outcome": {p: float(outcome[b, p]) for p in players},
                "blocks": blocks,
            }
        )
    return episodes


class DeviceRollout:
    """Compile-once wrapper: generate whole batches of finished episodes
    with a single device call each."""

    def __init__(self, venv, module, args: Dict[str, Any], n_games: int = 256):
        self.venv = venv
        self.args = args
        self.n_games = n_games
        self._fn = build_selfplay_fn(venv, module, n_games)

    def generate(self, params, key) -> List[Dict[str, Any]]:
        cols = self._fn(params, key)
        return columns_to_episodes(jax.device_get(cols), self.venv, self.args)


# ---------------------------------------------------------------------------
# Streaming rollout for simultaneous-move envs (VectorHungryGeese)
# ---------------------------------------------------------------------------


def build_streaming_fn(venv, module, n_lanes: int, k_steps: int):
    """Compile-once streaming self-play step for a simultaneous-move vector
    env (``venv.simultaneous``): ``fn(params, state, key) -> (state, record)``
    scans ``k_steps`` game steps over ``n_lanes`` persistent lanes,
    auto-resetting finished lanes at each iteration start so no device work
    is wasted on dead games.  Episodes are stitched across calls by
    StreamingDeviceRollout from the COMPACT per-step record (occupancy +
    heads + food, not full observation planes) — ~40x less HBM->host
    traffic than shipping the 17-plane observations, which the host
    reconstructs with pure numpy scatter ops."""

    def fn(params, state, key):
        def body(state, key_t):
            kr, ka, kf = jax.random.split(key_t, 3)
            reset = state["done"]
            state = venv.reset_done(state, kr)
            active = state["active"]                     # (B, P) acting mask
            obs = venv.observation(state)                # (B, P, ...)
            B, P = active.shape
            flat = obs.reshape((B * P,) + obs.shape[2:])
            out = module.apply({"params": params}, flat, None)
            logits = out["policy"].astype(jnp.float32).reshape(B, P, -1)
            # every action is legal in these envs (reversal is legal-but-
            # lethal, host legal_actions); Gumbel-max == softmax sampling
            g = jax.random.gumbel(ka, logits.shape)
            action = jnp.argmax(logits + g, axis=-1).astype(jnp.int32)
            probs = jax.nn.softmax(logits, axis=-1)
            prob = jnp.take_along_axis(probs, action[..., None], axis=-1)[..., 0]
            value = (
                out["value"].reshape(B, P)
                if out.get("value") is not None
                else jnp.zeros_like(prob)
            )
            record = {
                "reset": reset,
                "active": active,
                "occ": state["occ"],
                "head": venv.head_cell(state).astype(jnp.int8),
                "tail": venv.tail_cell(state).astype(jnp.int8),
                "prev_head": state["prev_head"].astype(jnp.int8),
                "food": state["food"],
                "action": action.astype(jnp.int8),
                "prob": prob,
                "value": value,
            }
            state = venv.step(state, action, kf)
            record["done"] = state["done"]   # reset_done cleared stale flags
            record["rank"] = state["rank"]   # final ranks where done
            return state, record

        return jax.lax.scan(body, state, jax.random.split(key, k_steps))

    return jax.jit(fn, donate_argnums=(1,))


def _streaming_episode(venv, steps: List[tuple], done_rec, done_k: int, lane: int,
                       args: Dict[str, Any]) -> Dict[str, Any]:
    """Assemble one finished lane into the standard columnar episode.

    ``steps`` is the lane's buffered [(record, k)] history (possibly
    spanning several device calls); observation planes are rebuilt from the
    compact occupancy record exactly as the host env builds them
    (envs/hungry_geese.py:242-256) — pinned against the host by
    tests/test_device_rollout.py."""
    P = venv.num_players
    A = venv.num_actions
    T = len(steps)
    b = lane

    def gather(name, dtype=np.float32):
        return np.stack([np.asarray(rec[name][k][b]) for rec, k in steps]).astype(dtype)

    occ = gather("occ")                    # (T, P, C) 0/1
    head = gather("head", np.int32)        # (T, P) -1 absent
    tail = gather("tail", np.int32)
    prev = gather("prev_head", np.int32)
    food = gather("food")                  # (T, C)
    action = gather("action", np.int32)
    prob = gather("prob")
    value = gather("value")
    active = gather("active")              # (T, P) 0/1

    C = occ.shape[-1]
    cell_ids = np.arange(C, dtype=np.int32)
    heads_oh = (head[..., None] == cell_ids).astype(np.float32)   # (T, P, C)
    tails_oh = (tail[..., None] == cell_ids).astype(np.float32)
    prev_oh = (prev[..., None] == cell_ids).astype(np.float32)
    food_pl = food[:, None, :]

    views = []
    for p in range(P):
        planes = np.concatenate(
            [
                np.roll(heads_oh, -p, axis=1),
                np.roll(tails_oh, -p, axis=1),
                np.roll(occ, -p, axis=1),
                np.roll(prev_oh, -p, axis=1),
                food_pl,
            ],
            axis=1,
        )  # (T, 4*P+1, C)
        views.append(planes * active[:, p, None, None])
    obs = np.stack(views, axis=1)  # (T, P, planes, C)
    obs = obs.reshape(obs.shape[:3] + venv.board_shape)

    final_rank = np.asarray(done_rec["rank"][done_k][b])
    outcome = venv.outcome_from_rank(final_rank)
    players = list(range(P))

    block_len = args["compress_steps"]
    blocks = []
    for lo in range(0, T, block_len):
        hi = min(lo + block_len, T)
        t = hi - lo
        act = active[lo:hi]
        cols = {
            "obs": obs[lo:hi],
            "prob": np.where(act > 0, prob[lo:hi], 1.0).astype(np.float32),
            "action": (action[lo:hi] * (act > 0)).astype(np.int32),
            "amask": np.broadcast_to(
                np.where(act[..., None] > 0, 0.0, ILLEGAL), (t, P, A)
            ).astype(np.float32),
            "value": (value[lo:hi] * act).astype(np.float32),
            "reward": np.zeros((t, P), np.float32),
            "ret": np.zeros((t, P), np.float32),
            "tmask": act.astype(np.float32),
            "omask": act.astype(np.float32),
            "turn": np.argmax(act, axis=1).astype(np.int32),
        }
        blocks.append(compress_block(cols))

    return {
        "args": {"player": players, "model_id": {p: -1 for p in players}},
        "steps": T,
        "players": players,
        "outcome": outcome,
        "blocks": blocks,
    }


def make_device_rollout(venv, module, args: Dict[str, Any], n_games: int):
    """Pick the rollout driver for a vector env: episodic single-call
    games for strict-alternation envs (VectorTicTacToe), persistent
    streaming lanes for simultaneous-move envs (VectorHungryGeese)."""
    if getattr(venv, "simultaneous", False):
        return StreamingDeviceRollout(venv, module, args, n_lanes=n_games)
    return DeviceRollout(venv, module, args, n_games)


class StreamingDeviceRollout:
    """Persistent-lane self-play for simultaneous-move vector envs.

    Each ``generate`` call advances every lane ``k_steps`` game steps in
    ONE device call and returns the episodes that finished; in-progress
    games carry over (their lanes keep stepping next call).  Lanes reset
    the moment their game ends, so device utilization is independent of
    episode length — the design point behind the HungryGeese north star.

    Params may change between calls (the learner publishes new epochs);
    in-flight games finish under the newest params and are credited to the
    model_id the caller stamps at flush time — the same staleness the
    IMPALA off-policy corrections (ops/losses.py) already absorb.
    """

    def __init__(self, venv, module, args: Dict[str, Any], n_lanes: int = 256,
                 k_steps: int = 32):
        self.venv = venv
        self.args = args
        self.n_lanes = n_lanes
        self.k_steps = k_steps
        self._fn = build_streaming_fn(venv, module, n_lanes, k_steps)
        self._state = None
        self._partial: List[List[tuple]] = [[] for _ in range(n_lanes)]
        self.game_steps = 0          # lifetime game-steps (>=1 goose acting)
        self.player_steps = 0        # lifetime per-player acting steps

    def generate(self, params, key) -> List[Dict[str, Any]]:
        import jax as _jax

        if self._state is None:
            key, k0 = _jax.random.split(key)
            self._state = self.venv.init(self.n_lanes, k0)
        self._state, record = self._fn(params, self._state, key)
        record = _jax.device_get(record)

        active = record["active"]                    # (K, B, P)
        self.game_steps += int((active.sum(axis=2) > 0).sum())
        self.player_steps += int(active.sum())

        episodes = []
        reset = record["reset"]
        done = record["done"]
        for k in range(self.k_steps):
            for b in np.flatnonzero(reset[k]):
                self._partial[b] = []    # lane restarted (episode already flushed)
            for b in range(self.n_lanes):
                self._partial[b].append((record, k))
            for b in np.flatnonzero(done[k]):
                episodes.append(
                    _streaming_episode(
                        self.venv, self._partial[b], record, k, b, self.args
                    )
                )
                self._partial[b] = []
        return episodes
