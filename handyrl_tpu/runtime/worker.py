"""Actor workers and the local worker pool.

Topology vs the reference (handyrl/worker.py:26-189): the reference forks
Gather processes each owning ~16 Worker processes doing batch-1 torch-CPU
inference.  Here actors are *threads* sharing one device model through the
batched inference engine — the env step is cheap host python (no GIL
problem: the heavy part releases it inside XLA), and cross-env batching is
exactly what the TPU wants.  The remote path (TCP workers on other
machines, worker.py:192-271) plugs the same Worker loop into a socket
connection instead of a direct callable.

Protocol parity (worker.py:66-87): workers ask ``('args', None)``, run one
generation or evaluation job, and report ``('episode', ep)`` /
``('result', res)``.  Model ids: 0 = random model, -1 = latest, epoch
numbers otherwise.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ..envs import make_env, prepare_env
from ..models import InferenceModel, RandomModel, init_variables
from .evaluation import Evaluator
from .generation import Generator
from .inference_engine import BatchedInferenceEngine


class LocalModelServer:
    """Serves model handles by id to in-process workers.

    The latest model lives behind ONE BatchedInferenceEngine shared by all
    actor threads; older epoch snapshots are loaded from disk on demand
    (reference train.py:604-614); id 0 is the zero-output RandomModel
    (reference worker.py:56-59).
    """

    def __init__(self, module, env, args: Dict[str, Any]):
        self.module = module
        self.args = args
        self.model_dir = args.get("model_dir", "models")
        variables = init_variables(module, env)
        self._model = InferenceModel(module, variables)
        env.reset()
        self._random = RandomModel.from_model(self._model, env.observation(env.players()[0]))
        self.engine = BatchedInferenceEngine(
            self._model, max_batch=args.get("inference_batch_size", 64)
        ).start()
        self.model_id = 0
        self._lock = threading.Lock()
        # cumulative count of requested snapshots served as LATEST instead
        # (missing / GC'd / corrupt file).  The substitution itself is the
        # right degradation — but an eval book quietly scored against the
        # wrong model must be VISIBLE, so the learner surfaces this in
        # metrics.jsonl as serve_snapshot_substituted
        self.substituted_snapshots = 0

    def publish(self, model_id: int, params) -> None:
        """Swap the served latest model (called by the learner per epoch)."""
        with self._lock:
            self._model = InferenceModel(self.module, {"params": params})
            self.engine.update_model(self._model)
            self.model_id = model_id

    def latest_params(self):
        return self._model.variables["params"]

    def latest_snapshot(self):
        """(model_id, params) read atomically — callers caching per id must
        not pair a stale id with newer params published in between."""
        with self._lock:
            return self.model_id, self._model.variables["params"]

    def stop(self) -> None:
        """Release the serving plane (Learner teardown); subclasses with
        more resident machinery (the league's router engines) extend it."""
        self.engine.stop()

    def get(self, model_id: int):
        if model_id == 0:
            return self._random
        with self._lock:
            current = self.model_id
        if model_id < 0 or model_id >= current:
            return self.engine.client()
        # old snapshot from disk; rare (transient stale ids / explicit
        # evals).  Digest-verified: a bit-rotted old snapshot silently
        # deciding evaluation outcomes would poison the win-rate books.
        from .checkpoint import load_verified_params

        try:
            params = load_verified_params(
                self.model_dir, model_id, self.latest_params()
            )
            return InferenceModel(self.module, {"params": params})
        except Exception:
            # missing / GC'd / corrupt snapshot: serve latest instead —
            # counted, so a poisoned eval book shows up in metrics.jsonl
            with self._lock:
                self.substituted_snapshots += 1
            return self.engine.client()


class Worker:
    """One actor loop: ask for a job, run it, report (worker.py:66-87)."""

    def __init__(self, env, args: Dict[str, Any], conn: Callable, model_server: LocalModelServer, wid: int = 0):
        self.env = env
        self.args = args
        self.conn = conn  # callable (req, data) -> response
        self.model_server = model_server
        self.wid = wid
        self.generator = Generator(env, args)
        self.evaluator = Evaluator(env, args)

    def _gather_models(self, model_ids: Dict[int, int]) -> Dict[int, Any]:
        return {p: self.model_server.get(mid) for p, mid in model_ids.items()}

    def run(self) -> None:
        from .inference_engine import EngineStopped

        while True:
            try:
                args = self.conn("args", None)
            except (ConnectionResetError, BrokenPipeError, OSError):
                break  # transport gone (severed/stalled gather); exit cleanly
            if args is None:
                break
            role = args["role"]
            try:
                models = self._gather_models(args["model_id"])
                if role == "g":
                    episode = self.generator.execute(models, args)
                    self.conn("episode", episode)
                elif role == "e":
                    result = self.evaluator.execute(models, args)
                    self.conn("result", result)
            except EngineStopped:
                break  # learner shut the engine down mid-job; drain quietly
            except (ConnectionResetError, BrokenPipeError, OSError):
                break  # transport gone; nothing left to report to
            except Exception as exc:
                # a transient job failure (e.g. one bad XLA batch fanned out
                # to every engine waiter) must not kill the actor thread —
                # a dead thread shrinks the pool and hangs learner shutdown
                print(f"worker {self.wid} job failed: {type(exc).__name__}: {exc}")
                if role == "g":
                    self.conn("episode", None)  # keep the server's books consistent
                elif role == "e":
                    self.conn("result", None)


class LocalWorkerPool:
    """Thread-per-actor pool feeding the learner directly (no sockets).

    Replaces WorkerCluster's Gather/Worker process tree (worker.py:99-189):
    with the shared inference engine there is nothing to fan out — request
    batching happens at the engine, so workers talk straight to the
    learner's request handler.
    """

    def __init__(self, args: Dict[str, Any], handler: Callable, model_server: LocalModelServer):
        self.args = args
        self.handler = handler  # learner's (req, data) -> response
        self.model_server = model_server
        self.threads: List[threading.Thread] = []

    def run(self) -> None:
        env_args = self.args["env"]
        num_parallel = self.args["worker"]["num_parallel"]
        prepare_env(env_args)
        for wid in range(num_parallel):
            worker = Worker(
                make_env(env_args), self.args, self.handler, self.model_server, wid
            )
            t = threading.Thread(target=worker.run, daemon=True, name=f"actor-{wid}")
            t.start()
            self.threads.append(t)

    def join(self, timeout: Optional[float] = None) -> None:
        deadline = None if timeout is None else time.monotonic() + timeout
        for t in self.threads:
            remaining = None if deadline is None else max(0.0, deadline - time.monotonic())
            t.join(remaining)
