"""Checkpointing: epoch-indexed model snapshots + full training state.

Improves on the reference (train.py:448-455, which saves only the model
state_dict): the full checkpoint carries params, optimizer state and step
count so resume continues Adam moments instead of restarting them.
Format is flax msgpack (framework-portable numpy trees).

Layout mirrors the reference naming so tooling ports over:
    models/{epoch}.ckpt    per-epoch params snapshot (servable to workers)
    models/latest.ckpt     copy of the newest snapshot
    models/state.ckpt      params + opt_state + steps (resume)
"""

from __future__ import annotations

import os
from typing import Any, Dict

import jax

from flax import serialization


def save_params(path: str, params: Any) -> None:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "wb") as f:
        f.write(serialization.to_bytes(jax.device_get(params)))


def load_params(path: str, template: Any) -> Any:
    with open(path, "rb") as f:
        return serialization.from_bytes(template, f.read())


def params_to_bytes(params: Any) -> bytes:
    return serialization.to_bytes(jax.device_get(params))


def params_from_bytes(template: Any, blob: bytes) -> Any:
    return serialization.from_bytes(template, blob)


def save_train_state(path: str, state: Dict[str, Any]) -> None:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    host = jax.device_get(state)
    with open(path, "wb") as f:
        f.write(serialization.to_bytes(host))


def load_train_state(path: str, template: Dict[str, Any]) -> Dict[str, Any]:
    with open(path, "rb") as f:
        return serialization.from_bytes(template, f.read())


def model_path(model_dir: str, epoch: int) -> str:
    return os.path.join(model_dir, f"{epoch}.ckpt")


def latest_model_path(model_dir: str) -> str:
    return os.path.join(model_dir, "latest.ckpt")
