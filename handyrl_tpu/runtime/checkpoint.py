"""Checkpointing: epoch-indexed model snapshots + full training state.

Improves on the reference (train.py:448-455, which saves only the model
state_dict): the full checkpoint carries params, optimizer state and step
count so resume continues Adam moments instead of restarting them.
Format is flax msgpack (framework-portable numpy trees).

Layout mirrors the reference naming so tooling ports over:
    models/{epoch}.ckpt    per-epoch params snapshot (servable to workers)
    models/latest.ckpt     copy of the newest snapshot
    models/state.ckpt      params + opt_state + steps (resume)
    models/MANIFEST.json   per-epoch CRC32 digests of the files above

Durability contract (docs/fault_tolerance.md): every write here is
tmp-file -> fsync -> atomic rename, so a crash mid-save can never corrupt
an existing resume point — the worst case is a stray ``*.tmp.*`` file.
The manifest records epoch, step count and a CRC32 + size per file;
``restart_epoch: -1`` resumes from the newest manifest entry whose
snapshot still verifies, falling back to older verified entries, and an
explicitly requested epoch REFUSES to load a file whose digest no longer
matches (silent corruption must fail loudly, not train on garbage).
"""

from __future__ import annotations

import json
import os
import re
import tempfile
import zlib
from typing import Any, Dict, List, Optional, Tuple

import jax

from flax import serialization

MANIFEST_NAME = "MANIFEST.json"

_EPOCH_CKPT_RE = re.compile(r"^(\d+)\.ckpt$")


class CheckpointError(RuntimeError):
    """A checkpoint file failed digest verification or cannot be trusted."""


# ---------------------------------------------------------------------------
# atomic file plumbing
# ---------------------------------------------------------------------------


def _fsync_dir(path: str) -> None:
    """Durably record a rename in its directory (best-effort off Linux)."""
    try:
        fd = os.open(path or ".", os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def atomic_write_bytes(path: str, data: bytes) -> None:
    """tmp file in the target dir -> write -> fsync -> atomic rename.

    A reader can only ever observe the old complete file or the new
    complete file; a crash at any instant leaves at most a stray tmp file
    (which resume ignores — only manifest-recorded names are considered).
    """
    directory = os.path.dirname(path) or "."
    os.makedirs(directory, exist_ok=True)
    fd, tmp = tempfile.mkstemp(
        prefix=os.path.basename(path) + ".tmp.", dir=directory
    )
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        _fsync_dir(directory)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def file_digest(path: str) -> Tuple[int, int]:
    """(crc32, size) of a file, streamed (snapshots can be large)."""
    crc = 0
    size = 0
    with open(path, "rb") as f:
        while True:
            chunk = f.read(1 << 20)
            if not chunk:
                break
            crc = zlib.crc32(chunk, crc)
            size += len(chunk)
    return crc, size


# ---------------------------------------------------------------------------
# serialization (kept signature-compatible with the pre-manifest API; all
# saves are atomic now)
# ---------------------------------------------------------------------------


def save_params(path: str, params: Any) -> None:
    atomic_write_bytes(path, serialization.to_bytes(jax.device_get(params)))


def load_params(path: str, template: Any) -> Any:
    with open(path, "rb") as f:
        return serialization.from_bytes(template, f.read())


def params_to_bytes(params: Any) -> bytes:
    return serialization.to_bytes(jax.device_get(params))


def params_from_bytes(template: Any, blob: bytes) -> Any:
    return serialization.from_bytes(template, blob)


def save_train_state(path: str, state: Dict[str, Any]) -> None:
    atomic_write_bytes(path, serialization.to_bytes(jax.device_get(state)))


def load_train_state(path: str, template: Dict[str, Any]) -> Dict[str, Any]:
    with open(path, "rb") as f:
        return serialization.from_bytes(template, f.read())


def model_path(model_dir: str, epoch: int) -> str:
    return os.path.join(model_dir, f"{epoch}.ckpt")


def latest_model_path(model_dir: str) -> str:
    return os.path.join(model_dir, "latest.ckpt")


# ---------------------------------------------------------------------------
# manifest
# ---------------------------------------------------------------------------


def load_manifest(model_dir: str, strict: bool = False) -> Dict[str, Any]:
    """The digest manifest; a MISSING file is an empty manifest (pre-
    manifest runs must keep loading).

    An UNPARSEABLE file is different: manifest writes are atomic, so
    invalid JSON means real corruption is present on this disk — with
    ``strict`` (every verification path) that raises CheckpointError
    rather than silently disabling all digest checks exactly when they
    matter most.  Non-strict callers (the save path, GC) start a fresh
    manifest instead: refusing to record NEW snapshots because an old
    record rotted would kill a healthy training run, and the rewrite
    self-heals the file.
    """
    path = os.path.join(model_dir, MANIFEST_NAME)
    try:
        with open(path) as f:
            manifest = json.load(f)
        if not isinstance(manifest, dict) or not isinstance(manifest.get("epochs"), dict):
            raise ValueError("manifest is not an object with an 'epochs' map")
    except OSError:
        return {"version": 1, "epochs": {}}
    except ValueError as exc:
        if strict:
            raise CheckpointError(
                f"{path} is corrupt ({exc}); digest verification is "
                "impossible — inspect the checkpoint dir (delete the "
                "manifest to explicitly accept an unverified resume)"
            )
        return {"version": 1, "epochs": {}}
    return manifest


def _write_manifest(model_dir: str, manifest: Dict[str, Any]) -> None:
    atomic_write_bytes(
        os.path.join(model_dir, MANIFEST_NAME),
        json.dumps(manifest, indent=1, sort_keys=True).encode(),
    )


def _verify_file(path: str, meta: Dict[str, Any]) -> bool:
    try:
        crc, size = file_digest(path)
    except OSError:
        return False
    return crc == int(meta["crc32"]) and size == int(meta["size"])


def verify_snapshot(model_dir: str, epoch: int) -> Optional[bool]:
    """Does ``{epoch}.ckpt`` match its manifest digest?

    None = the manifest has no record of this epoch (pre-manifest file:
    nothing to verify against); True/False otherwise.
    """
    entry = load_manifest(model_dir, strict=True)["epochs"].get(str(int(epoch)))
    if entry is None:
        return None
    meta = entry.get("files", {}).get(f"{int(epoch)}.ckpt")
    if meta is None:
        return None
    return _verify_file(model_path(model_dir, epoch), meta)


def verify_state(model_dir: str, epoch: int) -> Optional[bool]:
    """Does state.ckpt match the digest recorded at ``epoch``?

    state.ckpt is overwritten every epoch, so only the NEWEST manifest
    entry's digest can match a healthy file; older entries' records are
    stale by construction and the epoch guard in Trainer.load_state
    handles that case.  None = no record to verify against.
    """
    entry = load_manifest(model_dir, strict=True)["epochs"].get(str(int(epoch)))
    meta = (entry or {}).get("files", {}).get("state.ckpt")
    if meta is None:
        return None
    return _verify_file(os.path.join(model_dir, "state.ckpt"), meta)


def record_snapshot(
    model_dir: str,
    epoch: int,
    steps: int,
    file_digests: Dict[str, Tuple[int, int]],
) -> None:
    """Append one epoch's entry to the manifest (atomically rewritten)."""
    manifest = load_manifest(model_dir)
    manifest["epochs"][str(int(epoch))] = {
        "steps": int(steps),
        "files": {
            name: {"crc32": int(crc), "size": int(size)}
            for name, (crc, size) in file_digests.items()
        },
    }
    _write_manifest(model_dir, manifest)


def save_epoch_snapshot(
    model_dir: str, epoch: int, params: Any, state_payload: Dict[str, Any], steps: int
) -> None:
    """One epoch boundary's full durable save: ``{epoch}.ckpt`` +
    ``latest.ckpt`` + ``state.ckpt``, each tmp->fsync->rename, then the
    manifest entry with a CRC32 per file.  Params serialize once; the
    digests come from the in-memory blobs (no read-back)."""
    params_blob = params_to_bytes(params)
    state_blob = serialization.to_bytes(jax.device_get(state_payload))
    atomic_write_bytes(model_path(model_dir, epoch), params_blob)
    atomic_write_bytes(latest_model_path(model_dir), params_blob)
    atomic_write_bytes(os.path.join(model_dir, "state.ckpt"), state_blob)
    params_digest = (zlib.crc32(params_blob), len(params_blob))
    record_snapshot(
        model_dir,
        epoch,
        steps,
        {
            f"{int(epoch)}.ckpt": params_digest,
            "latest.ckpt": params_digest,
            "state.ckpt": (zlib.crc32(state_blob), len(state_blob)),
        },
    )


def latest_verified_epoch(model_dir: str) -> int:
    """Newest epoch whose snapshot verifies; 0 when none does.

    The auto-resume entry point (``restart_epoch: -1``): corrupt or
    missing snapshots are skipped, falling back to the next-older verified
    entry, so a crash mid-write (or a bit-flipped file) costs at most one
    epoch, never the run.  Pre-manifest run directories (an upgraded
    long-running job) fall back to the newest on-disk ``{N}.ckpt`` the
    manifest never recorded — mirroring ``load_verified_params``'s
    leniency for unrecorded files, so flipping a launcher to ``-1`` can
    never silently restart an old run from scratch.  Files the manifest
    DOES record but that fail verification stay refused.
    """
    manifest = load_manifest(model_dir, strict=True)
    recorded = manifest["epochs"]
    for key in sorted(recorded, key=int, reverse=True):
        meta = recorded[key].get("files", {}).get(f"{key}.ckpt")
        if meta is not None and _verify_file(model_path(model_dir, int(key)), meta):
            return int(key)
    try:
        names = os.listdir(model_dir)
    except OSError:
        return 0
    unrecorded = [
        int(m.group(1))
        for name in names
        if (m := _EPOCH_CKPT_RE.match(name)) and str(int(m.group(1))) not in recorded
    ]
    return max(unrecorded, default=0)


def load_verified_params(
    model_dir: str, epoch: int, template: Any, pre_verified: bool = False
) -> Any:
    """load_params that refuses a digest-mismatched snapshot.

    Files the manifest never recorded (pre-manifest runs) load as before;
    a recorded file whose bytes no longer match raises CheckpointError —
    silently training on a corrupt snapshot is the one unrecoverable
    failure mode.  ``pre_verified`` skips the digest scan when the caller
    JUST verified this epoch (auto-resume via latest_verified_epoch):
    multi-GB snapshots should not be streamed twice at startup.
    """
    verdict = None if pre_verified else verify_snapshot(model_dir, epoch)
    if verdict is False:
        raise CheckpointError(
            f"{model_path(model_dir, epoch)} does not match its manifest "
            "digest (truncated or corrupt); refusing to load — use "
            "restart_epoch: -1 to fall back to the newest verified snapshot"
        )
    return load_params(model_path(model_dir, epoch), template)


def _newest_verified_recorded(model_dir: str) -> int:
    """Newest manifest-recorded epoch whose snapshot digest-verifies
    (0 = none).  Non-strict manifest load: this runs on the GC/save path,
    where a rotted manifest must not kill a healthy run (load_manifest's
    contract); the rollback entry points stay strict."""
    recorded = load_manifest(model_dir)["epochs"]
    for key in sorted(recorded, key=int, reverse=True):
        meta = recorded[key].get("files", {}).get(f"{key}.ckpt")
        if meta is not None and _verify_file(model_path(model_dir, int(key)), meta):
            return int(key)
    return 0


def gc_snapshots(model_dir: str, keep: int, pin=()) -> List[int]:
    """Delete epoch snapshots older than the newest ``keep`` (0 = keep
    all), pruning their manifest entries.  Only ``{N}.ckpt`` files are
    touched; latest.ckpt / state.ckpt always survive.  Returns the epochs
    removed.

    The newest VERIFIED snapshot is PINNED (never collected) even when it
    falls outside the retention window: it is the divergence sentinel's
    rollback target and auto-resume's landing point — if the newest
    ``keep`` snapshots are all corrupt, collecting the last verified one
    would turn a one-epoch rollback into a from-scratch restart.  The
    verification walk is newest-first, so on a healthy directory it costs
    one digest stream of the just-saved snapshot.

    ``pin`` names further epochs the caller needs durable beyond the
    retention window — the league's frozen population members reference
    their snapshots for the whole run (handyrl_tpu/league), and a frozen
    opponent GC'd mid-run would silently flip matches onto substitute
    params and poison the payoff books."""
    if keep <= 0:
        return []
    try:
        names = os.listdir(model_dir)
    except OSError:
        return []
    epochs = sorted(
        int(m.group(1)) for name in names if (m := _EPOCH_CKPT_RE.match(name))
    )
    doomed = epochs[:-keep] if len(epochs) > keep else []
    if not doomed:
        return []
    pinned = {_newest_verified_recorded(model_dir)} | {int(e) for e in pin}
    doomed = [e for e in doomed if e not in pinned]
    if not doomed:
        return []
    for epoch in doomed:
        try:
            os.unlink(model_path(model_dir, epoch))
        except OSError:
            pass
    manifest = load_manifest(model_dir)
    pruned = {k: v for k, v in manifest["epochs"].items() if int(k) not in set(doomed)}
    if len(pruned) != len(manifest["epochs"]):
        manifest["epochs"] = pruned
        _write_manifest(model_dir, manifest)
    return doomed
