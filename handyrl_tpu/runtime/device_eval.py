"""On-device evaluation: batched net-vs-baseline matches in one jit.

The host evaluator (runtime/evaluation.py, reference evaluation.py:153-261)
plays one game per thread through per-step inference calls — on a 1-core
host or a high-RTT tunnel it starves: both round-3 learning soaks recorded
NaN/sparse per-epoch win-rate curves because the single eval worker could
not finish games between epoch boundaries.  This module is the device twin
of that loop for vector envs: N lanes play the NET (greedy argmax, the
host Agent's temperature-0 behavior) on designated seats against a
scripted baseline on the others — ``rulebase`` via the env's
``rule_based_action_all`` device twin, or ``random`` via Gumbel-max over
the legal mask — with streaming auto-reset, emitting only (done, outcome)
per step.  The host aggregates exact outcome counts, so ``wp_func`` and
the soak margin calibration apply unchanged.

Seat balancing: ``net_seat`` assigns the net's seat PER LANE (round-robin
by default), the batched analogue of evaluate_mp's first/second patterns
(evaluation.py:216-219).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..utils import tree_map

ILLEGAL = 1e32


def build_eval_stream_fn(venv, module, n_lanes: int, k_steps: int,
                         opponent: str = "rulebase", mesh=None):
    """Compile-once ``fn(params, state, hidden, net_seat, key) ->
    (state, hidden, record)``: scan ``k_steps`` game steps over
    ``n_lanes`` auto-resetting eval matches.

    ``net_seat`` is a (B,) int32 array: the seat the net plays in each
    lane (every other seat runs the baseline).  The record carries
    ``done`` (K, B) and ``outcome`` (K, B, P) — final scores where done,
    the same contract as the streaming rollout's record fields.
    """
    if opponent == "rulebase" and not hasattr(venv, "rule_based_action_all"):
        raise ValueError(
            f"{getattr(venv, '__name__', type(venv).__name__)} has no "
            "rule_based_action_all device twin; use opponent='random'"
        )
    if opponent not in ("rulebase", "random"):
        raise ValueError(f"device eval opponent must be rulebase|random, got {opponent!r}")
    P = venv.num_players

    def fn(params, state, hidden, net_seat, key):
        def body(carry, key_t):
            state, hidden = carry
            kr, ka, kf = jax.random.split(key_t, 3)
            reset = state["done"]
            state = venv.reset_done(state, kr)
            if hidden is not None:
                hidden = tree_map(
                    lambda h: h * ~reset.reshape((-1,) + (1,) * (h.ndim - 1)),
                    hidden,
                )
            obs = venv.observation(state)                # leaves (B, P, ...)
            B = state["done"].shape[0]
            flat = tree_map(lambda x: x.reshape((B * P,) + x.shape[2:]), obs)
            h_flat = (
                None if hidden is None
                else tree_map(lambda h: h.reshape((B * P,) + h.shape[2:]), hidden)
            )
            out = module.apply({"params": params}, flat, h_flat)
            if hidden is not None:
                # eval advances hidden for every seat every step, like the
                # host Agent with observation=True (agents.py observe())
                hidden = tree_map(
                    lambda h: h.reshape((B, P) + h.shape[1:]), out["hidden"]
                )
            logits = out["policy"].astype(jnp.float32).reshape(B, P, -1)
            legal = venv.legal_mask_all(state)           # (B, P, A)
            masked = jnp.where(legal, logits, logits - ILLEGAL)
            net_act = jnp.argmax(masked, axis=-1).astype(jnp.int32)  # greedy
            if opponent == "rulebase":
                opp_act = venv.rule_based_action_all(state, ka)
            else:
                g = jax.random.gumbel(ka, masked.shape)
                opp_act = jnp.argmax(
                    jnp.where(legal, g, -jnp.inf), axis=-1
                ).astype(jnp.int32)
            is_net = jnp.arange(P, dtype=jnp.int32)[None, :] == net_seat[:, None]
            actions = jnp.where(is_net, net_act, opp_act)
            state = venv.step(state, actions, kf)
            record = {
                "done": state["done"],
                "outcome": venv.outcome_scores(state),
            }
            return (state, hidden), record

        (state, hidden), records = jax.lax.scan(
            body, (state, hidden), jax.random.split(key, k_steps)
        )
        return state, hidden, records

    if mesh is None:
        return jax.jit(fn, donate_argnums=(1, 2))
    from jax.sharding import NamedSharding, PartitionSpec

    lanes = NamedSharding(mesh, PartitionSpec("dp"))
    rec = NamedSharding(mesh, PartitionSpec(None, "dp"))
    rep = NamedSharding(mesh, PartitionSpec())
    return jax.jit(
        fn, donate_argnums=(1, 2),
        in_shardings=(rep, lanes, lanes, lanes, rep),
        out_shardings=(lanes, lanes, rec),
    )


class DeviceEvaluator:
    """Reusable evaluator: counts net-seat outcomes over >= num_games
    finished matches, reporting {outcome: count} like evaluate_mp's
    totals (so wp_func applies)."""

    def __init__(self, venv, module, n_lanes: int,
                 opponent: str = "rulebase", k_steps: int = 32, mesh=None):
        # fail at construction, not at the first evaluate() trace: the
        # eval stream drives the STREAMING contract; episodic twins
        # (VectorTicTacToe-style) don't have it
        if not (hasattr(venv, "reset_done") and hasattr(venv, "step")):
            raise ValueError(
                f"DeviceEvaluator needs a streaming vector env "
                f"(reset_done/step hooks); "
                f"{getattr(venv, '__name__', type(venv).__name__)} is "
                "episodic — use host eval workers for this env"
            )
        self.venv = venv
        self.module = module
        self.n_lanes = n_lanes
        self.opponent = opponent
        # a size-1 mesh gets no sharding, but the dispatch locks must
        # still cover only ITS device: locking all local devices (the
        # None legacy scope) would stall a split actor plane for the
        # whole multi-dispatch eval at every epoch boundary
        self.mesh = mesh if mesh is not None and mesh.size > 1 else None
        self._lock_devices = (
            list(mesh.devices.flat) if mesh is not None else None
        )
        self._fn = build_eval_stream_fn(
            venv, module, n_lanes, k_steps, opponent=opponent, mesh=self.mesh,
        )
        # per-lane net seat, round-robin: the batched first/second balance
        self._net_seat = jnp.arange(n_lanes, dtype=jnp.int32) % venv.num_players
        self._net_seat_host = np.asarray(self._net_seat)

    def evaluate(self, params, num_games: int, key,
                 max_calls: int = 64) -> Dict[float, int]:
        """Play until ``num_games`` matches finish (or ``max_calls``
        dispatches); returns exact outcome counts for the net's seat."""
        from ..parallel.mesh import dispatch_serialized

        venv = self.venv
        key, k0 = jax.random.split(key)
        state = venv.init(self.n_lanes, k0)
        hidden = self.module.initial_state((self.n_lanes, venv.num_players))
        net_seat = self._net_seat
        seat = self._net_seat_host
        counts: Dict[float, int] = {}
        games = 0
        for _ in range(max_calls):
            key, sub = jax.random.split(key)
            state, hidden, rec = dispatch_serialized(
                lambda: self._fn(params, state, hidden, net_seat, sub),
                self._lock_devices,
            )
            # graftlint: allow[HS001] reason=epoch-boundary eval consumes (done, outcome) on host by design; this loop runs between epochs, not in the training hot loop
            done = np.asarray(jax.device_get(rec["done"]))       # (K, B)
            # graftlint: allow[HS001] reason=epoch-boundary eval consumes (done, outcome) on host by design; this loop runs between epochs, not in the training hot loop
            outcome = np.asarray(jax.device_get(rec["outcome"]))  # (K, B, P)
            ks, bs = np.nonzero(done)
            for k, b in zip(ks, bs):
                o = float(outcome[k, b, seat[b]])
                counts[o] = counts.get(o, 0) + 1
                games += 1
            if games >= num_games:
                break
        return counts
