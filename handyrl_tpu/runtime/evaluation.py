"""Match execution and evaluation harnesses.

Capability parity with reference handyrl/evaluation.py:
* ``exec_match`` — shared-env match loop (evaluation.py:83-109).
* ``exec_network_match`` — split-env match driven by diff_info/update
  deltas (evaluation.py:112-141); agents carry their own replica env.
* ``Evaluator`` — worker-side model-vs-opponent evaluation
  (evaluation.py:153-177).
* ``evaluate`` / ``evaluate_mp`` — standalone eval with first/second
  balancing and per-pattern win-rate report (evaluation.py:180-261).

TPU-first difference: parallel evaluation uses a thread pool sharing one
jitted model (optionally through the batched inference engine) instead of
forking processes that each re-compile; the env step is cheap host python,
the model call is the device-bound part.
"""

from __future__ import annotations

import random
import threading
from typing import Any, Dict, List, Optional



from ..agents import Agent, RandomAgent, RuleBasedAgent
from ..envs import make_env
from ..models import InferenceModel
from .checkpoint import load_params


def view(env, player: Optional[int] = None) -> None:
    if hasattr(env, "view"):
        env.view(player=player)
    else:
        print(env)


def exec_match(env, agents: Dict[int, Any], critic=None, show: bool = False, game_args=None):
    """Run one match on a shared env; returns outcome dict or None on error."""
    if env.reset(game_args or {}):
        return None
    for agent in agents.values():
        agent.reset(env, show=show)
    while not env.terminal():
        if show:
            view(env)
        turn_players = env.turns()
        observers = env.observers()
        actions = {}
        for p, agent in agents.items():
            if p in turn_players:
                actions[p] = agent.action(env, p, show=show)
            elif p in observers:
                agent.observe(env, p, show=show)
        if env.step(actions):
            return None
        if show and critic is not None:
            print("cv = ", critic.observe(env, None, show=False)[0])
    if show:
        view(env)
        print("final outcome = %s" % env.outcome())
    return env.outcome()


def exec_network_match(env, network_agents: Dict[int, Any], critic=None, show: bool = False, game_args=None):
    """Split-env match: each agent holds a replica env synced by deltas."""
    if env.reset(game_args or {}):
        return None
    for p, agent in network_agents.items():
        info = env.diff_info(p)
        agent.update(info, True)
    while not env.terminal():
        if show:
            view(env)
        turn_players = env.turns()
        observers = env.observers()
        actions = {}
        for p, agent in network_agents.items():
            if p in turn_players:
                action = agent.action(p)
                actions[p] = env.str2action(action, p)
            elif p in observers:
                agent.observe(p)
        if env.step(actions):
            return None
        for p, agent in network_agents.items():
            info = env.diff_info(p)
            agent.update(info, False)
    outcome = env.outcome()
    for p, agent in network_agents.items():
        agent.outcome(outcome[p])
    return outcome


def build_agent(raw: Any, env=None) -> Optional[Any]:
    """'random' / 'rulebase[-key]' spec -> agent (evaluation.py:144-150)."""
    if raw == "random":
        return RandomAgent()
    if isinstance(raw, str) and raw.startswith("rulebase"):
        key = raw.split("-")[1] if "-" in raw else None
        return RuleBasedAgent(key)
    return None


def load_model_agent(model_path: str, env, module=None) -> Agent:
    """Checkpoint (.ckpt), exported StableHLO (.hlo), TF SavedModel
    (.tf directory) or ONNX (.onnx, needs onnxruntime) path -> greedy Agent.

    Mirrors reference load_model dispatch (.pth vs .onnx,
    evaluation.py:356-365); exported artifacts need no model code.
    """
    if model_path.endswith(".hlo"):
        from ..models.export import ExportedModel

        return Agent(ExportedModel(model_path))
    if model_path.endswith(".tf"):
        from ..models.export import SavedModelModel

        return Agent(SavedModelModel(model_path))
    if model_path.endswith(".onnx"):
        from ..models.export import OnnxModel

        return Agent(OnnxModel(model_path))
    from ..models import init_variables

    module = module or env.net()
    variables = init_variables(module, env)
    params = load_params(model_path, variables["params"])
    return Agent(InferenceModel(module, {"params": params}))


class Evaluator:
    """Worker-side evaluation executor (evaluation.py:153-177)."""

    def __init__(self, env, args: Dict[str, Any]):
        self.env = env
        self.args = args
        self.opponent = args.get("eval", {}).get("opponent", ["random"])
        if not isinstance(self.opponent, list):
            self.opponent = [self.opponent]

    def execute(self, models: Dict[int, Any], args: Dict[str, Any]) -> Optional[Dict[str, Any]]:
        opponents = [o for o in self.opponent if build_agent(o, self.env) is not None] or ["random"]
        opponent = random.choice(opponents)

        agents = {}
        for p in self.env.players():
            if p in args["player"]:
                agents[p] = Agent(models[p], observation=self.args.get("observation", False))
            else:
                agents[p] = build_agent(opponent, self.env)
        outcome = exec_match(self.env, agents)
        if outcome is None:
            print("None episode in evaluation!")
            return None
        return {"args": args, "result": outcome, "opponent": opponent}


def wp_func(results: Dict[Any, int]) -> float:
    """Win points: 1 per win, 0.5 per draw, over finished games."""
    games = sum(results.values())
    win = sum(v for k, v in results.items() if k is not None and k > 0)
    draw = sum(v for k, v in results.items() if k == 0)
    return (win + draw / 2) / max(games, 1e-6)


def evaluate_mp(env_args: Dict[str, Any], agents: Dict[int, Any], num_games: int, num_workers: int = 4, seed: int = 0):
    """Parallel evaluation over a thread pool with first/second balancing.

    Returns {pattern: {outcome: count}} keyed by the player-order pattern.
    """
    players = make_env(env_args).players()
    patterns: List[List[int]] = []
    if len(players) == 2:
        # balance first/second seats (evaluation.py:216-219)
        patterns = [[0, 1], [1, 0]]
    else:
        patterns = [list(players)]

    jobs: List = []
    for i in range(num_games):
        pat = patterns[i % len(patterns)]
        jobs.append((i, pat))

    results: Dict[str, Dict[Any, int]] = {str(p): {} for p in patterns}
    lock = threading.Lock()
    job_iter = iter(jobs)

    def run():
        import copy

        env = make_env(env_args)
        # per-thread shallow clones: models are shared (thread-safe jitted
        # apply) but Agent.hidden is per-game state and must not be raced
        local_agents = {k: copy.copy(a) for k, a in agents.items()}
        while True:
            with lock:
                job = next(job_iter, None)
            if job is None:
                return
            _, pat = job
            # pattern maps seat -> agent key; agents keyed by original order
            seat_agents = {seat: local_agents[pat[idx]] for idx, seat in enumerate(env.players())}
            try:
                outcome = exec_match(env, seat_agents)
            except Exception as exc:
                # a broken agent/model must not silently zero the report
                print(f"match failed: {type(exc).__name__}: {exc}")
                continue
            if outcome is None:
                continue
            # score from agent 0's perspective wherever it sat
            seat0 = env.players()[pat.index(0)]
            o = outcome[seat0]
            with lock:
                results[str(pat)][o] = results[str(pat)].get(o, 0) + 1

    threads = [threading.Thread(target=run) for _ in range(max(1, num_workers))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    total: Dict[Any, int] = {}
    for pat, res in results.items():
        games = sum(res.values())
        print("%s = %.3f (%d)" % (pat, wp_func(res), games))
        for k, v in res.items():
            total[k] = total.get(k, 0) + v
    print("total = %.3f (%d)" % (wp_func(total), sum(total.values())))
    return results


def eval_vs_baseline(env_args: Dict[str, Any], agent0, opponent: str,
                     num_games: int, num_workers: int = 4):
    """(win points, mean outcome) for ``agent0`` with every other seat
    played by ``opponent`` (an agent spec for build_agent, e.g. 'rulebase').

    Mean outcome is the finer signal on rank-ladder envs: HungryGeese
    outcomes are {-1, -1/3, +1/3, +1} (hungry_geese.py outcome), so the
    mean moves with every rank gained, while win points only see the
    top-half/bottom-half boundary.  The learning soaks' margin calibration
    (tests/test_soak.py) is defined against THIS aggregation — keep the
    single copy."""
    env = make_env(env_args)
    agents: Dict[int, Any] = {0: agent0}
    for k in env.players()[1:]:
        opp = build_agent(opponent)
        if opp is None:
            raise ValueError(f"unknown baseline opponent spec {opponent!r}")
        agents[k] = opp
    results = evaluate_mp(env_args, agents, num_games, num_workers)
    total: Dict[Any, int] = {}
    for res in results.values():
        for k, v in res.items():
            total[k] = total.get(k, 0) + v
    scored = {k: v for k, v in total.items() if k is not None}
    games = sum(scored.values())
    mean_outcome = sum(k * v for k, v in scored.items()) / max(games, 1)
    return wp_func(total), mean_outcome


def parse_eval_spec(raw: str) -> Dict[str, Any]:
    """`A[:B]` -> {"main": A, "opponent": B or 'random'}.

    ':' separates the evaluated agent from the opponent (reference
    evaluation.py:383-402: ``model_paths[1]`` becomes every other seat's
    agent); '+' inside either side joins checkpoint paths into an ensemble.
    """
    parts = raw.split(":")
    if len(parts) > 2:
        raise ValueError(
            f"eval spec {raw!r} has more than one ':'; use A:B (opponent) "
            "and '+' to join ensemble members"
        )
    return {"main": parts[0], "opponent": parts[1] if len(parts) > 1 else "random"}


def eval_main(args: Dict[str, Any], argv: List[str]) -> None:
    """`main.py --eval MODELS NUM_GAMES NUM_WORKERS` (evaluation.py:377-404).

    MODELS is `A[:B]`: A is evaluated, B (default 'random') fills every
    other seat.  Each side may be 'random', 'rulebase[-key]', a checkpoint
    or .hlo path, or a '+'-joined ensemble of checkpoint paths.
    """
    from ..agents import EnsembleAgent
    from ..envs import prepare_env
    from ..models import InferenceModel, init_variables

    from .inference_engine import BatchedInferenceEngine

    env_args = args["env_args"]
    prepare_env(env_args)
    env = make_env(env_args)

    raw = argv[0] if argv else "models/latest.ckpt"
    num_games = int(argv[1]) if len(argv) >= 2 else 100
    num_workers = int(argv[2]) if len(argv) >= 3 else 4

    # one batched engine per distinct model: eval threads submit through a
    # single dispatcher, which batches inference across concurrent games
    # (the TPU-first path — and a single device entry point)
    engines: List[BatchedInferenceEngine] = []

    def share(model):
        if num_workers <= 1:
            return model
        engine = BatchedInferenceEngine(model, max_batch=max(8, num_workers)).start()
        engines.append(engine)
        return engine.client()

    def resolve(spec: str):
        agent = build_agent(spec, env)
        if agent is not None:
            return agent
        paths = spec.split("+")
        if len(paths) > 1:
            module = env.net()
            variables = init_variables(module, env)
            models = [
                share(InferenceModel(module, {"params": load_params(p, variables["params"])}))
                for p in paths
            ]
            return EnsembleAgent(models)
        agent = load_model_agent(spec, env)
        agent.models[0] = share(agent.models[0])
        return agent

    spec = parse_eval_spec(raw)
    agents = {0: resolve(spec["main"])}
    if len(env.players()) > 1:
        # resolve once: all opponent seats share one model/engine (per-game
        # agent state is cloned per thread by evaluate_mp)
        opponent = resolve(spec["opponent"])
        for i in range(1, len(env.players())):
            agents[i] = opponent
    try:
        evaluate_mp(env_args, agents, num_games, num_workers)
    finally:
        for engine in engines:
            engine.stop()
