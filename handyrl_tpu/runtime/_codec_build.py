"""Compile-on-first-use loader for the codec C accelerator.

No install step: the extension (`_codec_accel.c`) is compiled with the
plain system compiler into a per-ABI cache next to the package (or under
``~/.cache/handyrl_tpu`` when the package dir is read-only) and loaded
from there; subsequent imports hit the cached .so.  Any failure —
no compiler, sandboxed filesystem, exotic platform — raises, and
codec.py falls back to the pure-Python implementation, so the
accelerator is strictly optional.

Concurrent builders (e.g. worker processes starting together) compile to
a unique temp file and atomically rename it into place.
"""

from __future__ import annotations

import hashlib
import importlib.util
import os
import subprocess
import sysconfig
import tempfile
from pathlib import Path

_SRC = Path(__file__).with_name("_codec_accel.c")

# every symbol the runtime dispatches to: the wire codec pair plus the
# columnar batch-fill kernels (runtime/batch.py).  The source-hash cache
# name makes a stale .so unloadable in practice, but a hand-copied or
# truncated binary must fail HERE, loudly, not as AttributeError deep in
# a batcher process.
_REQUIRED_SYMBOLS = ("init", "dumps", "loads", "fill_rows", "fill_column")


def _cache_dir() -> Path:
    pkg = _SRC.parent
    if os.access(pkg, os.W_OK):
        return pkg
    root = Path(os.environ.get("XDG_CACHE_HOME", Path.home() / ".cache"))
    d = root / "handyrl_tpu"
    d.mkdir(parents=True, exist_ok=True)
    return d


def _so_path() -> Path:
    """Per-ABI, per-SOURCE-CONTENT cache name: embedding the source hash
    makes stale-binary loads impossible (mtime comparison is unreliable —
    package managers preserve archive mtimes, and a shared ~/.cache can
    hold a .so built from another checkout's older source)."""
    tag = sysconfig.get_config_var("SOABI") or "abi3"
    digest = hashlib.sha256(_SRC.read_bytes()).hexdigest()[:12]
    return _cache_dir() / f"_codec_accel.{tag}.{digest}.so"


def _gc_stale(so: Path) -> None:
    """Remove superseded hash-suffixed builds next to the fresh one.

    Every source edit changes the cache name, so without this the package
    dir accumulates one dead .so per rebuild forever.  PACKAGE-DIR ONLY:
    in that dir a different digest can only be a stale build of THIS
    checkout, while the shared ``~/.cache`` fallback may legitimately hold
    live builds from other checkouts at other source versions (the very
    scenario the content-hash cache names exist for) — deleting those
    would force a from-scratch recompile on every checkout alternation.
    Only artifacts of the same ABI tag are touched; a concurrently racing
    builder's tmp files don't match the glob."""
    if so.parent != _SRC.parent:
        return
    prefix = so.name.rsplit(".", 2)[0]  # '_codec_accel.<SOABI>'
    for stale in so.parent.glob(f"{prefix}.*.so"):
        if stale != so:
            try:
                stale.unlink()
            except OSError:
                pass  # another builder already removed it / read-only dir


def _compile(so: Path) -> None:
    cc = os.environ.get("CC", "cc")
    include = sysconfig.get_paths()["include"]
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=str(so.parent))
    os.close(fd)
    try:
        subprocess.run(
            [cc, "-O2", "-shared", "-fPIC", f"-I{include}", str(_SRC),
             "-o", tmp],
            check=True,
            capture_output=True,
            timeout=120,
        )
        os.replace(tmp, so)  # atomic: racing builders both win
        _gc_stale(so)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def load():
    """Import the accelerator, compiling it first if needed (raises on any
    failure; the caller falls back to pure Python)."""
    so = _so_path()
    if not so.exists():
        _compile(so)
    spec = importlib.util.spec_from_file_location("handyrl_tpu.runtime._codec_accel", so)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    missing = [s for s in _REQUIRED_SYMBOLS if not hasattr(mod, s)]
    if missing:
        raise ImportError(
            f"_codec_accel at {so} lacks {missing}; rebuild from _codec_accel.c"
        )
    return mod
