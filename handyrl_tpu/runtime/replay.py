"""Episode store: compressed columnar trajectories + recency-biased sampling.

Design vs the reference (train.py:271-319, generation.py:84-91):

* Episodes are **columnar**: per-episode numpy arrays (T, P, ...) instead
  of per-step python dicts.  Batch assembly is then pure array slicing —
  no python loop over timesteps — which is what keeps the TPU learner fed.
* Blocks of ``compress_steps`` timesteps are zlib-compressed so sampling a
  training window only decompresses the blocks it touches (same trick as
  the reference's bz2 chunks, faster codec).
* Same recency-biased sampling: index i of an N-episode buffer is
  accepted with probability 1 - (N-1-i)/N (train.py:292-303), and windows
  of ``forward_steps`` start uniformly, extended backwards by
  ``burn_in_steps`` when possible.
"""

from __future__ import annotations

import random
import threading
import zlib
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional

import jax
import numpy as np

from . import codec

try:
    import psutil
except ImportError:  # pragma: no cover
    psutil = None


def compress_block(columns: Dict[str, Any]) -> bytes:
    # codec, not pickle: blocks travel the wire from remote workers and are
    # decoded on the learner — they must never carry executable payloads
    return zlib.compress(codec.dumps(columns), level=1)


# Recency-biased sampling hits the same episodes' blocks over and over;
# decoding each block once per *batch row* was ~27% of batch-assembly time
# on HungryGeese.  Bytes hash by content (python caches the hash in the
# object), so the cache also dedups identical blocks across episodes.
# Decoded leaves are frozen read-only: every consumer slices or gathers
# (copies), and an accidental in-place write must fail loudly, not corrupt
# every later batch that samples the block.
_BLOCK_CACHE: "OrderedDict[bytes, Dict[str, Any]]" = OrderedDict()
_BLOCK_CACHE_MAX_BYTES = 256 << 20  # decoded-leaf budget, LRU-evicted
_BLOCK_CACHE_LOCK = threading.Lock()
_block_cache_bytes = 0


def _block_nbytes(cols) -> int:
    return sum(
        leaf.nbytes for leaf in jax.tree.leaves(cols) if isinstance(leaf, np.ndarray)
    )


def reset_block_cache() -> None:
    """Re-create the decoded-block cache AND its lock.

    Forked batcher processes (runtime/shm_batch.py) inherit this module's
    state as of the fork instant — including a lock some parent thread
    may have been holding.  A child that kept the inherited lock would
    deadlock on its first decompress_block; calling this first in the
    child makes the cache private and the lock fresh."""
    global _BLOCK_CACHE, _BLOCK_CACHE_LOCK, _block_cache_bytes
    _BLOCK_CACHE = OrderedDict()
    _BLOCK_CACHE_LOCK = threading.Lock()
    _block_cache_bytes = 0


def decompress_block(blob: bytes) -> Dict[str, Any]:
    global _block_cache_bytes
    with _BLOCK_CACHE_LOCK:
        cols = _BLOCK_CACHE.get(blob)
        if cols is not None:
            _BLOCK_CACHE.move_to_end(blob)
            return cols
    cols = codec.loads(zlib.decompress(blob))
    for leaf in jax.tree.leaves(cols):
        if isinstance(leaf, np.ndarray):
            leaf.flags.writeable = False
    with _BLOCK_CACHE_LOCK:
        _BLOCK_CACHE[blob] = cols
        _block_cache_bytes += _block_nbytes(cols)
        while _block_cache_bytes > _BLOCK_CACHE_MAX_BYTES and len(_BLOCK_CACHE) > 1:
            _, evicted = _BLOCK_CACHE.popitem(last=False)
            _block_cache_bytes -= _block_nbytes(evicted)
    return cols


class EpisodeStore:
    """Thread-safe bounded episode buffer with recency-biased sampling."""

    def __init__(self, maximum_episodes: int):
        self.maximum_episodes = maximum_episodes
        self._episodes: deque = deque()
        self._lock = threading.Lock()
        self._listeners: List[Any] = []
        self.total_added = 0

    def __len__(self) -> int:
        return len(self._episodes)

    def subscribe(self, listener) -> None:
        """Register ``listener(episodes)`` to be called with every batch of
        newly added episodes (outside the store lock).  The shared-memory
        batch pipeline uses this to mirror the stream into its batcher
        processes' replica stores."""
        with self._lock:
            self._listeners.append(listener)

    def unsubscribe(self, listener) -> None:
        with self._lock:
            if listener in self._listeners:
                self._listeners.remove(listener)

    def snapshot(self) -> List[Dict[str, Any]]:
        """Consistent copy of the current episode list (the episodes
        themselves are immutable once stored: compressed block bytes)."""
        with self._lock:
            return list(self._episodes)

    def extend(self, episodes: List[Dict[str, Any]]) -> None:
        episodes = [e for e in episodes if e is not None]
        with self._lock:
            self._episodes.extend(episodes)
            self.total_added += len(episodes)
            limit = self._memory_limited_max()
            while len(self._episodes) > limit:
                self._episodes.popleft()
            listeners = list(self._listeners)
        for listener in listeners:
            if episodes:
                listener(episodes)

    def _memory_limited_max(self) -> int:
        """Shrink the buffer under memory pressure (reference train.py:474-483)."""
        if psutil is not None:
            mem_percent = psutil.virtual_memory().percent
            if mem_percent > 95:
                return max(1, int(len(self._episodes) * 95 / mem_percent))
        return self.maximum_episodes

    def sample_window(self, forward_steps: int, burn_in_steps: int, compress_steps: int) -> Optional[Dict[str, Any]]:
        """Pick one episode (recency-biased) and one training window in it."""
        with self._lock:
            n = len(self._episodes)
            if n == 0:
                return None
            while True:
                idx = random.randrange(n)
                accept = 1 - (n - 1 - idx) / n
                if random.random() < accept:
                    break
            ep = self._episodes[idx]

        steps = ep["steps"]
        train_start = random.randrange(1 + max(0, steps - forward_steps))
        start = max(0, train_start - burn_in_steps)
        end = min(train_start + forward_steps, steps)
        first_block = start // compress_steps
        last_block = (end - 1) // compress_steps + 1
        return {
            "args": ep["args"],
            # outcome as an array ordered like ep['players'] for batching
            "outcome": np.asarray([ep["outcome"][p] for p in ep["players"]], np.float32),
            "players": ep["players"],
            "blocks": ep["blocks"][first_block:last_block],
            "base": first_block * compress_steps,
            "start": start,
            "end": end,
            "train_start": train_start,
            "total": steps,
        }
