"""Split actor/learner device planes: cross-mesh param + record flow.

The fused north-star loop is production-bound by construction: one
self-play env-step costs ~100x one trained env-step in device time, so a
single program queue spends >90% of its time in rollout however the duty
cycle is tuned (round-4 sweep, bench.py northstar2).  The Podracer/
Sebulba answer (Hessel et al. 2021; IMPALA, Espeholt et al. 2018) is to
stop time-slicing: pin self-play to an **actor mesh** and training to a
disjoint **learner mesh** (parallel/mesh.py:split_mesh) so both planes
run at full duty concurrently — made safe by the per-device dispatch
locks (disjoint planes share no lock).  Two flows cross the planes:

* params, learner -> actor: ``PlaneParamCache`` holds a versioned
  replicated copy on the actor mesh, refreshed by a cross-mesh
  ``device_put`` every ``param_refresh_updates`` learner steps; staleness
  is the ``plane_param_lag`` metric (actor params are at most that many
  updates behind — the same staleness the IMPALA off-policy corrections
  in ops/losses.py absorb).
* trajectories, actor -> learner: ``transfer_records`` re-lays a
  streaming rollout's (K, B, ...) record batch out on the learner mesh so
  DeviceReplay (whose rings — and donation-safety contract — live on the
  learner plane) can ingest it.

Both directions count bytes so metrics.jsonl can report the cross-mesh
transfer rate (``plane_xfer_bytes_per_sec``).

**Pod-slice rung 2** (docs/performance.md §Pod-slice topology): the same
two flows generalized across HOSTS.  ``PlaneGateway`` is the learner-side
TCP server (the health plane's framing: newline-delimited JSON headers,
here followed by byte-counted npz payloads) and ``PlaneClient`` the
actor-host side.  Params flow learner -> actor hosts as monotonically
versioned snapshots (an actor polls with the version it has; the gateway
answers bytes only when newer); records flow actor hosts -> learner over
DCN and land in the learner's device rings through the same ingest path
local rollouts use.  Actor hosts stay OUTSIDE jax.distributed by design:
a lost actor host must be a throughput degrade (survivors absorb its game
quota), never a wedged collective — the asymmetry
docs/fault_tolerance.md's matrix pins.
"""

from __future__ import annotations

import io
import json
import socket
import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from ..utils.trace import trace_span


def _tree_bytes(tree) -> int:
    return sum(x.nbytes for x in jax.tree.leaves(tree))


def _local_view(x):
    """A process-local view of one param leaf, safe to hand to device_put
    or np.asarray.

    Under a multi-process run the learner's params live REPLICATED on the
    global train mesh, which is not fully addressable from any one
    process — and device_put of such an array onto a local mesh has been
    observed (jax 0.4.37 CPU) to silently rewrap the sharding metadata
    WITHOUT moving the buffers, handing the actor plane's Execute()
    learner-device buffers (it kills the rollout thread with placement
    errors); np.asarray on one raises outright.  A replicated array's
    value is whole on every addressable shard, so shard 0 IS the value;
    return that single-device array, which copies like any local one."""
    if not isinstance(x, jax.Array) or x.sharding.is_fully_addressable:
        return x
    if not x.sharding.is_fully_replicated:
        raise ValueError(
            "cross-plane publish needs replicated params; got "
            f"sharding {x.sharding} for shape {x.shape}"
        )
    return x.addressable_shards[0].data


class PlaneParamCache:
    """Versioned replicated param copy on the actor mesh.

    The learner thread calls ``publish(params, version)`` between train
    dispatches (the params are the just-returned state's — still valid;
    the copy dispatched here holds its own buffer reference, so the next
    step's donation cannot pull it out from under the transfer).  The
    actor thread reads ``latest()`` each rollout dispatch.  Versions are
    learner step counts and must advance monotonically — pinned by
    tests/test_plane.py.
    """

    def __init__(self, actor_mesh):
        self.mesh = actor_mesh
        self._sharding = NamedSharding(actor_mesh, PartitionSpec())
        self._lock = threading.Lock()
        self._params = None
        self.version = -1
        self.refreshes = 0
        self.bytes_transferred = 0

    def publish(self, params, version: int) -> None:
        """Cross-mesh copy of ``params`` onto the actor mesh (replicated),
        stamped ``version``.  Monotonicity is enforced: the planes'
        staleness accounting is meaningless if versions can rewind."""
        version = int(version)
        with self._lock:
            if version <= self.version:
                raise ValueError(
                    f"param version must advance monotonically: "
                    f"{version} <= {self.version}"
                )
            # the device_put stays under the lock so a concurrent publisher
            # cannot interleave between check and store (the dispatch is
            # async — latest() readers block only for the enqueue)
            fresh = jax.device_put(
                jax.tree.map(self._local_view, params), self._sharding
            )
            self._params = fresh
            self.version = version
            self.refreshes += 1
            self.bytes_transferred += _tree_bytes(fresh)

    _local_view = staticmethod(_local_view)

    def latest(self) -> Tuple[int, Any]:
        """(version, actor-mesh params) of the newest published copy."""
        with self._lock:
            if self._params is None:
                raise RuntimeError("PlaneParamCache.latest() before first publish")
            return self.version, self._params

    def lag(self, learner_steps: int) -> int:
        """How many learner updates behind the actor plane's params are."""
        return max(0, int(learner_steps) - self.version) if self.refreshes else 0


class RecordTransfer:
    """Actor -> learner record re-layout with byte accounting.

    A streaming rollout's (K, B, ...) record batch lives lane-sharded on
    the actor mesh; DeviceReplay's ingest program runs on the learner
    mesh and its jit pins ``in_shardings`` there, so the batch must move
    first.  ``device_put`` to the learner sharding is that move (host
    round-trip on CPU, direct transfer where the runtime supports it);
    the dispatch needs NO plane lock — a copy is not a collective-bearing
    program, so it cannot perturb either plane's program order.
    """

    def __init__(self, learner_mesh):
        self.mesh = learner_mesh
        self._sharding = NamedSharding(learner_mesh, PartitionSpec(None, "dp"))
        self.transfers = 0
        self.bytes_transferred = 0

    def __call__(self, records: Dict[str, Any]) -> Dict[str, Any]:
        moved = jax.device_put(records, self._sharding)
        self.transfers += 1
        self.bytes_transferred += _tree_bytes(moved)
        return moved


class PlaneStats:
    """Shared cumulative counters for the split-plane loop, read (and
    diffed per epoch) by the learner's metrics record.  All writers hold
    the lock; snapshot() returns a plain dict."""

    def __init__(self):
        self._lock = threading.Lock()
        self._c: Dict[str, float] = {
            "actor_dispatches": 0.0,
            "actor_busy_s": 0.0,     # inside rollout dispatch + ingest
            "actor_idle_s": 0.0,     # backpressure sleeps / server waits
            "param_lag_sum": 0.0,    # summed over rollout dispatches
        }

    def bump(self, **kv: float) -> None:
        with self._lock:
            for k, v in kv.items():
                self._c[k] += v

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._c)


# -- rung 2: cross-HOST transports (docs/performance.md §Pod-slice) ----------
#
# Wire protocol, shared by both directions (the health plane's framing
# plus byte-counted payloads):
#
#   header:  one JSON line ending "\n"
#            {"kind": ..., "nbytes": N, ...}
#   payload: exactly N raw bytes (an npz of the tree's leaves keyed by
#            "\x1f"-joined dict paths), present iff nbytes > 0
#
# Every request gets exactly one reply.  A gateway that is shutting down
# answers {"kind": "stop"} — the client exits CLEANLY; a dead socket is
# the loud path (the actor host announces and exits 75: its learner is
# gone, so relaunch-and-reconnect is the only recovery).


def resolve_plane_port(dist_args: Dict[str, Any]) -> int:
    """The plane gateway's TCP port: ``distributed.plane_port`` when set,
    else health port + 1 (one launcher knob covers all three planes)."""
    port = int(dist_args.get("plane_port") or 0)
    if port:
        return port
    from ..parallel.health import resolve_health_port

    return resolve_health_port(dist_args) + 1


def _pack_tree(tree) -> bytes:
    """Nested-dict tree of arrays -> npz bytes, keys = joined dict paths.

    Dict-only on purpose: params and record batches are dict trees, and a
    self-describing dict flattening means neither side needs to ship a
    treedef over the wire.  Raises on any other container so a structure
    this cannot round-trip fails loudly at the sender."""
    flat: Dict[str, np.ndarray] = {}

    def walk(node, path: str) -> None:
        if isinstance(node, dict):
            for k, v in node.items():
                if "\x1f" in str(k):
                    raise ValueError(f"tree key {k!r} contains the path separator")
                walk(v, path + "\x1f" + str(k) if path else str(k))
            return
        if isinstance(node, (list, tuple)):
            raise ValueError(
                "plane transport trees must be nested dicts of arrays "
                f"(got {type(node).__name__} at {path!r})"
            )
        # graftlint: allow[HS001] reason=serialization IS the host crossing: these bytes leave the machine over DCN, and callers run this off the trainer hot loop (gateway serve thread / actor-host loop)
        flat[path] = np.asarray(_local_view(node))

    walk(tree, "")
    buf = io.BytesIO()
    np.savez(buf, **flat)
    return buf.getvalue()


def _unpack_tree(payload: bytes) -> Dict[str, Any]:
    """Inverse of _pack_tree: npz bytes -> nested dict of numpy arrays."""
    out: Dict[str, Any] = {}
    with np.load(io.BytesIO(payload)) as z:
        for key in z.files:
            node = out
            parts = key.split("\x1f")
            for p in parts[:-1]:
                node = node.setdefault(p, {})
            node[parts[-1]] = z[key]
    return out


def _send_msg(wfile, header: Dict[str, Any], payload: bytes = b"") -> int:
    """One header line + optional payload; returns bytes written."""
    header = dict(header, nbytes=len(payload))
    line = (json.dumps(header) + "\n").encode()
    wfile.write(line + payload)
    wfile.flush()
    return len(line) + len(payload)


def _recv_msg(rfile) -> Tuple[Optional[Dict[str, Any]], bytes, int]:
    """One (header, payload, bytes_read); header None on a closed peer."""
    line = rfile.readline()
    if not line:
        return None, b"", 0
    header = json.loads(line)
    n = int(header.get("nbytes", 0))
    payload = rfile.read(n) if n else b""
    if len(payload) != n:
        raise ConnectionError(
            f"plane transport: truncated payload ({len(payload)}/{n} bytes)"
        )
    return header, payload, len(line) + n


class PlaneGateway:
    """Learner-side plane server: versioned params out, records in.

    The trainer publishes through the same ``publish(params, version)``
    surface as ``PlaneParamCache`` (and delegates to one, ``inner``, when
    the learner also runs a local split plane) — publish stores a REFERENCE
    under the version lock and returns; the D2H + npz serialization happen
    lazily in the serving thread on the first actor poll of that version,
    off the trainer hot loop.  ``on_records`` receives each decoded host
    record tree on a serving thread; the learner's callback validates the
    lane count and ingests into the device rings.

    An actor-host disconnect after hello bumps ``actor_host_losses`` and
    the run CONTINUES — the remaining producers absorb the game quota
    (the epoch episode budget is global, so backpressure redistributes
    automatically).  ``stop()`` makes every subsequent request answer
    {"kind": "stop"} so actor hosts exit cleanly at run end.
    """

    def __init__(self, dist_args: Dict[str, Any],
                 on_records: Callable[[Dict[str, Any]], None],
                 inner: Optional[PlaneParamCache] = None):
        self._port = resolve_plane_port(dist_args)
        self.on_records = on_records
        self.inner = inner
        self._lock = threading.Lock()
        self._params = None          # newest published tree (reference)
        self._packed: Optional[Tuple[int, bytes]] = None  # lazy (version, npz)
        self.version = -1
        self.refreshes = 0
        self._stop = threading.Event()
        self._stopping = threading.Event()  # answer "stop" from here on
        self._server: Optional[socket.socket] = None
        self._threads: list = []
        self.bytes_in = 0
        self.bytes_out = 0
        self.record_batches = 0
        self.actor_hosts = 0         # currently connected (post-hello)
        self.actor_hosts_seen = 0
        self.actor_host_losses = 0

    # -- trainer-facing surface (PlaneParamCache duck type) ------------------

    def publish(self, params, version: int) -> None:
        version = int(version)
        if self.inner is not None:
            # local actor mesh first: monotonicity is enforced there and a
            # raise must leave the gateway untouched too
            self.inner.publish(params, version)
        with self._lock:
            if self.inner is None and version <= self.version:
                raise ValueError(
                    f"param version must advance monotonically: "
                    f"{version} <= {self.version}"
                )
            self._params = params
            self.version = version
            self.refreshes += 1
            self._packed = None      # serialized lazily on next poll

    def latest(self):
        if self.inner is not None:
            return self.inner.latest()
        with self._lock:
            if self._params is None:
                raise RuntimeError("PlaneGateway.latest() before first publish")
            return self.version, self._params

    def lag(self, learner_steps: int) -> int:
        return max(0, int(learner_steps) - self.version) if self.refreshes else 0

    @property
    def bytes_transferred(self) -> int:
        with self._lock:
            inner = self.inner.bytes_transferred if self.inner is not None else 0
        return self.bytes_in + self.bytes_out + inner

    def _packed_params(self) -> Tuple[int, bytes]:
        """(version, npz bytes) of the newest publish, serialized at most
        once per version — on a serving thread, never the trainer's."""
        with self._lock:
            if self._packed is not None and self._packed[0] == self.version:
                return self._packed
            version, params = self.version, self._params
        with trace_span("plane.param_publish", version=version):
            payload = _pack_tree(params)
        with self._lock:
            if self._packed is None or self._packed[0] < version:
                self._packed = (version, payload)
            return self._packed

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._server.bind(("", self._port))
        self._server.listen(8)
        self._server.settimeout(0.5)
        t = threading.Thread(
            target=self._accept_loop, daemon=True, name="plane-gateway-accept"
        )
        t.start()
        self._threads.append(t)
        print(f"plane gateway: listening on port {self._port}")

    def begin_stop(self) -> None:
        """Run concluding: answer every further request with a clean stop
        (actor hosts exit 0) but keep serving until stop()."""
        self._stopping.set()

    def stop(self) -> None:
        self._stopping.set()
        self._stop.set()
        server, self._server = self._server, None
        if server is not None:
            try:
                server.close()
            except OSError:
                pass

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            server = self._server
            if server is None:
                return
            try:
                conn, _addr = server.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            t = threading.Thread(
                target=self._serve, args=(conn,), daemon=True,
                name="plane-gateway-serve",
            )
            t.start()
            self._threads.append(t)

    def _serve(self, conn: socket.socket) -> None:
        import sys

        conn.settimeout(300.0)
        rfile = conn.makefile("rb")
        wfile = conn.makefile("wb")
        hello = False
        try:
            while not self._stop.is_set():
                header, payload, n_in = _recv_msg(rfile)
                if header is None:
                    break   # peer closed
                with self._lock:
                    self.bytes_in += n_in
                if self._stopping.is_set():
                    _send_msg(wfile, {"kind": "stop"})
                    hello = False   # clean goodbye, not a loss
                    break
                kind = header.get("kind")
                if kind == "hello":
                    hello = True
                    with self._lock:
                        self.actor_hosts += 1
                        self.actor_hosts_seen += 1
                    print(
                        "plane gateway: actor host connected "
                        f"({header.get('host', '?')}, "
                        f"{self.actor_hosts} live)"
                    )
                    n = _send_msg(wfile, {"kind": "ok", "version": self.version})
                elif kind == "records":
                    with trace_span("plane.record_xfer",
                                    nbytes=len(payload), direction="in"):
                        records = _unpack_tree(payload)
                        self.on_records(records)
                    with self._lock:
                        self.record_batches += 1
                    n = _send_msg(wfile, {"kind": "ok", "version": self.version})
                elif kind == "params":
                    have = int(header.get("have", -1))
                    version, packed = (
                        self._packed_params()
                        if self.version > have and self._params is not None
                        else (self.version, b"")
                    )
                    n = _send_msg(
                        wfile, {"kind": "params", "version": version},
                        packed if version > have else b"",
                    )
                else:
                    n = _send_msg(
                        wfile, {"kind": "error", "error": f"unknown kind {kind!r}"}
                    )
                with self._lock:
                    self.bytes_out += n
        except (OSError, ValueError, ConnectionError) as e:
            if not self._stop.is_set():
                print(
                    f"[handyrl_tpu] plane gateway: actor connection error: {e}",
                    file=sys.stderr,
                )
        finally:
            if hello:
                with self._lock:
                    self.actor_hosts -= 1
                    if not self._stopping.is_set():
                        # a loss, not a goodbye: throughput degrades, the
                        # run continues (the degradable direction of the
                        # fault matrix)
                        self.actor_host_losses += 1
                        print(
                            "[handyrl_tpu] plane gateway: actor host LOST "
                            f"({self.actor_hosts} live; survivors absorb "
                            "its game quota)",
                            file=sys.stderr,
                        )
            for f in (rfile, wfile):
                try:
                    f.close()
                except OSError:
                    pass
            try:
                conn.close()
            except OSError:
                pass


class PlaneClient:
    """Actor-host side of the plane gateway protocol.

    One blocking request/reply socket per actor host (the rollout loop is
    itself serial: generate -> ship -> maybe refresh params).  Methods
    return None once the gateway said "stop" (clean run end); a dead
    socket raises ConnectionError — the actor host's loop announces the
    lost learner loudly and exits 75 (resumable: a relaunched learner is
    reconnectable).
    """

    def __init__(self, dist_args: Dict[str, Any], timeout: float = 300.0):
        from ..parallel.health import _split_address

        self._host = _split_address(dist_args["coordinator_address"])[0]
        self._port = resolve_plane_port(dist_args)
        self._timeout = float(timeout)
        self._sock: Optional[socket.socket] = None
        self._rfile = None
        self._wfile = None
        self._lock = threading.Lock()
        self.bytes_in = 0
        self.bytes_out = 0
        self.param_version = -1
        self.stopped = False

    def connect(self, retry_for: float = 60.0) -> int:
        """Dial the gateway (retrying — the learner may still be
        compiling), send hello, return the gateway's param version."""
        deadline = time.monotonic() + float(retry_for)
        last: Optional[Exception] = None
        while time.monotonic() < deadline:
            try:
                sock = socket.create_connection(
                    (self._host, self._port), timeout=self._timeout
                )
                break
            except OSError as e:
                last = e
                time.sleep(1.0)
        else:
            raise ConnectionError(
                f"plane gateway at {self._host}:{self._port} unreachable "
                f"for {retry_for:.0f}s: {last}"
            )
        sock.settimeout(self._timeout)
        self._sock = sock
        self._rfile = sock.makefile("rb")
        self._wfile = sock.makefile("wb")
        import platform

        reply, _payload = self._roundtrip(
            {"kind": "hello", "host": platform.node()}
        )
        if reply is None:
            return -1
        self.param_version = int(reply.get("version", -1))
        return self.param_version

    def _roundtrip(self, header: Dict[str, Any], payload: bytes = b""):
        """(reply header, reply payload); None header once stopped."""
        with self._lock:
            if self.stopped:
                return None, b""
            self.bytes_out += _send_msg(self._wfile, header, payload)
            reply, rpayload, n_in = _recv_msg(self._rfile)
            self.bytes_in += n_in
            if reply is None:
                raise ConnectionError("plane gateway closed the connection")
            if reply.get("kind") == "stop":
                self.stopped = True
                return None, b""
            if reply.get("kind") == "error":
                raise ConnectionError(f"plane gateway: {reply.get('error')}")
            return reply, rpayload

    def ship_records(self, records: Dict[str, Any]) -> Optional[int]:
        """Send one host record tree; returns the gateway's current param
        version (the poll hint), or None once the run is stopping."""
        with trace_span("plane.record_xfer", direction="out"):
            payload = _pack_tree(records)
            reply, _ = self._roundtrip({"kind": "records"}, payload)
        if reply is None:
            return None
        return int(reply.get("version", -1))

    def poll_params(self, have: Optional[int] = None):
        """(version, params-or-None): params bytes come back only when the
        gateway holds a newer version than ``have`` (default: the newest
        this client has seen).  Returns None once the run is stopping."""
        have = self.param_version if have is None else int(have)
        reply, payload = self._roundtrip({"kind": "params", "have": have})
        if reply is None:
            return None
        version = int(reply.get("version", -1))
        if not payload:
            return version, None
        self.param_version = version
        return version, _unpack_tree(payload)

    def close(self) -> None:
        with self._lock:
            for f in (self._rfile, self._wfile):
                try:
                    if f is not None:
                        f.close()
                except OSError:
                    pass
            if self._sock is not None:
                try:
                    self._sock.close()
                except OSError:
                    pass
            self._sock = self._rfile = self._wfile = None
