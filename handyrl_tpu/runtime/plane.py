"""Split actor/learner device planes: cross-mesh param + record flow.

The fused north-star loop is production-bound by construction: one
self-play env-step costs ~100x one trained env-step in device time, so a
single program queue spends >90% of its time in rollout however the duty
cycle is tuned (round-4 sweep, bench.py northstar2).  The Podracer/
Sebulba answer (Hessel et al. 2021; IMPALA, Espeholt et al. 2018) is to
stop time-slicing: pin self-play to an **actor mesh** and training to a
disjoint **learner mesh** (parallel/mesh.py:split_mesh) so both planes
run at full duty concurrently — made safe by the per-device dispatch
locks (disjoint planes share no lock).  Two flows cross the planes:

* params, learner -> actor: ``PlaneParamCache`` holds a versioned
  replicated copy on the actor mesh, refreshed by a cross-mesh
  ``device_put`` every ``param_refresh_updates`` learner steps; staleness
  is the ``plane_param_lag`` metric (actor params are at most that many
  updates behind — the same staleness the IMPALA off-policy corrections
  in ops/losses.py absorb).
* trajectories, actor -> learner: ``transfer_records`` re-lays a
  streaming rollout's (K, B, ...) record batch out on the learner mesh so
  DeviceReplay (whose rings — and donation-safety contract — live on the
  learner plane) can ingest it.

Both directions count bytes so metrics.jsonl can report the cross-mesh
transfer rate (``plane_xfer_bytes_per_sec``).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional, Tuple

import jax
from jax.sharding import NamedSharding, PartitionSpec


def _tree_bytes(tree) -> int:
    return sum(x.nbytes for x in jax.tree.leaves(tree))


class PlaneParamCache:
    """Versioned replicated param copy on the actor mesh.

    The learner thread calls ``publish(params, version)`` between train
    dispatches (the params are the just-returned state's — still valid;
    the copy dispatched here holds its own buffer reference, so the next
    step's donation cannot pull it out from under the transfer).  The
    actor thread reads ``latest()`` each rollout dispatch.  Versions are
    learner step counts and must advance monotonically — pinned by
    tests/test_plane.py.
    """

    def __init__(self, actor_mesh):
        self.mesh = actor_mesh
        self._sharding = NamedSharding(actor_mesh, PartitionSpec())
        self._lock = threading.Lock()
        self._params = None
        self.version = -1
        self.refreshes = 0
        self.bytes_transferred = 0

    def publish(self, params, version: int) -> None:
        """Cross-mesh copy of ``params`` onto the actor mesh (replicated),
        stamped ``version``.  Monotonicity is enforced: the planes'
        staleness accounting is meaningless if versions can rewind."""
        version = int(version)
        with self._lock:
            if version <= self.version:
                raise ValueError(
                    f"param version must advance monotonically: "
                    f"{version} <= {self.version}"
                )
            # the device_put stays under the lock so a concurrent publisher
            # cannot interleave between check and store (the dispatch is
            # async — latest() readers block only for the enqueue)
            fresh = jax.device_put(params, self._sharding)
            self._params = fresh
            self.version = version
            self.refreshes += 1
            self.bytes_transferred += _tree_bytes(fresh)

    def latest(self) -> Tuple[int, Any]:
        """(version, actor-mesh params) of the newest published copy."""
        with self._lock:
            if self._params is None:
                raise RuntimeError("PlaneParamCache.latest() before first publish")
            return self.version, self._params

    def lag(self, learner_steps: int) -> int:
        """How many learner updates behind the actor plane's params are."""
        return max(0, int(learner_steps) - self.version) if self.refreshes else 0


class RecordTransfer:
    """Actor -> learner record re-layout with byte accounting.

    A streaming rollout's (K, B, ...) record batch lives lane-sharded on
    the actor mesh; DeviceReplay's ingest program runs on the learner
    mesh and its jit pins ``in_shardings`` there, so the batch must move
    first.  ``device_put`` to the learner sharding is that move (host
    round-trip on CPU, direct transfer where the runtime supports it);
    the dispatch needs NO plane lock — a copy is not a collective-bearing
    program, so it cannot perturb either plane's program order.
    """

    def __init__(self, learner_mesh):
        self.mesh = learner_mesh
        self._sharding = NamedSharding(learner_mesh, PartitionSpec(None, "dp"))
        self.transfers = 0
        self.bytes_transferred = 0

    def __call__(self, records: Dict[str, Any]) -> Dict[str, Any]:
        moved = jax.device_put(records, self._sharding)
        self.transfers += 1
        self.bytes_transferred += _tree_bytes(moved)
        return moved


class PlaneStats:
    """Shared cumulative counters for the split-plane loop, read (and
    diffed per epoch) by the learner's metrics record.  All writers hold
    the lock; snapshot() returns a plain dict."""

    def __init__(self):
        self._lock = threading.Lock()
        self._c: Dict[str, float] = {
            "actor_dispatches": 0.0,
            "actor_busy_s": 0.0,     # inside rollout dispatch + ingest
            "actor_idle_s": 0.0,     # backpressure sleeps / server waits
            "param_lag_sum": 0.0,    # summed over rollout dispatches
        }

    def bump(self, **kv: float) -> None:
        with self._lock:
            for k, v in kv.items():
                self._c[k] += v

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._c)
