"""Cross-environment batched inference engine (the actor-side TPU path).

The reference runs batch-1 CPU inference inside every worker process
(handyrl/model.py:50-60 via generation.py:45) — fine for torch-CPU, fatal
for a TPU whose MXU wants large batches.  Here many host-side actor threads
share ONE device model: each submits its (obs, hidden) and blocks on a
future; a dispatcher thread drains the request queue, stacks observations
into a single padded batch, runs one jitted apply, and scatters results.

Static shapes: batches are padded to power-of-two buckets up to
``max_batch`` so XLA compiles a handful of shapes, not one per batch size.

Recurrent models: per-request hidden pytrees are stacked alongside the
observations; requests with ``hidden=None`` get the module's initial state
slice so one batch can mix fresh and mid-episode environments.
"""

from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future
from typing import Any, Dict, List, Optional

import numpy as np

from ..utils import tree_map, tree_stack


class EngineStopped(RuntimeError):
    """Raised to waiters when the engine is stopped with requests pending."""


def _next_bucket(n: int, max_batch: int) -> int:
    b = 1
    while b < n:
        b *= 2
    return min(b, max_batch)


class BatchedInferenceClient:
    """Per-actor facade with the reference inference API (model.py:50-60)."""

    def __init__(self, engine: "BatchedInferenceEngine"):
        self._engine = engine

    def init_hidden(self, batch_dims=()):
        return self._engine.init_hidden(batch_dims)

    def inference(self, obs, hidden=None) -> Dict[str, Any]:
        return self._engine.submit(obs, hidden).result()

    def submit(self, obs, hidden=None) -> Future:
        """Async request entry — lets a caller queue several players'
        observations before blocking, so they land in one device batch."""
        return self._engine.submit(obs, hidden)


class BatchedInferenceEngine:
    """One device model serving many actor threads with batched inference."""

    def __init__(self, model, max_batch: int = 64, max_wait_ms: float = 2.0):
        self.model = model  # InferenceModel (numpy in/out, jitted apply)
        self.max_batch = max(1, max_batch)
        self.max_wait = max_wait_ms / 1000.0
        self._queue: queue.Queue = queue.Queue()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.batches_served = 0
        self.requests_served = 0

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "BatchedInferenceEngine":
        if self._thread is None:
            self._thread = threading.Thread(target=self._serve_loop, daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._queue.put(None)
        # fail any requests that raced past the serve loop's exit
        while True:
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                break
            if item is not None and not item[2].done():
                item[2].set_exception(EngineStopped("inference engine stopped"))

    def update_model(self, model) -> None:
        """Swap in new variables (same module); takes effect next batch."""
        self.model = model

    # -- client API ---------------------------------------------------------

    def init_hidden(self, batch_dims=()):
        return self.model.init_hidden(batch_dims)

    def client(self) -> BatchedInferenceClient:
        return BatchedInferenceClient(self)

    def submit(self, obs, hidden=None) -> Future:
        fut: Future = Future()
        if self._stop.is_set():
            fut.set_exception(EngineStopped("inference engine stopped"))
            return fut
        self._queue.put((obs, hidden, fut))
        if self._stop.is_set():  # raced with stop(): don't strand the waiter
            self.stop()
        return fut

    # -- dispatcher ---------------------------------------------------------

    def _drain(self) -> List:
        """Block for the first request, then gather more up to max_batch."""
        first = self._queue.get()
        if first is None:
            return []
        requests = [first]
        deadline = time.monotonic() + self.max_wait
        while len(requests) < self.max_batch:
            timeout = deadline - time.monotonic()
            try:
                if timeout <= 0:
                    item = self._queue.get_nowait()
                else:
                    item = self._queue.get(timeout=timeout)
            except queue.Empty:
                break
            if item is None:
                break
            requests.append(item)
        return requests

    def _serve_loop(self) -> None:
        while not self._stop.is_set():
            requests = self._drain()
            if not requests:
                continue
            try:
                self._serve(requests)
            except Exception as exc:  # propagate to every waiter
                for _, _, fut in requests:
                    if not fut.done():
                        fut.set_exception(exc)

    def _serve(self, requests: List) -> None:
        model = self.model
        n = len(requests)
        bucket = _next_bucket(n, self.max_batch)

        obs_list = [r[0] for r in requests]
        obs_list += [obs_list[0]] * (bucket - n)
        obs_batch = tree_stack(obs_list)

        hidden_batch = None
        template = model.init_hidden()
        if template is not None:
            hid_list = [r[1] if r[1] is not None else template for r in requests]
            hid_list += [template] * (bucket - n)
            hidden_batch = tree_stack(hid_list)

        outputs = model.inference_batch(obs_batch, hidden_batch)
        outputs = tree_map(np.asarray, outputs)
        for i, (_, _, fut) in enumerate(requests):
            fut.set_result(tree_map(lambda x: x[i], outputs))

        self.batches_served += 1
        self.requests_served += n
