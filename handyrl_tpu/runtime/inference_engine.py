"""Cross-environment batched inference engine (the actor-side TPU path).

The reference runs batch-1 CPU inference inside every worker process
(handyrl/model.py:50-60 via generation.py:45) — fine for torch-CPU, fatal
for a TPU whose MXU wants large batches.  Here many host-side actor threads
share ONE device model: each submits its (obs, hidden) and blocks on a
future; a dispatcher thread drains the request queue, stacks observations
into a single padded batch, runs one jitted apply, and scatters results.

Static shapes: batches are padded to power-of-two buckets up to
``max_batch`` so XLA compiles a handful of shapes, not one per batch size.

Recurrent models: per-request hidden pytrees are stacked alongside the
observations; requests with ``hidden=None`` get the module's initial state
slice so one batch can mix fresh and mid-episode environments.
"""

from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future
from typing import Any, Dict, List, Optional

import numpy as np

from ..utils import tree_map, tree_stack


class EngineStopped(RuntimeError):
    """Raised to waiters when the engine is stopped with requests pending."""


def next_bucket(n: int, max_batch: int) -> int:
    """Smallest power-of-two >= n, capped at max_batch — the static batch
    shapes XLA compiles (shared with the serving plane's batcher)."""
    b = 1
    while b < n:
        b *= 2
    return min(b, max_batch)


_next_bucket = next_bucket  # pre-serving-plane spelling


def stack_padded(obs_list, hid_list, bucket: int, hidden_template):
    """Pad to ``bucket`` rows and stack into one batch (shared by this
    engine and the serving batcher — the padding semantics are subtle and
    must not drift: pad rows REPLICATE real entries, because they must be
    valid observations/state or XLA's output for the live rows changes).
    ``hid_list`` entries of None take the module's initial-state template;
    a None ``hidden_template`` means a stateless model (no hidden batch).
    """
    obs_list = list(obs_list)
    obs_list += [obs_list[0]] * (bucket - len(obs_list))
    obs_batch = tree_stack(obs_list)
    hidden_batch = None
    if hidden_template is not None:
        hid_list = [h if h is not None else hidden_template for h in hid_list]
        hid_list += [hidden_template] * (bucket - len(hid_list))
        hidden_batch = tree_stack(hid_list)
    return obs_batch, hidden_batch


class BatchedInferenceClient:
    """Per-actor facade with the reference inference API (model.py:50-60)."""

    def __init__(self, engine: "BatchedInferenceEngine"):
        self._engine = engine

    def init_hidden(self, batch_dims=()):
        return self._engine.init_hidden(batch_dims)

    def inference(self, obs, hidden=None) -> Dict[str, Any]:
        return self._engine.submit(obs, hidden).result()

    def submit(self, obs, hidden=None) -> Future:
        """Async request entry — lets a caller queue several players'
        observations before blocking, so they land in one device batch."""
        return self._engine.submit(obs, hidden)


class BatchedInferenceEngine:
    """One device model serving many actor threads with batched inference."""

    def __init__(self, model, max_batch: int = 64, max_wait_ms: float = 2.0):
        self.model = model  # InferenceModel (numpy in/out, jitted apply)
        self.max_batch = max(1, max_batch)
        self.max_wait = max_wait_ms / 1000.0
        self._queue: queue.Queue = queue.Queue()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # orders submit vs stop: an item can only be enqueued while the
        # stop flag is provably unset, so exactly one party ever owns the
        # final drain (the serve thread when it exists, stop() otherwise)
        self._lifecycle = threading.Lock()
        self.batches_served = 0
        self.requests_served = 0

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "BatchedInferenceEngine":
        if self._thread is None:
            self._thread = threading.Thread(target=self._serve_loop, daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        with self._lifecycle:
            if self._stop.is_set():
                return  # idempotent; the first stop already arranged the drain
            self._stop.set()
            self._queue.put(None)  # wake the dispatcher
            thread = self._thread
        if thread is None:
            # never started: there is no serve thread to own the drain
            self._fail_pending()

    def _fail_pending(self) -> None:
        """Fail every queued request.  Called exactly once, by the drain
        owner: the serve loop after it observes stop (requests admitted
        before the flag flipped are drained there), or stop() itself when
        the engine never started."""
        while True:
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                break
            if item is not None and not item[2].done():
                item[2].set_exception(EngineStopped("inference engine stopped"))

    def update_model(self, model) -> None:
        """Swap in new variables (same module); takes effect next batch."""
        self.model = model

    # -- client API ---------------------------------------------------------

    def init_hidden(self, batch_dims=()):
        return self.model.init_hidden(batch_dims)

    def client(self) -> BatchedInferenceClient:
        return BatchedInferenceClient(self)

    def submit(self, obs, hidden=None) -> Future:
        fut: Future = Future()
        with self._lifecycle:
            # check-and-enqueue is atomic against stop(): after stop flips
            # the flag (under this lock) no request can enter the queue, so
            # the drain owner's final sweep provably sees every waiter —
            # the old post-put "if stopped: re-drain" dance raced a second
            # submit into a queue nobody would ever drain again
            if self._stop.is_set():
                fut.set_exception(EngineStopped("inference engine stopped"))
                return fut
            self._queue.put((obs, hidden, fut))
        return fut

    # -- dispatcher ---------------------------------------------------------

    def _drain(self) -> List:
        """Block for the first request, then gather more up to max_batch."""
        first = self._queue.get()
        if first is None:
            return []
        requests = [first]
        deadline = time.monotonic() + self.max_wait
        while len(requests) < self.max_batch:
            timeout = deadline - time.monotonic()
            try:
                if timeout <= 0:
                    item = self._queue.get_nowait()
                else:
                    item = self._queue.get(timeout=timeout)
            except queue.Empty:
                break
            if item is None:
                break
            requests.append(item)
        return requests

    def _serve_loop(self) -> None:
        while not self._stop.is_set():
            requests = self._drain()
            if not requests:
                continue
            try:
                self._serve(requests)
            except Exception as exc:  # propagate to every waiter
                for _, _, fut in requests:
                    if not fut.done():
                        fut.set_exception(exc)
        # single-owner drain: requests enqueued before stop flipped the
        # flag (submit holds the lifecycle lock, so none land after) are
        # failed here, on the one thread that also consumed them live
        self._fail_pending()

    def _serve(self, requests: List) -> None:
        model = self.model
        n = len(requests)
        bucket = next_bucket(n, self.max_batch)
        obs_batch, hidden_batch = stack_padded(
            [r[0] for r in requests], [r[1] for r in requests],
            bucket, model.init_hidden(),
        )
        outputs = model.inference_batch(obs_batch, hidden_batch)
        outputs = tree_map(np.asarray, outputs)
        for i, (_, _, fut) in enumerate(requests):
            fut.set_result(tree_map(lambda x: x[i], outputs))

        self.batches_served += 1
        self.requests_served += n
