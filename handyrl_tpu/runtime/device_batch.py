"""Host-bypass batch assembly: ``batch_pipeline: device``.

BENCH_r05 on a real TPU v5 lite: the chip consumes 376 updates/s on the
direct path while the host-fed pipeline delivers 3.0 — batch assembly
(make_batch + the ~43 MB/update observation H2D re-upload) feeds the
device at under 1% of what it can eat, and no batcher count fixes a
per-update host round-trip.  The Sebulba/Podracer lesson the repo already
builds on (PR 3) applies to the DATA plane too: when the host loses, take
the host out of the data path.

This pipeline is the drop-in (start()/batch()/stop()/stats()) that does
that for HOST-BORN episodes (worker actors, remote workers — the episodes
``device_replay: true`` cannot cover because its data never leaves the
device):

    EpisodeStore ── episodes (subscribe/snapshot, the same stream the
      │             shm plane mirrors to its children)
      ▼
    feeder thread: decode once -> DeviceEpisodeStage lane queues
      -> fixed-size (chunk, lanes) ring ingest      [one H2D per chunk]
    batch(): jitted window sample+assembly FROM the rings
      -> device-resident (B, T, P, ...) batch       [zero H2D]

make_batch, the C fill kernels, and the per-update observation upload all
leave the hot loop: each episode's bytes cross to the device exactly once,
and every training batch after that is gathers on device memory.  Window
assembly reuses DeviceReplay's sampling programs, so sampling parity with
make_batch is pinned by the same key-by-key tests as the streaming path
(tests/test_device_stage.py).

The shm plane stays the default and the fallback: this pipeline refuses
misconfigured stage modes at construction time, and ``make_pipeline``
then falls back loudly.

Multi-process (docs/performance.md §Pod-slice topology): each process
stages its OWN host-born episodes into rings on its LOCAL devices and
samples ``batch_size / num_processes`` rows per update; the local rows
hop through host once (one D2H of the sampled windows, not the per-step
observation re-upload this plane exists to kill) and re-enter the
collective mesh through ``TrainContext.put_batch`` — jax's
``make_array_from_process_local_data`` seam — so the cross-host train
step sees one global batch assembled from per-host rings.  The sampling
key is rank-decorrelated (fold_in(process_index)) or every process
would draw the same window indices from different rings.
"""

from __future__ import annotations

import threading
import time
import traceback
from collections import deque
from typing import Any, Dict, Optional

from ..utils.trace import trace_event
from .device_replay import DeviceEpisodeStage, _lane_sharding
from .replay import EpisodeStore
from .trainer import PIPE_EVENT_KEYS, PIPE_STAT_KEYS


class DeviceBatchPipeline:
    """On-device batch assembly for host-born episodes.

    Drop-in for trainer.BatchPipeline: same constructor signature, same
    ``start()``/``batch()``/``stop()``/``stats()`` surface.  ``batch()``
    returns DEVICE-resident batches (dp-sharded exactly like
    ``TrainContext.put_batch`` output; a (k, B, ...) stack under
    ``fused_steps`` > 1), so the trainer's step dispatch consumes them
    with no host round-trip.
    """

    mode = "device"

    def __init__(self, args: Dict[str, Any], store: EpisodeStore, ctx,
                 stop_event: Optional[threading.Event] = None):
        import jax

        self.args = args
        self.store = store
        self.ctx = ctx
        self.stop_event = stop_event or threading.Event()
        from ..parallel import local_batch_size

        self._local_batch = local_batch_size(args["batch_size"])
        self._fused = max(1, args.get("fused_steps", 1))
        # multi-process: rings/stage/sampling live on this process's LOCAL
        # devices (each host assembles its own shard of the global batch);
        # the sampled rows cross to the collective ctx.mesh through
        # put_batch in batch() below.  Single-process: the stage shares
        # the train mesh and batch() returns device-resident output
        self._multiproc = jax.process_count() > 1
        if self._multiproc:
            from ..parallel.mesh import make_mesh

            self._mesh = make_mesh({"dp": -1}, jax.local_devices())
        else:
            self._mesh = ctx.mesh
        # raises on mode misconfiguration (recurrent net without turn
        # windows, missing observation flag, slots too shallow) — caught
        # by make_pipeline, which falls back loudly
        self.stage = DeviceEpisodeStage(
            ctx.module, args, self._mesh,
            n_lanes=int(args.get("device_stage_lanes", 8)),
            slots=int(args.get("device_stage_slots", 1024)),
            chunk_steps=int(args.get("device_stage_chunk", 64)),
        )
        self._key = jax.random.PRNGKey(int(args.get("seed", 0)) ^ 0xD17A)
        if self._multiproc:
            # rank-decorrelated draws: every process holds DIFFERENT
            # episodes, and must also draw different window indices (the
            # seed + 1009*rank pattern, as a key fold); single-process
            # keys are untouched so the existing parity pins hold
            self._key = jax.random.fold_in(self._key, jax.process_index())
        self._sampler = None
        self._eligible = False
        self._started = False
        self._lock = threading.Lock()
        self._stats: Dict[str, float] = {k: 0.0 for k in PIPE_STAT_KEYS}
        self._stats.update({k: 0.0 for k in PIPE_EVENT_KEYS})
        self._stats.update(batches=0.0, device_queue_depth_sum=0.0, gets=0.0)
        self._pending: deque = deque()
        self._pending_cv = threading.Condition()

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        if self._started:
            return
        self._started = True
        # subscribe BEFORE snapshotting (same reasoning as the shm plane:
        # an episode landing in between is staged twice, which only skews
        # lane balance slightly; missing one is a hole forever)
        self.store.subscribe(self._on_episodes)
        snapshot = self.store.snapshot()
        with self._pending_cv:
            self._pending.extend(snapshot)
            self._pending_cv.notify()
        self._feeder_thread = threading.Thread(
            target=self._feeder_loop, daemon=True
        )
        self._feeder_thread.start()

    def _on_episodes(self, episodes) -> None:
        with self._pending_cv:
            self._pending.extend(episodes)
            self._pending_cv.notify()

    def _feeder_loop(self) -> None:
        """Decode + stage + flush on a dedicated thread: the decode cost is
        paid once per EPISODE (not per update), and the ingest dispatches
        take the mesh's dispatch locks like every multi-device program."""
        try:
            while not self.stop_event.is_set():
                with self._pending_cv:
                    if not self._pending:
                        self._pending_cv.wait(timeout=0.3)
                    batch = list(self._pending)
                    self._pending.clear()
                if not batch:
                    continue
                t0 = time.perf_counter()
                for episode in batch:
                    try:
                        self.stage.add_episode(episode)
                    except Exception:
                        # one malformed episode must not take down the
                        # whole assembly plane (the shm feeder tolerates
                        # the same); the flush/ingest path below failing
                        # IS fatal — that's ring state, not one input
                        traceback.print_exc()
                t1 = time.perf_counter()
                self.stage.flush()
                t2 = time.perf_counter()
                with self._lock:
                    # assemble = host decode/staging, put = ring ingest
                    # (the once-per-chunk H2D) — same stat vocabulary as
                    # the host pipelines so trainer/bench diffs apply
                    self._stats["assemble_s"] += t1 - t0
                    self._stats["put_s"] += t2 - t1
        except Exception:
            # a dead silent pipeline deadlocks the trainer — fail loudly
            traceback.print_exc()
            self.stop_event.set()
        finally:
            try:
                self.stage.drain()
            except Exception:
                pass

    # -- consumer side -------------------------------------------------------

    def _build_sampler(self):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec

        from ..parallel.mesh import dispatch_serialized

        replay = self.stage.replay
        mesh = self._mesh
        B, fused = self._local_batch, self._fused
        rep = NamedSharding(mesh, PartitionSpec())
        out_shard = (
            NamedSharding(mesh, PartitionSpec("dp"))
            if fused == 1
            else NamedSharding(mesh, PartitionSpec(None, "dp"))
        )

        def sample(rings, key):
            batch = replay._sample(rings, key, fused * B)
            if fused > 1:
                # rows are i.i.d. draws, so a reshape to the stacked
                # (k, B, ...) layout put_batches produces is equivalent
                # to k independent B-row samples
                batch = jax.tree.map(
                    lambda x: x.reshape((fused, B) + x.shape[1:]), batch
                )
            return batch

        ring_shard = _lane_sharding(mesh, replay.rings)
        fn = jax.jit(sample, in_shardings=(ring_shard, rep),
                     out_shardings=out_shard)

        def call(key):
            # replay.rings is read INSIDE the locked lambda: a concurrent
            # ingest donates the old ring buffers under the same locks
            return dispatch_serialized(lambda: fn(replay.rings, key), mesh)

        return call

    def batch(self):
        """Next device-resident batch, or None when shutting down.  The
        None on stop is LOAD-BEARING: the trainer's epoch loop has no
        other exit once update_flag stays false (same contract as the
        host pipelines' batch())."""
        import jax

        if self.stop_event.is_set():
            return None
        with self._lock:
            self._stats["gets"] += 1
        if not self._eligible:
            t0 = time.perf_counter()
            warned_at = t0
            while not self.stop_event.is_set():
                if self.stage.eligible() > 0:
                    self._eligible = True
                    break
                now = time.perf_counter()
                if now - warned_at > 30.0:
                    # a chunk flushes only when EVERY lane has chunk steps
                    # queued — a too-large lanes x chunk for the episode
                    # supply waits here forever; say so instead of hanging
                    # silently
                    warned_at = now
                    import sys

                    print(
                        f"[handyrl_tpu] device batch pipeline waiting for "
                        f"sampleable windows ({now - t0:.0f}s): "
                        f"{self.stage.steps_staged} steps staged over "
                        f"{self.stage.n_lanes} lanes, first flush needs "
                        f"{self.stage.n_lanes * self.stage.chunk_steps} — "
                        "lower device_stage_lanes/device_stage_chunk if "
                        "this persists",
                        file=sys.stderr,
                    )
                time.sleep(0.05)
            wait = time.perf_counter() - t0
            with self._lock:
                self._stats["ready_wait_s"] += wait
            trace_event("pipe.ready_wait", wait, plane="pipeline", mode="device")
            if not self._eligible:
                return None
        if self._sampler is None:
            self._sampler = self._build_sampler()
        self._key, sub = jax.random.split(self._key)
        t0 = time.perf_counter()
        out = self._sampler(sub)
        if self._multiproc:
            # the one deliberate host hop of the multi-process path: the
            # local rows leave the local mesh ONCE (B/nprocs sampled
            # windows, not the per-step observation re-upload this plane
            # kills) and re-enter the collective mesh via put_batch's
            # make_array_from_process_local_data seam, which takes host
            # buffers by contract
            # graftlint: allow[HS001] reason=documented local-shard crossing: make_array_from_process_local_data consumes host buffers; one D2H of sampled rows per update, not per step
            host = jax.device_get(out)
            if self._fused == 1:
                out = self.ctx.put_batch(host)
            else:
                out = self.ctx.put_batches(
                    [
                        jax.tree.map(lambda x, i=i: x[i], host)
                        for i in range(self._fused)
                    ]
                )
        with self._lock:
            self._stats["sample_s"] += time.perf_counter() - t0
            self._stats["batches"] += self._fused
        return out

    # -- teardown / introspection -------------------------------------------

    def stop(self) -> None:
        self.stop_event.set()
        try:
            self.store.unsubscribe(self._on_episodes)
        except Exception:
            pass
        # join the feeder before returning: tearing the interpreter down
        # while a daemon thread is inside an XLA execute aborts the
        # process (C++ terminate at exit) — same reasoning as the
        # learner's rollout-thread join
        feeder = getattr(self, "_feeder_thread", None)
        if feeder is not None and feeder is not threading.current_thread():
            feeder.join(timeout=30.0)
        try:
            self.stage.drain()
        except Exception:
            pass

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            out = dict(self._stats)
        out["mode"] = self.mode
        out["episodes_staged"] = self.stage.episodes_staged
        out["steps_staged"] = self.stage.steps_staged
        out["chunks_flushed"] = self.stage.chunks_flushed
        return out
