"""Env-driven fault injection (``HANDYRL_FAULT_*``) for the self-healing
run plane — the knobs the sentinel/watchdog/drain e2e tests turn
(tests/test_sentinel.py, marker ``sentinel``) so the whole
skip -> rollback -> degrade -> drain loop is exercisable on the
4-virtual-device CPU mesh with no real divergence or preemption.

All hooks are parsed lazily at their use site (Trainer / rollout-loop
entry), never at import time, so an in-process test can set the env var
right before constructing the Learner.  Unset vars mean no injection; a
malformed value raises immediately (a typo'd injection silently doing
nothing would fake a green e2e).

Hooks:

* ``HANDYRL_FAULT_NAN_AT_STEP="N"`` or ``"N:M"`` — poison the learning
  rate with NaN for absolute SGD steps [N, N+M) (M defaults to 1).  A
  NaN anywhere in the update chain is exactly what the divergence
  sentinel's in-step finite-check must catch: with ``sentinel: true``
  the steps are skipped and params stay finite; with ``sentinel: false``
  the params are poisoned forever (the pre-sentinel failure mode).
* ``HANDYRL_FAULT_WEDGE_ROLLOUT="N"`` or ``"N:all"`` — after N
  successful rollout dispatches the device-rollout thread stops making
  progress (it idles without heartbeating, simulating a wedged XLA
  execute).  Bare ``N`` wedges only the FIRST thread generation, so a
  watchdog restart heals the run; ``N:all`` wedges every generation, so
  the restart budget burns down and a split-plane run must degrade to
  fused.
* ``HANDYRL_FAULT_SIGTERM_AT_STEP="N"`` — the trainer delivers SIGTERM
  to its own process once the step counter reaches N (mid-epoch, the
  way a TPU-VM preemption lands), driving the preemption-safe drain.
* ``HANDYRL_FAULT_SIGTERM_REPLICA="N"`` — a serving replica
  (serving/server.py) SIGTERMs its own process after its N-th served
  reply, the way a spot-instance preemption lands mid-storm.  Drives
  the preemption-aware drain: the replica broadcasts its ``draining``
  notice, the fleet router migrates its sessions to a survivor inside
  ``drain_deadline_seconds``, and the process exits 75 (EX_TEMPFAIL) —
  the replica-preemption e2e in tests/test_fleet_elastic.py.
* ``HANDYRL_FAULT_KILL_PROCESS_AT_EPOCH="E:R"`` (or bare ``"E"`` = rank
  0) — the jax.distributed process with index R dies hard
  (``os._exit``) the moment its model epoch reaches E, simulating a
  lost host mid-run.  The survivors must detect the loss through the
  cross-host health plane (parallel/health.py) within the configured
  bound, drain-save on the coordinator, and exit 75 — the host-loss
  e2e in tests/test_multihost.py.
* ``HANDYRL_FAULT_WEDGE_PROCESS="E:R"`` (or bare ``"E"``) — the same
  trigger, but instead of dying the process FREEZES: heartbeats stop,
  the trainer stops joining collectives, threads spin without progress
  (a wedged-but-not-dead host).  Survivors must escape through the
  heartbeat timeout or the collective watchdog, never hang.
* ``HANDYRL_FAULT_POISON_SNAPSHOT_AT_EPOCH="E"`` — the learner SAVES a
  sabotaged snapshot (negated params — digest-valid, loads cleanly,
  plays terribly) at model epoch E while keeping its own in-memory
  params clean.  The checkpoint plane cannot catch this: the file
  verifies.  Only the flywheel's live quality plane can — the promotion
  gate must refuse it (or the quality sentinel demote it) and signal a
  training-side rollback.  Drives the bad-promotion e2e in
  tests/test_flywheel.py.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple


def _get(name: str) -> Optional[str]:
    raw = os.environ.get(name, "").strip()
    return raw or None


def nan_window() -> Optional[Tuple[int, int]]:
    """(first_step, n_steps) to poison with a NaN lr, or None."""
    raw = _get("HANDYRL_FAULT_NAN_AT_STEP")
    if raw is None:
        return None
    if ":" in raw:
        start, count = raw.split(":", 1)
        return int(start), max(1, int(count))
    return int(raw), 1


def wedge_rollout() -> Optional[Tuple[int, bool]]:
    """(after_n_dispatches, every_generation) for the rollout wedge, or
    None.  ``every_generation`` False wedges only generation 1."""
    raw = _get("HANDYRL_FAULT_WEDGE_ROLLOUT")
    if raw is None:
        return None
    if ":" in raw:
        after, scope = raw.split(":", 1)
        if scope != "all":
            raise ValueError(
                f"HANDYRL_FAULT_WEDGE_ROLLOUT={raw!r}: expected 'N' or 'N:all'"
            )
        return int(after), True
    return int(raw), False


def sigterm_at_step() -> Optional[int]:
    """Absolute SGD step at which the trainer SIGTERMs its own process."""
    raw = _get("HANDYRL_FAULT_SIGTERM_AT_STEP")
    return None if raw is None else int(raw)


def sigterm_replica() -> Optional[int]:
    """Served-reply count at which a serving replica SIGTERMs its own
    process (the spot-preemption injection), or None.  Malformed values
    raise immediately — a typo'd injection silently doing nothing would
    fake a green preemption e2e."""
    raw = _get("HANDYRL_FAULT_SIGTERM_REPLICA")
    if raw is None:
        return None
    try:
        n = int(raw)
    except ValueError:
        raise ValueError(
            f"HANDYRL_FAULT_SIGTERM_REPLICA={raw!r}: expected an int "
            "reply count"
        ) from None
    if n < 1:
        raise ValueError(
            f"HANDYRL_FAULT_SIGTERM_REPLICA={raw!r}: reply count must be >= 1"
        )
    return n


def _epoch_rank(name: str) -> Optional[Tuple[int, int]]:
    """Parse an ``"E:R"`` (epoch, rank) injection; bare ``"E"`` = rank 0.
    Malformed values raise immediately — a typo'd injection silently doing
    nothing would fake a green host-loss e2e."""
    raw = _get(name)
    if raw is None:
        return None
    epoch, _, rank = raw.partition(":")
    try:
        return int(epoch), int(rank) if rank else 0
    except ValueError:
        raise ValueError(
            f"{name}={raw!r}: expected 'EPOCH' or 'EPOCH:RANK' (ints)"
        ) from None


def poison_snapshot_epoch() -> Optional[int]:
    """Model epoch at which the learner saves a sabotaged (negated-param)
    snapshot, or None.  Malformed values raise immediately — a typo'd
    injection silently doing nothing would fake a green promotion e2e."""
    raw = _get("HANDYRL_FAULT_POISON_SNAPSHOT_AT_EPOCH")
    if raw is None:
        return None
    try:
        epoch = int(raw)
    except ValueError:
        raise ValueError(
            f"HANDYRL_FAULT_POISON_SNAPSHOT_AT_EPOCH={raw!r}: expected an "
            "int model epoch"
        ) from None
    if epoch < 1:
        raise ValueError(
            f"HANDYRL_FAULT_POISON_SNAPSHOT_AT_EPOCH={raw!r}: epoch must "
            "be >= 1"
        )
    return epoch


def kill_process_at_epoch() -> Optional[Tuple[int, int]]:
    """(epoch, rank) at which that jax.distributed process dies hard."""
    return _epoch_rank("HANDYRL_FAULT_KILL_PROCESS_AT_EPOCH")


def wedge_process_at_epoch() -> Optional[Tuple[int, int]]:
    """(epoch, rank) at which that process freezes (silent, not dead)."""
    return _epoch_rank("HANDYRL_FAULT_WEDGE_PROCESS")
