"""Self-play episode generation (actor side).

Behavioral parity with reference Generator (generation.py:15-99): per-turn
inference with per-player hidden state, legal-action masking (+1e32),
softmax sampling, immediate-reward collection and discounted-return
backfill.  Differences:

* Episodes are emitted **columnar** (see runtime/batch.py for the block
  schema) and zlib-compressed in ``compress_steps`` blocks, so learner-side
  batch assembly is pure array slicing.
* ``models[player]`` may be any object with ``inference``/``init_hidden``
  — an InferenceModel (jitted, possibly shared through the batched
  inference engine), a RandomModel, or an ONNX/ensemble wrapper.
"""

from __future__ import annotations

import random
from typing import Any, Dict, List, Optional

import numpy as np

from ..utils import softmax, tree_map, tree_stack
from .replay import compress_block


def stack_obs(obs_leaves):
    """[[pytree per player] per step] -> pytree with (t, P, ...) leaves."""
    return tree_stack([tree_stack(step) for step in obs_leaves])


def finalize_episode(rows, players, outcome, args, gen_args, obs_spec_fn=None):
    """Columnar-finalize per-step rows into a compressed-block episode.

    This is THE episode recipe: the self-play Generator and the serving-
    tier HarvestRecorder (flywheel/harvest.py) both finalize through this
    one function, so a served session's episode is bit-identical to the
    self-play encoding by construction — pinned by the flywheel parity
    suite, never re-derived per caller.

    ``rows`` are per-step dicts of per-player values (None = absent) with
    keys obs/prob/amask/action/value/reward plus a scalar "turn" index.
    ``gen_args`` supplies gamma / compress_steps / obs_int8; ``obs_spec_fn``
    (obs_template -> per-leaf (scale, zero) spec) is required only when
    obs_int8 is set.
    """
    P, T = len(players), len(rows)
    gamma = gen_args["gamma"]

    # discounted return-to-go per player (generation.py:78-82)
    returns = np.zeros((T, P), np.float32)
    for j, p in enumerate(players):
        acc = 0.0
        for t in range(T - 1, -1, -1):
            acc = (rows[t]["reward"][p] or 0.0) + gamma * acc
            returns[t, j] = acc

    obs_template = tree_map(
        np.zeros_like,
        next(o for row in rows for o in row["obs"].values() if o is not None),
    )
    amask_template = np.full_like(
        next(a for row in rows for a in row["amask"].values() if a is not None), 1e32
    )

    block_len = gen_args["compress_steps"]
    blocks = []
    for lo in range(0, T, block_len):
        chunk = rows[lo : lo + block_len]
        t = len(chunk)
        cols = {
            "prob": np.ones((t, P), np.float32),
            "action": np.zeros((t, P), np.int32),
            "amask": np.tile(amask_template, (t, P) + (1,) * amask_template.ndim),
            "value": np.zeros((t, P), np.float32),
            "reward": np.zeros((t, P), np.float32),
            "ret": returns[lo : lo + t],
            "tmask": np.zeros((t, P), np.float32),
            "omask": np.zeros((t, P), np.float32),
            "turn": np.asarray([row["turn"] for row in chunk], np.int32),
        }
        obs_leaves = []
        for i, row in enumerate(chunk):
            for j, p in enumerate(players):
                if row["obs"][p] is not None:
                    cols["omask"][i, j] = 1.0
                if row["value"][p] is not None:
                    cols["value"][i, j] = row["value"][p]
                if row["reward"][p] is not None:
                    cols["reward"][i, j] = row["reward"][p]
                if row["prob"][p] is not None:
                    cols["tmask"][i, j] = 1.0
                    cols["prob"][i, j] = row["prob"][p]
                    cols["action"][i, j] = row["action"][p]
                    cols["amask"][i, j] = row["amask"][p]
            obs_leaves.append(
                [
                    row["obs"][p] if row["obs"][p] is not None else obs_template
                    for p in players
                ]
            )
        cols["obs"] = stack_obs(obs_leaves)  # (t, P, ...) leaf-wise
        if gen_args.get("obs_int8"):
            # quantize ONCE at finalize: the compressed wire blocks,
            # the shm ring slots, and the device replay rings all
            # inherit the int8 leaves; dequantize runs on device at
            # the consumption seams (models/quantize.py)
            from ..models.quantize import quantize_obs_tree

            cols["obs"] = quantize_obs_tree(cols["obs"], obs_spec_fn(obs_template))
        blocks.append(compress_block(cols))

    episode = {
        "args": args,
        "steps": T,
        "players": players,
        "outcome": outcome,
        "blocks": blocks,
    }
    if gen_args.get("obs_int8"):
        # the spec rides WITH the episode so every consumer (device
        # stage, train step) dequantizes with the scales the data was
        # actually quantized under — no env re-derivation stage-side
        spec = obs_spec_fn(obs_template)
        episode["obs_scale"] = np.asarray([s for s, _ in spec], np.float32)
        episode["obs_zero"] = np.asarray([z for _, z in spec], np.float32)
    return episode


class Generator:
    def __init__(self, env, args: Dict[str, Any], on_step=None):
        self.env = env
        self.args = args
        self.on_step = on_step  # called once per env step (throughput probes)
        # obs_int8: per-leaf (scale, zero_point), resolved once from env
        # metadata (models/quantize.py obs_quant_spec)
        self._obs_spec = None

    def _obs_quant_spec(self, obs_template):
        if self._obs_spec is None:
            from ..models.quantize import obs_quant_spec

            self._obs_spec = obs_quant_spec(self.env, obs=obs_template)
        return self._obs_spec

    def generate(self, models: Dict[int, Any], args: Dict[str, Any]) -> Optional[Dict[str, Any]]:
        env = self.env
        players: List[int] = env.players()
        hidden = {p: models[p].init_hidden() for p in players}

        if env.reset():
            return None

        rows = []  # per-step dicts of per-player values (None = absent)
        while not env.terminal():
            row = {
                key: {p: None for p in players}
                for key in ("obs", "prob", "amask", "action", "value", "reward")
            }
            turn_players = env.turns()
            observers = env.observers()
            actions: Dict[int, Optional[int]] = {}

            active = []
            for player in players:
                if player not in turn_players and player not in observers:
                    continue
                if (
                    player not in turn_players
                    and player in args["player"]
                    and not self.args["observation"]
                ):
                    continue
                active.append((player, env.observation(player)))

            # issue every player's request before waiting on any: engine-
            # backed models (inference_engine.py) expose ``submit`` and
            # coalesce the concurrent requests into one device batch —
            # simultaneous-move games (HungryGeese: 4 players/step) would
            # otherwise pay one engine round-trip per player per step
            futures = {
                p: models[p].submit(o, hidden[p])
                for p, o in active
                if hasattr(models[p], "submit")
            }

            for player, obs in active:
                if player in futures:
                    outputs = futures[player].result()
                else:
                    outputs = models[player].inference(obs, hidden[player])
                hidden[player] = outputs.get("hidden")
                row["obs"][player] = obs
                if outputs.get("value") is not None:
                    row["value"][player] = float(np.asarray(outputs["value"]).reshape(-1)[0])

                if player in turn_players:
                    logits = np.asarray(outputs["policy"], dtype=np.float32)
                    legal = env.legal_actions(player)
                    amask = np.full_like(logits, 1e32)
                    amask[legal] = 0.0
                    probs = softmax(logits - amask)
                    action = random.choices(legal, weights=probs[legal])[0]
                    row["prob"][player] = float(probs[action])
                    row["amask"][player] = amask
                    row["action"][player] = int(action)
                    actions[player] = action

            if env.step(actions):
                return None
            if self.on_step is not None:
                self.on_step()

            reward = env.reward()
            for p in players:
                row["reward"][p] = reward.get(p)
            row["turn"] = players.index(turn_players[0]) if turn_players else 0
            rows.append(row)

        if not rows:
            return None

        return self._finalize(rows, players, env.outcome(), args)

    def _finalize(self, rows, players, outcome, args) -> Dict[str, Any]:
        return finalize_episode(
            rows, players, outcome, args, self.args, obs_spec_fn=self._obs_quant_spec
        )

    @staticmethod
    def _stack_obs(obs_leaves):
        """[[pytree per player] per step] -> pytree with (t, P, ...) leaves."""
        return stack_obs(obs_leaves)

    def execute(self, models, args):
        episode = self.generate(models, args)
        if episode is None:
            print("None episode in generation!")
        return episode
