"""Remote worker machines over TCP: server side and worker side.

Topology parity with reference handyrl/worker.py:192-271: an entry
listener hands joining machines the full training config plus a
``base_worker_id`` (worker.py:199-213); each machine then opens data
connections that carry job args, episodes, eval results and model blobs.
Two-level aggregation is kept — a machine multiplexes its actors over
``num_gathers`` connections (one per ~16 actors, worker.py:110-124) so the
server's connection count stays O(gathers), not O(actors).

TPU-first differences:

* Actors on a worker machine are threads sharing one
  ``BatchedInferenceEngine`` (cross-env batched inference), not
  process-per-actor batch-1 inference.
* Model parameters travel as flax-msgpack byte blobs, decoded into the
  machine's local engine — never pickled module code (SURVEY.md §2.5).
* A gather prefetches job assignments in bulk and flushes episode/result
  uploads in bulk (worker.py:136-168 semantics) to amortize WAN RTT.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional

from ..envs import make_env, prepare_env
from ..models import InferenceModel, RandomModel, init_variables
from .checkpoint import load_params, model_path, params_from_bytes, params_to_bytes
from .connection import (
    FramedConnection,
    QueueCommunicator,
    accept_socket_connections,
    connect_socket_connection,
    send_recv,
)
from .inference_engine import BatchedInferenceEngine
from .worker import Worker

ENTRY_PORT = 9999
DATA_PORT = 9998


# ---------------------------------------------------------------------------
# learner side
# ---------------------------------------------------------------------------


class WorkerServer(QueueCommunicator):
    """Serves remote worker machines (reference WorkerServer, worker.py:192-224).

    Same ``run()`` surface as LocalWorkerPool so the Learner treats local
    and remote actor planes identically: requests are dispatched to the
    learner's ``handler`` callable; ``model`` requests are answered here
    from the model server (bytes), without a round-trip through the
    learner loop.
    """

    def __init__(self, args: Dict[str, Any], handler: Callable, model_server):
        super().__init__()
        self.args = args
        self.handler = handler
        self.model_server = model_server
        self.entry_port = int(args["worker"].get("entry_port", ENTRY_PORT))
        self.data_port = int(args["worker"].get("data_port", DATA_PORT))
        self.total_worker_count = 0
        self._threads: List[threading.Thread] = []
        self._blob_cache: Dict[int, bytes] = {}

    def run(self) -> None:
        for target in (self._entry_server, self._data_server, self._dispatch):
            t = threading.Thread(target=target, daemon=True)
            t.start()
            self._threads.append(t)

    def _entry_server(self) -> None:
        print("started entry server %d" % self.entry_port)
        for conn in accept_socket_connections(port=self.entry_port, timeout=0.5):
            if conn is None:
                if self.shutdown_flag:
                    break
                continue
            try:
                worker_args = conn.recv()
                n = int(worker_args.get("num_parallel", 8))
                reply = {
                    "env_args": self.args["env"],
                    "train_args": {k: v for k, v in self.args.items() if k != "env"},
                    "worker_args": dict(worker_args, base_worker_id=self.total_worker_count),
                }
                self.total_worker_count += n
                conn.send(reply)
            except Exception as exc:
                print("entry handshake failed:", exc)
            finally:
                conn.close()
        print("finished entry server")

    def _data_server(self) -> None:
        print("started worker server %d" % self.data_port)
        for conn in accept_socket_connections(port=self.data_port, timeout=0.5):
            if conn is None:
                if self.shutdown_flag:
                    break
                continue
            self.add_connection(conn)
        print("finished worker server")

    def _dispatch(self) -> None:
        import queue as _queue

        while not self.shutdown_flag:
            try:
                conn, (req, data) = self.recv(timeout=0.3)
            except _queue.Empty:
                continue
            except (TypeError, ValueError):
                continue
            if req == "model":
                self.send(conn, self._model_bytes(int(data)))
            else:
                self.send(conn, self.handler(req, data))

    def _model_bytes(self, requested_id: int):
        """(model_id, params_blob) for a snapshot id (train.py:604-614).

        Blobs are cached per id: each epoch M worker machines ask for the
        same latest params, and serialization must not stall the dispatch
        thread M times.
        """
        latest_id, latest_params = self.model_server.latest_snapshot()
        if 0 < requested_id < latest_id:
            cached = self._blob_cache.get(requested_id)
            if cached is not None:
                return requested_id, cached
            try:
                params = load_params(
                    model_path(self.model_server.model_dir, requested_id), latest_params
                )
                blob = params_to_bytes(params)
                self._trim_blob_cache()
                self._blob_cache[requested_id] = blob
                return requested_id, blob
            except Exception:
                pass  # fall back to latest (reference train.py:608-613)
        cached = self._blob_cache.get(latest_id)
        if cached is None:
            # id and params read atomically above, so the cache key is honest
            cached = params_to_bytes(latest_params)
            self._trim_blob_cache()
            self._blob_cache[latest_id] = cached
        return latest_id, cached

    def _trim_blob_cache(self, keep: int = 4) -> None:
        while len(self._blob_cache) >= keep:
            self._blob_cache.pop(next(iter(self._blob_cache)))


# ---------------------------------------------------------------------------
# worker machine side
# ---------------------------------------------------------------------------


class RemoteModelServer:
    """Machine-local model cache fed by ('model', id) RPCs (worker.py:43-64).

    The newest params live behind the shared BatchedInferenceEngine; id 0
    is the zero-output RandomModel; stale ids resolve to standalone
    InferenceModels fetched once and cached.
    """

    def __init__(self, module, env, args: Dict[str, Any], fetch: Callable[[int], tuple]):
        self.module = module
        self._fetch = fetch
        variables = init_variables(module, env)
        self._template = variables["params"]
        self._model = InferenceModel(module, variables)
        env.reset()
        self._random = RandomModel.from_model(self._model, env.observation(env.players()[0]))
        self.engine = BatchedInferenceEngine(
            self._model, max_batch=args.get("inference_batch_size", 64)
        ).start()
        self.model_id = -1
        self._cache: Dict[int, InferenceModel] = {}
        self._lock = threading.Lock()
        # seed the engine with the learner's actual latest params — without
        # this, jobs with model_id -1 would run on local random-init weights
        # until the first concrete-epoch fetch (a whole epoch at join time)
        got_id, blob = self._fetch(-1)
        self.model_id = got_id
        self.engine.update_model(
            InferenceModel(self.module, {"params": params_from_bytes(self._template, blob)})
        )

    def get(self, model_id: int):
        if model_id == 0:
            return self._random
        with self._lock:
            current = self.model_id
            if model_id < 0 or model_id == current:
                return self.engine.client()
            cached = self._cache.get(model_id)
        if cached is not None:
            return cached
        got_id, blob = self._fetch(model_id)
        params = params_from_bytes(self._template, blob)
        model = InferenceModel(self.module, {"params": params})
        with self._lock:
            if got_id > self.model_id:
                self.model_id = got_id
                self.engine.update_model(model)
                # drop stale snapshots; only explicitly-pinned old ids recur
                self._cache = {k: v for k, v in self._cache.items() if k == model_id}
            if got_id != model_id:
                # server substituted latest for a missing snapshot
                return self.engine.client() if got_id == self.model_id else model
            if model_id != self.model_id:
                self._cache[model_id] = model
        return self.engine.client() if model_id == self.model_id else model

    def stop(self) -> None:
        self.engine.stop()


class RemoteGather:
    """One data connection multiplexing ~16 actor threads (worker.py:99-173).

    Prefetches job args in blocks and flushes episode/result uploads in
    blocks; all RPCs are serialized on the single connection.
    """

    def __init__(self, conn: FramedConnection, n_workers: int):
        self.conn = conn
        self.buffer_length = 1 + n_workers // 4
        self._lock = threading.Lock()
        self._args_queue: List[Any] = []
        self._uploads: Dict[str, List[Any]] = {"episode": [], "result": []}
        self.closed = False

    def __call__(self, req: str, data: Any) -> Any:
        with self._lock:
            if req == "args":
                return self._next_args()
            if req in self._uploads:
                self._uploads[req].append(data)
                if len(self._uploads[req]) >= self.buffer_length:
                    self._flush(req)
                return None
            if self.closed:
                return None
            return send_recv(self.conn, (req, data))

    def _next_args(self) -> Optional[Dict[str, Any]]:
        if self.closed:
            return None
        if not self._args_queue:
            for req in ("episode", "result"):
                self._flush(req)  # don't let uploads sit behind idle prefetch
            batch = send_recv(self.conn, ("args", self.buffer_length))
            if batch is None:
                self.close()
                return None
            self._args_queue = [a for a in batch if a is not None]
            if not self._args_queue:
                self.close()
                return None
        return self._args_queue.pop(0)

    def _flush(self, req: str) -> None:
        if self._uploads[req] and not self.closed:
            send_recv(self.conn, (req, self._uploads[req]))
            self._uploads[req] = []

    def fetch_model(self, model_id: int) -> tuple:
        with self._lock:
            if self.closed:
                raise ConnectionResetError("gather connection closed")
            return send_recv(self.conn, ("model", model_id))

    def close(self) -> None:
        if not self.closed:
            for req in ("episode", "result"):
                try:
                    self._flush(req)
                except OSError:
                    pass
            self.closed = True
            self.conn.close()


class RemoteWorkerCluster:
    """Worker-machine main (reference RemoteWorkerCluster, worker.py:235-261)."""

    def __init__(self, worker_args: Dict[str, Any]):
        self.worker_args = dict(worker_args)
        self.server_address = worker_args["server_address"]
        self.entry_port = int(worker_args.get("entry_port", ENTRY_PORT))
        self.num_parallel = int(worker_args.get("num_parallel", 8))

    def _entry(self, retry_seconds: float = 60.0) -> Dict[str, Any]:
        conn = connect_socket_connection(
            self.server_address, self.entry_port, retry_seconds=retry_seconds
        )
        try:
            return send_recv(conn, dict(self.worker_args, num_parallel=self.num_parallel))
        finally:
            conn.close()

    def run(self) -> None:
        cfg = self._entry()
        args = dict(cfg["train_args"])
        args["env"] = cfg["env_args"]
        base_worker_id = cfg["worker_args"].get("base_worker_id", 0)
        data_port = int(args["worker"].get("data_port", DATA_PORT))
        prepare_env(args["env"])

        num_gathers = 1 + (self.num_parallel - 1) // 16
        gathers: List[RemoteGather] = []
        shares: List[int] = []
        for g in range(num_gathers):
            share = self.num_parallel // num_gathers + int(g < self.num_parallel % num_gathers)
            conn = connect_socket_connection(self.server_address, data_port)
            gathers.append(RemoteGather(conn, share))
            shares.append(share)

        model_server = RemoteModelServer(
            make_env(args["env"]).net(), make_env(args["env"]), args, gathers[0].fetch_model
        )

        threads: List[threading.Thread] = []
        wid = base_worker_id
        for gather, share in zip(gathers, shares):
            for _ in range(share):
                worker = Worker(make_env(args["env"]), args, gather, model_server, wid)
                t = threading.Thread(target=worker.run, daemon=True, name=f"remote-actor-{wid}")
                t.start()
                threads.append(t)
                wid += 1
        try:
            for t in threads:
                t.join()
        finally:
            for gather in gathers:
                gather.close()
            model_server.stop()


def worker_main(args: Dict[str, Any], argv: Optional[List[str]] = None) -> None:
    """`main.py --worker [NUM_PARALLEL]` (reference worker.py:264-271)."""
    worker_args = dict(args["worker_args"])
    if argv and len(argv) >= 3:
        worker_args["num_parallel"] = int(argv[2])
    RemoteWorkerCluster(worker_args).run()
