"""Remote worker machines over TCP: server side and worker side.

Topology parity with reference handyrl/worker.py:192-271: an entry
listener hands joining machines the full training config plus a
``base_worker_id`` (worker.py:199-213); each machine then opens data
connections that carry job args, episodes, eval results and model blobs.
Two-level aggregation is kept — a machine multiplexes its actors over
``num_gathers`` connections (one per ~16 actors, worker.py:110-124) so the
server's connection count stays O(gathers), not O(actors).

TPU-first differences:

* Actors on a worker machine are threads sharing one
  ``BatchedInferenceEngine`` (cross-env batched inference), not
  process-per-actor batch-1 inference.
* Model parameters travel as flax-msgpack byte blobs, decoded into the
  machine's local engine — never pickled module code (SURVEY.md §2.5).
* A gather prefetches job assignments in bulk and flushes episode/result
  uploads in bulk (worker.py:136-168 semantics) to amortize WAN RTT.

Fault tolerance (docs/fault_tolerance.md):

* The entry handshake has a deadline — a client that connects and then
  stalls is dropped after ``entry_timeout`` instead of wedging the single
  entry thread for every later join.
* Liveness is heartbeat-based in BOTH directions.  The server pings every
  gather connection each ``heartbeat_interval`` from a dedicated thread
  (so pings flow even while the learner spends minutes inside an epoch
  boundary), and drops peers silent for ~3 intervals; gathers ping the
  server the same way and treat ~3 silent intervals as a dead link.
* A severed gather connection is not fatal to the worker machine: the
  cluster tears down its session (no actor thread survives) and re-enters
  through the entry port with exponential backoff.  The server reclaims
  the vanished connection's in-flight jobs via ``jobs_lost`` so the
  learner's generation/evaluation balance re-dispatches them.
"""

from __future__ import annotations

import socket
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ..envs import make_env, prepare_env
from ..models import InferenceModel, RandomModel, init_variables
from .checkpoint import load_verified_params, params_from_bytes, params_to_bytes
from .connection import (
    FramedConnection,
    QueueCommunicator,
    accept_socket_connections,
    connect_socket_connection,
    send_recv,
)
from .inference_engine import BatchedInferenceEngine
from .worker import Worker

ENTRY_PORT = 9999
DATA_PORT = 9998

_HB = ("__hb__",)  # liveness ping frame (both directions); never a reply


def _is_hb(frame: Any) -> bool:
    return isinstance(frame, tuple) and len(frame) == 1 and frame[0] == "__hb__"


# ---------------------------------------------------------------------------
# learner side
# ---------------------------------------------------------------------------


class WorkerServer(QueueCommunicator):
    """Serves remote worker machines (reference WorkerServer, worker.py:192-224).

    Same ``run()`` surface as LocalWorkerPool so the Learner treats local
    and remote actor planes identically: requests are dispatched to the
    learner's ``handler`` callable; ``model`` requests are answered here
    from the model server (bytes), without a round-trip through the
    learner loop.
    """

    def __init__(self, args: Dict[str, Any], handler: Callable, model_server):
        worker_cfg = args["worker"]
        self.heartbeat_interval = float(worker_cfg.get("heartbeat_interval", 10.0))
        super().__init__(
            recv_timeout=(
                3.0 * self.heartbeat_interval if self.heartbeat_interval > 0 else None
            )
        )
        self.args = args
        self.handler = handler
        self.model_server = model_server
        self.entry_port = int(worker_cfg.get("entry_port", ENTRY_PORT))
        self.data_port = int(worker_cfg.get("data_port", DATA_PORT))
        self.entry_timeout = float(worker_cfg.get("entry_timeout", 10.0))
        self.total_worker_count = 0
        self._threads: List[threading.Thread] = []
        self._blob_cache: Dict[int, bytes] = {}
        # in-flight job ledger per connection: assignments sent minus
        # uploads received; a vanished peer's balance is handed back to the
        # learner as ('jobs_lost', {'g': n, 'e': m}) so it re-dispatches
        self._inflight: Dict[FramedConnection, Dict[str, int]] = {}
        self._inflight_lock = threading.Lock()

    def run(self) -> None:
        targets = [self._entry_server, self._data_server, self._dispatch]
        if self.heartbeat_interval > 0:
            targets.append(self._heartbeat_loop)
        for target in targets:
            t = threading.Thread(target=target, daemon=True)
            t.start()
            self._threads.append(t)

    def _entry_server(self) -> None:
        print("started entry server %d" % self.entry_port)
        for conn in accept_socket_connections(port=self.entry_port, timeout=0.5):
            if conn is None:
                if self.shutdown_flag:
                    break
                continue
            try:
                # HARD deadline on the single entry thread: a client that
                # connects and then stalls — or drip-feeds one byte per
                # gap — must not wedge every later join.  Handshake frames
                # are tiny, so an absolute budget is the right semantics
                # here (unlike the data plane's stall-bounded transfers)
                worker_args = conn.recv(timeout=self.entry_timeout, hard=True)
                n = int(worker_args.get("num_parallel", 8))
                reply = {
                    "env_args": self.args["env"],
                    "train_args": {k: v for k, v in self.args.items() if k != "env"},
                    "worker_args": dict(worker_args, base_worker_id=self.total_worker_count),
                }
                self.total_worker_count += n
                conn.send(reply, timeout=self.entry_timeout, hard=True)
            except socket.timeout:
                print("entry handshake timed out; dropping slow client")
            except Exception as exc:
                print("entry handshake failed:", exc)
            finally:
                conn.close()
        print("finished entry server")

    def _data_server(self) -> None:
        print("started worker server %d" % self.data_port)
        for conn in accept_socket_connections(port=self.data_port, timeout=0.5):
            if conn is None:
                if self.shutdown_flag:
                    break
                continue
            self.add_connection(conn)
        print("finished worker server")

    def _heartbeat_loop(self) -> None:
        """Ping every peer each interval, from OUTSIDE the dispatch path:
        the learner can be busy for minutes at an epoch boundary (first
        jit compile) and gathers must still see a live link."""
        while not self.shutdown_flag:
            time.sleep(self.heartbeat_interval)
            for conn in self.connections():
                self.send(conn, _HB, droppable=True)

    def add_connection(self, conn: FramedConnection) -> None:
        # ledger exists for the connection's whole lifetime: created here,
        # removed exactly once by on_disconnect.  _count_jobs never creates
        # entries, so a frame drained from input_queue AFTER its peer was
        # reaped cannot resurrect a popped ledger (which would leak the
        # entry and strand its job counts forever)
        with self._inflight_lock:
            self._inflight[conn] = {"g": 0, "e": 0}
        super().add_connection(conn)

    def _count_jobs(self, conn: FramedConnection, role_counts: Dict[str, int]) -> None:
        with self._inflight_lock:
            ledger = self._inflight.get(conn)
            if ledger is not None:
                for role, n in role_counts.items():
                    ledger[role] = max(0, ledger[role] + n)
                return
        # peer already reaped.  Positive counts (assignments) can never
        # come back — hand them to the learner as lost.  Negative counts
        # are uploads that DID arrive after the disconnect report already
        # wrote them off wholesale: pass them through too (the learner
        # subtracts, so a negative count adds the balance back) or the
        # generation/evaluation ratio skews by that much per disconnect.
        self._report_lost({k: v for k, v in role_counts.items() if v})

    def _report_lost(self, counts: Dict[str, int]) -> None:
        if not (counts.get("g") or counts.get("e")) or self.shutdown_flag:
            return

        def report():
            try:
                self.handler("jobs_lost", counts, timeout=30.0)
            except Exception:
                pass  # learner already draining; the balance no longer matters

        # own thread: this is reached from on_disconnect, which can run on
        # the heartbeat or a receiver thread — and the learner can be busy
        # for minutes at an epoch boundary, so a blocking handler call here
        # would suppress pings to every OTHER (healthy) peer meanwhile
        threading.Thread(target=report, daemon=True).start()

    def on_disconnect(self, conn: FramedConnection) -> None:
        with self._inflight_lock:
            ledger = self._inflight.pop(conn, None)
        if ledger:
            self._report_lost(ledger)

    def _dispatch(self) -> None:
        import queue as _queue

        while not self.shutdown_flag:
            try:
                conn, (req, data) = self.recv(timeout=0.3)
            except _queue.Empty:
                continue
            except (TypeError, ValueError):
                continue
            if req == "heartbeat":
                continue  # liveness traffic only; no reply by design
            if req == "model":
                self.send(conn, self._model_bytes(int(data)))
                continue
            reply = self.handler(req, data)
            if req == "args" and isinstance(reply, list):
                roles: Dict[str, int] = {"g": 0, "e": 0}
                for a in reply:
                    if a is not None:
                        roles[a["role"]] += 1
                self._count_jobs(conn, roles)
            elif req in ("episode", "result"):
                role = "g" if req == "episode" else "e"
                n = len(data) if isinstance(data, list) else 1
                self._count_jobs(conn, {role: -n})
            self.send(conn, reply)

    def _model_bytes(self, requested_id: int):
        """(model_id, params_blob) for a snapshot id (train.py:604-614).

        Blobs are cached per id: each epoch M worker machines ask for the
        same latest params, and serialization must not stall the dispatch
        thread M times.
        """
        latest_id, latest_params = self.model_server.latest_snapshot()
        if 0 < requested_id < latest_id:
            cached = self._blob_cache.get(requested_id)
            if cached is not None:
                return requested_id, cached
            try:
                # digest-verified: serving a silently-corrupt snapshot to a
                # whole worker machine poisons every episode it generates
                params = load_verified_params(
                    self.model_server.model_dir, requested_id, latest_params
                )
                blob = params_to_bytes(params)
                self._trim_blob_cache()
                self._blob_cache[requested_id] = blob
                return requested_id, blob
            except Exception:
                # CheckpointError (digest mismatch) included: fall back to
                # latest (reference train.py:608-613)
                pass
        cached = self._blob_cache.get(latest_id)
        if cached is None:
            # id and params read atomically above, so the cache key is honest
            cached = params_to_bytes(latest_params)
            self._trim_blob_cache()
            self._blob_cache[latest_id] = cached
        return latest_id, cached

    def _trim_blob_cache(self, keep: int = 4) -> None:
        while len(self._blob_cache) >= keep:
            self._blob_cache.pop(next(iter(self._blob_cache)))


# ---------------------------------------------------------------------------
# worker machine side
# ---------------------------------------------------------------------------


class RemoteModelServer:
    """Machine-local model cache fed by ('model', id) RPCs (worker.py:43-64).

    The newest params live behind the shared BatchedInferenceEngine; id 0
    is the zero-output RandomModel; stale ids resolve to standalone
    InferenceModels fetched once and cached.
    """

    def __init__(self, module, env, args: Dict[str, Any], fetch: Callable[[int], tuple]):
        self.module = module
        self._fetch = fetch
        variables = init_variables(module, env)
        self._template = variables["params"]
        self._model = InferenceModel(module, variables)
        env.reset()
        self._random = RandomModel.from_model(self._model, env.observation(env.players()[0]))
        self.engine = BatchedInferenceEngine(
            self._model, max_batch=args.get("inference_batch_size", 64)
        ).start()
        self.model_id = -1
        self._cache: Dict[int, InferenceModel] = {}
        self._lock = threading.Lock()
        # seed the engine with the learner's actual latest params — without
        # this, jobs with model_id -1 would run on local random-init weights
        # until the first concrete-epoch fetch (a whole epoch at join time)
        got_id, blob = self._fetch(-1)
        self.model_id = got_id
        self.engine.update_model(
            InferenceModel(self.module, {"params": params_from_bytes(self._template, blob)})
        )

    def get(self, model_id: int):
        if model_id == 0:
            return self._random
        with self._lock:
            current = self.model_id
            if model_id < 0 or model_id == current:
                return self.engine.client()
            cached = self._cache.get(model_id)
        if cached is not None:
            return cached
        got_id, blob = self._fetch(model_id)
        params = params_from_bytes(self._template, blob)
        model = InferenceModel(self.module, {"params": params})
        with self._lock:
            if got_id > self.model_id:
                self.model_id = got_id
                self.engine.update_model(model)
                # drop stale snapshots; only explicitly-pinned old ids recur
                self._cache = {k: v for k, v in self._cache.items() if k == model_id}
            if got_id != model_id:
                # server substituted latest for a missing snapshot
                return self.engine.client() if got_id == self.model_id else model
            if model_id != self.model_id:
                self._cache[model_id] = model
        return self.engine.client() if model_id == self.model_id else model

    def stop(self) -> None:
        self.engine.stop()


class RemoteGather:
    """One data connection multiplexing ~16 actor threads (worker.py:99-173).

    Prefetches job args in blocks and flushes episode/result uploads in
    blocks; all RPCs are serialized on the single connection.  Every RPC
    runs under a deadline: the reply wait tolerates server heartbeat
    frames (the learner can be minutes inside an epoch boundary while the
    link stays provably alive) but ~3 silent heartbeat intervals raise,
    mark the gather ``failed``, and trigger the cluster's rejoin path.
    """

    def __init__(
        self,
        conn: FramedConnection,
        n_workers: int,
        heartbeat_interval: float = 10.0,
        io_timeout: float = 60.0,
    ):
        self.conn = conn
        self.buffer_length = 1 + n_workers // 4
        self.io_timeout = io_timeout
        self.hb_timeout = (
            max(3.0 * heartbeat_interval, io_timeout) if heartbeat_interval > 0 else None
        )
        self._lock = threading.Lock()
        self._args_queue: List[Any] = []
        self._uploads: Dict[str, List[Any]] = {"episode": [], "result": []}
        self.closed = False
        self.failed = False

    def _rpc(self, payload: Any) -> Any:
        """send + recv-until-reply, discarding interleaved server
        heartbeats (each one restarts the silence deadline)."""
        if self.failed:
            # a previous deadline fired, possibly mid-frame: the stream may
            # be desynchronized (a late reply to the timed-out RPC would be
            # read as THIS call's reply) — nothing may use it again
            raise ConnectionResetError("gather link failed; stream not reusable")
        try:
            self.conn.send(payload, timeout=self.io_timeout)
            while True:
                frame = self.conn.recv(timeout=self.hb_timeout)
                if _is_hb(frame):
                    continue
                return frame
        except (socket.timeout, ConnectionResetError, BrokenPipeError, OSError):
            self.failed = True
            raise

    def ping(self) -> None:
        """One-way liveness frame; bypasses the RPC lock on purpose — the
        send must flow even while an RPC waits minutes for its reply, or
        the server would drop this link as silent mid-epoch-boundary.
        Non-blocking at the frame level too (``try_send``): a frame
        already in flight IS liveness traffic, and blocking here would
        starve the single ping thread's other gathers behind one slow
        upload."""
        if self.closed or self.failed:
            return
        try:
            self.conn.try_send(("heartbeat", None), timeout=self.io_timeout)
        except (socket.timeout, ConnectionResetError, BrokenPipeError, OSError):
            self.failed = True

    def __call__(self, req: str, data: Any) -> Any:
        with self._lock:
            if self.failed:
                return None  # actors drain; the cluster is tearing down
            if req == "args":
                return self._next_args()
            if req in self._uploads:
                self._uploads[req].append(data)
                if len(self._uploads[req]) >= self.buffer_length:
                    self._flush(req)
                return None
            if self.closed:
                return None
            return self._rpc((req, data))

    def _next_args(self) -> Optional[Dict[str, Any]]:
        if self.closed:
            return None
        if not self._args_queue:
            for req in ("episode", "result"):
                self._flush(req)  # don't let uploads sit behind idle prefetch
            batch = self._rpc(("args", self.buffer_length))
            if batch is None:
                self.close()
                return None
            self._args_queue = [a for a in batch if a is not None]
            if not self._args_queue:
                self.close()
                return None
        return self._args_queue.pop(0)

    def _flush(self, req: str) -> None:
        if self._uploads[req] and not self.closed:
            self._rpc((req, self._uploads[req]))
            self._uploads[req] = []

    def fetch_model(self, model_id: int) -> tuple:
        with self._lock:
            if self.closed:
                raise ConnectionResetError("gather connection closed")
            return self._rpc(("model", model_id))

    def close(self, abort: bool = False) -> None:
        """``abort`` skips the final upload flush — used when the link (or
        a sibling gather's link) already failed and blocking on a dead
        socket would stall the whole teardown."""
        if not self.closed:
            if not abort and not self.failed:
                for req in ("episode", "result"):
                    try:
                        self._flush(req)
                    except OSError:
                        break
            self.closed = True
            self.conn.close()


class RemoteWorkerCluster:
    """Worker-machine main (reference RemoteWorkerCluster, worker.py:235-261).

    ``run()`` is a supervision loop: one *session* (entry handshake, data
    connections, actor threads) runs until either the learner drains it
    cleanly (job assignment returns None → exit) or a connection fails —
    then every gather is torn down, every actor thread exits, and the
    machine re-enters through the entry port with exponential backoff.
    """

    def __init__(self, worker_args: Dict[str, Any]):
        self.worker_args = dict(worker_args)
        self.server_address = worker_args["server_address"]
        self.entry_port = int(worker_args.get("entry_port", ENTRY_PORT))
        self.num_parallel = int(worker_args.get("num_parallel", 8))
        self.rejoin = bool(worker_args.get("rejoin", True))
        self.rejoin_backoff = float(worker_args.get("rejoin_backoff", 1.0))
        self.rejoin_backoff_max = float(worker_args.get("rejoin_backoff_max", 60.0))
        self.max_rejoins = int(worker_args.get("max_rejoins", -1))
        self.entry_retry_seconds = float(worker_args.get("entry_retry_seconds", 60.0))

    def _entry(self) -> Dict[str, Any]:
        conn = connect_socket_connection(
            self.server_address, self.entry_port,
            retry_seconds=self.entry_retry_seconds,
        )
        try:
            return send_recv(conn, dict(self.worker_args, num_parallel=self.num_parallel),
                             timeout=30.0)
        finally:
            conn.close()

    def run(self) -> None:
        backoff = self.rejoin_backoff
        rejoins = 0
        while True:
            t0 = time.monotonic()
            try:
                clean = self._run_session()
            except (socket.timeout, OSError) as exc:
                print(f"worker session failed: {type(exc).__name__}: {exc}")
                clean = False
            if clean or time.monotonic() - t0 > self.rejoin_backoff_max:
                # a session that ended clean OR genuinely worked for a
                # while (outlived the max backoff) resets the clock —
                # max_rejoins bounds CONSECUTIVE failures, not lifetime
                # blips spread over weeks of healthy sessions; a server
                # crash-looping seconds after each join must NOT reset,
                # or the budget and the exponential backoff never bite
                backoff = self.rejoin_backoff
                rejoins = 0
            if clean or not self.rejoin:
                return
            rejoins += 1
            if 0 <= self.max_rejoins < rejoins:
                print(f"giving up after {self.max_rejoins} rejoins")
                return
            print(f"rejoining server in {backoff:.1f}s")
            time.sleep(backoff)
            backoff = min(backoff * 2.0, self.rejoin_backoff_max)

    def _run_session(self) -> bool:
        """One join→work→drain cycle.  True = the learner drained us
        cleanly (run over); False = a connection failed mid-session."""
        cfg = self._entry()
        args = dict(cfg["train_args"])
        args["env"] = cfg["env_args"]
        base_worker_id = cfg["worker_args"].get("base_worker_id", 0)
        worker_cfg = args["worker"]
        data_port = int(worker_cfg.get("data_port", DATA_PORT))
        heartbeat_interval = float(worker_cfg.get("heartbeat_interval", 10.0))
        io_timeout = float(worker_cfg.get("socket_timeout", 60.0))
        prepare_env(args["env"])

        num_gathers = 1 + (self.num_parallel - 1) // 16
        gathers: List[RemoteGather] = []
        shares: List[int] = []
        for g in range(num_gathers):
            share = self.num_parallel // num_gathers + int(g < self.num_parallel % num_gathers)
            conn = connect_socket_connection(self.server_address, data_port)
            gathers.append(RemoteGather(conn, share, heartbeat_interval, io_timeout))
            shares.append(share)

        # pings start BEFORE the model server's blocking initial fetch: the
        # other gathers would otherwise sit silent through a whole params
        # download + env/net init, and the server's ~3-interval silence
        # deadline would reap them before this machine ever got going
        ping_stop = threading.Event()
        if heartbeat_interval > 0:
            def _ping_loop():
                while not ping_stop.is_set():
                    for g in gathers:
                        g.ping()
                    ping_stop.wait(heartbeat_interval)

            threading.Thread(target=_ping_loop, daemon=True).start()

        model_server = None
        try:
            model_server = RemoteModelServer(
                make_env(args["env"]).net(), make_env(args["env"]), args,
                gathers[0].fetch_model,
            )

            threads: List[threading.Thread] = []
            wid = base_worker_id
            for gather, share in zip(gathers, shares):
                for _ in range(share):
                    worker = Worker(make_env(args["env"]), args, gather, model_server, wid)
                    t = threading.Thread(target=worker.run, daemon=True, name=f"remote-actor-{wid}")
                    t.start()
                    threads.append(t)
                    wid += 1
            while any(t.is_alive() for t in threads):
                if any(g.failed for g in gathers):
                    # one dead link poisons the session: abort every gather
                    # so each blocked RPC raises and its actors exit — no
                    # thread may outlive the session (rejoin would leak it)
                    for g in gathers:
                        g.close(abort=True)
                time.sleep(0.2)
            for t in threads:
                t.join()
        finally:
            ping_stop.set()
            failed = any(g.failed for g in gathers)
            for gather in gathers:
                gather.close(abort=failed)
            if model_server is not None:
                model_server.stop()
        return not failed


def worker_main(args: Dict[str, Any], argv: Optional[List[str]] = None) -> None:
    """`main.py --worker [NUM_PARALLEL]` (reference worker.py:264-271)."""
    worker_args = dict(args["worker_args"])
    if argv and len(argv) >= 3:
        worker_args["num_parallel"] = int(argv[2])
    RemoteWorkerCluster(worker_args).run()
