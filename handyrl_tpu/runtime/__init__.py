from .replay import EpisodeStore, compress_block, decompress_block
from .batch import make_batch
from .generation import Generator
from .evaluation import Evaluator, exec_match, exec_network_match, evaluate_mp
from .inference_engine import BatchedInferenceEngine
from .trainer import Trainer
from .worker import LocalModelServer, LocalWorkerPool, Worker
from .learner import Learner, train_main

__all__ = [
    "EpisodeStore",
    "compress_block",
    "decompress_block",
    "make_batch",
    "Generator",
    "Evaluator",
    "exec_match",
    "exec_network_match",
    "evaluate_mp",
    "BatchedInferenceEngine",
    "Trainer",
    "LocalModelServer",
    "LocalWorkerPool",
    "Worker",
    "Learner",
    "train_main",
]
