"""Learner-side training loop: batch pipeline + epoch-cadenced SGD thread.

Process topology vs the reference (train.py:271-401): the reference forks
``num_batchers`` processes for make_batch and trains on the main GPU
thread.  The DEFAULT assembly plane here does the same, GIL-free —
batcher processes writing columnar batches straight into shared-memory
ring slots (runtime/shm_batch.py, ``batch_pipeline: shm``).  The threaded
pipeline below (``batch_pipeline: thread``) is kept as the portable
fallback and the in-process reference implementation:

    batcher threads (sample windows + columnar make_batch, numpy)
      -> host batch queue
      -> device-put thread (sharded transfer, double-buffered)
      -> device batch queue
      -> Trainer.train() loop calling the compiled train step

Both pipelines expose per-stage cumulative timings through ``stats()``
(sample / assemble / free-slot or host-queue wait / ready wait / device
put / device-queue depth); the trainer diffs them per epoch into
``pipe_*`` keys in metrics.jsonl so a nonzero ``input_wait_frac`` can be
attributed to a specific stage.

Epoch handoff keeps the reference semantics (train.py:343-346, 390-401):
``update()`` flips a flag and blocks on a 1-slot queue for the snapshot;
the learning rate follows the data-count EMA schedule (train.py:328-332,
383-385).
"""

from __future__ import annotations

import os
import queue
import signal
import sys
import threading
import time
import traceback
from typing import Any, Dict, List, Optional

import jax
import numpy as np

from ..parallel import TrainContext
from ..utils.trace import trace_event, trace_span
from . import faults
from .batch import make_batch
from .replay import EpisodeStore


# the one canonical stage-key list: every consumer (both pipeline
# classes, the per-epoch metrics diff below, bench.py's stage report)
# imports THIS tuple, so adding a stage cannot silently miss a site
PIPE_STAT_KEYS = ("sample_s", "assemble_s", "free_wait_s", "ready_wait_s", "put_s")

# supervision event counters (runtime/shm_batch.py): child deaths,
# respawns, and the degraded-to-thread flip.  Recorded CUMULATIVE in
# metrics.jsonl (pipe_batcher_*) — a nonzero value anywhere in the run
# means the assembly plane took a fault, and the per-epoch diff of rare
# events would mostly print zeros
PIPE_EVENT_KEYS = ("batcher_deaths", "batcher_restarts", "batcher_fallback")

# divergence-sentinel event counters, CUMULATIVE in metrics.jsonl for the
# same reason: in-step skips (nonfinite loss/grad-norm/lr), host-detected
# loss spikes (EMA detector), and verified-checkpoint rollbacks
SENTINEL_EVENT_KEYS = (
    "sentinel_skipped_steps",
    "sentinel_spike_steps",
    "sentinel_rollbacks",
    # rollbacks requested by the serving tier's quality sentinel
    # (flywheel/quality.py signal -> Learner -> request_rollback)
    "sentinel_flywheel_rollbacks",
)


def make_pipeline(args: Dict[str, Any], store: EpisodeStore, ctx: TrainContext,
                  stop_event: Optional[threading.Event] = None):
    """Build the configured batch-assembly pipeline.

    ``batch_pipeline: shm`` (the default) with ``num_batchers > 0`` forks
    GIL-free batcher processes writing into shared memory
    (runtime/shm_batch.py); ``device`` uploads host-born episodes ONCE
    into device ring buffers and samples/assembles training windows on
    device (runtime/device_batch.py — make_batch and the per-update
    observation H2D re-upload leave the hot loop); ``thread`` — or
    num_batchers 0, or any platform where the shm plane cannot come up —
    uses the in-process threaded pipeline.  All three expose
    start()/batch()/stop()/stats()."""
    mode = args.get("batch_pipeline", "shm")
    if mode == "device":
        try:
            from .device_batch import DeviceBatchPipeline

            return DeviceBatchPipeline(args, store, ctx, stop_event)
        except Exception:
            traceback.print_exc()
            print(
                "[handyrl_tpu] device batch pipeline unavailable (above); "
                "falling back to the shm assembly plane",
                file=sys.stderr,
            )
            mode = "shm"
    if mode == "shm" and int(args.get("num_batchers", 0)) > 0:
        try:
            from .shm_batch import ShmBatchPipeline

            return ShmBatchPipeline(args, store, ctx, stop_event)
        except Exception:
            traceback.print_exc()
            print(
                "[handyrl_tpu] shared-memory batch pipeline unavailable "
                "(above); using threaded batchers",
                file=sys.stderr,
            )
    return BatchPipeline(args, store, ctx, stop_event)


class BatchPipeline:
    """Threaded replay -> numpy batch -> sharded device batch pipeline."""

    mode = "thread"

    def __init__(self, args: Dict[str, Any], store: EpisodeStore, ctx: TrainContext, stop_event: Optional[threading.Event] = None):
        self.args = args
        self.store = store
        self.ctx = ctx
        self.stop_event = stop_event or threading.Event()
        self._host_queue: queue.Queue = queue.Queue(maxsize=max(2, args["num_batchers"]))
        self._device_queue: queue.Queue = queue.Queue(maxsize=args.get("prefetch_batches", 2))
        self._started = False
        self._stats_lock = threading.Lock()
        self._stats: Dict[str, float] = {k: 0.0 for k in PIPE_STAT_KEYS}
        self._stats.update({k: 0.0 for k in PIPE_EVENT_KEYS})
        self._stats.update(batches=0.0, device_queue_depth_sum=0.0, gets=0.0)
        # under jax.distributed each process assembles its local shard of
        # the global batch (TrainContext.put_batch builds the global array)
        from ..parallel import local_batch_size

        self._local_batch = local_batch_size(args["batch_size"])

    def start(self):
        if self._started:
            return
        self._started = True
        for _ in range(max(1, self.args["num_batchers"])):
            threading.Thread(target=self._assemble_loop, daemon=True).start()
        threading.Thread(target=self._device_put_loop, daemon=True).start()

    def _sample_windows(self):
        windows = []
        while len(windows) < self._local_batch:
            if self.stop_event.is_set():
                return None
            w = self.store.sample_window(
                self.args["forward_steps"],
                self.args["burn_in_steps"],
                self.args["compress_steps"],
            )
            if w is None:
                time.sleep(0.5)
                continue
            windows.append(w)
        return windows

    def _put(self, q: queue.Queue, item) -> bool:
        while not self.stop_event.is_set():
            try:
                q.put(item, timeout=0.3)
                return True
            except queue.Full:
                continue
        return False

    def _get(self, q: queue.Queue):
        while not self.stop_event.is_set():
            try:
                return q.get(timeout=0.3)
            except queue.Empty:
                continue
        return None

    def _bump(self, key: str, value: float) -> None:
        with self._stats_lock:
            self._stats[key] += value

    def _assemble_loop(self):
        try:
            while not self.stop_event.is_set():
                t0 = time.perf_counter()
                windows = self._sample_windows()
                if windows is None:
                    return
                t1 = time.perf_counter()
                batch = make_batch(windows, self.args)
                t2 = time.perf_counter()
                self._put(self._host_queue, batch)
                t3 = time.perf_counter()
                with self._stats_lock:
                    self._stats["sample_s"] += t1 - t0
                    self._stats["assemble_s"] += t2 - t1
                    # host-queue full = consumer-bound, the thread analogue
                    # of waiting for a free shm slot
                    self._stats["free_wait_s"] += t3 - t2
        except Exception:
            # a dead silent pipeline deadlocks the trainer — fail loudly
            traceback.print_exc()
            self.stop_event.set()

    def _host_get_timed(self):
        t0 = time.perf_counter()
        batch = self._get(self._host_queue)
        wait = time.perf_counter() - t0
        self._bump("ready_wait_s", wait)
        trace_event("pipe.ready_wait", wait, plane="pipeline", mode=self.mode)
        return batch

    def _device_put_loop(self):
        try:
            fused = self.args.get("fused_steps", 1)
            while not self.stop_event.is_set():
                if fused > 1:
                    group = []
                    while len(group) < fused:
                        batch = self._host_get_timed()
                        if batch is None:  # stop_event or shutdown sentinel
                            return
                        group.append(batch)
                    t0 = time.perf_counter()
                    device_batch = self.ctx.put_batches(group)
                else:
                    batch = self._host_get_timed()
                    if batch is None:
                        return
                    group = [batch]
                    t0 = time.perf_counter()
                    device_batch = self.ctx.put_batch(batch)
                with self._stats_lock:
                    self._stats["put_s"] += time.perf_counter() - t0
                    self._stats["batches"] += len(group)
                self._put(self._device_queue, device_batch)
        except Exception:
            traceback.print_exc()
            self.stop_event.set()

    def batch(self):
        """Next device batch, or None when shutting down."""
        with self._stats_lock:
            self._stats["device_queue_depth_sum"] += self._device_queue.qsize()
            self._stats["gets"] += 1
        return self._get(self._device_queue)

    def stop(self):
        self.stop_event.set()

    def stats(self) -> Dict[str, float]:
        with self._stats_lock:
            out = dict(self._stats)
        out["mode"] = self.mode
        return out


class Trainer:
    """Runs the SGD loop in a daemon thread; epoch handoff via update()."""

    def __init__(self, args: Dict[str, Any], module, params, mesh):
        self.args = args
        self.ctx = TrainContext(module, args, mesh)
        self.state = self.ctx.init_state(params)
        # Host snapshot for checkpointing: the device state is donated into
        # every train step, so other threads must never read self.state.
        self.state_host = jax.device_get(self.state)
        self.store = EpisodeStore(args["maximum_episodes"])
        self.stop_event = threading.Event()

        self.fused = max(1, args.get("fused_steps", 1))
        if self.fused > 1 and jax.default_backend() == "cpu" and mesh.size > 1:
            # fused updates are a lax.scan whose body XLA:CPU executes
            # without its fast kernel runtime, with per-step collectives
            # across VIRTUAL devices sharing one thunk pool — measured as
            # minutes per dispatch (trainer stack-dumped inside
            # block_until_ready for 15+ min on the 8-device CPU mesh).
            # The knob is for real accelerators; degrade loudly.
            import sys

            print(
                "[handyrl_tpu] fused_steps > 1 on a multi-device CPU mesh "
                "executes scan-bodied collectives at pathological speed; "
                "forcing fused_steps=1 (use a TPU or a {'dp': 1} mesh)",
                file=sys.stderr,
            )
            self.fused = 1
        # the pipeline groups k host batches per device call iff the
        # trainer will actually run the fused path — same clamped value
        self.batcher = make_pipeline(
            dict(args, fused_steps=self.fused), self.store, self.ctx, self.stop_event
        )
        self._pipe_stats0: Dict[str, float] = {}
        # the run's FIRST batch wait is pipeline warm-up (template
        # assembly, child spawn + replica seeding, ring prefill), not
        # steady-state starvation — reported separately so the north-star
        # input_wait_frac stays honest (mirrors the plane watchdog's
        # compile-grace: warm-up must not read as a fault)
        self._warmup_wait_pending = True

        # device-resident replay (runtime/device_replay.py): set by the
        # Learner before run() when train_args.device_replay is true; the
        # SGD loop then samples on device instead of pulling host batches
        self.device_replay = None
        self._replay_key = jax.random.PRNGKey(args["seed"] ^ 0x7EA1)

        # split-plane param flow (runtime/plane.py): set by the Learner
        # under plane: split — the SGD loop then pushes a versioned param
        # copy to the actor mesh every param_refresh_updates steps
        self.param_cache = None
        self.param_refresh = max(1, int(args.get("param_refresh_updates", 8)))

        # -- multi-process epoch cadence (parallel/distributed.py) --------
        # Set by the Learner when jax.process_count() > 1: every train
        # step is then a cross-process collective, so epoch end / shutdown
        # / drain are agreed through the coordinator's broadcasts instead
        # of local flags — a local decision would wedge the other
        # processes inside a collective forever.  The collective watchdog
        # (parallel/health.py) bounds exactly that wedge when a peer dies.
        self.cadence = None
        self.collective_watchdog = None
        self.on_agreed_finish = None  # learner disarms the health plane here
        self.finished = False        # run() returned via an agreed stop
        self.drain_agreed = False    # the epoch ended with the DRAIN bit
        self._drain_flag = False     # coordinator: broadcast DRAIN next
        self._fault_wedge_process = False  # freeze before the next collective
        self._proceed_queue: queue.Queue = queue.Queue(maxsize=1)
        self._awaiting_proceed = False
        self._collective_dispatched = False  # arms the watchdog post-compile

        # -- divergence sentinel (docs/fault_tolerance.md) ----------------
        # The compiled step already SKIPPED any step with a nonfinite
        # loss/grad-norm/lr (parallel/train_step.py) — params can never be
        # poisoned by a single bad batch.  Host-side, this layer counts the
        # flags riding back in the epoch's metrics, runs a loss-spike EMA
        # detector over the same fetched values (PaLM-style: spikes are
        # expected events, Chowdhery et al. 2022), and escalates a streak of
        # ``sentinel_rollback_after`` consecutive bad steps to a rollback
        # onto the newest VERIFIED manifest checkpoint with re-seeded RNG.
        self.sentinel = bool(args.get("sentinel", True))
        self.sentinel_rollback_after = int(args.get("sentinel_rollback_after", 8))
        self._spike_factor = float(args.get("sentinel_spike_factor", 10.0))
        self._loss_ema_decay = float(args.get("sentinel_loss_ema_decay", 0.9))
        self._loss_ema: Optional[float] = None
        self._sentinel_streak = 0
        self.sentinel_events: Dict[str, int] = {k: 0 for k in SENTINEL_EVENT_KEYS}
        # quality-plane rollback request (epoch, or None): SET from the
        # learner's server thread (request_rollback), CONSUMED at the next
        # train_epoch entry on the trainer's own thread — the state reset
        # must never race an in-flight device step
        self._requested_rollback: Optional[int] = None
        # env-driven injections (runtime/faults.py): NaN lr window and
        # self-SIGTERM, parsed here so tests set the env before construction
        self._fault_nan = faults.nan_window()
        self._fault_sigterm = faults.sigterm_at_step()
        self._fault_sigterm_fired = False

        self.default_lr = 3e-8 * args["lr_scale"]
        self.data_cnt_ema = args["batch_size"] * args["forward_steps"]
        # FLOPs of one SGD update, resolved once at the end of the first
        # trained epoch (0.0 = tried, unavailable) — feeds the per-epoch
        # "mfu" stat in metrics.jsonl when the chip's peak rate is known
        self._flops_per_update: Optional[float] = None
        self.steps = 0
        self.last_loss: Dict[str, float] = {}
        self.stats: Dict[str, float] = {}  # step timing / input-starvation
        self.update_flag = False
        self.update_queue: queue.Queue = queue.Queue(maxsize=1)

    def save_payload(self, epoch: int) -> Dict[str, Any]:
        """Checkpoint payload: train state + epoch tag + lr-schedule EMA."""
        return {
            **self.state_host,
            "epoch": np.int32(epoch),
            "data_cnt_ema": np.float64(self.data_cnt_ema),
        }

    def drain_payload(self, epoch: int):
        """(params, state_payload, steps) for the preemption-drain
        checkpoint, all read from ONE ``state_host`` reference: the trainer
        thread swaps that reference atomically at epoch end, so even if the
        drain races a swap the three pieces stay mutually consistent
        (save_payload + params_host read it twice and could straddle)."""
        host = self.state_host
        payload = {
            **host,
            "epoch": np.int32(epoch),
            "data_cnt_ema": np.float64(self.data_cnt_ema),
        }
        return host["params"], payload, int(host["steps"])

    def load_state(self, path: str, expected_epoch: int) -> bool:
        """Resume params + Adam moments + step count + lr EMA from state.ckpt.

        The reference restarts Adam from scratch on resume (SURVEY.md §5.4);
        here the full state round-trips, so the lr schedule and moments
        continue where they left off.  Returns False (fresh optimizer) when
        the file was written at a different epoch than ``expected_epoch`` —
        restarting from an *earlier* snapshot is a branch, not a resume,
        and must not adopt the later run's weights.  An unreadable file
        (truncated by a crash mid-write in a pre-manifest layout, or
        garbage) also returns False — a broken optimizer checkpoint must
        degrade to a fresh optimizer, never kill the resume.
        """
        from .checkpoint import load_train_state

        try:
            host = load_train_state(path, self.save_payload(0))
        except Exception as exc:
            print(
                f"state.ckpt unreadable ({type(exc).__name__}: {exc}); "
                "resuming with a fresh optimizer"
            )
            return False
        ckpt_epoch = int(host.pop("epoch"))
        if ckpt_epoch != expected_epoch:
            print(
                f"state.ckpt is from epoch {ckpt_epoch}, not {expected_epoch}; "
                "branching with a fresh optimizer"
            )
            return False
        self.data_cnt_ema = float(host.pop("data_cnt_ema"))
        self.state = self.ctx.put_state(host)
        self.state_host = host
        self.steps = int(host["steps"])
        print(f"resumed train state at step {self.steps} from {path}")
        return True

    @property
    def lr(self) -> float:
        return self.default_lr * self.data_cnt_ema / (1 + self.steps * 1e-5)

    def params_host(self):
        return self.state_host["params"]

    def update(self):
        """Request an epoch boundary; blocks until the snapshot is ready.

        Before the warmup threshold no training has happened — return
        immediately so the learner keeps serving (reference train.py:343-346).

        On a multi-process FOLLOWER the boundary is not requested here at
        all: the coordinator's broadcast ends the epoch on every process,
        the snapshot lands in the queue, and the follower's learner calls
        this only once it sees the queue populated — so the get below
        never blocks on an epoch that was not already agreed.
        """
        if self.cadence is not None and not self.cadence.is_coordinator:
            while not self.stop_event.is_set():
                try:
                    return self.update_queue.get(timeout=1.0)
                except queue.Empty:
                    continue
            return None, self.steps
        if not self._warmed_up():
            return None, self.steps
        self.update_flag = True
        while not self.stop_event.is_set():
            try:
                return self.update_queue.get(timeout=1.0)
            except queue.Empty:
                continue
        return None, self.steps

    def proceed(self, stop: bool) -> None:
        """Multi-process coordinator only: the learner's continue/shutdown
        decision for the epoch whose snapshot it just consumed.  run()
        holds the next cadence collective until this arrives, then
        broadcasts the decision so every trainer stops (or continues)
        together.  A no-op unless run() is actually waiting — pre-warmup
        boundaries deliver no snapshot and expect no proceed."""
        if self.cadence is None or not self._awaiting_proceed:
            return
        self._proceed_queue.put(bool(stop))

    def request_drain(self) -> None:
        """Preemption drain entry point, cadence-aware.  Single-process:
        stop the trainer mid-epoch (the historical behavior).  Multi-
        process coordinator: set the DRAIN bit instead — the next cadence
        broadcast ends the epoch on EVERY process coherently (a hard local
        stop would leave the peers wedged in the next collective).  A
        follower getting a local SIGTERM cannot drive the cadence; it
        waits for the agreed drain or its drain deadline."""
        if self.cadence is None:
            self.stop()
        elif self.cadence.is_coordinator:
            self._drain_flag = True

    def _await_proceed(self):
        """Coordinator trainer, post-snapshot: block for the learner's
        proceed decision (True = shutdown), or None when stop() forced the
        thread down with no verdict ever delivered.  Bounded by stop_event
        so a drain that bypasses the boundary cannot wedge the thread —
        but a verdict that was ALREADY delivered must still be returned:
        the learner's shutdown path is proceed(stop) immediately followed
        by stop(), and if stop_event winning that race swallowed the
        verdict, the final agree_stop broadcast would never be dispatched
        and every follower would sit abandoned inside the collective until
        the watchdog exits them 75 out of a CLEAN run."""
        while not self.stop_event.is_set():
            try:
                return self._proceed_queue.get(timeout=1.0)
            except queue.Empty:
                continue
        try:
            return self._proceed_queue.get_nowait()
        except queue.Empty:
            return None

    def _agreed_finish(self) -> None:
        """The stop/drain broadcast just returned on THIS rank — and, being
        a collective, on every other rank within the same dispatch: the run
        is coherently over everywhere.  Tell the learner so it disarms the
        health plane NOW, not at run() teardown — teardown skews ranks by
        arbitrary seconds (worker joins, final fetches), and an armed plane
        would misread the first rank's silence as a lost host (pinned by
        tests/test_health.py::test_disarm_silences_both_detectors)."""
        if self.on_agreed_finish is not None:
            self.on_agreed_finish()

    # -- cadence / watchdog plumbing -----------------------------------------

    def _wedge_forever(self) -> None:
        """HANDYRL_FAULT_WEDGE_PROCESS landed on this rank: simulate a
        frozen host — this thread never progresses and never exits."""
        print(
            "[fault] trainer wedged: no longer joining collectives "
            "(HANDYRL_FAULT_WEDGE_PROCESS)",
            file=sys.stderr,
        )
        while True:
            time.sleep(60.0)

    def _arm(self, tag: str) -> None:
        wd = self.collective_watchdog
        if wd is not None and self._collective_dispatched:
            # first-ever dispatch pays jit compilation — the heartbeat
            # plane covers pre-first-step peer deaths (compile-grace,
            # same rationale as the plane watchdog's)
            wd.arm(tag)

    def _disarm(self) -> None:
        wd = self.collective_watchdog
        if wd is not None:
            wd.disarm()

    def _agree_step(self, stepped: bool) -> int:
        """One cadence broadcast per loop iteration (multi-process only):
        returns the agreed command.  The coordinator's epoch-end verdict
        mirrors the single-process loop condition (update_flag armed and
        at least one step taken); the DRAIN bit rides the same broadcast."""
        if self._fault_wedge_process:
            self._wedge_forever()
        from ..parallel.distributed import CMD_DRAIN

        self._arm("cadence agree_step")
        try:
            cmd = self.cadence.agree_step(
                end=stepped and self.update_flag, drain=self._drain_flag
            )
        finally:
            self._disarm()
        if cmd & CMD_DRAIN:
            self.drain_agreed = True
        return cmd

    def _warmed_up(self) -> bool:
        """Epoch boundaries before the warmup threshold return immediately
        (reference train.py:343-346); device-replay mode counts ingested
        episodes (the store is bypassed)."""
        if self.device_replay is not None:
            return self.device_replay.counters["episodes"] >= self.args["minimum_episodes"]
        return len(self.store) >= self.args["minimum_episodes"]

    def _maybe_publish_params(self) -> None:
        """Split plane only: push a versioned replicated param copy onto
        the actor mesh once param_refresh_updates steps have passed since
        the last publish.  Runs on the SGD thread between dispatches, so
        ``self.state["params"]`` is the just-returned state's — valid
        until the NEXT train step donates it, and the cross-mesh copy
        dispatched here holds its own buffer reference."""
        cache = self.param_cache
        if cache is not None and self.steps - cache.version >= self.param_refresh:
            cache.publish(self.state["params"], self.steps)

    def _step_lr(self, lr: float, k: int) -> float:
        """The lr for the next k-step dispatch, with the NaN fault window
        applied (HANDYRL_FAULT_NAN_AT_STEP): a NaN anywhere in the update
        chain is what the in-step sentinel must catch."""
        w = self._fault_nan
        if w is not None:
            start, count = w
            if self.steps < start + count and self.steps + k > start:
                return float("nan")
        return lr

    def _maybe_fault_sigterm(self) -> None:
        """HANDYRL_FAULT_SIGTERM_AT_STEP: deliver a preemption mid-epoch."""
        if (
            self._fault_sigterm is not None
            and not self._fault_sigterm_fired
            and self.steps >= self._fault_sigterm
        ):
            self._fault_sigterm_fired = True
            print(
                f"[fault] SIGTERM at step {self.steps} "
                "(HANDYRL_FAULT_SIGTERM_AT_STEP)",
                file=sys.stderr,
            )
            os.kill(os.getpid(), signal.SIGTERM)

    def _sentinel_account(self, fetched: List[Dict[str, Any]]) -> int:
        """Epoch-end sentinel bookkeeping over the fetched per-dispatch
        metrics (no extra device syncs: these values were coming to host
        anyway).  In-step skip flags and host-detected loss spikes extend
        one consecutive-bad streak; a clean dispatch resets it.  Skipped
        and spiked dispatches never feed the EMA — a diverging loss must
        not drag the detector's baseline up after it.  Returns the number
        of in-step-SKIPPED steps this epoch (their dcnt was zeroed, so the
        caller must exclude them from the lr schedule's per-step data-count
        average too)."""
        skipped = 0
        for m in fetched:
            bad = int(round(float(m.get("sentinel_bad", 0.0))))
            if bad:
                skipped += bad
                self.sentinel_events["sentinel_skipped_steps"] += bad
                self._sentinel_streak += bad
                continue
            dcnt = float(m["dcnt"])
            if dcnt <= 0:
                continue
            loss = abs(float(m["total"])) / dcnt
            if (
                self._loss_ema is not None
                and loss > self._spike_factor * max(self._loss_ema, 1e-8)
            ):
                self.sentinel_events["sentinel_spike_steps"] += self.fused
                self._sentinel_streak += self.fused
                continue
            self._sentinel_streak = 0
            d = self._loss_ema_decay
            self._loss_ema = (
                loss if self._loss_ema is None else d * self._loss_ema + (1 - d) * loss
            )
        if self._sentinel_streak >= self.sentinel_rollback_after:
            self._sentinel_rollback()
        return skipped

    def _sentinel_rollback(self) -> None:
        """Roll the train state back to the newest VERIFIED manifest
        checkpoint (PR 2's machinery): params from the snapshot, a fresh
        optimizer (the moments fed the divergence), the step counter kept
        MONOTONE (lr schedule, param-cache publish versions and the host
        books all key off it), and the device-replay sampling RNG
        re-seeded past the poison window.  No verified snapshot (or a
        corrupt manifest) keeps the current params — the in-step skip
        already prevents poisoning, so continuing is safe — and resets
        the streak so the decision is re-evaluated on fresh evidence."""
        from . import checkpoint as ckpt

        self._sentinel_streak = 0
        self._loss_ema = None
        model_dir = self.args.get("model_dir", "models")
        if self.cadence is not None:
            # cross-process coherence: the streak that got us here is
            # computed from the COLLECTIVE step metrics, so every rank is
            # in this call together — but only the coordinator owns the
            # checkpoint files.  Its manifest verdict AND the snapshot
            # params themselves ride broadcasts, so all ranks roll back
            # to the SAME manifest entry (or all keep params) and stay
            # bit-identical; a follower scanning its own (possibly empty)
            # model_dir would silently diverge.
            from ..parallel.distributed import broadcast_params

            local_epoch = 0
            if self.cadence.is_coordinator:
                try:
                    local_epoch = ckpt.latest_verified_epoch(model_dir)
                except ckpt.CheckpointError as exc:
                    print(
                        f"[sentinel] rollback wanted but the manifest is "
                        f"corrupt ({exc}); keeping current params on every "
                        "process",
                        file=sys.stderr,
                    )
            self._arm("sentinel rollback agreement")
            try:
                epoch = self.cadence.agree_rollback_epoch(local_epoch)
            finally:
                self._disarm()
            if epoch <= 0:
                print(
                    "[sentinel] divergence streak hit the rollback "
                    "threshold but the coordinator has no verified "
                    "snapshot; keeping current params (in-step skips "
                    "already suppressed the bad updates)",
                    file=sys.stderr,
                )
                return
            if self.cadence.is_coordinator:
                params = ckpt.load_verified_params(
                    model_dir, epoch, self.state_host["params"],
                    pre_verified=True,
                )
            else:
                # like-shaped input; values replaced by the broadcast
                params = self.state_host["params"]
            self._arm("sentinel rollback params broadcast")
            try:
                params = broadcast_params(params, self.ctx.mesh)
            finally:
                self._disarm()
        else:
            try:
                epoch = ckpt.latest_verified_epoch(model_dir)
            except ckpt.CheckpointError as exc:
                print(
                    f"[sentinel] rollback wanted but the manifest is corrupt "
                    f"({exc}); keeping current params",
                    file=sys.stderr,
                )
                return
            if epoch <= 0:
                print(
                    "[sentinel] divergence streak hit the rollback threshold "
                    "but no verified snapshot exists yet; keeping current "
                    "params (in-step skips already suppressed the bad updates)",
                    file=sys.stderr,
                )
                return
            params = ckpt.load_verified_params(
                model_dir, epoch, self.state_host["params"], pre_verified=True
            )
        self.sentinel_events["sentinel_rollbacks"] += 1
        self._reset_state_from(params)
        print(
            f"[sentinel] rolled back to verified epoch {epoch} after a "
            f"divergence streak (step counter stays at {self.steps}; "
            "fresh optimizer; re-seeded sampling RNG)",
            file=sys.stderr,
        )

    def _reset_state_from(self, params) -> None:
        """The shared rollback tail: rebuild the train state around
        ``params`` with a fresh optimizer (the moments fed the problem),
        the step counter kept MONOTONE (lr schedule, param-cache publish
        versions and the host books all key off it), and the device-replay
        sampling RNG jumped far from the stream that fed the poison.
        Callers bump their event counter FIRST — the re-seed keys off the
        total rollback count."""
        # init_state dispatches multi-device layout programs; mid-run the
        # rollout thread may be dispatching concurrently — init_state now
        # takes the learner mesh's locks per program itself (the locks are
        # not reentrant, so wrapping it here again would deadlock)
        state = self.ctx.init_state(params)
        state["steps"] = jax.device_put(
            np.int32(self.steps), self.ctx._replicated
        )
        self.state = state
        # graftlint: allow[HS001] reason=rollback is a rare recovery path; the host snapshot is what checkpoints/drains read
        self.state_host = jax.device_get(state)
        self._replay_key = jax.random.PRNGKey(
            (self.args["seed"] ^ 0x7EA1)
            + 0x9E3779B9 * (
                self.sentinel_events["sentinel_rollbacks"]
                + self.sentinel_events["sentinel_flywheel_rollbacks"]
            )
            + self.steps
        )

    # -- quality-plane rollback (flywheel/quality.py signal) ------------------

    def request_rollback(self, epoch: int) -> None:
        """Ask for a rollback to verified ``epoch`` (<= 0 = newest
        verified).  Called from the learner's server thread when the
        serving tier's quality sentinel signals a regressed snapshot; the
        actual state reset happens at the next ``train_epoch`` entry on
        the trainer's own thread, so it can never race a device step the
        trainer is mid-way through dispatching."""
        self._requested_rollback = int(epoch)

    def _consume_requested_rollback(self) -> None:
        requested = self._requested_rollback
        if requested is None:
            return
        self._requested_rollback = None
        from . import checkpoint as ckpt

        if self.cadence is not None:
            # the collective path needs every rank in the call together
            # (agree + broadcast); a one-sided quality signal cannot drive
            # it safely — the divergence sentinel's collective machinery
            # remains the multi-process recovery story
            print(
                "[flywheel] quality rollback requested but a multi-process "
                "cadence is active; skipping the one-sided reset",
                file=sys.stderr,
            )
            return
        model_dir = self.args.get("model_dir", "models")
        try:
            target = requested if requested > 0 else \
                ckpt.latest_verified_epoch(model_dir)
            if target <= 0:
                print(
                    "[flywheel] quality rollback requested but no verified "
                    "snapshot exists; keeping current params",
                    file=sys.stderr,
                )
                return
            # full digest scan, not pre_verified: the signal names an epoch
            # the SERVING tier trusted — this process has not verified it
            params = ckpt.load_verified_params(
                model_dir, target, self.state_host["params"]
            )
        except ckpt.CheckpointError as exc:
            print(
                f"[flywheel] quality rollback to epoch {requested} refused "
                f"({exc}); keeping current params",
                file=sys.stderr,
            )
            return
        self._sentinel_streak = 0
        self._loss_ema = None
        self.sentinel_events["sentinel_flywheel_rollbacks"] += 1
        self._reset_state_from(params)
        print(
            f"[flywheel] rolled back to verified epoch {target} on the "
            f"serving tier's quality signal (step counter stays at "
            f"{self.steps}; fresh optimizer; re-seeded sampling RNG)",
            file=sys.stderr,
        )

    def train_epoch(self) -> Any:
        """Train until the learner flags an epoch end; return param snapshot."""
        self._consume_requested_rollback()
        batch_cnt, data_cnt = 0, 0
        metric_accum = []
        lr = self.lr
        wait_s = 0.0
        warmup_wait_s = 0.0
        t_epoch = time.perf_counter()
        fused = self.fused
        replay_train = None
        last_batch = None
        if self.device_replay is not None and self.cadence is None:
            # all-on-device SGD: sample + assemble + step in one dispatch.
            # One-deep pipelining (block on update N-1 before dispatching
            # N+1) keeps the dispatch queue shallow so the concurrent
            # rollout thread gets device time at every boundary.
            replay_train = train = self.device_replay.train_fn(self.ctx, fused)
            on_cpu = jax.default_backend() == "cpu"
            while data_cnt == 0 or not self.update_flag:
                if self.stop_event.is_set():
                    break
                self._replay_key, sub = jax.random.split(self._replay_key)
                with trace_span("train_step", plane="learner"):
                    self.state, metrics = train(
                        self.state, sub, self._step_lr(lr, fused)
                    )
                if metric_accum:
                    # graftlint: allow[HS001] reason=deliberate one-deep pipelining: block on update N-1 so the dispatch queue stays shallow and the concurrent rollout thread gets device time
                    jax.block_until_ready(metric_accum[-1]["total"])
                metric_accum.append(metrics)
                batch_cnt += fused
                self.steps += fused
                self._maybe_publish_params()
                self._maybe_fault_sigterm()
                data_cnt = 1
                if on_cpu:
                    # On the CPU backend dispatch_serialized blocks INSIDE
                    # the dispatch lock, and this loop re-acquires it
                    # microseconds after releasing — an unfair
                    # threading.Lock then starves the rollout thread
                    # indefinitely (observed: 35 min, zero episodes).  A
                    # real sleep hands the lock to the waiting producer;
                    # on TPU dispatch is async and the gap never forms.
                    time.sleep(0.02)
        elif self.device_replay is not None:
            # pod-slice rung 1 (docs/performance.md §Pod-slice topology):
            # per-process rings under the coordinator cadence.  The fused
            # all-on-device path above cannot run here — it would fuse a
            # process-LOCAL ring gather into the cross-host collective
            # program (the rings live on different local meshes per
            # process).  Instead each agreed iteration samples this
            # process's B/nprocs shard to host (one D2H of sampled rows)
            # and re-enters the collective mesh through put_batch's
            # make_array_from_process_local_data seam — so every device
            # dispatch (local sample AND collective step) happens inside
            # the agreed cadence window, never racing the lockstep
            # collectives.  The local sample holds a SUBSET of the global
            # step's device locks, so the per-device dispatch order stays
            # consistent across ranks.
            from ..parallel import local_batch_size
            from ..parallel.distributed import CMD_END

            B_local = local_batch_size(self.args["batch_size"])
            on_cpu = jax.default_backend() == "cpu"
            while True:
                # coordinator-broadcast epoch end: every process runs the
                # SAME step count, or the next collective wedges
                if self._agree_step(data_cnt > 0) & CMD_END:
                    break
                if self.stop_event.is_set():
                    if self.cadence.is_coordinator:
                        # end the epoch THROUGH the cadence (see the host
                        # branch's batch-None path: a bare break abandons
                        # the broadcast the followers are blocked in)
                        self._drain_flag = True
                        continue
                    break
                self._replay_key, sub = jax.random.split(self._replay_key)
                t0 = time.perf_counter()
                rows = self.device_replay.sample_host(sub, fused * B_local)
                if fused > 1:
                    # i.i.d. draws: slicing fused*B rows into k groups is
                    # equivalent to k independent B-row samples
                    batch = self.ctx.put_batches([
                        jax.tree.map(
                            lambda x, i=i: x[i * B_local:(i + 1) * B_local],
                            rows,
                        )
                        for i in range(fused)
                    ])
                else:
                    batch = self.ctx.put_batch(rows)
                sample_wait = time.perf_counter() - t0
                trace_event("batch.wait", sample_wait, plane="learner")
                if self._warmup_wait_pending:
                    self._warmup_wait_pending = False
                    warmup_wait_s = sample_wait
                else:
                    wait_s += sample_wait  # data-plane time (north-star)
                last_batch = batch  # batches aren't donated; safe to re-lower
                step_lr = self._step_lr(lr, fused)
                self._arm("train_step @ step %d" % self.steps)
                try:
                    with trace_span("train_step", plane="learner"):
                        if fused > 1:
                            self.state, metrics = self.ctx.train_steps(self.state, batch, step_lr)
                        else:
                            self.state, metrics = self.ctx.train_step(self.state, batch, step_lr)
                finally:
                    self._disarm()
                self._collective_dispatched = True
                metric_accum.append(metrics)
                batch_cnt += fused
                self.steps += fused
                self._maybe_publish_params()
                self._maybe_fault_sigterm()
                data_cnt = 1
                if on_cpu:
                    # same rollout-thread fairness as the fused path: the
                    # local sample re-takes the actor-overlapping dispatch
                    # locks every iteration on the CPU backend
                    time.sleep(0.02)
        else:
            from ..parallel.distributed import CMD_END

            while True:
                if self.cadence is not None:
                    # coordinator-broadcast epoch end: every process runs
                    # the SAME step count, or the next collective wedges
                    if self._agree_step(data_cnt > 0) & CMD_END:
                        break
                elif data_cnt > 0 and self.update_flag:
                    break
                t0 = time.perf_counter()
                batch = self.batcher.batch()
                batch_wait = time.perf_counter() - t0
                # already-measured duration -> span (no second clock read
                # on the disabled path; trace_event is a no-op there)
                trace_event("batch.wait", batch_wait, plane="learner")
                if self._warmup_wait_pending:
                    # first batch of the RUN: the wait covers the assembly
                    # plane's one-off warm-up, and the first train dispatch
                    # right after it pays the jit compile — neither is
                    # steady-state input starvation, so it must not sit in
                    # the north-star input_wait_frac (it lands in its own
                    # input_wait_warmup_s stat instead)
                    self._warmup_wait_pending = False
                    warmup_wait_s = batch_wait
                else:
                    wait_s += batch_wait  # input starvation (north-star)
                if batch is None:  # shutting down
                    if (
                        self.cadence is not None
                        and self.cadence.is_coordinator
                    ):
                        # the stop landed while this rank was starved in
                        # batch() (forced drain-deadline shutdown): end
                        # the epoch THROUGH the cadence — a bare break
                        # would abandon the broadcast the followers are
                        # (or will be) blocked in, stranding them on the
                        # collective watchdog's full timeout.  This holds
                        # even when _drain_flag is ALREADY set: reaching
                        # here proves the bit never rode a broadcast (a
                        # broadcast DRAIN breaks the loop at agree_step,
                        # before batch() runs again), so the next loop-top
                        # iteration is the one that finally sends END|DRAIN.
                        # The watchdog armed around that broadcast still
                        # bounds this rank if the peers are already gone.
                        self._drain_flag = True
                        continue
                    break
                last_batch = batch  # batches aren't donated; safe to re-lower
                step_lr = self._step_lr(lr, fused)
                self._arm("train_step @ step %d" % self.steps)
                try:
                    with trace_span("train_step", plane="learner"):
                        if fused > 1:  # k updates per device call, metrics pre-summed
                            self.state, metrics = self.ctx.train_steps(self.state, batch, step_lr)
                        else:
                            self.state, metrics = self.ctx.train_step(self.state, batch, step_lr)
                finally:
                    self._disarm()
                self._collective_dispatched = True
                metric_accum.append(metrics)
                batch_cnt += fused
                self.steps += fused
                self._maybe_publish_params()
                self._maybe_fault_sigterm()
                data_cnt = 1  # real count resolved below without device sync per step
        if not metric_accum:
            return self.state_host["params"]

        self._arm("epoch-end metrics fetch")
        try:
            with trace_span("epoch.metrics_fetch", plane="learner"):
                # graftlint: allow[HS001] reason=epoch-end fetch of the whole epoch's metrics in one device_get — once per epoch, not per dispatch
                fetched = jax.device_get(metric_accum)
        finally:
            self._disarm()
        skipped_steps = 0
        if self.sentinel:
            # skip flags + spike detection + (possibly) rollback — all on
            # values already fetched for the loss report, no extra syncs
            skipped_steps = self._sentinel_account(fetched)
        data_cnt = float(sum(m["dcnt"] for m in fetched))
        loss_sum = {
            k: float(sum(m[k] for m in fetched))
            for k in fetched[0]
            if k not in ("dcnt", "sentinel_bad")
        }
        self.last_loss = {k: v / max(data_cnt, 1) for k, v in loss_sum.items()}
        print("loss = %s" % " ".join(f"{k}:{v:.3f}" for k, v in self.last_loss.items()))
        elapsed = max(time.perf_counter() - t_epoch, 1e-9)
        self.stats = {
            "train_steps_per_sec": batch_cnt / elapsed,
            "input_wait_frac": wait_s / elapsed,
        }
        if warmup_wait_s:
            # one-off, first trained epoch only: the pipeline warm-up wait
            # excluded from input_wait_frac above
            self.stats["input_wait_warmup_s"] = round(warmup_wait_s, 4)
        if self.sentinel:
            # cumulative, like pipe_batcher_*: a nonzero value anywhere in
            # the run means the sentinel fired at some point
            for key, value in self.sentinel_events.items():
                self.stats[key] = value
        if self.param_cache is not None:
            # realized actor-plane staleness at the boundary (cumulative
            # refresh count rides along so soaks can spot a stalled flow)
            self.stats["plane_param_lag"] = self.param_cache.lag(self.steps)
            self.stats["plane_param_refreshes"] = self.param_cache.refreshes
        if self.device_replay is None:
            # per-epoch pipeline stage breakdown (cumulative counters
            # diffed against the previous epoch's snapshot) — attributes
            # any input_wait_frac to sample / assemble / queueing / put
            cur = self.batcher.stats()
            prev = self._pipe_stats0
            for key in PIPE_STAT_KEYS:
                self.stats["pipe_" + key] = round(
                    cur.get(key, 0.0) - prev.get(key, 0.0), 4
                )
            for key in PIPE_EVENT_KEYS:
                # cumulative, not diffed: any nonzero value flags that the
                # assembly plane took a fault at some point this run
                self.stats["pipe_" + key] = cur.get(key, 0.0)
            gets = cur.get("gets", 0.0) - prev.get("gets", 0.0)
            if gets > 0:
                self.stats["pipe_device_queue_depth"] = round(
                    (cur.get("device_queue_depth_sum", 0.0)
                     - prev.get("device_queue_depth_sum", 0.0)) / gets, 3
                )
            self._pipe_stats0 = cur
        from ..parallel.train_step import peak_flops_per_chip

        peak = peak_flops_per_chip(jax.devices()[0])
        if peak:  # unknown device kind (e.g. CPU): stat omitted, and the
            # one-time trace below is skipped — it could never be used.
            # Resolution happens AFTER `elapsed` is taken: a multi-second
            # lowering must not deflate the first epoch's rate stats.
            if self._flops_per_update is None:
                self._resolve_flops(replay_train, last_batch)
            if self._flops_per_update:
                self.stats["mfu"] = round(
                    self._flops_per_update * batch_cnt
                    / (elapsed * peak * self.ctx.mesh.size),
                    6,
                )
        # skipped steps zeroed their dcnt contribution, so they must not
        # sit in the divisor either — a NaN spell would otherwise silently
        # depress the lr schedule's per-step data-count average (an
        # all-skipped epoch leaves the EMA untouched: no evidence)
        applied_cnt = batch_cnt - skipped_steps
        if applied_cnt > 0:
            self.data_cnt_ema = (
                self.data_cnt_ema * 0.8 + data_cnt / (1e-2 + applied_cnt) * 0.2
            )
        # graftlint: allow[HS001] reason=epoch-boundary host snapshot: the device state is donated every step, so checkpoint/publish readers need this copy
        self.state_host = jax.device_get(self.state)
        return self.state_host["params"]

    def _resolve_flops(self, replay_train, batch) -> None:
        """One-time FLOPs-per-update resolution at the end of the first
        trained epoch (a lowering / trace, nothing executes).  Failure
        records 0.0 so it is never retried every epoch."""
        try:
            if replay_train is not None:
                self._flops_per_update = float(
                    replay_train.flops_per_update(self.state)
                )
            elif batch is not None:
                if self.fused > 1:
                    # stacked (k, B, ...) tree -> one batch of AVALS: a
                    # concrete x[0] slice would dispatch multi-device
                    # gathers outside the per-device dispatch locks (the
                    # serialized-dispatch invariant, parallel/mesh.py);
                    # the lowering only needs shapes
                    batch = jax.tree.map(
                        lambda x: jax.ShapeDtypeStruct(x.shape[1:], x.dtype),
                        batch,
                    )
                self._flops_per_update = float(
                    self.ctx.flops_per_step(self.state, batch) or 0.0
                )
            else:
                self._flops_per_update = 0.0
        except Exception:
            # degrade loudly, once: a silent 0.0 would drop the mfu stat
            # from metrics.jsonl for the whole run with no hint why
            import sys

            traceback.print_exc(limit=2, file=sys.stderr)
            print(
                "[handyrl_tpu] FLOPs-per-update resolution failed (above); "
                "metrics.jsonl will carry no 'mfu' stat this run",
                file=sys.stderr,
            )
            self._flops_per_update = 0.0

    def stop(self):
        self.stop_event.set()
        # process batchers need an explicit join + shm unlink; the
        # threaded pipeline's stop() is just the event set again
        self.batcher.stop()

    def run(self):
        print("waiting training")
        while not self._warmed_up():
            if self.stop_event.is_set():
                return
            time.sleep(1)
        if self.device_replay is None:
            self.batcher.start()
        print("started training")
        profile_dir = self.args.get("profile_dir")
        tracing = False
        if profile_dir:
            # capture the first trained epoch (SURVEY.md §5.1: the reference
            # has no tracing at all; here it's one config key away)
            jax.profiler.start_trace(profile_dir)
            tracing = True
        try:
            while not self.stop_event.is_set():
                params = self.train_epoch()
                if tracing:
                    jax.profiler.stop_trace()
                    print(f"wrote profiler trace to {profile_dir}")
                    tracing = False
                self.update_flag = False
                if self.cadence is not None:
                    self._awaiting_proceed = True
                self.update_queue.put((params, self.steps))
                if self.cadence is not None:
                    if self.drain_agreed:
                        # agreed preemption drain: no further collectives;
                        # every process leaves the loop at this boundary
                        self.finished = True
                        self._agreed_finish()
                        return
                    # the coordinator waits for its learner's shutdown
                    # decision, then broadcasts it; followers join the
                    # broadcast directly — all trainers stop (or start the
                    # next epoch) together.  The coordinator skips the
                    # broadcast ONLY when no verdict was ever delivered
                    # (forced stop mid-drain): a delivered verdict is
                    # always broadcast even if stop() already landed,
                    # because the followers are (or will be) blocked in
                    # this collective waiting for it.
                    if self.cadence.is_coordinator:
                        stop_local = self._await_proceed()
                        self._awaiting_proceed = False
                        if stop_local is None:
                            return
                        # only the coordinator arms the boundary stop: a
                        # follower reaches this collective right after its
                        # queue put, but the coordinator joins only after
                        # its learner's boundary work (eval feed, verified
                        # checkpoint write, snapshot GC) — at production
                        # sizes that legitimately exceeds the collective
                        # bound, and an armed follower would exit 75 out
                        # of a healthy fleet.  A coordinator that dies in
                        # that window is the heartbeat plane's catch.
                        self._arm("cadence agree_stop")
                    else:
                        stop_local = False
                        self._awaiting_proceed = False
                        if self.stop_event.is_set():
                            # follower forced down locally (drain deadline
                            # past): it cannot drive the cadence; peers
                            # escape through the collective watchdog
                            return
                    try:
                        stop = self.cadence.agree_stop(stop_local)
                    finally:
                        self._disarm()
                    if stop:
                        self.finished = True
                        self._agreed_finish()
                        return
        finally:
            if tracing:  # interrupted mid-first-epoch: still flush the trace
                jax.profiler.stop_trace()
                print(f"wrote profiler trace to {profile_dir}")
