"""Learner-side training loop: batch pipeline + epoch-cadenced SGD thread.

Process topology vs the reference (train.py:271-401): the reference forks
``num_batchers`` processes for make_batch and trains on the main GPU
thread.  Here the expensive per-step math is already on the TPU inside one
jitted call, so the host side is a thread pipeline:

    batcher threads (sample windows + columnar make_batch, numpy)
      -> host batch queue
      -> device-put thread (sharded transfer, double-buffered)
      -> device batch queue
      -> Trainer.train() loop calling the compiled train step

Epoch handoff keeps the reference semantics (train.py:343-346, 390-401):
``update()`` flips a flag and blocks on a 1-slot queue for the snapshot;
the learning rate follows the data-count EMA schedule (train.py:328-332,
383-385).
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Dict, Optional

import jax
import numpy as np

from ..parallel import TrainContext
from .batch import make_batch
from .replay import EpisodeStore


class BatchPipeline:
    """Threaded replay -> numpy batch -> sharded device batch pipeline."""

    def __init__(self, args: Dict[str, Any], store: EpisodeStore, ctx: TrainContext):
        self.args = args
        self.store = store
        self.ctx = ctx
        self._host_queue: queue.Queue = queue.Queue(maxsize=max(2, args["num_batchers"]))
        self._device_queue: queue.Queue = queue.Queue(maxsize=args.get("prefetch_batches", 2))
        self._started = False

    def start(self):
        if self._started:
            return
        self._started = True
        for _ in range(max(1, self.args["num_batchers"])):
            threading.Thread(target=self._assemble_loop, daemon=True).start()
        threading.Thread(target=self._device_put_loop, daemon=True).start()

    def _sample_windows(self):
        windows = []
        while len(windows) < self.args["batch_size"]:
            w = self.store.sample_window(
                self.args["forward_steps"],
                self.args["burn_in_steps"],
                self.args["compress_steps"],
            )
            if w is None:
                time.sleep(0.5)
                continue
            windows.append(w)
        return windows

    def _assemble_loop(self):
        while True:
            batch = make_batch(self._sample_windows(), self.args)
            self._host_queue.put(batch)

    def _device_put_loop(self):
        while True:
            batch = self._host_queue.get()
            self._device_queue.put(self.ctx.put_batch(batch))

    def batch(self):
        return self._device_queue.get()


class Trainer:
    """Runs the SGD loop in a daemon thread; epoch handoff via update()."""

    def __init__(self, args: Dict[str, Any], module, params, mesh):
        self.args = args
        self.ctx = TrainContext(module, args, mesh)
        self.state = self.ctx.init_state(params)
        self.store = EpisodeStore(args["maximum_episodes"])
        self.batcher = BatchPipeline(args, self.store, self.ctx)

        self.default_lr = 3e-8
        self.data_cnt_ema = args["batch_size"] * args["forward_steps"]
        self.steps = 0
        self.update_flag = False
        self.update_queue: queue.Queue = queue.Queue(maxsize=1)

    @property
    def lr(self) -> float:
        return self.default_lr * self.data_cnt_ema / (1 + self.steps * 1e-5)

    def params_host(self):
        return jax.device_get(self.state["params"])

    def update(self):
        """Request an epoch boundary; blocks until the snapshot is ready."""
        self.update_flag = True
        params, steps = self.update_queue.get()
        return params, steps

    def train_epoch(self) -> Any:
        """Train until the learner flags an epoch end; return param snapshot."""
        batch_cnt, data_cnt = 0, 0
        metric_accum = []
        lr = self.lr
        while data_cnt == 0 or not self.update_flag:
            batch = self.batcher.batch()
            self.state, metrics = self.ctx.train_step(self.state, batch, lr)
            metric_accum.append(metrics)
            batch_cnt += 1
            self.steps += 1
            data_cnt = 1  # real count resolved below without device sync per step

        fetched = jax.device_get(metric_accum)
        data_cnt = float(sum(m["dcnt"] for m in fetched))
        loss_sum = {
            k: float(sum(m[k] for m in fetched))
            for k in fetched[0]
            if k != "dcnt"
        }
        print(
            "loss = %s"
            % " ".join(f"{k}:{v / max(data_cnt, 1):.3f}" for k, v in loss_sum.items())
        )
        self.data_cnt_ema = self.data_cnt_ema * 0.8 + data_cnt / (1e-2 + batch_cnt) * 0.2
        return self.params_host()

    def run(self):
        print("waiting training")
        while len(self.store) < self.args["minimum_episodes"]:
            time.sleep(1)
        self.batcher.start()
        print("started training")
        while True:
            params = self.train_epoch()
            self.update_flag = False
            self.update_queue.put((params, self.steps))
