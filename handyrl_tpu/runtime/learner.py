"""Central learner: role assignment, episode ingestion, epoch cadence.

Semantics parity with reference Learner (handyrl/train.py:404-633):

* role assignment 'g'/'e' with effective eval rate
  ``max(eval_rate, update_episodes**-0.15)`` (train.py:415-416, 564-576);
* per-model-id generation stats and per-opponent evaluation aggregation
  (train.py:457-500);
* epoch boundary every ``update_episodes`` returned episodes after a
  ``minimum_episodes`` warmup; trainer handoff; epoch-indexed checkpoints
  (train.py:540-626);
* shutdown after ``epochs`` epochs; 'args' answered None so workers drain.

TPU-first differences: workers are in-process threads sharing the batched
inference engine (runtime/worker.py), requests arrive on a queue consumed
by this single server loop (the reference's QueueCommunicator collapses to
queue.Queue — no sockets locally), and each epoch appends a machine-
readable metrics record (metrics.jsonl) alongside the human log lines the
reference's plotters parse (win_rate_plot.py:34-45).
"""

from __future__ import annotations

import json
import os
import queue
import random
import signal
import sys
import threading
import time
from concurrent.futures import Future
from concurrent.futures import TimeoutError as FutureTimeoutError  # plain Exception subclass until py3.11
from typing import Any, Dict, List, Optional

from ..envs import make_env, prepare_env
from ..models import init_variables
from ..parallel import is_coordinator, make_mesh
from ..utils import trace
from ..utils.trace import trace_span
from . import faults
from .checkpoint import (
    gc_snapshots,
    latest_verified_epoch,
    load_verified_params,
    save_epoch_snapshot,
    verify_state,
)
from .trainer import Trainer
from .worker import LocalModelServer, LocalWorkerPool

# Exit status after a preemption-safe drain (SIGTERM/SIGINT): the run
# stopped with a VERIFIED resume point on disk and wants to be relaunched
# with ``restart_epoch: -1``.  75 = BSD EX_TEMPFAIL ("temporary failure,
# retry"), the conventional please-reschedule-me code supervisors honor.
EXIT_RESUMABLE = 75

# cumulative plane-watchdog event counters in metrics.jsonl (same
# convention as pipe_batcher_* / sentinel_*: rare events diffed per epoch
# would mostly print zeros)
WATCHDOG_EVENT_KEYS = (
    "plane_watchdog_stalls",
    "plane_watchdog_restarts",
    "plane_watchdog_degraded",
)


class Learner:
    def __init__(self, args: Dict[str, Any], net=None, remote: bool = False):
        train_args = dict(args["train_args"])
        train_args["env"] = args["env_args"]
        self.args = train_args

        # -- multi-process role (parallel/distributed.py) -----------------
        # jax.distributed must already be initialized by the entry point
        # (main.py calls init_distributed before constructing the Learner);
        # single-process runs see nprocs == 1 and none of the distributed
        # machinery below activates.
        import jax

        from ..parallel.distributed import process_index

        self._dist_nprocs = jax.process_count()
        self._dist_rank = process_index() if self._dist_nprocs > 1 else 0
        self._dist_follower = self._dist_nprocs > 1 and not is_coordinator()
        # generation diversity: each process contributes DIFFERENT episodes
        # to the global batch (the model-init seed stays the base seed on
        # every process — params must start identical everywhere)
        random.seed(self.args["seed"] + 1009 * self._dist_rank)
        # host-loss fault injections (runtime/faults.py), parsed here so
        # tests set the env right before construction; malformed = loud
        self._fault_kill_proc = faults.kill_process_at_epoch()
        self._fault_wedge_proc = faults.wedge_process_at_epoch()
        self._health = None
        self._collective_watchdog = None
        self._host_faulted = False
        # -- observability plane (docs/observability.md) ------------------
        # span tracing arms here, BEFORE any pipeline/trainer construction,
        # so startup dispatches are in the trace too; configure() validates
        # the sink is writable (a run asked to trace must fail loudly at
        # startup).  Off by default: trace_span is then one attribute check
        if trace.configure(self.args.get("trace"), rank=self._dist_rank):
            print(f"trace: spans -> {trace.current_path()} (rank {self._dist_rank})")
        self._rank_metrics = bool(
            (self.args.get("observability") or {}).get("rank_metrics", True)
        )

        prepare_env(args["env_args"])
        self.env = make_env(args["env_args"])
        eval_modify_rate = (self.args["update_episodes"] ** 0.85) / self.args["update_episodes"]
        self.eval_rate = max(self.args["eval_rate"], eval_modify_rate)
        self.shutdown_flag = False

        self.model_dir = self.args.get("model_dir", "models")
        self.module = net if net is not None else self.env.net()
        variables = init_variables(self.module, self.env, self.args["seed"])
        params = variables["params"]

        self.model_epoch = self.args["restart_epoch"]
        auto_resumed = False
        if self.model_epoch < 0:
            # auto-resume: newest manifest entry whose snapshot digest
            # still verifies, falling back to older verified epochs when a
            # crash or bit-rot corrupted the newest one (0 = fresh start)
            if self._dist_nprocs > 1:
                # every SPMD process must resume the SAME epoch, and only
                # the coordinator writes checkpoints — so only IT scans
                # (the digest sweep can stream many GB; N-1 redundant
                # sweeps of a shared filesystem would all be discarded)
                # and broadcasts its verdict (parallel/distributed.py,
                # pinned by the 2-process resume test).  On a NON-shared
                # model_dir the other processes then fail LOUDLY below
                # (load_verified_params can't find the file) instead of
                # silently feeding fresh seed params into the collective
                # train step, exactly like an explicit restart_epoch.
                from ..parallel.distributed import broadcast_resume_epoch

                local = latest_verified_epoch(self.model_dir) if is_coordinator() else 0
                self.model_epoch = broadcast_resume_epoch(local)
                # coordinator-verified, not locally verified, off process 0
                auto_resumed = self.model_epoch > 0 and is_coordinator()
            else:
                self.model_epoch = latest_verified_epoch(self.model_dir)
                auto_resumed = self.model_epoch > 0
            print(
                f"auto-resume (restart_epoch: -1): epoch {self.model_epoch}"
                if self.model_epoch > 0
                else "auto-resume (restart_epoch: -1): no verified snapshot; fresh start"
            )
        if self.model_epoch > 0:
            # refuses a digest-mismatched file: silently training on a
            # corrupt snapshot is the one unrecoverable failure mode
            # (pre_verified: auto-resume just digest-scanned this epoch)
            params = load_verified_params(
                self.model_dir, self.model_epoch, params, pre_verified=auto_resumed
            )

        # generated datum
        self.generation_results: Dict[int, tuple] = {}
        self.num_episodes = 0
        self.num_returned_episodes = 0

        # evaluated datum
        self.results: Dict[int, tuple] = {}
        self.results_per_opponent: Dict[int, Dict[str, tuple]] = {}
        self.num_results = 0

        # device-plane topology: 'fused' trains and self-plays time-sliced
        # on one mesh; 'split' carves disjoint learner/actor meshes so both
        # planes dispatch concurrently (per-device locks, parallel/mesh.py)
        self._plane = self.args.get("plane", "fused")
        self._actor_mesh = None
        self._param_cache = None       # versioned params on the actor mesh
        self._record_xfer = None       # actor -> learner record transfer
        self._plane_stats = None
        self._plane_stats0: Dict[str, float] = {}
        if self._plane == "split":
            from ..parallel import split_mesh

            mesh, self._actor_mesh = split_mesh(
                self.args.get("mesh"), int(self.args["actor_chips"])
            )
            print(
                "device planes: split — learner %s on devices %s, actor "
                "{'dp': %d} on devices %s (param refresh every %d updates)"
                % (
                    dict(mesh.shape),
                    [d.id for d in mesh.devices.flat],
                    self._actor_mesh.size,
                    [d.id for d in self._actor_mesh.devices.flat],
                    int(self.args["param_refresh_updates"]),
                )
            )
        else:
            mesh = make_mesh(self.args.get("mesh"))
        if self.args.get("obs_int8"):
            # thread the generator's quantization spec to the train step:
            # forward_prediction dequantizes int8 obs planes under
            # args['_obs_quant'], derived once from the same env metadata
            # generation.py quantizes with
            from ..models.quantize import obs_quant_spec

            self.env.reset()
            self.args["_obs_quant"] = obs_quant_spec(
                self.env, obs=self.env.observation(self.env.players()[0])
            )
        self.trainer = Trainer(self.args, self.module, params, mesh)
        if self._dist_nprocs > 1:
            # distributed epoch loop: the coordinator's boundary/shutdown/
            # drain decisions reach every trainer as tiny broadcast
            # collectives (parallel/distributed.py), and the health plane
            # + collective watchdog bound a lost or wedged peer
            # (parallel/health.py — started in run())
            from ..parallel.distributed import DistributedCadence
            from ..parallel.health import CollectiveWatchdog, HostHealthPlane

            dist_args = dict(self.args.get("distributed") or {})
            self.trainer.cadence = DistributedCadence(self.trainer.ctx.mesh)
            timeout = float(dist_args.get("collective_timeout") or 0.0)
            if timeout > 0:
                self._collective_watchdog = CollectiveWatchdog(
                    timeout,
                    lambda reason: self._host_fault(reason, "collective_timeout"),
                )
                self.trainer.collective_watchdog = self._collective_watchdog
            if dist_args.get("coordinator_address"):
                self._health = HostHealthPlane(
                    dist_args,
                    self._dist_rank,
                    self._dist_nprocs,
                    lambda reason, kind: self._host_fault(reason, kind),
                )
            # the agreed stop/drain boundary reaches every rank in the same
            # broadcast; from there peer silence is teardown, not a fault —
            # run() teardown is too late (ranks skew by worker joins /
            # final fetches, and the skewed rank would exit 75 out of a
            # clean run)
            self.trainer.on_agreed_finish = self._disarm_host_fault
            print(
                "distributed learner: process %d/%d (%s), health plane %s, "
                "collective watchdog %s"
                % (
                    self._dist_rank,
                    self._dist_nprocs,
                    "coordinator" if not self._dist_follower else "follower",
                    "on" if (self._health and self._health.enabled) else "off",
                    f"{timeout:.0f}s" if timeout > 0 else "off",
                )
            )
        # the CONFIGURED assembly plane (start() hasn't run yet, so an shm
        # pipeline could still fall back to threads); metrics records read
        # the live mode from batcher.stats() at each epoch, which is the
        # attributable value — this line is the intent, not the outcome
        self.batch_pipeline_mode = getattr(self.trainer.batcher, "mode", "thread")
        print(
            "batch pipeline: %s configured (num_batchers=%d)"
            % (self.batch_pipeline_mode, self.args["num_batchers"])
        )
        if self.model_epoch > 0:
            state_path = os.path.join(self.model_dir, "state.ckpt")
            if not os.path.exists(state_path):
                print(f"{state_path} not found; resuming with a fresh optimizer")
            elif verify_state(self.model_dir, self.model_epoch) is False:
                # recorded digest mismatch: truncated/corrupt optimizer
                # state — params are verified above, so branch with a
                # fresh optimizer instead of deserializing garbage
                print(
                    f"{state_path} fails digest verification; "
                    "resuming with a fresh optimizer"
                )
            else:
                # adopts Adam moments + step count + lr EMA, but only when
                # the file matches restart_epoch (an earlier epoch = branch)
                self.trainer.load_state(state_path, self.model_epoch)
        self.model_server = self._make_model_server(args)
        router = getattr(self.model_server, "_router", None)
        if router is not None and getattr(router, "weight_dtype", "") == "int8":
            # publish-time int8 calibration replays REAL stored episodes:
            # the learner owns the episode store the router samples from
            from ..models.quantize import calibration_batches_from_store

            _store = self.trainer.store
            router.calibration_source = lambda: calibration_batches_from_store(
                _store, router.calibration_batches
            )
        self.model_server.publish(self.model_epoch, params)

        self.remote = remote
        if remote:
            from .server import WorkerServer  # noqa: avoid socket deps locally

            self.worker = WorkerServer(self.args, self.handle, self.model_server)
        else:
            self.worker = LocalWorkerPool(self.args, self.handle, self.model_server)

        # -- data flywheel (handyrl_tpu/flywheel/) -------------------------
        # learner side: the harvest ingest thread (started in run()) and
        # the quality-plane rollback signal.  The seq baseline is read at
        # startup so a stale FLYWHEEL_ROLLBACK.json from a previous run is
        # never re-applied — only signals written AFTER this process came
        # up count.
        self._flywheel_cfg = dict(self.args.get("flywheel") or {})
        self._flywheel_ingestor = None
        self.flywheel_rollbacks = 0
        self._flywheel_rollback_seq = 0
        if self._flywheel_cfg.get("enabled"):
            from ..flywheel import read_rollback_signal

            sig = read_rollback_signal(self.model_dir)
            self._flywheel_rollback_seq = int(sig.get("seq", 0)) if sig else 0
        # HANDYRL_FAULT_POISON_SNAPSHOT_AT_EPOCH: sabotage one SAVED
        # snapshot (update_model) while training continues on clean params
        self._fault_poison_epoch = faults.poison_snapshot_epoch()

        self._requests: queue.Queue = queue.Queue()
        self._active_workers = 0
        self._shutdown_t0 = 0.0
        self._epoch_t0 = time.time()
        self._epoch_steps0 = self.trainer.steps  # nonzero after a resume
        self._epoch_episodes0 = 0
        self._trainer_thread: Optional[threading.Thread] = None

        # -- preemption-safe drain (docs/fault_tolerance.md) --------------
        # SIGTERM (how TPU VMs are preempted) / SIGINT install a stop flag:
        # the pipelines drain, a final manifest-verified checkpoint lands
        # under drain_deadline_seconds, and run() returns EXIT_RESUMABLE so
        # the launcher relaunches with restart_epoch: -1.
        self.drain_deadline = float(self.args.get("drain_deadline_seconds", 60.0))
        self._drain_requested = False
        self._drain_t0 = 0.0
        self._drain_stopped = False     # trainer.stop() issued for the drain
        self._prev_handlers: Dict[int, Any] = {}

        # -- plane watchdog ------------------------------------------------
        # Liveness supervision of the device-rollout plane: a rollout
        # thread that dies or stops making progress for plane_stall_timeout
        # (or actor params lagging past plane_param_lag_bound) is restarted
        # up to plane_max_restarts times; past the budget a split-plane run
        # degrades split -> fused LOUDLY (the shm-batcher degrade pattern).
        self._rollout_thread: Optional[threading.Thread] = None
        self._rollout_gen = 0           # generation token: stale loops exit
        self._rollout_progress_t = time.monotonic()
        self._watchdog_events: Dict[str, int] = {k: 0 for k in WATCHDOG_EVENT_KEYS}
        self._fault_wedge = faults.wedge_rollout()

        # fully on-device self-play (runtime/device_rollout.py): env
        # stepping + inference + sampling in one jit call per batch of
        # games; workers then mostly evaluate
        self._device_games = int(self.args.get("device_rollout_games", 0))
        if self._dist_nprocs > 1 and self._device_games > 0:
            # pod-slice rung 1: device_rollout_games is the GLOBAL lane
            # count; each process runs its 1/nprocs share on its LOCAL
            # devices (divisibility validated in config.py) and the
            # shards meet in the collective train step via put_batch
            self._device_games //= self._dist_nprocs
        self._replay = None        # set below in device_replay mode
        self._data_mesh = None     # local mesh the data plane runs on
        self._plane_gateway = None  # rung-2 actor-host transport (run())
        # per-epoch device self-play volume -> mean episode length in
        # metrics.jsonl (the survival signal on episode-length envs)
        self._device_epoch_eps = 0
        self._device_epoch_steps = 0
        self._next_update_episodes = (
            self.args["minimum_episodes"] + self.args["update_episodes"]
        )
        if self._plane == "split" and self._device_games <= 0:
            raise ValueError(
                "plane: split needs device_rollout_games > 0 (the actor "
                "plane generates with the on-device streaming rollout)"
            )
        if self._device_games > 0:
            vector_env = getattr(self.env, "vector_env", None)
            if vector_env is None:
                raise ValueError(
                    f"device_rollout_games set but env "
                    f"{args['env_args'].get('env')} exposes no vector_env()"
                )
            self._venv = vector_env()
            n_verify = int(self.args.get("autovec_verify_games", 0))
            if n_verify > 0 and getattr(self._venv, "__autovec__", False):
                # autovec-lifted twin: refuse to train on a divergent lift
                # (random-game step-parity vs the numpy rules; raises
                # AutovecError naming the diverged observable)
                self._venv.verify(n_verify, int(self.args["seed"]))
                print(
                    f"autovec twin verified: {self._venv.__name__} parity "
                    f"over {n_verify} random games"
                )
            if self._plane == "split" and not hasattr(self._venv, "record"):
                raise ValueError(
                    "plane: split needs a STREAMING vector env (record/"
                    "reset_done/step hooks) — the episodic driver runs on "
                    f"the default device, not the actor mesh; "
                    f"{getattr(self._venv, '__name__', type(self._venv).__name__)} "
                    "lacks them"
                )
            if (
                self._actor_mesh is not None
                and self._device_games % self._actor_mesh.size
            ):
                # fail HERE, not as a sharding error inside the rollout
                # daemon thread — lanes shard over the actor mesh's dp
                raise ValueError(
                    f"device_rollout_games {self._device_games} not "
                    f"divisible by actor_chips {self._actor_mesh.size} "
                    "(plane: split shards the lanes over the actor mesh)"
                )
            if self.args["observation"] and not hasattr(self._venv, "observe_mask"):
                raise ValueError(
                    "device_rollout_games with observation: true requires a "
                    "vector env that records observer views (an observe_mask "
                    f"hook); {type(self._venv).__name__ if not isinstance(self._venv, type) else self._venv.__name__} "
                    "records acting players only — use host actors instead"
                )
            # pod-slice rung 1: under multi-process SPMD the data plane
            # (rollout lanes, rings, record transfer) is PER PROCESS on
            # this host's local learner devices — only the train step is
            # collective, and the local shard it samples enters via
            # TrainContext.put_batch's make_array_from_process_local_data
            # seam.  Single-process: the data plane IS the learner mesh.
            if self._dist_nprocs > 1:
                local = [
                    d
                    for d in self.trainer.ctx.mesh.devices.flat
                    if d.process_index == jax.process_index()
                ]
                self._data_mesh = make_mesh({"dp": -1}, local)
            else:
                self._data_mesh = self.trainer.ctx.mesh
            # constructed HERE so misconfiguration (e.g. lane count not
            # divisible by the mesh's dp axis) fails the run at startup
            # instead of silently killing the rollout daemon thread
            if self.args.get("device_replay"):
                # data stays on device end to end: rollout records ->
                # ring buffers -> sampled batches -> SGD, one dispatch
                # each (runtime/device_replay.py); DeviceReplay validates
                # the env/net/config constraints here, at startup
                from .device_replay import DeviceReplay
                from .device_rollout import build_streaming_fn

                mesh = self._data_mesh
                # rings (and the ingest/train donation contract) live on
                # the LEARNER data mesh (this process's learner devices);
                # under plane: split the rollout program runs on the actor
                # mesh and its records cross over
                self._replay = DeviceReplay(
                    self._venv, self.module, self.args, mesh,
                    self._device_games,
                    slots=self.args["device_replay_slots"],
                )
                roll_mesh = (
                    self._actor_mesh
                    if self._actor_mesh is not None
                    else (mesh if mesh.size > 1 else None)
                )
                self._stream_fn = build_streaming_fn(
                    self._venv, self.module, self._device_games,
                    self.args["device_replay_k_steps"],
                    mesh=roll_mesh,
                    use_observe_mask=bool(self.args["observation"]),
                )
                self.trainer.device_replay = self._replay
                self._device_roll = None
                if self._actor_mesh is not None:
                    from .plane import RecordTransfer

                    self._record_xfer = RecordTransfer(mesh)
            else:
                from .device_rollout import make_device_rollout

                self._device_roll = make_device_rollout(
                    self._venv, self.module, self.args, self._device_games,
                    mesh=self._actor_mesh
                    if self._actor_mesh is not None
                    else self._data_mesh,
                )
            if self._actor_mesh is not None:
                from .plane import PlaneParamCache, PlaneStats

                self._param_cache = PlaneParamCache(self._actor_mesh)
                self._plane_stats = PlaneStats()
                self.trainer.param_cache = self._param_cache
            # pod-slice rung 2: the coordinator fronts the cross-host
            # plane — record batches from distributed.actor_hosts land in
            # its device rings, versioned params go back over DCN
            # (runtime/plane.py).  Followers never host it: actor hosts
            # dial the one coordinator-derived plane port.
            dist_args = self.args.get("distributed") or {}
            if int(dist_args.get("actor_hosts") or 0) > 0 and not self._dist_follower:
                if self._replay is None:
                    raise ValueError(
                        "distributed.actor_hosts > 0 needs device_replay: "
                        "true on the learner tier — actor-host record "
                        "batches land in the device replay rings "
                        "(docs/performance.md §Pod-slice topology)"
                    )
                from .plane import PlaneGateway

                self._plane_gateway = PlaneGateway(
                    dist_args,
                    on_records=self._gateway_on_records,
                    inner=self._param_cache,
                )
                # one publish surface feeds both transports: the gateway
                # delegates to the local actor-mesh cache when plane:
                # split is also active on this host
                self.trainer.param_cache = self._plane_gateway
            if self.trainer.param_cache is not None:
                # version 0 .. steps: the resumed step count keeps publish
                # versions monotone across restarts
                self.trainer.param_cache.publish(
                    self.trainer.state["params"], self.trainer.steps
                )

        # on-device evaluation (runtime/device_eval.py): batched
        # net-vs-baseline matches at every epoch boundary — the per-epoch
        # win-rate curve that host eval workers starve on 1-core hosts
        # (both round-3 soaks recorded NaN/sparse curves)
        self._device_eval = None
        n_eval = int(self.args.get("device_eval_games", 0))
        if n_eval > 0:
            vector_env = getattr(self.env, "vector_env", None)
            if vector_env is None:
                raise ValueError(
                    f"device_eval_games set but env "
                    f"{args['env_args'].get('env')} exposes no vector_env()"
                )
            venv = vector_env()
            opp_list = self.args.get("eval", {}).get("opponent") or ["random"]
            if not isinstance(opp_list, list):  # same coercion as Evaluator
                opp_list = [opp_list]
            opp = opp_list[0]
            if opp not in ("random", "rulebase") or (
                opp == "rulebase" and not hasattr(venv, "rule_based_action_all")
            ):
                # downgrading must be loud: a config asking for rulebase
                # curves would otherwise quietly chart a different opponent
                print(
                    f"[handyrl_tpu] device eval: opponent '{opp}' unavailable "
                    f"for this vector env; evaluating vs 'random' instead"
                )
                opp = "random"
            # DeviceEvaluator rejects episodic twins (no streaming
            # reset_done/step hooks) at construction — surfacing the
            # device_eval_games misconfiguration at learner startup
            from .device_eval import DeviceEvaluator

            mesh = self.trainer.ctx.mesh
            lanes = min(64, max(8, n_eval))
            dp = mesh.shape.get("dp", 1)
            lanes = max(dp, lanes - lanes % dp)
            self._device_eval = DeviceEvaluator(
                venv, self.module, n_lanes=lanes, opponent=opp, mesh=mesh,
            )

    # -- subclass hooks (league/learner.py overrides these) -------------------

    def _make_model_server(self, args: Dict[str, Any]):
        """The model-id -> handle server actors resolve through; the
        league plane substitutes a ModelRouter-backed variant so frozen
        opponents get resident engines on distinct chips."""
        return LocalModelServer(self.module, make_env(args["env_args"]), self.args)

    def _epoch_hook(self, record: Dict[str, Any]) -> None:
        """Called at each epoch boundary just before the metrics record is
        written (snapshot for the new epoch already saved) — subsystems add
        their per-epoch bookkeeping/metrics here."""

    def _gc_pinned(self):
        """Epochs checkpoint GC must never collect (beyond the newest
        verified snapshot, which gc_snapshots always pins): the league pins
        its frozen population members here."""
        return ()

    def _gc_pin_set(self):
        """The full pin set every gc_snapshots call site passes: the
        subclass pins (league population) UNION the epochs the serving
        tier reports it is routing (SERVING.json — latest, a staged
        candidate, and the live incumbent).  A gated candidate can trail
        ``keep_checkpoints`` behind while the serving plane still needs
        its incumbent as the demote/rollback target; collecting it would
        turn a quality demote into a restart-from-nothing."""
        from ..flywheel.quality import serving_pinned_epochs

        pins = set(self._gc_pinned())
        pins |= serving_pinned_epochs(self.model_dir)
        return tuple(sorted(pins))

    def _flywheel_epoch(self, record: Dict[str, Any]) -> None:
        """Epoch-boundary flywheel bookkeeping: fold the harvest-ingest
        counters into the metrics record and consume any NEW quality-plane
        rollback signal (seq-gated — each signal is applied exactly once)
        by asking the trainer to roll back on its own thread."""
        if not self._flywheel_cfg.get("enabled"):
            return
        if self._flywheel_ingestor is not None:
            record.update(self._flywheel_ingestor.stats())
        from ..flywheel import read_rollback_signal

        sig = read_rollback_signal(self.model_dir)
        seq = int(sig.get("seq", 0)) if sig else 0
        if sig and seq > self._flywheel_rollback_seq:
            self._flywheel_rollback_seq = seq
            target = int(sig.get("target_epoch", 0))
            print(
                f"flywheel: serving tier flagged epoch "
                f"{sig.get('bad_epoch')} ({sig.get('reason')}); requesting "
                f"trainer rollback to verified epoch {target or 'newest'}"
            )
            self.trainer.request_rollback(target)
            self.flywheel_rollbacks += 1
        record["flywheel_rollbacks"] = self.flywheel_rollbacks

    # -- request plumbing ---------------------------------------------------

    def handle(self, req: str, data: Any, timeout: Optional[float] = None) -> Any:
        """Thread-safe entry point for workers; blocks until served (or
        until ``timeout`` — used by the device-rollout thread, whose
        submission can race server shutdown)."""
        fut: Future = Future()
        self._requests.put((req, data, fut))
        return fut.result(timeout=timeout)

    # -- bookkeeping (train.py:457-500) -------------------------------------

    def feed_episodes(self, episodes: List[Optional[Dict]]) -> None:
        for episode in episodes:
            if episode is None:
                continue
            for p in episode["args"]["player"]:
                model_id = episode["args"]["model_id"][p]
                outcome = episode["outcome"][p]
                n, r, r2 = self.generation_results.get(model_id, (0, 0, 0))
                self.generation_results[model_id] = n + 1, r + outcome, r2 + outcome ** 2
            self.num_returned_episodes += 1
            if self.num_returned_episodes % 100 == 0:
                print(self.num_returned_episodes, end=" ", flush=True)
        self.trainer.store.extend(episodes)

    def feed_results(self, results: List[Optional[Dict]]) -> None:
        for result in results:
            if result is None:
                continue
            for p in result["args"]["player"]:
                model_id = result["args"]["model_id"][p]
                res = result["result"][p]
                n, r, r2 = self.results.get(model_id, (0, 0, 0))
                self.results[model_id] = n + 1, r + res, r2 + res ** 2
                per_opp = self.results_per_opponent.setdefault(model_id, {})
                n, r, r2 = per_opp.get(result["opponent"], (0, 0, 0))
                per_opp[result["opponent"]] = n + 1, r + res, r2 + res ** 2

    # -- epoch boundary (train.py:502-538) -----------------------------------

    def _win_rate(self, stats) -> tuple:
        n, r, _ = stats
        mean = r / (n + 1e-6)
        return (mean + 1) / 2, n

    def _feed_device_eval(self) -> None:
        """Batched on-device matches with the current snapshot, filed into
        the same books as worker eval results (so _win_rate and the
        metrics.jsonl win_rate curve see them unchanged)."""
        import jax

        epoch, params = self.model_server.latest_snapshot()
        key = jax.random.PRNGKey(self.args["seed"] + 0xE7A1 + self.model_epoch)
        with trace_span("eval.device", plane="eval", epoch=self.model_epoch):
            counts = self._device_eval.evaluate(
                params, int(self.args["device_eval_games"]), key
            )
        opponent = "device-" + self._device_eval.opponent
        self.feed_results([
            {"args": {"player": [0], "model_id": {0: epoch}},
             "result": {0: outcome}, "opponent": opponent}
            for outcome, n in counts.items() for _ in range(n)
        ])

    def update(self) -> None:
        print()
        print("epoch %d" % self.model_epoch)
        record: Dict[str, Any] = {"epoch": self.model_epoch}

        if self._device_eval is not None:
            try:
                self._feed_device_eval()
            except Exception as exc:  # eval must never kill the boundary
                print(f"device eval failed: {type(exc).__name__}: {exc}")

        if self.model_epoch not in self.results:
            # no eval results this epoch: an explicit null record (tooling
            # can chart the gap) instead of the old misspelled "Nan" stdout
            # placeholder no parser ever matched
            print("win rate = n/a (0 games)")
            record["win_rate"] = None
        else:
            def output_wp(name, stats):
                wr, n = self._win_rate(stats)
                tag = " (%s)" % name if name else ""
                print("win rate%s = %.3f (%.1f / %d)" % (tag, wr, wr * n, n))
                record.setdefault("win_rate", {})[name or "total"] = wr

            per_opp = self.results_per_opponent.get(self.model_epoch, {})
            if len(self.args.get("eval", {}).get("opponent", [])) <= 1 and len(per_opp) <= 1:
                output_wp("", self.results[self.model_epoch])
            else:
                output_wp("total", self.results[self.model_epoch])
                for key in sorted(per_opp):
                    output_wp(key, per_opp[key])

        if self.model_epoch not in self.generation_results:
            print("generation stats = n/a (0 episodes)")
            record["generation_mean"] = None
        else:
            n, r, r2 = self.generation_results[self.model_epoch]
            mean = r / (n + 1e-6)
            std = max(r2 / (n + 1e-6) - mean ** 2, 0.0) ** 0.5
            print("generation stats = %.3f +- %.3f" % (mean, std))
            record["generation_mean"] = mean
            record["generation_std"] = std

        with trace_span("epoch.snapshot_wait", plane="learner"):
            params, steps = self.trainer.update()
        if params is None:
            params = self.model_server.latest_params()
        self.update_model(params, steps)

        if self.trainer.last_loss:
            record["loss"] = dict(self.trainer.last_loss)
        if self.trainer.stats:
            record.update(self.trainer.stats)
        if self.trainer.device_replay is None:
            # read the LIVE mode: an shm pipeline that fell back to
            # threads at start() must not be recorded as shm
            try:
                record["pipeline"] = self.trainer.batcher.stats()["mode"]
            except Exception:
                record["pipeline"] = self.batch_pipeline_mode
        now = time.time()
        record.update(
            steps=steps,
            episodes=self.num_returned_episodes,
            episodes_per_sec=(self.num_returned_episodes - self._epoch_episodes0) / max(now - self._epoch_t0, 1e-6),
            updates_per_sec=(steps - self._epoch_steps0) / max(now - self._epoch_t0, 1e-6),
        )
        if self._device_epoch_eps:
            record["device_mean_episode_len"] = self._device_epoch_steps / self._device_epoch_eps
            self._device_epoch_eps = 0
            self._device_epoch_steps = 0
        substituted = getattr(self.model_server, "substituted_snapshots", 0)
        if substituted:
            # cumulative: N old-snapshot requests were served LATEST params
            # instead (missing/corrupt file) — eval results attributed to
            # those epochs are suspect, and the books must say so
            record["serve_snapshot_substituted"] = substituted
        if self._device_games > 0:
            # live plane topology (flips split -> fused after a watchdog
            # degradation) + cumulative watchdog events
            record["plane"] = self._plane
            record.update(self._watchdog_events)
        if self._dist_nprocs > 1:
            # cross-host health (cumulative, like the other event
            # counters): nonzero anywhere in the run means the plane saw
            # trouble — the final pre-exit values ride the host-fault
            # drain record instead, since a drained process never reaches
            # another boundary
            record["dist_processes"] = self._dist_nprocs
            record.update(self._dist_events())
        if self._plane_gateway is not None:
            # cross-host actor tier health: live producer count plus the
            # cumulative losses (each one a degrade the survivors absorbed)
            record["dist_actor_hosts"] = int(self._plane_gateway.actor_hosts)
            record["dist_actor_host_losses"] = int(
                self._plane_gateway.actor_host_losses
            )
        if self._dist_nprocs > 1:
            if self._health is not None and self._rank_metrics:
                snap = self._rank_snapshot(steps)
                if self._dist_follower:
                    # PR 12 made metrics.jsonl coordinator-only; the
                    # snapshot rides the next heartbeat ack round so THIS
                    # rank shows up in the coordinator's rank_* aggregates
                    self._health.offer_metrics(snap)
                else:
                    record.update(self._health.rank_aggregates(snap))
        if trace.enabled():
            # tracer health next to the data it may be dropping: a nonzero
            # trace_dropped means the ring was outrun this run
            record.update(trace.trace_stats())
        # local refs: a concurrent watchdog degrade nulls these attributes
        # between the None-check and the reads (same hazard as
        # _actor_params) — the epoch record must not die on the very
        # degrade it is reporting
        plane_stats = self._plane_stats
        param_cache = self._param_cache
        record_xfer = self._record_xfer
        gateway = self._plane_gateway
        if gateway is not None or (
            plane_stats is not None and param_cache is not None
        ):
            # per-epoch plane health (diffed cumulative counters): realized
            # actor-plane duty, mean param staleness at dispatch, and the
            # cross-plane transfer rate (records learner-ward + params
            # actor-ward) — the plane_* keys soaks watch next to pipe_*.
            # The gateway's byte count already folds in the local cache
            # (``inner``), so it substitutes rather than adds.
            snap = plane_stats.snapshot() if plane_stats is not None else {}
            cache_bytes = (
                gateway.bytes_transferred
                if gateway is not None
                else param_cache.bytes_transferred
            )
            snap["xfer_bytes"] = cache_bytes + (
                record_xfer.bytes_transferred if record_xfer else 0
            )
            prev, dt = self._plane_stats0, max(now - self._epoch_t0, 1e-6)
            diff = lambda k: snap.get(k, 0.0) - prev.get(k, 0.0)
            if plane_stats is not None:
                record["plane_actor_busy_frac"] = round(diff("actor_busy_s") / dt, 4)
                record["plane_actor_idle_frac"] = round(diff("actor_idle_s") / dt, 4)
            record["plane_xfer_bytes_per_sec"] = round(diff("xfer_bytes") / dt, 1)
            if diff("actor_dispatches"):
                record["plane_param_lag_mean"] = round(
                    diff("param_lag_sum") / diff("actor_dispatches"), 2
                )
            self._plane_stats0 = snap
        self._epoch_t0 = now
        self._epoch_steps0 = steps
        self._epoch_episodes0 = self.num_returned_episodes
        self._flywheel_epoch(record)
        self._epoch_hook(record)
        self._write_metrics(record)

    def update_model(self, params, steps: int) -> None:
        print("updated model(%d)" % steps)
        self.model_epoch += 1
        self._dist_fault_hooks()
        save_params = params
        if self._fault_poison_epoch is not None \
                and self.model_epoch == self._fault_poison_epoch:
            # fault injection (runtime/faults.py): the SAVED snapshot is
            # sabotaged — negated params are digest-valid and load cleanly,
            # so only the flywheel's live quality gate can catch it.  The
            # in-memory/published params stay clean: training is healthy,
            # the artifact is the lie.
            from ..utils import tree_map

            print(f"[fault] poison_snapshot: epoch {self.model_epoch} "
                  "snapshot saved with NEGATED params (training params "
                  "stay clean)", flush=True)
            save_params = tree_map(lambda x: -x, params)
        if is_coordinator():
            # process-0 guard: under jax.distributed every process runs the
            # SPMD train step, but exactly one owns the checkpoint files.
            # Every file goes tmp -> fsync -> rename and lands in the CRC
            # manifest, so a crash at ANY instant leaves the previous
            # epoch's resume point intact and verifiable.
            with trace_span("checkpoint.save", plane="learner",
                            epoch=self.model_epoch):
                save_epoch_snapshot(
                    self.model_dir,
                    self.model_epoch,
                    save_params,
                    self.trainer.save_payload(self.model_epoch),
                    steps,
                )
                gc_snapshots(
                    self.model_dir,
                    int(self.args.get("keep_checkpoints", 0)),
                    pin=self._gc_pin_set(),
                )
        self.model_server.publish(self.model_epoch, params)

    def _repair_metrics_tail(self, path: str) -> None:
        """Drop a half-written final line left by a killed run BEFORE the
        resumed run appends to it: appending onto a truncated tail would
        glue two records into one mid-file invalid line, which readers
        rightly refuse (read_metrics only tolerates truncation at the
        END).  Runs once per process, on the first append."""
        try:
            with open(path, "rb+") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                if size == 0:
                    return
                f.seek(-1, os.SEEK_END)
                if f.read(1) == b"\n":
                    return
                back = min(size, 1 << 20)
                f.seek(size - back)
                cut = f.read(back).rfind(b"\n")
                f.truncate(size - back + cut + 1 if cut >= 0 else 0)
            print(
                f"[handyrl_tpu] {path}: dropped a truncated final line "
                "(half-written record from a killed run) before appending",
                file=sys.stderr,
            )
        except OSError:
            pass  # unreadable/missing file: the append below will surface it

    def _write_metrics(self, record: Dict[str, Any]) -> None:
        """Crash-safe metrics append: ONE write() per record (a single
        O_APPEND write of under a pipe-buffer's worth lands contiguously),
        flushed AND fsynced before returning, so a kill at any instant
        costs at most the final line — and readers tolerate exactly that
        (utils.metrics.read_metrics skips a truncated tail)."""
        path = self.args.get("metrics_path")
        if not path or not is_coordinator():
            return
        if not getattr(self, "_metrics_tail_checked", False):
            self._metrics_tail_checked = True
            if os.path.exists(path):
                self._repair_metrics_tail(path)
        # the ONE timestamp seam: every record carries wall-clock ts (cross
        # -run/cross-host alignment, absolute) and t_mono (monotonic — rate
        # math immune to NTP steps), so tooling stops using the record
        # index as a time axis (scripts/_logparse.py time_axis)
        record.setdefault("ts", round(time.time(), 6))
        record.setdefault("t_mono", round(time.monotonic(), 6))
        line = json.dumps(record, default=float) + "\n"
        with open(path, "a") as f:
            f.write(line)
            f.flush()
            try:
                os.fsync(f.fileno())
            except OSError:
                pass  # metrics durability is best-effort on exotic mounts

    # -- server loop (train.py:540-626) --------------------------------------

    def _assign_role(self) -> Dict[str, Any]:
        args: Dict[str, Any] = {"model_id": {}}
        # device_replay: generation lives entirely on device (host episodes
        # could not enter the ring buffers — they would be stored but never
        # trained on, while racing the epoch cadence), so host workers
        # evaluate only
        if self._replay is not None or self.num_results < self.eval_rate * self.num_episodes:
            args["role"] = "e"
            players = self.env.players()
            me = players[self.num_results % len(players)]
            args["player"] = [me]
            args["model_id"] = {p: (self.model_epoch if p == me else -1) for p in players}
            self.num_results += 1
        else:
            args["role"] = "g"
            args["player"] = self.env.players()
            args["model_id"] = {p: self.model_epoch for p in self.env.players()}
            self.num_episodes += 1
        return args

    def _workers_active(self) -> bool:
        """Drain condition: remote counts live connections, local counts threads."""
        if self.remote:
            if self._shutdown_t0 and time.time() - self._shutdown_t0 > 30.0:
                return False  # grace period for lingering connections
            return self.worker.connection_count() > 0
        return self._active_workers > 0

    # -- preemption-safe drain ------------------------------------------------

    def _drain_handler(self, signum, frame) -> None:
        """SIGTERM/SIGINT: install the stop flag and let the loops drain.
        Runs on the main thread (the server loop), so it only flips flags;
        the heavy lifting happens at the next loop iteration.  A second
        signal while draining is ignored (supervisors often double-tap)."""
        if self._drain_requested:
            return
        self._drain_requested = True
        self._drain_t0 = time.time()
        self.shutdown_flag = True
        try:
            name = signal.Signals(signum).name
        except ValueError:
            name = str(signum)
        print(
            f"[handyrl_tpu] {name} received: draining (final verified "
            f"checkpoint within {self.drain_deadline:.0f}s, then exit "
            f"{EXIT_RESUMABLE} for a restart_epoch: -1 relaunch)",
            file=sys.stderr,
        )

    def _install_signal_handlers(self) -> None:
        """Only the main thread may install handlers; elsewhere (a Learner
        driven from a test/helper thread) the drain is still reachable by
        calling _drain_handler directly."""
        if threading.current_thread() is not threading.main_thread():
            return
        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                self._prev_handlers[sig] = signal.signal(sig, self._drain_handler)
            except (ValueError, OSError):  # embedded interpreters
                pass

    def _restore_signal_handlers(self) -> None:
        for sig, prev in self._prev_handlers.items():
            try:
                signal.signal(sig, prev)
            except (ValueError, OSError):
                pass
        self._prev_handlers = {}

    def _drain_tick(self) -> bool:
        """Per-iteration drain bookkeeping; True = force the loop to end
        (deadline exhausted with workers still attached)."""
        if not self._drain_requested:
            return False
        if not self._drain_stopped:
            self._drain_stopped = True
            # stop the trainer mid-epoch: its thread snapshots state_host
            # on the way out, which becomes the drain checkpoint.  Multi-
            # process, this is cadence-aware (Trainer.request_drain): the
            # coordinator broadcasts the DRAIN bit so every process ends
            # the epoch together instead of wedging the peers mid-collective
            self.trainer.request_drain()
        if time.time() - self._drain_t0 > self.drain_deadline:
            print(
                "[handyrl_tpu] drain deadline exceeded; forcing shutdown "
                "(the checkpoint still lands from the last consistent state)",
                file=sys.stderr,
            )
            return True
        return False

    def _write_drain_checkpoint(self) -> None:
        """The drain's final durable save: epoch snapshot + state + manifest
        entry via the same atomic path as every boundary save, so
        ``restart_epoch: -1`` verifies and resumes it."""
        if not is_coordinator():
            return
        self.model_epoch += 1
        params, payload, steps = self.trainer.drain_payload(self.model_epoch)
        save_epoch_snapshot(self.model_dir, self.model_epoch, params, payload, steps)
        gc_snapshots(
            self.model_dir,
            int(self.args.get("keep_checkpoints", 0)),
            pin=self._gc_pin_set(),
        )
        print(
            f"[handyrl_tpu] drain checkpoint: epoch {self.model_epoch} at "
            f"step {steps} (manifest-verified; resume with restart_epoch: -1)",
            file=sys.stderr,
        )

    # -- cross-host fault handling (parallel/health.py) -----------------------

    def _rank_snapshot(self, steps: Optional[int] = None) -> Dict[str, Any]:
        """This rank's per-epoch metric snapshot for the cross-host relay
        (parallel/health.py): the fields the coordinator folds into the
        rank_* aggregates.  Small on purpose — it rides heartbeat lines."""
        stats = self.trainer.stats or {}
        return {
            "epoch": self.model_epoch,
            "steps": int(self.trainer.steps if steps is None else steps),
            "train_steps_per_sec": stats.get("train_steps_per_sec"),
            "input_wait_frac": stats.get("input_wait_frac"),
        }

    def _gateway_on_records(self, records: Dict[str, Any]) -> None:
        """Plane-gateway ingest (runs on a gateway serve thread): validate
        the lane width, ingest into this process's device rings, and book
        the counters through the same server-loop request the local
        rollout thread uses.

        ``defer=False`` on purpose: the deferred-stats FIFO belongs to the
        local rollout thread (``ingest_counted(defer=True)`` pairs each
        dispatch with a LATER fetch), and a second writer interleaving
        would misattribute both streams' stats.  One synchronous scalar
        fetch per record batch is noise next to the DCN payload it rode
        in on."""
        import jax

        widths = {x.shape[1] for x in jax.tree.leaves(records)}
        if widths != {self._device_games}:
            raise ValueError(
                f"plane gateway: record batch lane width {sorted(widths)} "
                f"!= this learner's {self._device_games} per-process lanes "
                "(device_rollout_games / num_processes must match on both "
                "tiers)"
            )
        stats = self._replay.ingest_counted(records, defer=False)
        episodes = int(stats["episodes"])
        if episodes <= 0 and int(stats["game_steps"]) <= 0:
            return
        counts = {
            "episodes": episodes,
            "players": self._venv.num_players,
            "model_id": self.model_epoch,
            "game_steps": int(stats["game_steps"]),
            "outcome_sum": float(stats["outcome_sum"].sum()),
            "outcome_sq_sum": float(stats["outcome_sq_sum"]),
        }
        # fire-and-forget: the serve thread must keep answering its actor
        # host; the server loop books the counts when it gets there
        self._requests.put(("device_counts", counts, Future()))

    def _dist_events(self) -> Dict[str, int]:
        """Cumulative cross-host health counters for the dist_* metrics."""
        health_ev = self._health.events if self._health is not None else {}
        return {
            "dist_heartbeat_misses": int(health_ev.get("heartbeat_misses", 0)),
            "dist_collective_timeouts": 1 if (
                self._collective_watchdog is not None
                and self._collective_watchdog.fired
            ) else 0,
            "dist_peer_loss_drains": int(health_ev.get("peer_losses", 0))
            + int(health_ev.get("coordinator_losses", 0)),
        }

    def _disarm_host_fault(self) -> None:
        """Called by the trainer the moment the agreed stop/drain broadcast
        returns: every rank is past its last collective, so the detectors
        must stand down before rank-skewed teardown starts."""
        if self._health is not None:
            self._health.disarm()
        if self._collective_watchdog is not None:
            self._collective_watchdog.stop()

    def _host_fault(self, reason: str, kind: str) -> None:
        """A peer process is lost or a collective wedged: runs on a health/
        watchdog thread while the trainer may be stuck inside a collective
        that can NEVER complete — no Python-level cancel exists for an
        in-flight XLA collective, so the only bounded recovery is to
        drain-save from the last consistent HOST snapshot (state_host is
        swapped atomically at each epoch end and never device-resident)
        and leave via os._exit: the normal interpreter teardown would
        block on the wedged thread.  Exit code 75 (EX_TEMPFAIL) tells the
        supervisor to relaunch every rank with restart_epoch: -1."""
        from ..parallel.health import announce_fault

        if self._host_faulted:
            return
        self._host_faulted = True
        announce_fault(reason, kind, EXIT_RESUMABLE)
        try:
            if is_coordinator():
                record = {"epoch": self.model_epoch, "dist_processes": self._dist_nprocs}
                record.update(self._dist_events())
                if self._health is not None and self._rank_metrics:
                    # last known per-rank picture rides the final record: a
                    # wedged-but-heartbeating peer shows up here as a stale
                    # epoch / grown report age — the post-mortem pointer
                    try:
                        record.update(
                            self._health.rank_aggregates(self._rank_snapshot())
                        )
                    except Exception:
                        pass  # the drain save must land regardless
                self._write_metrics(record)
                self._write_drain_checkpoint()
        except Exception:
            import traceback

            traceback.print_exc()
            print(
                "[handyrl_tpu] host-fault drain save failed (above); the "
                "previous epoch's verified checkpoint remains the resume "
                "point",
                file=sys.stderr,
            )
        sys.stderr.flush()
        sys.stdout.flush()
        os._exit(EXIT_RESUMABLE)

    def _dist_fault_hooks(self) -> None:
        """Host-loss fault injections, checked at each epoch publish
        (runtime/faults.py): rank-scoped hard kill / freeze."""
        kill = self._fault_kill_proc
        if kill is not None and self.model_epoch >= kill[0] and self._dist_rank == kill[1]:
            print(
                f"[fault] killing process rank {self._dist_rank} at epoch "
                f"{self.model_epoch} (HANDYRL_FAULT_KILL_PROCESS_AT_EPOCH)",
                file=sys.stderr,
            )
            sys.stderr.flush()
            os._exit(1)
        wedge = self._fault_wedge_proc
        if wedge is not None and self.model_epoch >= wedge[0] and self._dist_rank == wedge[1]:
            print(
                f"[fault] wedging process rank {self._dist_rank} at epoch "
                f"{self.model_epoch} (HANDYRL_FAULT_WEDGE_PROCESS): "
                "heartbeats stop, collectives stop, threads stay up",
                file=sys.stderr,
            )
            sys.stderr.flush()
            if self._health is not None:
                self._health.stop_heartbeats()
            self.trainer._fault_wedge_process = True
            while True:  # the frozen host never comes back
                time.sleep(60.0)

    def server(self) -> None:
        print("started server")
        prev_update_episodes = self.args["minimum_episodes"]
        next_update_episodes = prev_update_episodes + self.args["update_episodes"]
        self._shutdown_t0 = 0.0

        while self._workers_active() or not self.shutdown_flag:
            if self._drain_tick():
                break
            if self.shutdown_flag and not self._shutdown_t0:
                self._shutdown_t0 = time.time()
            try:
                req, data, fut = self._requests.get(timeout=0.3)
            except queue.Empty:
                continue

            if req == "args":
                # data None: one local worker; int n: a gather prefetching n
                if self.shutdown_flag:
                    fut.set_result(None)
                    self._active_workers -= 1
                elif data is None:
                    fut.set_result(self._assign_role())
                else:
                    fut.set_result([self._assign_role() for _ in range(int(data))])
            elif req == "episode":
                self.feed_episodes([data] if not isinstance(data, list) else data)
                fut.set_result(None)
            elif req == "device_episodes":
                # on-device generation bypasses role assignment; count the
                # episodes so the eval_rate balance still sees them
                self.feed_episodes(data)
                self.num_episodes += len(data)
                fut.set_result(None)
            elif req == "device_counts":
                # device-replay mode: episodes never materialize on host —
                # the rollout thread reports ingest counters instead, which
                # feed the same books (epoch cadence, generation stats,
                # eval_rate balance) as feed_episodes would
                n, P = data["episodes"], data["players"]
                st = self.generation_results.get(data["model_id"], (0, 0, 0))
                self.generation_results[data["model_id"]] = (
                    st[0] + n * P,
                    st[1] + data["outcome_sum"],
                    st[2] + data["outcome_sq_sum"],
                )
                self.num_returned_episodes += n
                self.num_episodes += n
                self._device_epoch_eps += n
                self._device_epoch_steps += data.get("game_steps", 0)
                fut.set_result(None)
            elif req == "result":
                self.feed_results([data] if not isinstance(data, list) else data)
                fut.set_result(None)
            elif req == "jobs_lost":
                # a worker connection vanished with jobs in flight: hand
                # their counts back so the generation/evaluation balance
                # re-dispatches equivalents to the surviving workers
                self.num_episodes = max(0, self.num_episodes - int(data.get("g", 0)))
                self.num_results = max(0, self.num_results - int(data.get("e", 0)))
                fut.set_result(None)
            elif req == "model":
                fut.set_result(self.model_server.get(data))
            else:
                fut.set_result(None)

            if self._dist_follower:
                # coordinator-driven boundary: the trainer's queue only
                # holds a snapshot once the coordinator ended the epoch on
                # EVERY process (DistributedCadence); local episode counts
                # play no cadence role on a follower
                if self.trainer.drain_agreed and not self._drain_requested:
                    # the coordinator broadcast a preemption drain: adopt
                    # it locally so this rank also lands on EXIT_RESUMABLE
                    self._drain_requested = True
                    self._drain_t0 = time.time()
                    self.shutdown_flag = True
                    print(
                        "[handyrl_tpu] coordinator-agreed drain: shutting "
                        f"down within {self.drain_deadline:.0f}s and exiting "
                        f"{EXIT_RESUMABLE} for the coordinated relaunch",
                        file=sys.stderr,
                    )
                elif (
                    not self._drain_requested
                    and not self.trainer.update_queue.empty()
                ):
                    self.update()
                elif (
                    self.trainer.finished
                    and self.trainer.update_queue.empty()
                    and not self._drain_requested
                ):
                    # the stop was agreed through the cadence; the final
                    # snapshot above has been consumed — drain the workers
                    self.shutdown_flag = True
            elif (
                self.num_returned_episodes >= next_update_episodes
                and not self._drain_requested  # draining: no new boundary work
            ):
                prev_update_episodes = next_update_episodes
                next_update_episodes = prev_update_episodes + self.args["update_episodes"]
                self._next_update_episodes = next_update_episodes
                if self._dist_nprocs > 1 and not self.trainer._warmed_up():
                    # multi-process coordinator, PRE-WARMUP boundary:
                    # followers only ever see AGREED epoch ends (their
                    # boundary is the cadence snapshot), so counting an
                    # epoch here would advance model_epoch on this rank
                    # alone — desyncing the epochs-limit shutdown (the
                    # stop is never broadcast pre-warmup) and the
                    # rank-scoped "E:R" fault injections.  Defer it.
                    continue
                self.update()
                shutdown = (
                    self.args["epochs"] >= 0
                    and self.model_epoch >= self.args["epochs"]
                )
                # multi-process coordinator: release the trainer's post-
                # epoch handshake with the continue/shutdown decision so
                # every process stops (or starts the next epoch) together;
                # a no-op single-process and on pre-warmup boundaries
                self.trainer.proceed(shutdown)
                if shutdown:
                    self.shutdown_flag = True
        self.trainer.stop()
        self.model_server.stop()
        # resolve any futures enqueued after the loop's final iteration
        # (e.g. the device-rollout thread racing shutdown) — a blocked
        # handle() would otherwise leak a permanently waiting thread
        while True:
            try:
                _, _, fut = self._requests.get_nowait()
            except queue.Empty:
                break
            if not fut.done():
                fut.set_result(None)
        if self._trainer_thread is not None:
            # under a drain, the join is bounded by what's left of the
            # deadline (floor 5s) so a wedged trainer can't eat the budget;
            # the checkpoint then falls back to the last consistent state.
            # Multi-process the bound is wider: the thread may still be
            # inside the final agree_stop broadcast (waiting on a slower
            # rank), and leaving for jax.distributed.shutdown before it
            # returns abandons the peers inside the collective
            timeout = 120.0 if self._dist_nprocs > 1 else 30.0
            if self._drain_requested:
                left = self.drain_deadline - (time.time() - self._drain_t0)
                timeout = max(5.0, min(timeout, left))
            self._trainer_thread.join(timeout=timeout)
        if self._drain_requested:
            self._write_drain_checkpoint()
        print("finished server")

    # -- rollout plane: generation-tokened loop + watchdog --------------------

    def _start_rollout_thread(self) -> threading.Thread:
        """(Re)start the device-rollout thread under a fresh generation
        token.  A superseded generation exits at its next liveness check
        (a thread truly wedged inside a dispatch cannot be killed from
        Python — it is abandoned and its generation invalidated, which is
        the best any host-side supervisor can do)."""
        self._rollout_gen += 1
        gen = self._rollout_gen
        self._rollout_progress_t = time.monotonic()
        # stall detection arms only after this generation's FIRST dispatch
        # completes: the first call pays jit compilation (minutes for a
        # big model on TPU), and declaring that a stall would burn the
        # whole restart budget on a healthy warm-up (a thread that DIES
        # during compile is still caught by the dead-thread check)
        self._rollout_dispatched = False
        t = threading.Thread(
            target=self._device_rollout_loop, args=(gen,), daemon=True,
            name=f"device-rollout-{gen}",
        )
        self._rollout_thread = t
        t.start()
        return t

    def _rollout_live(self, gen: int) -> bool:
        return not self.shutdown_flag and self._rollout_gen == gen

    def _rollout_beat(self) -> None:
        """Progress heartbeat for the plane watchdog: every dispatch,
        backpressure sleep, and server patience-wait counts as liveness —
        only a thread that stops doing ALL of those is stalled."""
        self._rollout_progress_t = time.monotonic()

    def _maybe_wedge(self, gen: int, dispatches: int) -> bool:
        """HANDYRL_FAULT_WEDGE_ROLLOUT: after N successful dispatches this
        generation stops heartbeating (simulating a wedged XLA execute) but
        politely exits once superseded or shut down.  Returns True when the
        caller should return."""
        w = self._fault_wedge
        if w is None or dispatches < w[0] or (not w[1] and gen != 1):
            return False
        print(
            f"[fault] wedging rollout thread generation {gen} after "
            f"{dispatches} dispatches (HANDYRL_FAULT_WEDGE_ROLLOUT)",
            file=sys.stderr,
        )
        while self._rollout_live(gen):
            time.sleep(0.05)  # no _rollout_beat: the watchdog must notice
        return True

    def _watchdog_loop(self) -> None:
        """Split/fused plane liveness supervision (runs whenever a device
        rollout thread exists).  Detects a dead rollout thread, a stalled
        one (no progress beat within plane_stall_timeout), or actor params
        lagging past plane_param_lag_bound; restarts the thread up to
        plane_max_restarts, then degrades split -> fused loudly."""
        timeout = float(self.args.get("plane_stall_timeout", 120.0))
        max_restarts = int(self.args.get("plane_max_restarts", 2))
        lag_bound = int(self.args.get("plane_param_lag_bound", 0))
        restarts = 0
        tick = max(0.05, min(1.0, timeout / 4.0))
        while not self.shutdown_flag:
            time.sleep(tick)
            if self.shutdown_flag or self._drain_requested:
                return
            thread = self._rollout_thread
            if thread is None:
                continue
            dead = not thread.is_alive()
            stall_s = time.monotonic() - self._rollout_progress_t
            # pre-first-dispatch silence is compile time, not a stall
            stalled = stall_s > timeout and self._rollout_dispatched
            cache = self._param_cache
            lagged = (
                lag_bound > 0
                and cache is not None
                and cache.lag(self.trainer.steps) > lag_bound
            )
            if not (dead or stalled or lagged):
                continue
            reason = (
                "thread died"
                if dead
                else f"no progress for {stall_s:.1f}s (> plane_stall_timeout)"
                if stalled
                else f"param lag {cache.lag(self.trainer.steps)} > "
                f"plane_param_lag_bound {lag_bound}"
            )
            self._watchdog_events["plane_watchdog_stalls"] += 1
            print(
                f"[handyrl_tpu] plane watchdog: rollout plane unhealthy "
                f"({reason})",
                file=sys.stderr,
            )
            if restarts < max_restarts:
                restarts += 1
                self._watchdog_events["plane_watchdog_restarts"] += 1
                print(
                    f"[handyrl_tpu] plane watchdog: restarting rollout "
                    f"thread ({restarts}/{max_restarts})",
                    file=sys.stderr,
                )
                self._start_rollout_thread()
            elif self._plane == "split":
                self._degrade_to_fused()
            else:
                print(
                    "[handyrl_tpu] plane watchdog: restart budget exhausted "
                    "on the fused plane; giving up on the rollout thread "
                    "(host actors keep generating if configured)",
                    file=sys.stderr,
                )
                return

    def _degrade_to_fused(self) -> None:
        """Split -> fused degradation (mirrors the shm-batcher degrade
        pattern): stop the cross-plane param/record flows, rebuild the
        rollout program on the LEARNER mesh, and restart the rollout
        thread there.  Training continues throughout — the learner plane
        never depended on the actor mesh."""
        self._rollout_gen += 1  # invalidate any live generation FIRST
        print(
            "[handyrl_tpu] plane watchdog: restart budget exhausted; "
            "degrading split -> fused (rollouts move to the learner mesh; "
            "cross-plane param/record flows stop)",
            file=sys.stderr,
        )
        if self._plane_gateway is not None:
            # the cross-HOST plane outlives a local split->fused degrade:
            # drop only the actor-mesh delegate, keep publishing to the
            # gateway so remote actor hosts still get fresh params
            self._plane_gateway.inner = None
            self.trainer.param_cache = self._plane_gateway
        else:
            self.trainer.param_cache = None
        self._param_cache = None
        self._record_xfer = None
        self._plane_stats = None
        self._actor_mesh = None
        self._plane = "fused"
        self._watchdog_events["plane_watchdog_degraded"] = 1
        mesh = (
            self._data_mesh
            if self._data_mesh is not None
            else self.trainer.ctx.mesh
        )
        try:
            if self._replay is not None:
                from .device_rollout import build_streaming_fn

                self._stream_fn = build_streaming_fn(
                    self._venv, self.module, self._device_games,
                    self.args["device_replay_k_steps"],
                    mesh=mesh if mesh.size > 1 else None,
                    use_observe_mask=bool(self.args["observation"]),
                )
            else:
                from .device_rollout import make_device_rollout

                self._device_roll = make_device_rollout(
                    self._venv, self.module, self.args, self._device_games,
                    mesh=mesh,
                )
        except Exception:
            import traceback

            traceback.print_exc()
            print(
                "[handyrl_tpu] plane watchdog: learner-mesh rollout rebuild "
                "failed (above); device generation stops (training continues "
                "on already-ingested data / host actors)",
                file=sys.stderr,
            )
            return
        self._start_rollout_thread()

    def _device_rollout_loop(self, gen: int) -> None:
        """Generate device self-play batches up to each epoch boundary
        (backpressure: pause once the boundary's episode budget is met, so
        the chip alternates between rollouts and train steps instead of
        flooding the store).  ``gen`` is this thread's generation token:
        the loop exits once the watchdog supersedes it."""
        import jax

        # a restarted generation must not replay the superseded stream;
        # the 1009 * rank fold decorrelates the per-process lane shares
        # (each rank generates DIFFERENT games into its local rings)
        key = jax.random.PRNGKey(
            self.args["seed"]
            + 0x5EED
            + 0x1009 * (gen - 1)
            + 1009 * self._dist_rank
        )
        if self._device_roll is None:          # device_replay mode
            try:
                self._device_replay_inner(key, gen)
            finally:
                if self._rollout_gen == gen:  # superseded: new gen owns it
                    self._replay.drain()
            return
        roll = self._device_roll
        try:
            self._device_rollout_inner(roll, key, gen)
        finally:
            # await the in-flight async dispatch; exiting the process with
            # an XLA execution still running aborts it (see
            # StreamingDeviceRollout.drain)
            if hasattr(roll, "drain") and self._rollout_gen == gen:
                roll.drain()

    def _actor_params(self):
        """(model_id, params) for the next rollout dispatch: under plane:
        split the versioned actor-mesh cache (bumping the realized-lag
        counter), else the model server's epoch snapshot."""
        cache = self._param_cache       # local refs: a concurrent watchdog
        stats = self._plane_stats       # degrade nulls these attributes
        if cache is None:
            return self.model_server.latest_snapshot()
        version, params = cache.latest()
        if stats is not None:
            stats.bump(
                actor_dispatches=1,
                param_lag_sum=max(0, self.trainer.steps - version),
            )
        return self.model_epoch, params

    def _device_replay_inner(self, key, gen: int) -> None:
        """Streaming rollout -> device-ring ingest; only scalar counters
        reach the host, reported to the server loop for the books.

        Under plane: split the rollout dispatch holds only the ACTOR
        mesh's locks — it overlaps the learner plane's train dispatches —
        and the record batch crosses to the learner mesh before ingest
        (which shares the learner locks with training, preserving the
        ring donation contract per plane).

        Split/fused and the meshes are resolved at ENTRY, so a watchdog
        restart after a split -> fused degradation re-enters here and
        picks up the learner-mesh plumbing."""
        import jax

        from ..parallel.mesh import dispatch_serialized

        split = self._param_cache is not None
        roll_mesh = (
            self._actor_mesh if split else self._data_mesh
        )
        # entry-captured refs: a concurrent watchdog degrade nulls the
        # attributes, and a late-waking superseded thread must die at its
        # liveness check, not on a None deref mid-iteration
        record_xfer = self._record_xfer
        plane_stats = self._plane_stats
        key, k0 = jax.random.split(key)
        vstate = self._venv.init(self._device_games, k0)
        hidden = self.module.initial_state(
            (self._device_games, self._venv.num_players)
        )
        if roll_mesh is not None:
            # commit every dispatch input onto the rollout mesh UP FRONT:
            # the loop's args then match the program's pinned in_shardings
            # exactly, so no dispatch triggers an implicit host->mesh
            # reshard.  That implicit copy is not just a per-dispatch
            # transfer on the hot path — under plane: split it races the
            # async ingest running on the OTHER plane's devices (observed
            # on the multi-process CPU backend as Execute() placement
            # errors killing the rollout thread), and committed args keep
            # every cross-device move explicit and plane-owned.  The key
            # stays mesh-resident too: split() of a committed key runs on
            # the actor mesh and its outputs inherit the placement.
            from jax.sharding import NamedSharding, PartitionSpec

            rep = NamedSharding(roll_mesh, PartitionSpec())
            lanes = NamedSharding(roll_mesh, PartitionSpec("dp"))
            key = jax.device_put(key, rep)
            vstate = jax.device_put(vstate, lanes)
            if hidden is not None:
                hidden = jax.device_put(hidden, lanes)
        from collections import deque

        pending_steps = 0   # game steps from batches that finished 0 episodes
        dispatches = 0
        # model epoch per in-flight deferred ingest, aligned with
        # DeviceReplay's stats FIFO: the stats that come back are one
        # dispatch old, and booking them under the CURRENT epoch would
        # misattribute one k_steps block's generation stats at every
        # model publish
        epoch_fifo: deque = deque()
        try:
            while self._rollout_live(gen):
                if self.num_returned_episodes >= self._next_update_episodes:
                    time.sleep(0.02)   # epoch episode budget met: yield the chip
                    self._rollout_beat()  # backpressure idle is healthy
                    if split:
                        plane_stats.bump(actor_idle_s=0.02)
                    continue
                if self._maybe_wedge(gen, dispatches):
                    return
                epoch, params = self._actor_params()
                t_busy = time.perf_counter()
                key, sub = jax.random.split(key)
                vstate, hidden, records = dispatch_serialized(
                    lambda: self._stream_fn(params, vstate, hidden, sub),
                    roll_mesh,
                )
                if split:
                    records = record_xfer(records)
                # deferred stats (the direct-ingest hot path): the records
                # go straight into the learner-mesh rings and the scalar
                # fetch for dispatch N happens only after N+1 is enqueued —
                # the rollout thread never synchronizes on an ingest.  The
                # returned stats are therefore ONE DISPATCH OLD (None on
                # the first), which only lags the books by one k_steps
                # block — their model epoch rides epoch_fifo so the
                # generation-stats attribution stays exact; the tail is
                # flushed in the finally below.
                epoch_fifo.append(epoch)
                stats = self._replay.ingest_counted(records, defer=True)
                dispatches += 1
                self._rollout_dispatched = True  # arms stall detection
                self._rollout_beat()
                if split:
                    plane_stats.bump(
                        actor_busy_s=time.perf_counter() - t_busy
                    )
                if not self._rollout_live(gen):
                    return
                if stats is None:
                    continue
                stats_epoch = epoch_fifo.popleft()  # the dispatch they're from
                n = int(stats["episodes"])
                pending_steps += int(stats["game_steps"])
                if n == 0:
                    continue   # steps stay in pending_steps for the next report
                counts = {
                    "episodes": n,
                    "players": self._venv.num_players,
                    "model_id": stats_epoch,
                    "game_steps": pending_steps,
                    # graftlint: allow[HS001] reason=stats are host numpy from the deferred ingest fetch (one dispatch old), not device values
                    "outcome_sum": float(stats["outcome_sum"].sum()),
                    # graftlint: allow[HS001] reason=stats are host numpy from the deferred ingest fetch (one dispatch old), not device values
                    "outcome_sq_sum": float(stats["outcome_sq_sum"]),
                }
                pending_steps = 0
                if not self._submit_counts(counts, gen):
                    return
        finally:
            # settle the deferred tail so its episodes still reach the
            # books — but only while the run is live (a watchdog restart):
            # a shutdown-time submission could push num_returned_episodes
            # over the next boundary and conjure a spurious extra epoch
            # out of the drain (pre-deferral behavior dropped the tail)
            try:
                left = self._replay.flush_counted()
            except Exception:
                left = None
            if self.shutdown_flag:
                left = None
            if left and (int(left["episodes"]) > 0 or pending_steps):
                counts = {
                    "episodes": int(left["episodes"]),
                    "players": self._venv.num_players,
                    # oldest in-flight dispatch's epoch, not the current
                    # model_epoch: a restart racing a model publish would
                    # otherwise book the tail under a model that never
                    # generated it (the tail can span several epochs; the
                    # oldest is the closest single attribution)
                    "model_id": int(epoch_fifo[0]) if epoch_fifo else self.model_epoch,
                    "game_steps": pending_steps + int(left["game_steps"]),
                    "outcome_sum": float(left["outcome_sum"]),
                    "outcome_sq_sum": float(left["outcome_sq_sum"]),
                }
                # same submission protocol as the loop body (patience while
                # this generation is live; a superseded/stopping thread
                # gives up instead of blocking teardown)
                self._submit_counts(counts, gen)

    def _submit_counts(self, counts: Dict[str, Any], gen: int) -> bool:
        """Report ingest counters to the server loop with the same patience
        loop as _device_rollout_inner (the server can be busy for minutes
        at an epoch boundary).  False = stop the rollout loop."""
        fut: Future = Future()
        self._requests.put(("device_counts", counts, fut))
        while not fut.done():
            try:
                fut.result(timeout=5.0)
                self._rollout_beat()  # served: the wait was the server's
            except (TimeoutError, FutureTimeoutError):
                self._rollout_beat()  # waiting on a busy server ≠ a stall
                if not self._rollout_live(gen):
                    return False
            except Exception:
                return False
        return True

    def _device_rollout_inner(self, roll, key, gen: int) -> None:
        import jax

        roll_mesh = getattr(roll, "mesh", None)
        if roll_mesh is not None:
            # mesh-resident key, same contract as _device_replay_inner:
            # dispatch args never ride an implicit host->mesh reshard
            from jax.sharding import NamedSharding, PartitionSpec

            key = jax.device_put(key, NamedSharding(roll_mesh, PartitionSpec()))
        dispatches = 0
        while self._rollout_live(gen):
            if self.num_returned_episodes >= self._next_update_episodes:
                time.sleep(0.02)
                self._rollout_beat()  # backpressure idle is healthy
                if self._plane_stats is not None:
                    self._plane_stats.bump(actor_idle_s=0.02)
                continue
            if self._maybe_wedge(gen, dispatches):
                return
            epoch, params = self._actor_params()
            t_busy = time.perf_counter()
            key, sub = jax.random.split(key)
            episodes = roll.generate(params, sub)
            dispatches += 1
            self._rollout_dispatched = True  # arms stall detection
            self._rollout_beat()
            if self._plane_stats is not None:
                self._plane_stats.bump(actor_busy_s=time.perf_counter() - t_busy)
            for ep in episodes:
                ep["args"]["model_id"] = {p: epoch for p in ep["players"]}
            if not self._rollout_live(gen):
                return
            # submit once and wait on the SAME future with a patience loop:
            # the server loop can be busy for minutes at an epoch boundary
            # (trainer snapshot + first-epoch jit compile), and re-raising
            # on a fixed timeout would silently kill on-device generation
            # for the rest of the run
            fut: Future = Future()
            self._requests.put(("device_episodes", episodes, fut))
            while not fut.done():
                try:
                    fut.result(timeout=5.0)
                    self._rollout_beat()
                except (TimeoutError, FutureTimeoutError):
                    self._rollout_beat()  # waiting on a busy server ≠ a stall
                    if not self._rollout_live(gen):
                        return  # server draining/exited; nothing to feed
                except Exception:
                    return

    def run(self) -> int:
        """Run to completion.  Returns 0 on a normal finish, EXIT_RESUMABLE
        (75) after a preemption-safe drain — callers (train_main) exit with
        it so the launcher knows a verified resume point is waiting."""
        self._install_signal_handlers()
        try:
            if self._health is not None:
                self._health.start()
            if self._collective_watchdog is not None:
                self._collective_watchdog.start()
            if self._plane_gateway is not None:
                self._plane_gateway.start()
            self._trainer_thread = threading.Thread(target=self.trainer.run, daemon=True)
            self._trainer_thread.start()
            self.worker.run()
            self._active_workers = len(getattr(self.worker, "threads", [])) or self.args["worker"]["num_parallel"]
            if self._device_games > 0:
                self._start_rollout_thread()
                threading.Thread(
                    target=self._watchdog_loop, daemon=True, name="plane-watchdog"
                ).start()
            self._start_flywheel_ingest()
            self.server()
            if self._plane_gateway is not None:
                # run concluding: answer every further actor-host request
                # with a clean stop (they exit 0, not as counted losses)
                self._plane_gateway.begin_stop()
            if self._rollout_thread is not None:
                # let an in-flight device call drain: tearing down the
                # interpreter while a daemon thread is inside an XLA execute
                # aborts the process (C++ exception at exit).  Under a drain
                # the join is bounded by the remaining deadline.
                timeout = 120.0
                if self._drain_requested:
                    left = self.drain_deadline - (time.time() - self._drain_t0)
                    timeout = max(5.0, min(120.0, left))
                self._rollout_thread.join(timeout=timeout)
        finally:
            if self._flywheel_ingestor is not None:
                self._flywheel_ingestor.stop()
            if self._health is not None:
                self._health.stop()
            if self._collective_watchdog is not None:
                self._collective_watchdog.stop()
            if self._plane_gateway is not None:
                self._plane_gateway.stop()
            self._restore_signal_handlers()
            trace.shutdown()  # flush the span ring tail; a no-op when off
        return EXIT_RESUMABLE if self._drain_requested else 0

    def _start_flywheel_ingest(self) -> None:
        """Arm the harvest-ingest poll loop (flywheel/ingest.py) when the
        flywheel is on and the mix wants served episodes.  Coordinator
        only: harvested episodes enter through feed_episodes, and under
        jax.distributed exactly one process drives the episode cadence."""
        cfg = self._flywheel_cfg
        if not cfg.get("enabled") or not is_coordinator():
            return
        if float(cfg.get("harvest_fraction", 0.5)) <= 0.0:
            return
        from ..flywheel import HarvestIngestor

        host = str(cfg.get("harvest_host", "127.0.0.1"))
        port = int(cfg.get("harvest_port", 0)) or int(
            (self.args.get("serving") or {}).get("port", 9997)
        )

        def make_client():
            from ..serving.client import ServingClient

            return ServingClient(host, port, timeout=10.0)

        def submit(episodes):
            # ride the standard request queue: feed_episodes books the
            # generation stats and drives the epoch cadence exactly as a
            # worker's self-play batch would
            self.handle("episode", episodes, timeout=60.0)

        self._flywheel_ingestor = HarvestIngestor(
            dict(cfg, update_episodes=self.args.get("update_episodes", 0)),
            submit,
            lambda: self.model_epoch,
            make_client,
        ).start()
        print(f"flywheel: harvest ingest armed ({host}:{port}, "
              f"fraction {cfg.get('harvest_fraction', 0.5)})")

    @property
    def shutdown_coherent(self) -> bool:
        """True when every process reached (or will reach) the same run
        end, so the synchronized ``jax.distributed.shutdown`` barrier is
        safe to join: a clean finish or a cadence-AGREED drain.  False
        after a follower-local drain (its SIGTERM never rode a broadcast)
        — the peers are still running or leaving via ``_host_fault``'s
        ``os._exit``, so they never join the barrier, and waiting in it
        would end in the coordination service's SIGABRT instead of the
        promised exit 75 (docs/fault_tolerance.md, one-rank SIGTERM row)."""
        if self._dist_nprocs <= 1 or not self._drain_requested:
            return True
        return bool(getattr(self.trainer, "drain_agreed", False))


def _finish_distributed(learner: "Learner") -> None:
    from ..parallel.distributed import shutdown_distributed

    if learner.shutdown_coherent:
        shutdown_distributed()


def train_main(args: Dict[str, Any]) -> None:
    learner = Learner(args)
    code = learner.run()
    _finish_distributed(learner)
    if code:
        sys.exit(code)


def train_server_main(args: Dict[str, Any]) -> None:
    learner = Learner(args, remote=True)
    code = learner.run()
    _finish_distributed(learner)
    if code:
        sys.exit(code)
