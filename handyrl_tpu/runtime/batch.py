"""Fixed-shape training batch assembly from sampled episode windows.

Mask/padding semantics parity with reference make_batch (train.py:33-125):

* Base shape is (B, T, P, ...), T always exactly ``burn_in_steps +
  forward_steps`` (XLA needs static shapes; the reference only pads short
  windows, we always emit the same shape).
* In turn-based training without observers, the *actor-side* arrays
  (observation / selected_prob / action / action_mask) carry only the
  turn player (P dim = 1) gathered per step, while *target-side* arrays
  (value / reward / return / masks / outcome) keep every player — the
  turn player's prediction is later broadcast against the full-player
  turn mask (see parallel/train_step.py and train.py:177-186).
* Padding: before the window (burn-in underflow) everything is zero;
  after episode end values become the final outcome, selected_prob 1,
  action_mask all-illegal (1e32), progress 1, episode_mask 0.

Episode columnar format (produced by runtime/generation.py):
  blocks[k] decompresses to a dict of arrays over t timesteps:
    obs    pytree, leaves (t, P, ...)
    prob   (t, P)   behavior probability of the selected action (1 if none)
    action (t, P)   int32
    amask  (t, P, A) 0 = legal / 1e32 = illegal (all-1e32 when not acting)
    value  (t, P)   critic estimate at acting time (0 when unobserved)
    reward (t, P)   immediate reward after the step
    ret    (t, P)   discounted return-to-go
    tmask  (t, P)   1 if the player acted this step
    omask  (t, P)   1 if the player observed this step
    turn   (t,)     index (into players) of the first turn player
"""

from __future__ import annotations

import random
from typing import Any, Dict, List

import jax
import numpy as np

from ..utils import tree_concat, tree_map
from .replay import decompress_block


def _concat_columns(blocks: List[Dict[str, Any]]) -> Dict[str, Any]:
    if len(blocks) == 1:
        return blocks[0]
    out = {
        key: np.concatenate([b[key] for b in blocks], axis=0)
        for key in blocks[0]
        if key != "obs"
    }
    out["obs"] = tree_concat([b["obs"] for b in blocks])
    return out


def _assemble_one(window: Dict[str, Any], args: Dict[str, Any]) -> Dict[str, Any]:
    cols = _concat_columns([decompress_block(b) for b in window["blocks"]])
    lo = window["start"] - window["base"]
    hi = window["end"] - window["base"]
    sl = slice(lo, hi)

    turn_based = args["turn_based_training"]
    num_players = cols["prob"].shape[1]
    if turn_based:
        target_players = list(range(num_players))
    else:
        target_players = [random.randrange(num_players)]

    obs = tree_map(lambda x: x[sl], cols["obs"])
    prob = cols["prob"][sl]
    action = cols["action"][sl]
    amask = cols["amask"][sl]

    if turn_based and not args["observation"]:
        # Actor-side arrays: gather the turn player per step -> P dim 1.
        turn = cols["turn"][sl]
        t_idx = np.arange(len(turn))
        obs = tree_map(lambda x: x[t_idx, turn][:, None], obs)
        prob = prob[t_idx, turn][:, None]
        action = action[t_idx, turn][:, None]
        amask = amask[t_idx, turn][:, None]
    else:
        obs = tree_map(lambda x: x[:, target_players], obs)
        prob = prob[:, target_players]
        action = action[:, target_players]
        amask = amask[:, target_players]

    value = cols["value"][sl][:, target_players, None]
    reward = cols["reward"][sl][:, target_players, None]
    ret = cols["ret"][sl][:, target_players, None]
    tmask = cols["tmask"][sl][:, target_players, None].astype(np.float32)
    omask = cols["omask"][sl][:, target_players, None].astype(np.float32)
    outcome = np.asarray(window["outcome"], dtype=np.float32)[target_players].reshape(1, -1, 1)

    steps = hi - lo
    progress = (np.arange(window["start"], window["end"], dtype=np.float32) / window["total"])[:, None]

    prob = prob[..., None]
    action = action[..., None].astype(np.int32)

    pad_b = 0
    if steps < args["burn_in_steps"] + args["forward_steps"]:
        pad_b = args["burn_in_steps"] - (window["train_start"] - window["start"])

    return {
        "pad_b": pad_b,
        "steps": steps,
        "obs": obs,
        "prob": prob,
        "value": value,
        "action": action,
        "outcome": outcome,
        "reward": reward,
        "ret": ret,
        "tmask": tmask,
        "omask": omask,
        "amask": amask,
        "progress": progress,
    }


def make_batch(windows: List[Dict[str, Any]], args: Dict[str, Any]) -> Dict[str, Any]:
    """Assemble B sampled windows into one (B, T, P, ...) numpy batch.

    Each window writes its unpadded slice directly into preallocated
    output arrays whose defaults ARE the padding semantics (zeros before
    the window; after episode end selected_prob 1, action_mask all-illegal
    1e32, value frozen at the outcome, progress 1, episode_mask 0) — one
    allocation + one copy per key instead of the np.pad-per-array +
    tree_stack version this replaces, which dominated the host-side batch
    assembly profile and starved the learner on HungryGeese-sized
    observations.
    """
    B = len(windows)
    T = args["burn_in_steps"] + args["forward_steps"]
    cores = [_assemble_one(w, args) for w in windows]
    c0 = cores[0]

    def alloc(leaf, fill=0.0, dtype=np.float32):
        shape = (B, T) + tuple(leaf.shape[1:])
        if fill == 0.0:
            return np.zeros(shape, dtype)
        return np.full(shape, fill, dtype)

    out = {
        "observation": tree_map(lambda x: alloc(x, 0.0, x.dtype), c0["obs"]),
        "selected_prob": alloc(c0["prob"], 1.0),
        "value": alloc(c0["value"]),
        "action": alloc(c0["action"], 0, np.int32),
        "outcome": np.zeros((B, 1) + tuple(c0["outcome"].shape[1:]), np.float32),
        "reward": alloc(c0["reward"]),
        "return": alloc(c0["ret"]),
        "episode_mask": np.zeros((B, T, 1, 1), np.float32),
        "turn_mask": alloc(c0["tmask"]),
        "observation_mask": alloc(c0["omask"]),
        "action_mask": alloc(c0["amask"], 1e32),
        "progress": alloc(c0["progress"], 1.0),
    }

    for b, c in enumerate(cores):
        lo, hi = c["pad_b"], c["pad_b"] + c["steps"]
        sl = slice(lo, hi)
        for dst, leaf in zip(
            jax.tree.leaves(out["observation"]), jax.tree.leaves(c["obs"])
        ):
            dst[b, sl] = leaf
        out["selected_prob"][b, sl] = c["prob"]
        out["value"][b, sl] = c["value"]
        out["value"][b, hi:] = c["outcome"]  # frozen at outcome past the end
        out["action"][b, sl] = c["action"]
        out["outcome"][b] = c["outcome"]
        out["reward"][b, sl] = c["reward"]
        out["return"][b, sl] = c["ret"]
        out["episode_mask"][b, sl] = 1.0
        out["turn_mask"][b, sl] = c["tmask"]
        out["observation_mask"][b, sl] = c["omask"]
        out["action_mask"][b, sl] = c["amask"]
        out["progress"][b, sl] = c["progress"]
    return out
