"""Fixed-shape training batch assembly from sampled episode windows.

Mask/padding semantics parity with reference make_batch (train.py:33-125):

* Base shape is (B, T, P, ...), T always exactly ``burn_in_steps +
  forward_steps`` (XLA needs static shapes; the reference only pads short
  windows, we always emit the same shape).
* In turn-based training without observers, the *actor-side* arrays
  (observation / selected_prob / action / action_mask) carry only the
  turn player (P dim = 1) gathered per step, while *target-side* arrays
  (value / reward / return / masks / outcome) keep every player — the
  turn player's prediction is later broadcast against the full-player
  turn mask (see parallel/train_step.py and train.py:177-186).
* Padding: before the window (burn-in underflow) everything is zero;
  after episode end values become the final outcome, selected_prob 1,
  action_mask all-illegal (1e32), progress 1, episode_mask 0.

Episode columnar format (produced by runtime/generation.py):
  blocks[k] decompresses to a dict of arrays over t timesteps:
    obs    pytree, leaves (t, P, ...)
    prob   (t, P)   behavior probability of the selected action (1 if none)
    action (t, P)   int32
    amask  (t, P, A) 0 = legal / 1e32 = illegal (all-1e32 when not acting)
    value  (t, P)   critic estimate at acting time (0 when unobserved)
    reward (t, P)   immediate reward after the step
    ret    (t, P)   discounted return-to-go
    tmask  (t, P)   1 if the player acted this step
    omask  (t, P)   1 if the player observed this step
    turn   (t,)     index (into players) of the first turn player
"""

from __future__ import annotations

import random
from typing import Any, Dict, List

import numpy as np

from ..utils import tree_concat, tree_map, tree_stack
from .replay import decompress_block


def _concat_columns(blocks: List[Dict[str, Any]]) -> Dict[str, Any]:
    if len(blocks) == 1:
        return blocks[0]
    out = {
        key: np.concatenate([b[key] for b in blocks], axis=0)
        for key in blocks[0]
        if key != "obs"
    }
    out["obs"] = tree_concat([b["obs"] for b in blocks])
    return out


def _assemble_one(window: Dict[str, Any], args: Dict[str, Any]) -> Dict[str, Any]:
    cols = _concat_columns([decompress_block(b) for b in window["blocks"]])
    lo = window["start"] - window["base"]
    hi = window["end"] - window["base"]
    sl = slice(lo, hi)

    turn_based = args["turn_based_training"]
    num_players = cols["prob"].shape[1]
    if turn_based:
        target_players = list(range(num_players))
    else:
        target_players = [random.randrange(num_players)]

    obs = tree_map(lambda x: x[sl], cols["obs"])
    prob = cols["prob"][sl]
    action = cols["action"][sl]
    amask = cols["amask"][sl]

    if turn_based and not args["observation"]:
        # Actor-side arrays: gather the turn player per step -> P dim 1.
        turn = cols["turn"][sl]
        t_idx = np.arange(len(turn))
        obs = tree_map(lambda x: x[t_idx, turn][:, None], obs)
        prob = prob[t_idx, turn][:, None]
        action = action[t_idx, turn][:, None]
        amask = amask[t_idx, turn][:, None]
    else:
        obs = tree_map(lambda x: x[:, target_players], obs)
        prob = prob[:, target_players]
        action = action[:, target_players]
        amask = amask[:, target_players]

    value = cols["value"][sl][:, target_players, None]
    reward = cols["reward"][sl][:, target_players, None]
    ret = cols["ret"][sl][:, target_players, None]
    tmask = cols["tmask"][sl][:, target_players, None].astype(np.float32)
    omask = cols["omask"][sl][:, target_players, None].astype(np.float32)
    outcome = np.asarray(window["outcome"], dtype=np.float32)[target_players].reshape(1, -1, 1)

    steps = hi - lo
    emask = np.ones((steps, 1, 1), dtype=np.float32)
    progress = (np.arange(window["start"], window["end"], dtype=np.float32) / window["total"])[:, None]

    prob = prob[..., None]
    action = action[..., None].astype(np.int32)

    batch_steps = args["burn_in_steps"] + args["forward_steps"]
    if steps < batch_steps:
        pad_b = args["burn_in_steps"] - (window["train_start"] - window["start"])
        pad_a = batch_steps - steps - pad_b

        def pad(x, value=0.0):
            width = [(pad_b, pad_a)] + [(0, 0)] * (x.ndim - 1)
            return np.pad(x, width, constant_values=value)

        obs = tree_map(pad, obs)
        prob = pad(prob, 1.0)
        action = pad(action, 0)
        amask = pad(amask, 1e32)
        # value: zero before the window, frozen at the outcome after the end
        value = np.concatenate(
            [np.pad(value, [(pad_b, 0), (0, 0), (0, 0)]), np.tile(outcome, (pad_a, 1, 1))]
        )
        reward = pad(reward)
        ret = pad(ret)
        tmask = pad(tmask)
        omask = pad(omask)
        emask = pad(emask)
        progress = pad(progress, 1.0)

    return {
        "observation": obs,
        "selected_prob": prob.astype(np.float32),
        "value": value.astype(np.float32),
        "action": action,
        "outcome": outcome,
        "reward": reward.astype(np.float32),
        "return": ret.astype(np.float32),
        "episode_mask": emask,
        "turn_mask": tmask,
        "observation_mask": omask,
        "action_mask": amask.astype(np.float32),
        "progress": progress.astype(np.float32),
    }


def make_batch(windows: List[Dict[str, Any]], args: Dict[str, Any]) -> Dict[str, Any]:
    """Assemble B sampled windows into one (B, T, P, ...) numpy batch."""
    return tree_stack([_assemble_one(w, args) for w in windows])
