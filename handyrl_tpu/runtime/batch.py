"""Fixed-shape training batch assembly from sampled episode windows.

Mask/padding semantics parity with reference make_batch (train.py:33-125):

* Base shape is (B, T, P, ...), T always exactly ``burn_in_steps +
  forward_steps`` (XLA needs static shapes; the reference only pads short
  windows, we always emit the same shape).
* In turn-based training without observers, the *actor-side* arrays
  (observation / selected_prob / action / action_mask) carry only the
  turn player (P dim = 1) gathered per step, while *target-side* arrays
  (value / reward / return / masks / outcome) keep every player — the
  turn player's prediction is later broadcast against the full-player
  turn mask (see parallel/train_step.py and train.py:177-186).
* Padding: before the window (burn-in underflow) everything is zero;
  after episode end values become the final outcome, selected_prob 1,
  action_mask all-illegal (1e32), progress 1, episode_mask 0.

Episode columnar format (produced by runtime/generation.py):
  blocks[k] decompresses to a dict of arrays over t timesteps:
    obs    pytree, leaves (t, P, ...)
    prob   (t, P)   behavior probability of the selected action (1 if none)
    action (t, P)   int32
    amask  (t, P, A) 0 = legal / 1e32 = illegal (all-1e32 when not acting)
    value  (t, P)   critic estimate at acting time (0 when unobserved)
    reward (t, P)   immediate reward after the step
    ret    (t, P)   discounted return-to-go
    tmask  (t, P)   1 if the player acted this step
    omask  (t, P)   1 if the player observed this step
    turn   (t,)     index (into players) of the first turn player
"""

from __future__ import annotations

import os
import random
from typing import Any, Dict, List, Optional

import jax
import numpy as np

from ..utils import tree_concat, tree_map
from . import codec
from .replay import decompress_block


def _fill_accel():
    """The C fast path for the per-window columnar fill (fill_window /
    fill_rows in _codec_accel.c), or None.  Rides the codec accelerator's
    build/load decision; ``HANDYRL_NO_FILL_ACCEL=1`` forces the numpy
    path independently (parity tests flip exactly this switch)."""
    if os.environ.get("HANDYRL_NO_FILL_ACCEL", "").strip().lower() not in (
        "", "0", "false", "no",
    ):
        return None
    acc = codec.get_accel()
    if acc is not None and all(
        hasattr(acc, sym) for sym in ("fill_rows", "fill_column")
    ):
        return acc
    return None


_ACCEL = _fill_accel()


def _broadcast_rows(dst: np.ndarray, b: int, lo: int, hi: int, row: np.ndarray) -> None:
    """dst[b, lo:hi] = row (one row broadcast across hi-lo steps)."""
    if hi <= lo:
        return
    if (
        _ACCEL is not None
        and dst.dtype == row.dtype
        and row.shape == dst.shape[2:]
        and dst.flags.c_contiguous
        and row.flags.c_contiguous
    ):
        _ACCEL.fill_rows(dst, b, lo, hi, row)
    else:
        dst[b, lo:hi] = row


def _fill_column(dst: np.ndarray, los: List[int], srcs: List[np.ndarray]) -> None:
    """dst[b, los[b]:los[b]+len(srcs[b])] = srcs[b] for every window b.

    One C call per COLUMN (destination buffer acquired once, then a plain
    memcpy per window) — per-window C calls pay two buffer-protocol
    acquisitions each, which measures SLOWER than numpy's fancy-index
    assignment on large columns.  Dtype/layout uniformity within a column
    is a pipeline invariant, so only srcs[0] is pre-checked; the C kernel
    still validates every src's shape/itemsize/bounds (memory safety) and
    any violation falls back to the numpy loop, which re-raises genuine
    shape bugs.  BufferError/TypeError cover what the kernel raises for
    a non-contiguous later src or a non-int lo — same fallback."""
    if (
        _ACCEL is not None
        and srcs
        and dst.dtype == srcs[0].dtype
        and dst.flags.c_contiguous
        and srcs[0].flags.c_contiguous
    ):
        try:
            _ACCEL.fill_column(dst, los, srcs)
            return
        except (ValueError, TypeError, BufferError):
            pass
    for b, (lo, src) in enumerate(zip(los, srcs)):
        dst[b, lo : lo + src.shape[0]] = src


def _concat_columns(blocks: List[Dict[str, Any]]) -> Dict[str, Any]:
    if len(blocks) == 1:
        return blocks[0]
    out = {
        key: np.concatenate([b[key] for b in blocks], axis=0)
        for key in blocks[0]
        if key != "obs"
    }
    out["obs"] = tree_concat([b["obs"] for b in blocks])
    return out


def _assemble_one(window: Dict[str, Any], args: Dict[str, Any]) -> Dict[str, Any]:
    cols = _concat_columns([decompress_block(b) for b in window["blocks"]])
    lo = window["start"] - window["base"]
    hi = window["end"] - window["base"]
    sl = slice(lo, hi)

    turn_based = args["turn_based_training"]
    num_players = cols["prob"].shape[1]
    if turn_based:
        target_players = list(range(num_players))
    else:
        target_players = [random.randrange(num_players)]

    obs = tree_map(lambda x: x[sl], cols["obs"])
    prob = cols["prob"][sl]
    action = cols["action"][sl]
    amask = cols["amask"][sl]

    if turn_based and not args["observation"]:
        # Actor-side arrays: gather the turn player per step -> P dim 1.
        turn = cols["turn"][sl]
        t_idx = np.arange(len(turn))
        obs = tree_map(lambda x: x[t_idx, turn][:, None], obs)
        prob = prob[t_idx, turn][:, None]
        action = action[t_idx, turn][:, None]
        amask = amask[t_idx, turn][:, None]
    else:
        obs = tree_map(lambda x: x[:, target_players], obs)
        prob = prob[:, target_players]
        action = action[:, target_players]
        amask = amask[:, target_players]

    value = cols["value"][sl][:, target_players, None]
    reward = cols["reward"][sl][:, target_players, None]
    ret = cols["ret"][sl][:, target_players, None]
    tmask = cols["tmask"][sl][:, target_players, None].astype(np.float32)
    omask = cols["omask"][sl][:, target_players, None].astype(np.float32)
    outcome = np.asarray(window["outcome"], dtype=np.float32)[target_players].reshape(1, -1, 1)

    steps = hi - lo
    progress = (np.arange(window["start"], window["end"], dtype=np.float32) / window["total"])[:, None]

    prob = prob[..., None]
    action = action[..., None].astype(np.int32)

    pad_b = 0
    if steps < args["burn_in_steps"] + args["forward_steps"]:
        pad_b = args["burn_in_steps"] - (window["train_start"] - window["start"])

    return {
        "pad_b": pad_b,
        "steps": steps,
        "obs": obs,
        "prob": prob,
        "value": value,
        "action": action,
        "outcome": outcome,
        "reward": reward,
        "ret": ret,
        "tmask": tmask,
        "omask": omask,
        "amask": amask,
        "progress": progress,
    }


# per-key default values: these ARE the padding semantics (zeros before
# the window; after episode end selected_prob 1, action_mask all-illegal
# 1e32, progress 1, episode_mask 0, value frozen at the outcome by an
# explicit fill-pass write).  Shared by the allocating path (make_batch)
# and the slot-reset path (fill_batch into a reused shared-memory slot).
_KEY_DEFAULTS = {"selected_prob": 1.0, "action_mask": 1e32, "progress": 1.0}


def _alloc_out(c0: Dict[str, Any], B: int, T: int) -> Dict[str, Any]:
    def alloc(leaf, fill=0.0, dtype=np.float32):
        shape = (B, T) + tuple(leaf.shape[1:])
        if fill == 0.0:
            return np.zeros(shape, dtype)
        return np.full(shape, fill, dtype)

    return {
        "observation": tree_map(lambda x: alloc(x, 0.0, x.dtype), c0["obs"]),
        "selected_prob": alloc(c0["prob"], _KEY_DEFAULTS["selected_prob"]),
        "value": alloc(c0["value"]),
        "action": alloc(c0["action"], 0, np.int32),
        "outcome": np.zeros((B, 1) + tuple(c0["outcome"].shape[1:]), np.float32),
        "reward": alloc(c0["reward"]),
        "return": alloc(c0["ret"]),
        "episode_mask": np.zeros((B, T, 1, 1), np.float32),
        "turn_mask": alloc(c0["tmask"]),
        "observation_mask": alloc(c0["omask"]),
        "action_mask": alloc(c0["amask"], _KEY_DEFAULTS["action_mask"]),
        "progress": alloc(c0["progress"], _KEY_DEFAULTS["progress"]),
    }


def reset_out(out: Dict[str, Any]) -> None:
    """Restore a preallocated/reused output batch to the padding defaults
    (what a fresh _alloc_out would hold) — required before every
    fill into a recycled shared-memory slot."""
    for key, arr in out.items():
        if key == "observation":
            for leaf in jax.tree.leaves(arr):
                leaf.fill(0)
        else:
            arr.fill(_KEY_DEFAULTS.get(key, 0.0))


_COLUMN_FIELDS = (
    ("selected_prob", "prob"),
    ("value", "value"),
    ("action", "action"),
    ("reward", "reward"),
    ("return", "ret"),
    ("turn_mask", "tmask"),
    ("observation_mask", "omask"),
    ("action_mask", "amask"),
    ("progress", "progress"),
)


def _fill_out(out: Dict[str, Any], cores: List[Dict[str, Any]], T: int) -> None:
    los = [c["pad_b"] for c in cores]
    obs_dsts = jax.tree.leaves(out["observation"])
    obs_srcs = [jax.tree.leaves(c["obs"]) for c in cores]
    for i, dst in enumerate(obs_dsts):
        _fill_column(dst, los, [leaves[i] for leaves in obs_srcs])
    for out_key, core_key in _COLUMN_FIELDS:
        _fill_column(out[out_key], los, [c[core_key] for c in cores])
    _fill_column(out["outcome"], [0] * len(cores), [c["outcome"] for c in cores])
    for b, c in enumerate(cores):
        lo, hi = los[b], los[b] + c["steps"]
        # value frozen at the outcome past episode end (AFTER the column
        # fill above, which wrote the in-window values)
        _broadcast_rows(out["value"], b, hi, T, c["outcome"][0])
        out["episode_mask"][b, lo:hi] = 1.0


def make_batch(
    windows: List[Dict[str, Any]],
    args: Dict[str, Any],
    out: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """Assemble B sampled windows into one (B, T, P, ...) numpy batch.

    Each window writes its unpadded slice directly into preallocated
    output arrays whose defaults ARE the padding semantics (zeros before
    the window; after episode end selected_prob 1, action_mask all-illegal
    1e32, value frozen at the outcome, progress 1, episode_mask 0) — one
    allocation + one copy per key instead of the np.pad-per-array +
    tree_stack version this replaces, which dominated the host-side batch
    assembly profile and starved the learner on HungryGeese-sized
    observations.  The per-window copies go through the C fill kernels
    (_codec_accel.c fill_window/fill_rows) when available.

    ``out``: a preallocated batch dict (e.g. numpy views over a
    shared-memory ring slot, runtime/shm_batch.py) to fill IN PLACE
    instead of allocating; it is reset to the padding defaults first so
    a recycled slot carries no previous batch's rows.
    """
    B = len(windows)
    T = args["burn_in_steps"] + args["forward_steps"]
    cores = [_assemble_one(w, args) for w in windows]
    if out is None:
        out = _alloc_out(cores[0], B, T)
    else:
        reset_out(out)
    _fill_out(out, cores, T)
    return out


def fill_batch(
    windows: List[Dict[str, Any]], args: Dict[str, Any], out: Dict[str, Any]
) -> Dict[str, Any]:
    """make_batch into a preallocated output (shared-memory slot views)."""
    return make_batch(windows, args, out=out)
