"""Pickle-free binary wire codec for the distributed actor plane.

The reference moves pickled python objects between machines
(handyrl/connection.py:45-69) — including pickled ``nn.Module``s, i.e.
code-execution-by-pickle between trusted nodes (SURVEY.md §2.5).  Here the
wire vocabulary is closed: None/bool/int/float/str/bytes/list/tuple/dict
(any encodable keys) and numpy arrays (raw buffer + dtype/shape header, no
object dtypes).  Model parameters travel as flax-msgpack byte blobs
(runtime/checkpoint.py:35-40), never as code.

Format: one tag byte per value, big-endian fixed-width lengths.  Arrays
are C-contiguous raw buffers, so encode/decode is O(bytes) memcpy — the
host-side framing never touches the device path.

Two interchangeable implementations share the format: this pure-Python
module (the specification, and the fallback) and a C extension
(`_codec_accel.c`, compiled on first import by `_codec_build.py`) that
removes the per-small-object overhead dominating episode-block encoding
on 1-core actor hosts.  ``dumps``/``loads`` dispatch to the accelerator
when it loaded; ``HANDYRL_NO_CODEC_ACCEL=1`` forces pure Python.
Cross-implementation byte-equality is pinned by tests/test_distributed.py.
"""

from __future__ import annotations

import os
import struct
from typing import Any

import numpy as np

_U32 = struct.Struct("!I")
_I64 = struct.Struct("!q")
_F64 = struct.Struct("!d")


class CodecError(ValueError):
    pass


# shared with the C accelerator (MAX_DEPTH in _codec_accel.c): both
# implementations must accept and reject the same nesting, or a frame
# encoded on an accelerated host would fail to decode on a fallback host
# (and deep nesting must surface as CodecError, not RecursionError, so
# connection loops handle it)
_MAX_DEPTH = 500


def _pack_u32(n: int) -> bytes:
    """Length header pack that fails the same way the C accelerator does:
    a >= 2**32 str/bytes/array/container length must raise CodecError on
    BOTH implementations (the accelerator's enc_len_u32 does; bare
    _U32.pack would let struct.error escape from the fallback host)."""
    try:
        return _U32.pack(n)
    except struct.error as exc:
        raise CodecError(f"length out of u32 range: {n}") from exc


def _encode(obj: Any, out: list, depth: int = 0) -> None:
    if depth > _MAX_DEPTH:
        raise CodecError("nesting too deep")
    if obj is None:
        out.append(b"N")
    elif obj is True:
        out.append(b"T")
    elif obj is False:
        out.append(b"F")
    elif isinstance(obj, int):
        out.append(b"i")
        try:
            out.append(_I64.pack(obj))
        except struct.error as exc:
            raise CodecError(f"int out of i64 range: {obj}") from exc
    elif isinstance(obj, float):
        out.append(b"f")
        out.append(_F64.pack(obj))
    elif isinstance(obj, str):
        raw = obj.encode("utf-8")
        out.append(b"s")
        out.append(_pack_u32(len(raw)))
        out.append(raw)
    elif isinstance(obj, (bytes, bytearray, memoryview)):
        raw = bytes(obj)
        out.append(b"b")
        out.append(_pack_u32(len(raw)))
        out.append(raw)
    elif isinstance(obj, np.ndarray):
        if obj.dtype.hasobject:
            raise CodecError("object-dtype arrays are not wire-encodable")
        shape = obj.shape  # before ascontiguousarray, which promotes 0-d to 1-d
        arr = np.ascontiguousarray(obj)
        dt = arr.dtype.str.encode("ascii")
        out.append(b"a")
        out.append(_pack_u32(len(dt)))
        out.append(dt)
        out.append(_pack_u32(len(shape)))
        for d in shape:
            out.append(_pack_u32(d))
        raw = arr.tobytes()
        out.append(_pack_u32(len(raw)))
        out.append(raw)
    elif isinstance(obj, (np.bool_, np.integer, np.floating)):
        _encode(obj.item(), out, depth + 1)
    elif isinstance(obj, list):
        out.append(b"l")
        out.append(_pack_u32(len(obj)))
        for item in obj:
            _encode(item, out, depth + 1)
    elif isinstance(obj, tuple):
        out.append(b"t")
        out.append(_pack_u32(len(obj)))
        for item in obj:
            _encode(item, out, depth + 1)
    elif isinstance(obj, dict):
        out.append(b"d")
        out.append(_pack_u32(len(obj)))
        for key, value in obj.items():
            _encode(key, out, depth + 1)
            _encode(value, out, depth + 1)
    else:
        raise CodecError(f"type {type(obj).__name__} is not wire-encodable")


def py_dumps(obj: Any) -> bytes:
    out: list = []
    _encode(obj, out)
    return b"".join(out)


class _Reader:
    __slots__ = ("buf", "pos")

    def __init__(self, buf: bytes):
        self.buf = buf
        self.pos = 0

    def take(self, n: int) -> bytes:
        end = self.pos + n
        if end > len(self.buf):
            raise CodecError("truncated message")
        raw = self.buf[self.pos : end]
        self.pos = end
        return raw

    def u32(self) -> int:
        return _U32.unpack(self.take(4))[0]


def _decode(r: _Reader, depth: int = 0) -> Any:
    if depth > _MAX_DEPTH:
        raise CodecError("nesting too deep")
    tag = r.take(1)
    if tag == b"N":
        return None
    if tag == b"T":
        return True
    if tag == b"F":
        return False
    if tag == b"i":
        return _I64.unpack(r.take(8))[0]
    if tag == b"f":
        return _F64.unpack(r.take(8))[0]
    if tag == b"s":
        return r.take(r.u32()).decode("utf-8")
    if tag == b"b":
        return r.take(r.u32())
    if tag == b"a":
        dt = np.dtype(r.take(r.u32()).decode("ascii"))
        shape = tuple(r.u32() for _ in range(r.u32()))
        raw = r.take(r.u32())
        return np.frombuffer(raw, dtype=dt).reshape(shape).copy()
    if tag == b"l":
        return [_decode(r, depth + 1) for _ in range(r.u32())]
    if tag == b"t":
        return tuple(_decode(r, depth + 1) for _ in range(r.u32()))
    if tag == b"d":
        return {_decode(r, depth + 1): _decode(r, depth + 1) for _ in range(r.u32())}
    raise CodecError(f"unknown tag {tag!r}")


def py_loads(buf: bytes) -> Any:
    r = _Reader(bytes(buf))
    try:
        obj = _decode(r)
    except CodecError:
        raise
    except Exception as exc:
        # a hostile/garbled frame must surface as CodecError so connection
        # receive loops (which catch CodecError/OSError) drop the peer
        # instead of dying: np.dtype(<junk>) raises TypeError, frombuffer /
        # reshape size mismatches raise bare ValueError, unhashable decoded
        # dict keys raise TypeError
        raise CodecError(f"malformed frame: {type(exc).__name__}: {exc}") from exc
    if r.pos != len(r.buf):
        raise CodecError("trailing bytes after message")
    return obj


# -- accelerator dispatch ----------------------------------------------------

def _accel_disabled() -> bool:
    # conventional boolean parsing: "0"/"false"/empty mean the switch is
    # OFF (accelerator stays on) — bare truthiness would read "=0" as
    # disable, the opposite of what an operator means by it
    return os.environ.get("HANDYRL_NO_CODEC_ACCEL", "").strip().lower() not in (
        "", "0", "false", "no",
    )


_accel = None
if not _accel_disabled():
    try:
        from . import _codec_build

        _accel = _codec_build.load()
        _accel.init(CodecError, np)
    except Exception:  # no compiler / read-only fs / exotic platform
        _accel = None

dumps = _accel.dumps if _accel is not None else py_dumps
loads = _accel.loads if _accel is not None else py_loads


def get_accel():
    """The loaded C accelerator module, or None when running pure Python.

    Other runtime modules (batch.py's columnar fill) dispatch through this
    instead of importing _codec_build themselves, so there is exactly one
    build/load/disable decision for the whole process."""
    return _accel
