"""Quality plane: live win-rate ledger, promotion gate, quality sentinel.

Three cooperating pieces, all serving-side (the training side only reads
the signal files):

* :class:`QualityLedger` — per-snapshot live outcome books.  Reuses the
  league's :class:`PayoffMatrix` (every serving epoch plays the pseudo-
  member ``"live"``) plus a windowed EMA per epoch, and emits the
  ``quality_wp{epoch}`` metric family.

* :class:`QualityController` — replaces the router's bare
  ``maybe_refresh`` watcher when gating is on.  A new verified snapshot
  is STAGED as a candidate route (``router.stage`` — resident and
  addressable, but ``latest`` does not flip); the server shadow-routes a
  ``flywheel.shadow_fraction`` slice of latest-addressed traffic to it;
  once ``promote_games`` live games are on the candidate's books the
  verdict is read off the ledger: win points ≥ ``promote_winrate`` flips
  ``latest`` (``router.promote_candidate``), anything less demotes the
  candidate and records a gate failure.  With gating off the controller
  degrades to exactly the old immediate-flip ``maybe_refresh`` path.

* the quality **sentinel** — after a promotion the displaced incumbent
  stays resident and pinned.  If the promoted snapshot's live EMA sinks
  more than ``demote_drop`` below the incumbent's baseline (the serving
  analogue of PR 5's loss-EMA spike bound), the router demotes back to
  the incumbent and a rollback signal is written for the trainer
  (``FLYWHEEL_ROLLBACK.json``, consumed by ``Trainer.request_rollback``
  via the learner's epoch hook).  The watch is a bounded canary, not an
  indefinite tribunal: a promotion that holds its quality through
  ``4 * quality_window`` live games is confirmed and the watch ends.

Signal files live in the model dir and are written with the checkpoint
plane's ``atomic_write_bytes`` — a reader sees an old complete file or a
new complete file, never a torn one.  ``SERVING.json`` additionally
feeds ``serving_pinned_epochs`` so ``gc_snapshots`` can never collect
the live incumbent/candidate out from under the serving tier.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Dict, Optional, Set

from ..league.matchmaker import PayoffMatrix
from ..runtime.checkpoint import (
    CheckpointError,
    atomic_write_bytes,
    latest_verified_epoch,
    load_verified_params,
)

__all__ = [
    "QualityLedger",
    "QualityController",
    "ROLLBACK_FILE",
    "SERVING_FILE",
    "read_rollback_signal",
    "write_rollback_signal",
    "read_serving_state",
    "write_serving_state",
    "serving_pinned_epochs",
]

ROLLBACK_FILE = "FLYWHEEL_ROLLBACK.json"
SERVING_FILE = "SERVING.json"


# -- cross-process signal files (serving tier -> trainer / GC) ----------------

def write_rollback_signal(model_dir: str, bad_epoch: int, target_epoch: int,
                          reason: str) -> int:
    """Tell the training side that ``bad_epoch`` regressed on live traffic
    and the verified ``target_epoch`` is the landing point.  ``seq`` is
    monotone so the learner can adopt each signal exactly once (it
    baselines the seq it finds at startup).  Returns the seq written."""
    path = os.path.join(model_dir, ROLLBACK_FILE)
    prior = read_rollback_signal(model_dir)
    seq = (prior.get("seq", 0) if prior else 0) + 1
    atomic_write_bytes(path, json.dumps({
        "seq": seq,
        "bad_epoch": int(bad_epoch),
        "target_epoch": int(target_epoch),
        "reason": str(reason),
    }, indent=2).encode())
    return seq


def read_rollback_signal(model_dir: str) -> Optional[Dict[str, Any]]:
    path = os.path.join(model_dir, ROLLBACK_FILE)
    try:
        with open(path, "r") as f:
            data = json.load(f)
    except FileNotFoundError:
        return None
    except (ValueError, OSError) as exc:
        # writes are atomic, so this is real corruption — say so loudly
        # but do not kill the reader (the signal plane is advisory)
        print(f"flywheel: unreadable rollback signal {path}: {exc}")
        return None
    return data if isinstance(data, dict) else None


def write_serving_state(model_dir: str, latest: Optional[int],
                        candidate: Optional[int],
                        incumbent: Optional[int]) -> None:
    """Publish which epochs the serving tier is ROUTING right now, for
    the GC pin (and operators).  Stale-on-crash is conservative: a dead
    server's last pins keep a few snapshots alive until it writes again."""
    atomic_write_bytes(
        os.path.join(model_dir, SERVING_FILE),
        json.dumps({
            "latest": latest, "candidate": candidate, "incumbent": incumbent,
        }, indent=2).encode(),
    )


def read_serving_state(model_dir: str) -> Optional[Dict[str, Any]]:
    try:
        with open(os.path.join(model_dir, SERVING_FILE), "r") as f:
            data = json.load(f)
    except (FileNotFoundError, ValueError, OSError):
        return None
    return data if isinstance(data, dict) else None


def serving_pinned_epochs(model_dir: str) -> Set[int]:
    """Epochs ``gc_snapshots`` must NOT collect because the serving tier
    is routing them: the live latest, a staged candidate, and the
    incumbent a promotion displaced (the sentinel's demote target — losing
    it would turn a quality demote into a cold resurrection-from-nothing)."""
    state = read_serving_state(model_dir) or {}
    pinned: Set[int] = set()
    for key in ("latest", "candidate", "incumbent"):
        value = state.get(key)
        if isinstance(value, int) and value > 0:
            pinned.add(value)
    return pinned


# -- live outcome books -------------------------------------------------------

def _name(epoch: int) -> str:
    return f"epoch_{int(epoch)}"


class QualityLedger:
    """Per-snapshot live outcome tracking.

    Outcomes arrive in the env convention ([-1, 1], higher is better) and
    are folded to win points in [0, 1].  Two views per epoch: exact win
    points over all recorded games (the promotion gate's verdict — a
    fresh candidate must not inherit smoothing lag), and an EMA with
    ``alpha = 2 / (window + 1)`` (the sentinel's drift detector, same
    smoothing family as the trainer's loss EMA)."""

    def __init__(self, window: int = 32):
        self.window = max(1, int(window))
        self._alpha = 2.0 / (self.window + 1.0)
        self._matrix = PayoffMatrix()
        self._ema: Dict[int, float] = {}
        # own cumulative game count: PayoffMatrix.matches only counts
        # whole matches recorded through record_outcome/record_forfeit,
        # not the per-game record_score entries this ledger books
        self._games = 0
        self._lock = threading.Lock()

    def record(self, epoch: int, outcome: float) -> None:
        epoch = int(epoch)
        if epoch <= 0:
            return  # id 0 is the fresh-init/random route — not a snapshot
        score = min(1.0, max(0.0, (float(outcome) + 1.0) / 2.0))
        with self._lock:
            self._matrix.record_score(_name(epoch), "live", score, 1.0 - score)
            self._games += 1
            prev = self._ema.get(epoch)
            self._ema[epoch] = (
                score if prev is None
                else prev + self._alpha * (score - prev)
            )

    def games(self, epoch: int) -> int:
        with self._lock:
            return self._matrix.games(_name(epoch), "live")

    def win_points(self, epoch: int) -> Optional[float]:
        with self._lock:
            return self._matrix.win_points(_name(epoch), "live")

    def ema(self, epoch: int) -> Optional[float]:
        with self._lock:
            return self._ema.get(int(epoch))

    def total_games(self) -> int:
        with self._lock:
            return self._games

    def snapshot(self) -> Dict[str, float]:
        """The ``quality_wp{epoch}`` windowed metric family."""
        with self._lock:
            out: Dict[str, float] = {}
            for epoch in self._ema:
                wp = self._matrix.win_points(_name(epoch), "live")
                if wp is not None:
                    out[f"quality_wp{epoch}"] = wp
            return out


# -- promotion gate + quality sentinel ----------------------------------------

class QualityController:
    """Drives the router from live quality verdicts.  ``tick()`` replaces
    the server watch loop's bare ``router.maybe_refresh()``; everything
    else is event-driven off ``record_outcome``."""

    def __init__(self, router, model_dir: str, cfg: Dict[str, Any],
                 ledger: Optional[QualityLedger] = None):
        self.router = router
        self.model_dir = model_dir
        self.gate = bool(cfg.get("gate_promotions", True))
        self.promote_winrate = float(cfg.get("promote_winrate", 0.55))
        self.promote_games = int(cfg.get("promote_games", 16))
        self.quality_window = int(cfg.get("quality_window", 32))
        self.demote_drop = float(cfg.get("demote_drop", 0.15))
        self.shadow_fraction = float(cfg.get("shadow_fraction", 0.25))
        self.ledger = ledger or QualityLedger(self.quality_window)
        self._lock = threading.Lock()
        # candidate bookkeeping: games already on the books when staged,
        # so the verdict counts only games the candidate actually served
        self._candidate_base = 0
        self._rejected: Set[int] = set()
        # sentinel baseline: (promoted_epoch, incumbent_wp_at_promotion)
        self._watch_epoch: Optional[int] = None
        self._baseline: Optional[float] = None
        self._watch_base_games = 0
        self.promotions = 0
        self.gate_failures = 0
        self.demotions = 0

    # server seam: every game-final outcome report lands here
    def record_outcome(self, epoch: Any, outcome: Any) -> None:
        try:
            self.ledger.record(int(epoch), float(outcome))
        except (TypeError, ValueError):
            raise ValueError(
                f"report_outcome needs an int epoch and a float outcome, "
                f"got {epoch!r} / {outcome!r}"
            )

    def candidate_id(self) -> Optional[int]:
        return self.router.candidate_id()

    # -- the watcher body -----------------------------------------------------

    def tick(self) -> Optional[str]:
        """One watch-loop beat.  Returns a human-readable event string when
        something happened (staged/promoted/gate_failed/demoted), else
        None.  Never raises: the watch loop must outlive a torn disk."""
        try:
            event = self._tick_inner()
        except Exception as exc:
            print(f"flywheel: quality tick failed: {exc}")
            return None
        try:
            write_serving_state(
                self.model_dir,
                self.router.latest_id(),
                self.router.candidate_id(),
                self.router.incumbent_id(),
            )
        except OSError as exc:
            print(f"flywheel: serving-state write failed: {exc}")
        return event

    def _tick_inner(self) -> Optional[str]:
        if not self.gate:
            published = self.router.maybe_refresh()
            return f"published epoch {published}" if published else None

        candidate = self.router.candidate_id()
        if candidate is None:
            return self._maybe_stage()
        return self._judge(candidate)

    def _maybe_stage(self) -> Optional[str]:
        newest = latest_verified_epoch(self.model_dir)
        current = self.router.latest_id() or 0
        if newest <= 0 or newest <= current or newest in self._rejected:
            return self._sentinel()
        try:
            params = load_verified_params(
                self.model_dir, newest, self.router._params_template(),
                pre_verified=True,
            )
        except CheckpointError as exc:
            print(f"flywheel: refusing to stage epoch {newest}: {exc}")
            return self._sentinel()
        self.router.stage(newest, params)
        with self._lock:
            self._candidate_base = self.ledger.games(newest)
        return f"staged candidate epoch {newest}"

    def _judge(self, candidate: int) -> Optional[str]:
        games = self.ledger.games(candidate) - self._candidate_base
        if games < self.promote_games:
            return self._sentinel()
        wp = self.ledger.win_points(candidate)
        incumbent = self.router.latest_id()
        if wp is not None and wp >= self.promote_winrate:
            # baseline for the sentinel: what the incumbent was actually
            # scoring when it was displaced; a fresh serve with no books
            # falls back to the bar the candidate just cleared
            baseline = (
                self.ledger.ema(incumbent) if incumbent else None
            )
            self.router.promote_candidate()
            with self._lock:
                self._watch_epoch = candidate
                self._baseline = baseline if baseline is not None else self.promote_winrate
                self._watch_base_games = self.ledger.games(candidate)
                self.promotions += 1
            return (
                f"promoted epoch {candidate} (wp {wp:.3f} >= "
                f"{self.promote_winrate} over {games} games)"
            )
        self.router.demote_candidate()
        with self._lock:
            self._rejected.add(candidate)
            self.gate_failures += 1
        write_rollback_signal(
            self.model_dir, candidate, incumbent or 0, "gate_failed"
        )
        return (
            f"gate failed for epoch {candidate} (wp "
            f"{-1.0 if wp is None else wp:.3f} < {self.promote_winrate} "
            f"over {games} games)"
        )

    def _sentinel(self) -> Optional[str]:
        """Demote a promoted snapshot whose live quality degraded past the
        drop bound — the serving analogue of the divergence sentinel."""
        with self._lock:
            watch, baseline, base_games = (
                self._watch_epoch, self._baseline, self._watch_base_games
            )
        if watch is None or baseline is None:
            return None
        if self.router.latest_id() != watch or self.router.incumbent_id() is None:
            return None  # already demoted / superseded — stop watching
        games = self.ledger.games(watch) - base_games
        if games < self.quality_window:
            return None
        live = self.ledger.ema(watch)
        if live is None or live >= baseline - self.demote_drop:
            # canary confirmation: a promotion that holds its quality
            # through 4 EMA windows of live games is CONFIRMED and the
            # watch ends.  An unbounded watch would eventually demote
            # every promotion — an EMA random-walks below any sub-mean
            # bar given enough games — and each false demote costs a
            # training-side rollback, so the churn compounds
            if games >= 4 * self.quality_window:
                with self._lock:
                    if self._watch_epoch == watch:
                        self._watch_epoch = None
                        self._baseline = None
            return None
        incumbent = self.router.incumbent_id()
        self.router.demote_latest()
        with self._lock:
            self._rejected.add(watch)
            self._watch_epoch = None
            self._baseline = None
            self.demotions += 1
        seq = write_rollback_signal(
            self.model_dir, watch, incumbent or 0, "quality_regression"
        )
        return (
            f"quality regression: demoted epoch {watch} (live wp "
            f"{live:.3f} < baseline {baseline:.3f} - {self.demote_drop}), "
            f"restored incumbent {incumbent}, rollback signal seq {seq}"
        )

    # -- metrics --------------------------------------------------------------

    def stats_record(self) -> Dict[str, float]:
        with self._lock:
            record: Dict[str, float] = {
                "quality_promotions": self.promotions,
                "quality_gate_failures": self.gate_failures,
                "quality_demotions": self.demotions,
                "quality_games": self.ledger.total_games(),
                "quality_candidate": self.router.candidate_id() or 0,
                "quality_incumbent": self.router.incumbent_id() or 0,
            }
        record.update(self.ledger.snapshot())
        return record
