"""Quality-guarded data flywheel: served traffic back into training.

The production loop before this package flowed one way — the manifest
watcher hot-swapped training checkpoints into serving, and served
episodes were discarded.  The flywheel closes the circle with guards at
every seam:

* ``harvest.py`` — the serving server assembles per-session transitions
  into complete Generator-format episodes (shared ``finalize_episode``
  recipe, bit-identical to self-play encoding) for the learner to pull;
* ``quality.py`` — per-snapshot live win-rate ledger, the promotion gate
  (a new checkpoint must beat ``flywheel.promote_winrate`` over
  ``promote_games`` live games before ``latest`` flips), and the quality
  sentinel (a promoted snapshot that regresses is demoted serving-side
  and rolled back training-side);
* ``ingest.py`` — the learner-side pull loop with staleness/shape/budget
  guards feeding the standard ``feed_episodes`` path.

:class:`FlywheelPlane` is the serving server's single attachment point:
it owns the recorder and the controller, drives both from the server's
existing watch loop, and answers the harvest wire frames.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from .harvest import HarvestError, HarvestRecorder
from .ingest import HarvestIngestor
from .quality import (
    ROLLBACK_FILE,
    SERVING_FILE,
    QualityController,
    QualityLedger,
    read_rollback_signal,
    read_serving_state,
    serving_pinned_epochs,
    write_rollback_signal,
    write_serving_state,
)

__all__ = [
    "FlywheelPlane",
    "HarvestError",
    "HarvestRecorder",
    "HarvestIngestor",
    "QualityController",
    "QualityLedger",
    "ROLLBACK_FILE",
    "SERVING_FILE",
    "read_rollback_signal",
    "write_rollback_signal",
    "read_serving_state",
    "write_serving_state",
    "serving_pinned_epochs",
]


class FlywheelPlane:
    """Everything the serving server needs, behind one object: harvest
    episode assembly, shadow-slice routing, the promotion gate and the
    quality sentinel.  Built by ``serve_main`` when ``flywheel.enabled``;
    when absent the server behaves exactly as before."""

    def __init__(self, router, model_dir: str, cfg: Dict[str, Any],
                 gen_args: Dict[str, Any], obs_spec_fn=None):
        self.cfg = dict(cfg)
        self.recorder = HarvestRecorder(
            gen_args,
            max_open=int(cfg.get("harvest_max_open", 256)),
            ttl_s=float(cfg.get("harvest_ttl_s", 600.0)),
            obs_spec_fn=obs_spec_fn,
        )
        self.quality = QualityController(router, model_dir, cfg)
        # deterministic shadow-slice accumulator (no RNG in the serve
        # path): every request that targets "latest" adds the fraction;
        # each time the accumulator crosses 1 one request shadows
        self._shadow_acc = 0.0

    # -- routing seam (server._do_infer) --------------------------------------

    def shadow_model(self, model_id: Any) -> Any:
        """Rewrite a latest-addressed request to the staged candidate for
        the configured traffic slice.  Pinned (explicit-epoch), ensemble
        and random requests pass through untouched — a client that pinned
        its game to one epoch must never be shadow-mixed mid-game."""
        if model_id not in (None, -1):
            return model_id
        candidate = self.quality.candidate_id()
        fraction = self.quality.shadow_fraction
        if candidate is None or fraction <= 0.0:
            return model_id
        self._shadow_acc += fraction
        if self._shadow_acc >= 1.0:
            self._shadow_acc -= 1.0
            return candidate
        return model_id

    # -- capture seams (server._do_infer / _reply) ----------------------------

    def capture_request(self, sid: Optional[str], obs: Any) -> None:
        self.recorder.capture_request(sid, obs)

    def capture_reply(self, sid: Optional[str], served: Any, out: Any) -> None:
        self.recorder.capture_reply(sid, served, out)

    # -- watch-loop beat -------------------------------------------------------

    def tick(self) -> Optional[str]:
        self.recorder.sweep()
        return self.quality.tick()

    # -- metrics ---------------------------------------------------------------

    def stats_record(self) -> Dict[str, float]:
        record: Dict[str, float] = dict(self.recorder.stats())
        record.update(self.quality.stats_record())
        return record
