"""Learner-side harvest ingest: pull served-traffic episodes into the
training rings.

The ingestor is a daemon thread next to the learner's gateway: it polls
the serving tier's ``harvest_pull`` endpoint, applies the learner-side
quality guards, and submits surviving episodes through the learner's own
request queue — so harvested episodes ride the exact same
``feed_episodes`` path as self-play (EpisodeStore extend, generation
books, epoch cadence), not a parallel one.

Learner-side guards (the serving side already dropped malformed and
truncated sessions):

* **staleness** — an episode served by a snapshot ``staleness_epochs``
  or more behind the CURRENT model epoch is off-policy garbage for the
  importance weights; dropped and counted (``flywheel_ingest_stale``);
* **shape** — a blob missing the episode contract (blocks/steps/args/
  outcome) is counted ``flywheel_ingest_malformed`` and dropped loudly;
* **budget** — with ``harvest_fraction < 1`` the ingestor submits at most
  ``round(fraction * update_episodes)`` episodes per model epoch, leaving
  the rest of the cadence to self-play; at 1.0 the feed is unthrottled
  (self-play-free operation, the flagship e2e mode).

Transport faults are survivable by design: the serving tier may start
after the learner, restart, or drain — the poll loop reconnects with
bounded backoff forever and only ever counts, never raises.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional

__all__ = ["HarvestIngestor"]

_REQUIRED_KEYS = ("args", "steps", "players", "outcome", "blocks")


class HarvestIngestor:
    """Polls a serving endpoint for harvested episodes and feeds them to
    the learner.

    ``submit(episodes)`` delivers a batch into the learner (blocking until
    accepted); ``current_epoch()`` reads the live model epoch for the
    staleness bound; ``make_client()`` builds a connected pull client
    exposing ``harvest_pull(max_episodes)`` and ``close()`` — injectable
    so tests run socket-free."""

    def __init__(
        self,
        cfg: Dict[str, Any],
        submit: Callable[[List[Dict[str, Any]]], None],
        current_epoch: Callable[[], int],
        make_client: Callable[[], Any],
    ):
        self.staleness_epochs = max(1, int(cfg.get("staleness_epochs", 4)))
        self.poll_s = float(cfg.get("harvest_poll_s", 1.0))
        self.max_pull = max(1, int(cfg.get("harvest_max_pull", 64)))
        fraction = float(cfg.get("harvest_fraction", 0.5))
        update_episodes = int(cfg.get("update_episodes", 0))
        # per-epoch submission budget; None = unthrottled (fraction 1.0
        # or an owner that did not wire the cadence in)
        self.epoch_budget: Optional[int] = (
            None if fraction >= 1.0 or update_episodes <= 0
            else max(0, round(fraction * update_episodes))
        )
        self._submit = submit
        self._current_epoch = current_epoch
        self._make_client = make_client
        self._client: Any = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self._budget_epoch = -1
        self._budget_left = 0
        # over-budget episodes wait here for the next epoch's budget; they
        # re-enter through the staleness check, so a feed the mix never
        # wants ages out instead of accumulating
        self._deferred: List[Dict[str, Any]] = []
        # books (folded into the learner's epoch record)
        self.ingested = 0
        self.dropped_stale = 0
        self.dropped_malformed = 0
        self.server_counts: Dict[str, int] = {}

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> "HarvestIngestor":
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="flywheel-ingest"
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        self._close_client()

    def _close_client(self) -> None:
        client, self._client = self._client, None
        if client is not None:
            try:
                client.close()
            except Exception:
                pass

    # -- poll loop ------------------------------------------------------------

    def _loop(self) -> None:
        backoff = min(self.poll_s, 0.5)
        while not self._stop.is_set():
            try:
                if self._client is None:
                    self._client = self._make_client()
                    backoff = min(self.poll_s, 0.5)
                episodes, counts = self._client.harvest_pull(self.max_pull)
                if counts:
                    self.server_counts = dict(counts)
                if episodes:
                    self.ingest(episodes)
                if self._stop.wait(self.poll_s):
                    return
            except (ConnectionError, OSError, TimeoutError):
                # serving tier absent/draining: reconnect forever with
                # bounded backoff — harvest starvation shows up as a flat
                # flywheel_ingested counter, never as a learner crash
                self._close_client()
                if self._stop.wait(backoff):
                    return
                backoff = min(backoff * 2.0, 10.0)
            except Exception as exc:
                print(f"flywheel: ingest poll failed: {exc}")
                self._close_client()
                if self._stop.wait(max(self.poll_s, 1.0)):
                    return

    # -- the guarded feed (separable for tests) -------------------------------

    def ingest(self, episodes: List[Any]) -> int:
        """Apply the learner-side guards and submit the survivors.
        Returns the number submitted."""
        current = int(self._current_epoch())
        with self._lock:
            deferred, self._deferred = self._deferred, []
        fresh: List[Dict[str, Any]] = []
        for episode in deferred + list(episodes):
            if not isinstance(episode, dict) or any(
                key not in episode for key in _REQUIRED_KEYS
            ):
                with self._lock:
                    self.dropped_malformed += 1
                print("flywheel: dropped malformed harvested blob "
                      f"(keys {sorted(episode)[:8] if isinstance(episode, dict) else type(episode).__name__})")
                continue
            served = int(episode.get("model_epoch", 0))
            if current - served >= self.staleness_epochs:
                with self._lock:
                    self.dropped_stale += 1
                continue
            fresh.append(episode)
        if not fresh:
            return 0
        fresh = self._apply_budget(current, fresh)
        if not fresh:
            return 0
        self._submit(fresh)
        with self._lock:
            self.ingested += len(fresh)
        return len(fresh)

    def _apply_budget(self, current: int, episodes: List[Dict[str, Any]],
                      ) -> List[Dict[str, Any]]:
        if self.epoch_budget is None:
            return episodes
        with self._lock:
            if current != self._budget_epoch:
                self._budget_epoch = current
                self._budget_left = self.epoch_budget
            take = max(0, min(self._budget_left, len(episodes)))
            self._budget_left -= take
            # bound the parking lot: beyond ~4 pulls of backlog the oldest
            # entries are the next staleness casualties anyway
            self._deferred = (self._deferred + episodes[take:])[-4 * self.max_pull:]
        return episodes[:take]

    # -- metrics --------------------------------------------------------------

    def stats(self) -> Dict[str, int]:
        with self._lock:
            record = {
                "flywheel_ingested": self.ingested,
                "flywheel_ingest_stale": self.dropped_stale,
                "flywheel_ingest_malformed": self.dropped_malformed,
            }
            record.update(self.server_counts)
        return record
