"""Population-based league training plane (docs/league.md).

AlphaStar-style league over the env zoo: a persistent population of
frozen snapshots + anchors (league.py) backed by the manifest-verified
checkpoint store, PFSP matchmaking over a shared payoff ledger
(matchmaker.py — the repo's ONE win-rate bookkeeping, also fed by
runtime/battle.py network matches and tools/head_to_head.py), and a
learner driver that routes frozen opponents through resident ModelRouter
engines so distinct opponents dispatch concurrently on distinct chips
(learner.py).  Entry point: ``main.py --league``.
"""

from .league import ANCHOR, CANDIDATE, League, Member
from .learner import LeagueLearner, LeagueModelServer, league_main
from .matchmaker import Matchmaker, PayoffMatrix, pfsp_weights

__all__ = [
    "ANCHOR", "CANDIDATE", "League", "Member",
    "LeagueLearner", "LeagueModelServer", "league_main",
    "Matchmaker", "PayoffMatrix", "pfsp_weights",
]
