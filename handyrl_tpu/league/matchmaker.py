"""Payoff bookkeeping + PFSP matchmaking for the league plane.

``PayoffMatrix`` is the ONE win-rate ledger of the repo: league
generation matches (league/learner.py), network battle matches
(runtime/battle.py, ``exec_network_match`` results incl. forfeits) and
ad-hoc head-to-heads (tools/head_to_head.py) all record into this shape,
so every consumer shares one win-points convention — win + draw/2 over
games, exactly ``runtime.evaluation.wp_func`` (the convention
tools/ablate_sampler.py reports deltas in).

Accounting rules (pinned by tests/test_battle_books.py /
tests/test_league.py):

* a finished match records one entry per ORDERED pair of distinct
  member names, pairwise from the per-seat scores: higher score = win,
  equal = draw — which makes multi-player placement outcomes
  (HungryGeese's {-1, -1/3, +1/3, +1} ranks) decompose into pairwise
  results with no extra convention;
* two seats held by the SAME member record nothing (self-pairs carry no
  information);
* a severed peer forfeits: the severed seat takes a LOSS against every
  surviving seat; survivor-vs-survivor pairs are NOT recorded (their
  game never finished — inventing a draw would bias the books toward
  0.5 exactly when a flaky peer is in the population).

``Matchmaker`` samples opponents for the league candidate by
prioritized fictitious self-play (AlphaStar): the frozen population is
weighted by a function of the candidate's current win rate p against
each member — 'var' p(1-p) (near-peers), 'hard' (1-p)² (hardest first),
'even' (uniform).  Unplayed members default to p = 0.5, which under both
non-uniform weightings is the maximum — new members get probed first.
"""

from __future__ import annotations

import math
import random
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

__all__ = ["PayoffMatrix", "Matchmaker", "pfsp_weights"]


class PayoffMatrix:
    """Win/draw/loss books per ordered (member, member) pair."""

    def __init__(self):
        # (a, b) -> [wins, draws, losses] from a's perspective
        self._books: Dict[Tuple[str, str], List[int]] = {}
        self.matches = 0          # finished/forfeited MATCHES recorded
        self.forfeits = 0

    # -- recording -----------------------------------------------------------

    def record_score(self, a: str, b: str, score_a: float, score_b: float,
                     n: int = 1) -> None:
        """``n`` pairwise results between ``a`` and ``b`` from final
        scores: higher score wins, equal draws.  Records BOTH ordered
        directions; self-pairs are ignored."""
        if a == b or n <= 0:
            return
        if score_a > score_b:
            i, j = 0, 2
        elif score_a < score_b:
            i, j = 2, 0
        else:
            i = j = 1
        self._books.setdefault((a, b), [0, 0, 0])[i] += n
        self._books.setdefault((b, a), [0, 0, 0])[j] += n

    def record_outcome(self, names: Mapping[Any, str],
                       outcome: Mapping[Any, float]) -> None:
        """One finished match: ``names`` maps seats to member names,
        ``outcome`` seats to final scores (an ``exec_match`` /
        ``exec_network_match`` outcome dict).  Every unordered seat pair
        with distinct names records pairwise."""
        seats = [s for s in names if s in outcome]
        for x in range(len(seats)):
            for y in range(x + 1, len(seats)):
                sa, sb = seats[x], seats[y]
                self.record_score(
                    names[sa], names[sb],
                    float(outcome[sa]), float(outcome[sb]),
                )
        self.matches += 1

    def record_forfeit(self, names: Mapping[Any, str], severed_seat) -> None:
        """A peer severed mid-match: its seat loses to every surviving
        seat; survivor pairs record nothing (their game never finished)."""
        loser = names[severed_seat]
        for seat, name in names.items():
            if seat == severed_seat:
                continue
            self.record_score(name, loser, 1.0, -1.0)
        self.matches += 1
        self.forfeits += 1

    def adopt(self, old: str, new: str) -> None:
        """Rename ``old``'s books to ``new`` (candidate -> frozen member
        at promotion).  Any pre-existing books under ``new`` are dropped
        first: a resurrected name must not inherit a dead member's
        record."""
        if old == new:
            return
        for pair in [p for p in self._books if new in p]:
            del self._books[pair]
        for (a, b) in list(self._books):
            if a == old:
                self._books[(new, b)] = self._books.pop((a, b))
            elif b == old:
                self._books[(a, new)] = self._books.pop((a, b))

    # -- reading ---------------------------------------------------------------

    def games(self, a: str, b: str) -> int:
        return sum(self._books.get((a, b), (0, 0, 0)))

    def win_points(self, a: str, b: str) -> Optional[float]:
        """(wins + draws/2) / games from ``a``'s perspective — the
        ``wp_func`` convention; None with no games on the books."""
        w, d, l = self._books.get((a, b), (0, 0, 0))
        n = w + d + l
        return None if n == 0 else (w + d / 2) / n

    def aggregate_win_points(self, a: str,
                             opponents: Sequence[str]) -> Optional[float]:
        """Pooled win points of ``a`` over every listed opponent (game-
        weighted, not mean-of-means — 3 games vs X must not outweigh 300
        vs Y)."""
        w = d = n = 0
        for b in opponents:
            bw, bd, bl = self._books.get((a, b), (0, 0, 0))
            w, d, n = w + bw, d + bd, n + bw + bd + bl
        return None if n == 0 else (w + d / 2) / n

    def members(self) -> List[str]:
        return sorted({a for a, _ in self._books})

    def coverage(self, a: str, opponents: Sequence[str],
                 min_games: int = 1) -> float:
        """Fraction of ``opponents`` against whom ``a`` has at least
        ``min_games`` on the books (1.0 over an empty pool: nothing is
        missing)."""
        if not opponents:
            return 1.0
        hit = sum(1 for b in opponents if self.games(a, b) >= min_games)
        return hit / len(opponents)

    def elo(self, members: Sequence[str],
            anchor: Optional[str] = None) -> Dict[str, float]:
        """Per-member Elo estimates from pooled win points against the
        listed members: r = 400·log10(p/(1-p)) with p clipped away from
        {0, 1} (a member yet to lose is 'at least +478', not infinity).
        Coarse by design — a population spread/ordering signal for the
        bench and metrics, not a ladder rating; ``anchor`` (when listed)
        is shifted to exactly 0 so ratings are comparable across epochs."""
        ratings: Dict[str, float] = {}
        for m in members:
            p = self.aggregate_win_points(m, [x for x in members if x != m])
            if p is None:
                continue
            p = min(max(p, 0.06), 0.94)
            ratings[m] = 400.0 * math.log10(p / (1.0 - p))
        if anchor in ratings:
            shift = ratings[anchor]
            ratings = {m: r - shift for m, r in ratings.items()}
        return ratings

    # -- persistence -----------------------------------------------------------

    def to_dict(self) -> Dict[str, Any]:
        return {
            "matches": self.matches,
            "forfeits": self.forfeits,
            "books": {f"{a}\x00{b}": wdl for (a, b), wdl in self._books.items()},
        }

    @classmethod
    def from_dict(cls, data: Mapping[str, Any]) -> "PayoffMatrix":
        out = cls()
        out.matches = int(data.get("matches", 0))
        out.forfeits = int(data.get("forfeits", 0))
        for key, wdl in dict(data.get("books", {})).items():
            a, _, b = key.partition("\x00")
            out._books[(a, b)] = [int(x) for x in wdl]
        return out


def pfsp_weights(win_rates: Sequence[Optional[float]],
                 weighting: str = "var") -> List[float]:
    """PFSP opponent weights from the candidate's win rate p per member
    (None = unplayed -> 0.5, the maximum of both non-uniform schemes, so
    fresh members get probed first).  Weights get a small floor so no
    member is ever starved entirely (a 'solved' member can un-solve as
    the candidate churns)."""
    out = []
    for p in win_rates:
        p = 0.5 if p is None else min(max(float(p), 0.0), 1.0)
        if weighting == "even":
            w = 1.0
        elif weighting == "hard":
            w = (1.0 - p) ** 2
        elif weighting == "var":
            w = p * (1.0 - p)
        else:
            raise ValueError(f"unknown pfsp weighting {weighting!r}")
        out.append(max(w, 1e-3))
    return out


class Matchmaker:
    """Samples the candidate's next opponent from the active population.

    Stateless beyond its RNG: the payoff ledger is the input, so local
    generation matches and network battle results steer the SAME
    sampling distribution the moment they are recorded.
    """

    def __init__(self, payoff: PayoffMatrix, weighting: str = "var",
                 seed: int = 0):
        self.payoff = payoff
        self.weighting = weighting
        self._rng = random.Random(seed ^ 0x1EA90E)

    def sample_opponent(self, candidate: str, pool: Sequence[str],
                        min_games: int = 0) -> Optional[str]:
        """PFSP draw over ``pool`` (member names); None on an empty pool.

        ``min_games > 0`` adds a PROBE QUOTA ahead of the PFSP draw:
        members with fewer than that many games against the candidate
        sample uniformly first.  Without it, one decisive first game
        pins p at 0 or 1, the 'var'/'hard' weight collapses to the
        floor, and that member starves — permanently blocking any
        coverage-gated promotion (the learner passes its
        ``promote_games`` here so the gate's requirement and the
        sampler's guarantee are the same number).  Win rates feed the
        weighting Laplace-smoothed toward 0.5 (prior weight 2) so small
        samples cannot pin the distribution either way."""
        if not pool:
            return None
        if min_games > 0:
            under = [b for b in pool if self.payoff.games(candidate, b) < min_games]
            if under:
                return self._rng.choice(under)
        rates = []
        for b in pool:
            p, n = self.payoff.win_points(candidate, b), self.payoff.games(candidate, b)
            rates.append(None if p is None else (p * n + 0.5 * 2) / (n + 2))
        weights = pfsp_weights(rates, self.weighting)
        return self._rng.choices(list(pool), weights=weights)[0]
