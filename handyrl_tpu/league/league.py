"""League registry: the persistent population behind league training.

A population member is a ROLE plus a CHECKPOINT EPOCH in the PR 2
manifest-verified store (``models/{epoch}.ckpt``):

* ``anchor``  — the fixed reference opponent (epoch 0 = the zero-output
  RandomModel, ``LocalModelServer.get(0)`` semantics).  Anchors never
  retire: they give the payoff matrix a stationary column, which is what
  makes Elo comparable across the run;
* ``frozen``  — a past main-agent snapshot frozen by the promotion gate
  (named ``main-{epoch}``), the fictitious-self-play pool;
* ``main``    — the live training candidate (tracked for bookkeeping; it
  plays under the reserved name ``candidate`` until frozen);
* ``exploiter`` — a member registered to attack a specific main (the
  AlphaStar role); the registry and matchmaker carry the role, and a
  separate ``--league`` run with its own model_dir trains one.

The registry (members + the payoff ledger) persists to
``models/LEAGUE.json`` with the checkpoint plane's atomic-write
discipline, so a league run resumes with its population and books
intact.  On load, frozen members whose snapshots no longer digest-verify
are DROPPED LOUDLY (their books survive): matching against a corrupt
snapshot would silently substitute latest params and poison the matrix
(the LocalModelServer substitution lesson).
"""

from __future__ import annotations

import json
import os
from dataclasses import asdict, dataclass, field
from typing import Any, Dict, List, Optional

from ..runtime.checkpoint import atomic_write_bytes, verify_snapshot
from .matchmaker import PayoffMatrix

LEAGUE_NAME = "LEAGUE.json"
CANDIDATE = "candidate"      # the live (not yet frozen) main agent's ledger name
ANCHOR = "random"            # the epoch-0 RandomModel anchor

ROLES = ("anchor", "frozen", "main", "exploiter")


@dataclass
class Member:
    name: str
    epoch: int
    role: str = "frozen"
    frozen_at_step: int = 0

    def __post_init__(self):
        if self.role not in ROLES:
            raise ValueError(f"member role {self.role!r} not one of {ROLES}")


class League:
    """Population registry + the shared payoff ledger, disk-backed."""

    def __init__(self, model_dir: str, league_args: Optional[Dict[str, Any]] = None):
        cfg = dict(league_args or {})
        self.model_dir = model_dir
        self.max_population = max(2, int(cfg.get("max_population", 16)))
        self.members: Dict[str, Member] = {}
        self.payoff = PayoffMatrix()
        self.promotions = 0
        # registry file ownership: under jax.distributed exactly one
        # process may write models/LEAGUE.json (the same coordinator-only
        # discipline as checkpoints/metrics) — LeagueLearner flips this
        # off on non-coordinators; their in-memory state stays live
        self.owner = True
        if not self.load():
            # fresh league: the anchor seeds the population so the very
            # first candidate generation has an opponent and a fixed Elo
            # reference
            self.members[ANCHOR] = Member(ANCHOR, 0, "anchor")

    # -- membership -----------------------------------------------------------

    def add(self, name: str, epoch: int, role: str = "frozen",
            frozen_at_step: int = 0) -> Member:
        if name in self.members:
            raise ValueError(f"league member {name!r} already registered")
        if name == CANDIDATE:
            raise ValueError(
                f"{CANDIDATE!r} is the reserved ledger name of the live "
                "candidate; frozen members need concrete names"
            )
        member = Member(name, int(epoch), role, int(frozen_at_step))
        self.members[name] = member
        return member

    def freeze_candidate(self, epoch: int, steps: int = 0) -> Member:
        """The promotion gate passed: freeze the candidate's current
        snapshot into the population as ``main-{epoch}``, and hand the
        candidate's ledger row to the new member (the games that earned
        the promotion describe the frozen policy) so the next candidate
        generation starts with clean books."""
        member = self.add(f"main-{int(epoch)}", epoch, "frozen", steps)
        self.payoff.adopt(CANDIDATE, member.name)
        self.promotions += 1
        self.save()
        return member

    def opponent_pool(self) -> List[Member]:
        """Active matchmaking pool: anchors + the newest frozen members up
        to ``max_population`` (anchors always stay; older frozen members
        retire from matchmaking but keep their snapshots and books)."""
        anchors = [m for m in self.members.values() if m.role == "anchor"]
        frozen = sorted(
            (m for m in self.members.values() if m.role in ("frozen", "exploiter")),
            key=lambda m: m.epoch,
        )
        slots = max(0, self.max_population - len(anchors))
        return anchors + frozen[-slots:] if slots else anchors

    def frozen_epochs(self) -> List[int]:
        """Every registered snapshot epoch (checkpoint-GC pin set) —
        retired members included: their books reference those params."""
        return sorted({m.epoch for m in self.members.values() if m.epoch > 0})

    # -- persistence ------------------------------------------------------------

    def _path(self) -> str:
        return os.path.join(self.model_dir, LEAGUE_NAME)

    def save(self) -> None:
        if not self.owner:
            return
        payload = {
            "version": 1,
            "promotions": self.promotions,
            "members": [asdict(m) for m in self.members.values()],
            "payoff": self.payoff.to_dict(),
        }
        atomic_write_bytes(
            self._path(), json.dumps(payload, indent=1, sort_keys=True).encode()
        )

    def load(self) -> bool:
        """Restore a persisted league; False when none exists.  Frozen
        members whose snapshots fail digest verification are dropped
        loudly (books survive — the next promotion may resurrect the
        name-space but never the corrupt file)."""
        try:
            with open(self._path()) as f:
                payload = json.load(f)
        except FileNotFoundError:
            return False
        except OSError as exc:
            # the file EXISTS but cannot be read (EACCES, EIO, an NFS
            # blip): starting a fresh anchor-only league here would empty
            # the GC pin set and let gc_snapshots permanently delete the
            # frozen members' snapshots — fail loudly instead
            raise RuntimeError(
                f"{self._path()} exists but cannot be read "
                f"({type(exc).__name__}: {exc}); refusing to start a fresh "
                "league over an unreadable registry (its frozen members' "
                "snapshots would be GC'd)"
            )
        except ValueError as exc:
            raise RuntimeError(
                f"{self._path()} is corrupt ({exc}); the league registry is "
                "atomic-write — inspect the model dir (delete the file to "
                "explicitly start a fresh league)"
            )
        self.promotions = int(payload.get("promotions", 0))
        self.payoff = PayoffMatrix.from_dict(payload.get("payoff", {}))
        self.members = {}
        for raw in payload.get("members", []):
            member = Member(
                str(raw["name"]), int(raw["epoch"]), str(raw.get("role", "frozen")),
                int(raw.get("frozen_at_step", 0)),
            )
            if member.epoch > 0 and verify_snapshot(self.model_dir, member.epoch) is False:
                print(
                    f"[handyrl_tpu] league: dropping member {member.name!r} — "
                    f"snapshot {member.epoch}.ckpt fails digest verification "
                    "(its payoff books are kept)"
                )
                continue
            self.members[member.name] = member
        if ANCHOR not in self.members:
            self.members[ANCHOR] = Member(ANCHOR, 0, "anchor")
        return True
