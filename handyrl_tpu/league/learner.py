"""League training driver: the population plane over the existing
learner/rollout machinery.

``LeagueLearner`` subclasses the Learner and changes exactly three seams:

* **model serving** — ``LeagueModelServer`` keeps the single shared
  engine for the latest (candidate) model, but frozen opponents resolve
  through a PR 10 ``ModelRouter``: each frozen snapshot gets a RESIDENT
  ``ContinuousBatcher`` engine, digest-verified-loaded once and
  round-robined across the device list, so distinct opponents batch and
  dispatch CONCURRENTLY on distinct chips (disjoint dispatch-lock
  scopes) instead of re-loading params from disk per job.  Under
  ``plane: split`` the router is scoped to the actor mesh's devices —
  opponent inference stays off the learner chips;

* **role assignment** — a ``selfplay_rate`` slice of generation jobs
  stays latest-vs-latest; the rest become league matches: the candidate
  takes one (rotating) seat, a PFSP-sampled frozen member takes the
  others, and only the candidate's columns train (opponent tmask/omask
  are zeroed at ingest — AlphaStar trains the learner's trajectories,
  not the frozen opponent's);

* **epoch boundary** — match outcomes recorded per ordered pair in the
  league's payoff ledger feed the promotion gate: once the candidate has
  ``promote_games`` against EVERY active member and its pooled win
  points clear ``promote_winrate``, the just-saved snapshot freezes into
  the population (``League.freeze_candidate``) and its checkpoint is
  pinned against GC.  ``league_*`` metrics land in metrics.jsonl next to
  the learner's records.

Run it with ``main.py --league`` (docs/league.md).
"""

from __future__ import annotations

import random
import sys
from typing import Any, Dict, List, Optional

import numpy as np

from ..envs import make_env
from ..runtime.inference_engine import EngineStopped
from ..runtime.learner import Learner
from ..runtime.replay import compress_block, decompress_block
from ..runtime.worker import LocalModelServer
from ..serving.router import ModelRouter, RouteError
from ..utils import tree_map
from .league import ANCHOR, CANDIDATE, League
from .matchmaker import Matchmaker

__all__ = ["LeagueLearner", "LeagueModelServer", "RouterOpponent", "league_main"]


class RouterOpponent:
    """A frozen member's model handle for actor threads: submits resolve
    through the router to that snapshot's resident engine (the engine
    batches across all concurrently-acting threads, exactly like the
    latest model's shared engine)."""

    def __init__(self, server: "LeagueModelServer", model_id: int):
        self._server = server
        self._mid = int(model_id)

    def init_hidden(self, batch_dims=()):
        hidden = self._server.module.initial_state(tuple(batch_dims))
        return None if hidden is None else tree_map(np.asarray, hidden)

    def submit(self, obs, hidden=None):
        return self._server.route_submit(self._mid, obs, hidden)

    def inference(self, obs, hidden=None) -> Dict[str, Any]:
        return self.submit(obs, hidden).result(timeout=600.0)


class LeagueModelServer(LocalModelServer):
    """LocalModelServer + a ModelRouter for frozen-opponent engines.

    Latest-model requests keep the existing shared engine; concrete OLD
    epochs — the league's frozen members, requested on every match job —
    route to resident router engines instead of a per-job disk load.
    Missing/corrupt snapshots substitute the latest engine COUNTED
    (router.substituted folds into ``substituted_snapshots``, so poisoned
    books stay visible in metrics.jsonl).
    """

    def __init__(self, module, env, args: Dict[str, Any], devices=None):
        super().__init__(module, env, args)
        serving_cfg = dict(args.get("serving", {}) or {})
        # rollout jobs are throughput work, not latency work: never shed,
        # never impose an SLO — a match must finish or fail loudly
        serving_cfg["shed_policy"] = "none"
        # every active pool member must stay RESIDENT (+1 for the pinned
        # latest engine): the serving default max_models=4 under a bigger
        # max_population would thrash evict/cold-reload — a disk load +
        # warm compile inside the actors' generation loop per match
        serving_cfg["max_models"] = max(
            int(serving_cfg.get("max_models", 4)),
            int((args.get("league", {}) or {}).get("max_population", 16)) + 1,
        )
        env.reset()
        template_obs = env.observation(env.players()[0])
        self._router = ModelRouter(
            module, template_obs, serving_cfg,
            model_dir=self.model_dir, devices=devices,
        )

    # base __init__ assigns the counter before the router exists; the
    # property folds the router's substitutions in on every read
    @property
    def substituted_snapshots(self) -> int:
        router = getattr(self, "_router", None)
        return self._substituted_base + (router.substituted if router else 0)

    @substituted_snapshots.setter
    def substituted_snapshots(self, value: int) -> None:
        self._substituted_base = int(value)

    def publish(self, model_id: int, params) -> None:
        super().publish(model_id, params)
        try:
            # the router's latest mirrors the served latest: it is the
            # params template for cold frozen-member loads and the counted
            # substitute when a member's snapshot is gone
            self._router.publish(int(model_id), params)
        except RouteError:
            pass  # router already stopped (shutdown race): nothing to serve

    def get(self, model_id: int):
        if model_id == 0:
            return self._random
        with self._lock:
            current = self.model_id
        if model_id < 0 or model_id >= current:
            return self.engine.client()
        return RouterOpponent(self, int(model_id))

    def route_submit(self, mid: int, obs, hidden=None):
        try:
            _, route = self._router.resolve(mid)
        except RouteError as exc:
            # stopped / nothing published: actor threads treat it like the
            # shared engine going away and drain cleanly
            raise EngineStopped(str(exc)) from exc
        return route.submit(obs, hidden)

    def router_stats(self) -> Dict[str, Any]:
        return self._router.stats()

    def stop(self) -> None:
        super().stop()
        self._router.stop()


class LeagueLearner(Learner):
    """Learner whose generation plane plays the league (docs/league.md)."""

    def __init__(self, args: Dict[str, Any], net=None, remote: bool = False):
        super().__init__(args, net, remote)
        from ..parallel import is_coordinator

        cfg = dict(self.args.get("league", {}) or {})
        self.league_args = cfg
        self.league = League(self.model_dir, cfg)
        # registry file ownership follows the checkpoint discipline: only
        # the coordinator writes models/LEAGUE.json under jax.distributed
        self.league.owner = is_coordinator()
        stale = sorted(
            m.name for m in self.league.members.values()
            if m.epoch > self.model_epoch
        )
        if stale:
            raise ValueError(
                f"league members {stale} reference snapshots newer than the "
                f"resumed model epoch {self.model_epoch}; resume the run "
                "with restart_epoch: -1 (or clear models/LEAGUE.json to "
                "start a fresh league)"
            )
        self.matchmaker = Matchmaker(
            self.league.payoff,
            cfg.get("pfsp_weighting", "var"),
            seed=int(self.args["seed"]),
        )
        self.selfplay_rate = float(cfg.get("selfplay_rate", 0.2))
        self._league_seat = 0
        self._league_rng = random.Random(int(self.args["seed"]) ^ 0x5EA6)
        pool = self.league.opponent_pool()
        print(
            "league: %d member(s), pool %s, pfsp=%s selfplay_rate=%.2f "
            "promote wp>=%.2f over >=%d games/pair"
            % (
                len(self.league.members),
                [m.name for m in pool],
                cfg.get("pfsp_weighting", "var"),
                self.selfplay_rate,
                float(cfg.get("promote_winrate", 0.55)),
                int(cfg.get("promote_games", 8)),
            )
        )

    # -- seams into the base learner ------------------------------------------

    def _make_model_server(self, args: Dict[str, Any]):
        devices: Optional[List] = None
        if self._actor_mesh is not None:
            # plane: split — opponent engines live on the actor mesh's
            # chips, concurrent with (never contending) the learner plane
            devices = list(self._actor_mesh.devices.flat)
        return LeagueModelServer(
            self.module, make_env(args["env_args"]), self.args, devices=devices
        )

    def _gc_pinned(self):
        return self.league.frozen_epochs()

    def _assign_role(self) -> Dict[str, Any]:
        args = super()._assign_role()
        if args["role"] != "g":
            return args
        pool = self.league.opponent_pool()
        if not pool or self._league_rng.random() < self.selfplay_rate:
            args["league"] = {"mode": "selfplay"}
            return args
        players = self.env.players()
        me = players[self._league_seat % len(players)]   # seat balance
        self._league_seat += 1
        opponent = self.matchmaker.sample_opponent(
            CANDIDATE,
            [m.name for m in pool],
            min_games=int(self.league_args.get("promote_games", 8)),
        )
        epoch = {m.name: m.epoch for m in pool}[opponent]
        args["player"] = [me]
        args["model_id"] = {
            p: (self.model_epoch if p == me else epoch) for p in players
        }
        args["league"] = {
            "mode": "match",
            "seats": {p: (CANDIDATE if p == me else opponent) for p in players},
        }
        return args

    def feed_episodes(self, episodes) -> None:
        for episode in episodes:
            if episode is None:
                continue
            meta = (episode.get("args") or {}).get("league")
            if not meta or meta.get("mode") != "match":
                continue
            seats = meta["seats"]
            self.league.payoff.record_outcome(seats, episode["outcome"])
            self._mask_non_candidate(
                episode, [p for p, name in seats.items() if name == CANDIDATE]
            )
        super().feed_episodes(episodes)

    @staticmethod
    def _mask_non_candidate(episode: Dict[str, Any], candidate_players) -> None:
        """Zero the frozen opponent's tmask/omask columns so only the
        candidate's steps carry loss: the league trains ONE agent; the
        opponent's (old-policy) actions are context, not targets."""
        players = episode["players"]
        mask = np.zeros(len(players), np.float32)
        for p in candidate_players:
            mask[players.index(p)] = 1.0
        blocks = []
        for blk in episode["blocks"]:
            cols = dict(decompress_block(blk))
            cols["tmask"] = (cols["tmask"] * mask[None, :]).astype(np.float32)
            cols["omask"] = (cols["omask"] * mask[None, :]).astype(np.float32)
            blocks.append(compress_block(cols))
        episode["blocks"] = blocks

    def _epoch_hook(self, record: Dict[str, Any]) -> None:
        payoff = self.league.payoff
        pool = [m.name for m in self.league.opponent_pool()]
        min_games = int(self.league_args.get("promote_games", 8))
        bar = float(self.league_args.get("promote_winrate", 0.55))
        coverage = payoff.coverage(CANDIDATE, pool, 1)
        wp = payoff.aggregate_win_points(CANDIDATE, pool)
        gate = (
            bool(pool)
            and wp is not None
            and wp >= bar
            and all(payoff.games(CANDIDATE, b) >= min_games for b in pool)
        )
        if gate and f"main-{self.model_epoch}" in self.league.members:
            # a sentinel rollback can replay epoch numbers; re-freezing an
            # existing member would crash the boundary — skip loudly, the
            # next (new) epoch promotes if the gate still holds
            print(
                f"league: main-{self.model_epoch} already frozen (epoch "
                "replayed after a rollback?) — promotion skipped"
            )
            gate = False
        if gate:
            member = self.league.freeze_candidate(
                self.model_epoch, self.trainer.steps
            )
            print(
                "league: promotion gate PASSED (wp %.3f >= %.2f, >=%d games "
                "vs each of %d opponents) — frozen %s"
                % (wp, bar, min_games, len(pool), member.name)
            )
        else:
            self.league.save()   # books/members durable every boundary
        rated = payoff.elo(pool + [CANDIDATE], anchor=ANCHOR)
        spread = (
            round(max(rated.values()) - min(rated.values()), 1)
            if len(rated) >= 2 else None
        )
        print(
            "league: pool %d/%d members, candidate wp %s, coverage %.2f, "
            "elo spread %s, promotions %d"
            % (
                len(pool), len(self.league.members),
                "n/a" if wp is None else "%.3f" % wp,
                coverage, spread, self.league.promotions,
            )
        )
        record["league_population"] = len(self.league.members)
        record["league_pool"] = len(pool)
        record["league_matches"] = payoff.matches
        record["league_forfeits"] = payoff.forfeits
        record["league_payoff_coverage"] = round(coverage, 4)
        record["league_candidate_wp"] = None if wp is None else round(wp, 4)
        record["league_elo_spread"] = spread
        record["league_promotions"] = self.league.promotions

    def run(self) -> int:
        try:
            return super().run()
        finally:
            # matches fed between the last epoch boundary and shutdown
            # (in-flight worker episodes draining) must survive the run
            self.league.save()


def league_main(args: Dict[str, Any]) -> None:
    """`main.py --league` entry point (league analogue of train_main)."""
    learner = LeagueLearner(args)
    code = learner.run()
    if code:
        sys.exit(code)
