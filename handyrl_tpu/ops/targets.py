"""Off-policy return/advantage targets as time-reversed ``lax.scan``s.

Semantics parity with reference handyrl/losses.py:16-81 (Monte Carlo,
TD(lambda), UPGO, V-Trace per arXiv:1802.01561), re-expressed for XLA:
the reference's per-timestep python deque recursions become single
``lax.scan``s over the time axis, so the whole target computation compiles
into the training step (no host loop, fuses with the loss).

Shape convention: all tensors are (B, T, P, C) — batch, time, player,
channel.  ``lambda_`` follows the reference's mask dispatch
(losses.py:71): lambda_ = lmb + (1 - lmb) * (1 - mask), i.e. unobserved
steps propagate the bootstrap straight through (lambda = 1).

The final-step bootstrap is ``returns[:, -1]`` — for the 'value' channel
callers pass the episode outcome as ``returns``, for the 'return' channel
the discounted reward sum (see ops/losses.py).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def _time_leading(x):
    return jnp.moveaxis(x, 1, 0)  # (B, T, ...) -> (T, B, ...)


def _batch_leading(x):
    return jnp.moveaxis(x, 0, 1)


def _reverse_scan(step_fn, bootstrap, xs_time_leading):
    """Run ``step_fn`` backwards over time, returning (T, ...) outputs
    where index i holds the carry computed at step i (and the last index
    holds ``bootstrap``)."""
    _, ys = jax.lax.scan(step_fn, bootstrap, xs_time_leading, reverse=True)
    return ys


def monte_carlo(values, returns):
    return returns, returns - values


def td_lambda(values, returns, rewards, lambda_, gamma):
    """TD(lambda) targets (reference losses.py:20-29)."""
    bootstrap = returns[:, -1]
    v_next = _time_leading(values[:, 1:])
    lam_next = _time_leading(lambda_[:, 1:])
    r_cur = _time_leading(rewards[:, :-1]) if rewards is not None else jnp.zeros_like(v_next)

    def step(carry, x):
        v1, lam, r = x
        tv = r + gamma * ((1 - lam) * v1 + lam * carry)
        return tv, tv

    ys = _reverse_scan(step, bootstrap, (v_next, lam_next, r_cur))
    targets = jnp.concatenate([_batch_leading(ys), bootstrap[:, None]], axis=1)
    return targets, targets - values


def upgo(values, returns, rewards, lambda_, gamma):
    """UPGO targets: bootstrap from max(V, lambda-mixture) (losses.py:32-42)."""
    bootstrap = returns[:, -1]
    v_next = _time_leading(values[:, 1:])
    lam_next = _time_leading(lambda_[:, 1:])
    r_cur = _time_leading(rewards[:, :-1]) if rewards is not None else jnp.zeros_like(v_next)

    def step(carry, x):
        v1, lam, r = x
        tv = r + gamma * jnp.maximum(v1, (1 - lam) * v1 + lam * carry)
        return tv, tv

    ys = _reverse_scan(step, bootstrap, (v_next, lam_next, r_cur))
    targets = jnp.concatenate([_batch_leading(ys), bootstrap[:, None]], axis=1)
    return targets, targets - values


def vtrace(values, returns, rewards, lambda_, gamma, rhos, cs):
    """V-Trace targets and advantages (losses.py:45-60, arXiv:1802.01561)."""
    r = rewards if rewards is not None else jnp.zeros_like(values)
    bootstrap = returns[:, -1:]
    v_next = jnp.concatenate([values[:, 1:], bootstrap], axis=1)
    deltas = rhos * (r + gamma * v_next - values)

    d = _time_leading(deltas[:, :-1])
    lam_next = _time_leading(lambda_[:, 1:])
    c_cur = _time_leading(cs[:, :-1])

    def step(carry, x):
        delta, lam, c = x
        acc = delta + gamma * lam * c * carry
        return acc, acc

    ys = _reverse_scan(step, deltas[:, -1], (d, lam_next, c_cur))
    vs_minus_v = jnp.concatenate([_batch_leading(ys), deltas[:, -1:]], axis=1)
    vs = vs_minus_v + values
    vs_next = jnp.concatenate([vs[:, 1:], bootstrap], axis=1)
    advantages = r + gamma * vs_next - values
    return vs, advantages


def compute_target(
    algorithm: str,
    values: Optional[jnp.ndarray],
    returns: jnp.ndarray,
    rewards: Optional[jnp.ndarray],
    lmb: float,
    gamma: float,
    rhos: jnp.ndarray,
    cs: jnp.ndarray,
    masks: jnp.ndarray,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Dispatch matching reference losses.py:63-81.

    ``algorithm`` is a static (trace-time) string: MC / TD / UPGO / VTRACE.
    Without a value baseline, Monte Carlo returns are target and advantage.
    """
    if values is None:
        return returns, returns
    if algorithm == "MC":
        return monte_carlo(values, returns)

    lambda_ = lmb + (1 - lmb) * (1 - masks)

    if algorithm == "TD":
        return td_lambda(values, returns, rewards, lambda_, gamma)
    if algorithm == "UPGO":
        return upgo(values, returns, rewards, lambda_, gamma)
    if algorithm == "VTRACE":
        return vtrace(values, returns, rewards, lambda_, gamma, rhos, cs)
    raise ValueError(f"unknown target algorithm {algorithm!r}")
