from .targets import compute_target
from .losses import compute_loss_from_outputs
from .flash_attention import flash_attention
from .ring_attention import (
    full_attention_reference,
    masked_ring_attention_shard,
    masked_ring_self_attention,
    ring_attention_shard,
    ring_self_attention,
)

__all__ = [
    "compute_target",
    "compute_loss_from_outputs",
    "flash_attention",
    "ring_attention_shard",
    "ring_self_attention",
    "masked_ring_attention_shard",
    "masked_ring_self_attention",
    "full_attention_reference",
]
