from .targets import compute_target
from .losses import compute_loss_from_outputs

__all__ = ["compute_target", "compute_loss_from_outputs"]
