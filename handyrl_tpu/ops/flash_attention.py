"""Pallas TPU flash-attention kernel with an O(T·blk)-memory backward.

A standalone long-context attention op: plain causal (or full) attention
over contiguous fully-observed sequences — the regime where the O(T^2)
score matrix stops fitting.  Note what it is NOT wired into: the
transformer's seq training mode (models/transformer.py) needs per-key
observation masks and observed-step age biases, which this kernel does
not support, so that path uses an exact-mask einsum (fine at RL window
lengths); ring attention (ops/ring_attention.py) needs externally-carried
softmax accumulators across ring steps, which a complete-attention kernel
cannot provide.  Callers with trivially-masked long sequences dispatch
here directly.

Forward: one grid program per (batch*head, query-tile, key-tile) — K/V
stream through VMEM one (blk_k, D) tile at a time while running
max / denominator / output accumulators persist in VMEM scratch across
the key-tile grid axis, so neither the score matrix nor the full K/V
ever resides on-chip.  fp32 accumulation on the MXU; causal key tiles
above the diagonal are predicated off.

Backward: recompute per query-chunk under ``lax.scan`` — softmax vjp on
a (blk, T) score slab per step, accumulating dK/dV — peak memory
O(T·blk) instead of the O(T^2) a naive vjp residual would keep.

Layout: (B, T, H, D) like the rest of the ops layer.  Head dims are
zero-padded to the 128-lane tile internally; tiles are 128-aligned per
the TPU tiling constraints (pallas_guide.md "Tiling Constraints").
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from .ring_attention import NEG_INF, full_attention_reference

_LANE = 128


def _flash_kernel(
    q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref, *, blk_q, blk_k, n_k, causal, scale
):
    """One (batch-head, q-tile, k-tile) program; accumulators in scratch."""
    pl = _pl()
    qi = pl.program_id(1)
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    # causal: key tiles strictly above the q tile's diagonal are no-ops
    live = (kb * blk_k < (qi + 1) * blk_q) if causal else True

    @pl.when(live)
    def _():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale                                           # (blk_q, blk_k)
        if causal:
            qpos = qi * blk_q + jax.lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 0)
            kpos = kb * blk_k + jax.lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 1)
            s = jnp.where(qpos >= kpos, s, NEG_INF)
        m_prev, l_prev, acc_prev = m_ref[:], l_ref[:], acc_ref[:]
        m_blk = s.max(axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_blk)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_ref[:] = l_prev * alpha + p.sum(axis=-1, keepdims=True)
        acc_ref[:] = acc_prev * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        m_ref[:] = m_new

    @pl.when(kb == n_k - 1)
    def _():
        o_ref[0] = (acc_ref[:] / jnp.maximum(l_ref[:], 1e-30)).astype(o_ref.dtype)


def _pl():
    from jax.experimental import pallas as pl

    return pl


def _flash_forward(q, k, v, causal, blk_q, blk_k, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, T, H, D = q.shape
    scale = 1.0 / (D ** 0.5)

    # (B*H, T, D_pad): fold heads into the grid, pad head dim to the lane tile
    def fold(x):
        x = jnp.moveaxis(x, 2, 1).reshape(B * H, T, D)
        if D % _LANE:
            x = jnp.pad(x, ((0, 0), (0, 0), (0, _LANE - D % _LANE)))
        return x

    qf, kf, vf = fold(q), fold(k), fold(v)
    Dp = qf.shape[-1]
    blk_q = min(blk_q, T)
    blk_k = min(blk_k, T)
    if T % blk_q or T % blk_k:
        raise ValueError(f"sequence length {T} must divide into tiles {blk_q}/{blk_k}")
    n_q, n_k = T // blk_q, T // blk_k

    kernel = functools.partial(
        _flash_kernel, blk_q=blk_q, blk_k=blk_k, n_k=n_k, causal=causal, scale=scale
    )
    out = pl.pallas_call(
        kernel,
        grid=(B * H, n_q, n_k),
        in_specs=[
            pl.BlockSpec((1, blk_q, Dp), lambda bh, qi, kb: (bh, qi, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, blk_k, Dp), lambda bh, qi, kb: (bh, kb, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, blk_k, Dp), lambda bh, qi, kb: (bh, kb, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (1, blk_q, Dp), lambda bh, qi, kb: (bh, qi, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((B * H, T, Dp), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((blk_q, Dp), jnp.float32),
            pltpu.VMEM((blk_q, 1), jnp.float32),
            pltpu.VMEM((blk_q, 1), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf)

    out = out[..., :D].reshape(B, H, T, D)
    return jnp.moveaxis(out, 1, 2)                          # (B, T, H, D)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(
    q,
    k,
    v,
    causal: bool = True,
    blk_q: int = 128,
    blk_k: int = 128,
    interpret: Optional[bool] = None,
):
    """Flash attention over (B, T, H, D); Pallas on TPU, interpreter elsewhere.

    ``interpret=None`` auto-selects: compiled kernel on TPU backends, the
    Pallas interpreter on CPU (slow but exact — for tests and dry runs).
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _flash_forward(q, k, v, causal, blk_q, blk_k, interpret)


def _fwd(q, k, v, causal, blk_q, blk_k, interpret):
    return flash_attention(q, k, v, causal, blk_q, blk_k, interpret), (q, k, v)


def _bwd(causal, blk_q, blk_k, interpret, residuals, g):
    """Chunked recompute backward: scan over query chunks, softmax-vjp each
    (blk, T) score slab, accumulate dK/dV — peak memory O(T·blk)."""
    q, k, v = residuals
    B, T, H, D = q.shape
    scale = 1.0 / (D ** 0.5)
    C = min(blk_q, T)
    n_c = T // C

    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    gf = g.astype(jnp.float32)

    q_chunks = jnp.moveaxis(qf.reshape(B, n_c, C, H, D), 1, 0)   # (n_c,B,C,H,D)
    g_chunks = jnp.moveaxis(gf.reshape(B, n_c, C, H, D), 1, 0)
    starts = jnp.arange(n_c) * C

    def body(carry, inp):
        dk, dv = carry
        q_c, g_c, q0 = inp
        s = jnp.einsum("bqhd,bkhd->bhqk", q_c, kf) * scale        # (B,H,C,T)
        if causal:
            qpos = q0 + jnp.arange(C)
            kpos = jnp.arange(T)
            s = jnp.where((qpos[:, None] >= kpos[None, :])[None, None], s, NEG_INF)
        p = jax.nn.softmax(s, axis=-1)
        dp = jnp.einsum("bqhd,bkhd->bhqk", g_c, vf)
        ds = p * (dp - (dp * p).sum(axis=-1, keepdims=True))      # softmax vjp
        dq_c = jnp.einsum("bhqk,bkhd->bqhd", ds, kf) * scale
        dk = dk + jnp.einsum("bhqk,bqhd->bkhd", ds, q_c) * scale
        dv = dv + jnp.einsum("bhqk,bqhd->bkhd", p, g_c)
        return (dk, dv), dq_c

    (dk, dv), dq_chunks = jax.lax.scan(
        body, (jnp.zeros_like(kf), jnp.zeros_like(vf)), (q_chunks, g_chunks, starts)
    )
    dq = jnp.moveaxis(dq_chunks, 0, 1).reshape(B, T, H, D)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


flash_attention.defvjp(_fwd, _bwd)
