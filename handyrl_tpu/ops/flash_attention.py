"""Pallas TPU flash-attention kernel (forward) with a recompute backward.

A standalone long-context attention op: plain causal (or full) attention
over contiguous fully-observed sequences — the regime where the O(T^2)
score matrix stops fitting.  Note what it is NOT wired into: the
transformer's seq training mode (models/transformer.py) needs per-key
observation masks and observed-step age biases, which this kernel does
not support, so that path uses an exact-mask einsum (fine at RL window
lengths); ring attention (ops/ring_attention.py) needs externally-carried
softmax accumulators across ring steps, which a complete-attention kernel
cannot provide.  Callers with trivially-masked long sequences dispatch
here directly.

The forward is an online-softmax (flash) kernel:
one grid program per (batch*head, query-tile) streams K/V tiles from VMEM,
keeping running max / denominator so the T x T score matrix never
materializes — O(T) memory instead of O(T^2), with the two matmuls on the
MXU in fp32 accumulation.  Causal masking prunes the K-tile loop at the
query tile's diagonal, halving work for causal training.

The backward recomputes attention with standard XLA einsums (flash
backward kernels trade FLOPs for memory the same way; XLA's fusion is
already good at this shape, and recompute keeps the save-for-backward
residuals at O(T)).

Layout: (B, T, H, D) like the rest of the ops layer.  The head dim is
zero-padded to the 128-lane tile internally; tiles are 128-aligned per
the TPU tiling constraints (pallas_guide.md "Tiling Constraints").
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e30
_LANE = 128


def _reference(q, k, v, causal):
    """XLA attention in fp32 — the math the kernel must match, also used to
    derive the backward pass by recompute."""
    scale = 1.0 / (q.shape[-1] ** 0.5)
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32)) * scale
    if causal:
        T = q.shape[1]
        mask = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(mask[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32)).astype(q.dtype)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, blk_q, blk_k, n_k, causal, scale):
    """One (batch-head, q-tile) program: stream K/V tiles with online softmax."""
    qi = jax.lax.convert_element_type(_pl().program_id(1), jnp.int32)
    q = q_ref[0].astype(jnp.float32)                       # (blk_q, D)

    acc = jnp.zeros(q.shape, jnp.float32)
    m = jnp.full((q.shape[0], 1), NEG_INF, jnp.float32)
    l = jnp.zeros((q.shape[0], 1), jnp.float32)

    # causal: tiles strictly above the diagonal contribute nothing
    upper = jnp.minimum((qi + 1) * blk_q, n_k * blk_k) if causal else n_k * blk_k
    n_tiles = _pl().cdiv(upper, blk_k) if causal else n_k

    def body(kb, carry):
        acc, m, l = carry
        k = k_ref[0, _pl().ds(kb * blk_k, blk_k), :].astype(jnp.float32)
        v = v_ref[0, _pl().ds(kb * blk_k, blk_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale                                           # (blk_q, blk_k)
        if causal:
            qpos = qi * blk_q + jax.lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 0)
            kpos = kb * blk_k + jax.lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 1)
            s = jnp.where(qpos >= kpos, s, NEG_INF)
        m_blk = s.max(axis=-1, keepdims=True)
        m_new = jnp.maximum(m, m_blk)
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)
        l = l * alpha + p.sum(axis=-1, keepdims=True)
        acc = acc * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return acc, m_new, l

    acc, m, l = jax.lax.fori_loop(0, n_tiles, body, (acc, m, l))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _pl():
    from jax.experimental import pallas as pl

    return pl


def _flash_forward(q, k, v, causal, blk_q, blk_k, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, T, H, D = q.shape
    scale = 1.0 / (D ** 0.5)

    # (B*H, T, D_pad): fold heads into the grid, pad head dim to the lane tile
    def fold(x):
        x = jnp.moveaxis(x, 2, 1).reshape(B * H, T, D)
        if D % _LANE:
            x = jnp.pad(x, ((0, 0), (0, 0), (0, _LANE - D % _LANE)))
        return x

    qf, kf, vf = fold(q), fold(k), fold(v)
    Dp = qf.shape[-1]
    blk_q = min(blk_q, T)
    blk_k = min(blk_k, T)
    if T % blk_q or T % blk_k:
        raise ValueError(f"sequence length {T} must divide into tiles {blk_q}/{blk_k}")
    n_q, n_k = T // blk_q, T // blk_k

    kernel = functools.partial(
        _flash_kernel, blk_q=blk_q, blk_k=blk_k, n_k=n_k, causal=causal, scale=scale
    )
    out = pl.pallas_call(
        kernel,
        grid=(B * H, n_q),
        in_specs=[
            pl.BlockSpec((1, blk_q, Dp), lambda bh, qi: (bh, qi, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, T, Dp), lambda bh, qi: (bh, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, T, Dp), lambda bh, qi: (bh, 0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (1, blk_q, Dp), lambda bh, qi: (bh, qi, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((B * H, T, Dp), q.dtype),
        interpret=interpret,
    )(qf, kf, vf)

    out = out[..., :D].reshape(B, H, T, D)
    return jnp.moveaxis(out, 1, 2)                          # (B, T, H, D)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(
    q,
    k,
    v,
    causal: bool = True,
    blk_q: int = 128,
    blk_k: int = 128,
    interpret: Optional[bool] = None,
):
    """Flash attention over (B, T, H, D); Pallas on TPU, interpreter elsewhere.

    ``interpret=None`` auto-selects: compiled kernel on TPU backends, the
    Pallas interpreter on CPU (slow but exact — for tests and dry runs).
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _flash_forward(q, k, v, causal, blk_q, blk_k, interpret)


def _fwd(q, k, v, causal, blk_q, blk_k, interpret):
    return flash_attention(q, k, v, causal, blk_q, blk_k, interpret), (q, k, v)


def _bwd(causal, blk_q, blk_k, interpret, residuals, g):
    q, k, v = residuals
    _, vjp = jax.vjp(lambda q, k, v: _reference(q, k, v, causal), q, k, v)
    return vjp(g)


flash_attention.defvjp(_fwd, _bwd)
