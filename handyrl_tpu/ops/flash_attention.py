"""Pallas TPU flash-attention kernels with O(T·blk)-memory backwards.

Two entry points:

* ``flash_attention`` — plain causal (or full) attention over contiguous
  fully-observed sequences; the regime where the O(T^2) score matrix
  stops fitting.
* ``masked_flash_attention`` — the production transformer training path
  (models/transformer.py seq mode): per-key observation masks, ALiBi-style
  biases over *observed-step* ages, and ring-buffer eviction (keys older
  than ``window`` observed steps invisible), all evaluated inside the
  kernel from streamed (B, T) mask/count rows.  Its exact einsum
  counterpart is ``masked_attention_reference`` — the same function
  ``CachedSelfAttention``'s einsum branch executes — and the two are
  golden-tested against each other (forward + custom-VJP gradients) in
  tests/test_flash_attention.py.

Ring attention (ops/ring_attention.py) still carries its own softmax
accumulators across ring steps and does not dispatch here.

Forward: one grid program per (batch*head, query-tile, key-tile) — K/V
stream through VMEM one (blk_k, D) tile at a time while running
max / denominator / output accumulators persist in VMEM scratch across
the key-tile grid axis, so neither the score matrix nor the full K/V
ever resides on-chip.  fp32 accumulation on the MXU; causal key tiles
above the diagonal are predicated off.

Backward: recompute per query-chunk under ``lax.scan`` — softmax vjp on
a (blk, T) score slab per step, accumulating dK/dV — peak memory
O(T·blk) instead of the O(T^2) a naive vjp residual would keep.

Layout: (B, T, H, D) like the rest of the ops layer.  Head dims are
zero-padded to the 128-lane tile internally; sequence lengths are padded
to the tile size with masked-off keys; tiles are 128-aligned per the TPU
tiling constraints (pallas_guide.md "Tiling Constraints").
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from .ring_attention import NEG_INF, full_attention_reference

_LANE = 128


def effective_blocks(T: int, blk_q: int, blk_k: int):
    """The (blk_q, blk_k, Tp) the masked kernel will actually run at for a
    window of length ``T``: blocks clamp to the 128-lane tile, and T pads
    up to a common multiple of both blocks.  The startup validation
    (config.validate_args + TrainContext) enforces power-of-two blocks,
    which makes the divisibility here hold BY CONSTRUCTION (the smaller
    power of two divides the larger); anyone relaxing that rule must add
    an explicit padded-window check against this function, or an invalid
    tiling will first fail inside the compiled kernel."""
    blk_q = min(int(blk_q), _LANE)
    blk_k = min(int(blk_k), _LANE)
    Tp = -(-T // blk_q) * blk_q
    Tp = -(-Tp // blk_k) * blk_k
    return blk_q, blk_k, Tp


def _flash_kernel(
    q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref, *, blk_q, blk_k, n_k, causal, scale
):
    """One (batch-head, q-tile, k-tile) program; accumulators in scratch."""
    pl = _pl()
    qi = pl.program_id(1)
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    # causal: key tiles strictly above the q tile's diagonal are no-ops
    live = (kb * blk_k < (qi + 1) * blk_q) if causal else True

    @pl.when(live)
    def _():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale                                           # (blk_q, blk_k)
        if causal:
            qpos = qi * blk_q + jax.lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 0)
            kpos = kb * blk_k + jax.lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 1)
            s = jnp.where(qpos >= kpos, s, NEG_INF)
        m_prev, l_prev, acc_prev = m_ref[:], l_ref[:], acc_ref[:]
        m_blk = s.max(axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_blk)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_ref[:] = l_prev * alpha + p.sum(axis=-1, keepdims=True)
        acc_ref[:] = acc_prev * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        m_ref[:] = m_new

    @pl.when(kb == n_k - 1)
    def _():
        o_ref[0] = (acc_ref[:] / jnp.maximum(l_ref[:], 1e-30)).astype(o_ref.dtype)


def _pl():
    from jax.experimental import pallas as pl

    return pl


def _flash_forward(q, k, v, causal, blk_q, blk_k, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, T, H, D = q.shape
    scale = 1.0 / (D ** 0.5)

    # (B*H, T, D_pad): fold heads into the grid, pad head dim to the lane tile
    def fold(x):
        x = jnp.moveaxis(x, 2, 1).reshape(B * H, T, D)
        if D % _LANE:
            x = jnp.pad(x, ((0, 0), (0, 0), (0, _LANE - D % _LANE)))
        return x

    qf, kf, vf = fold(q), fold(k), fold(v)
    Dp = qf.shape[-1]
    blk_q = min(blk_q, T)
    blk_k = min(blk_k, T)
    if T % blk_q or T % blk_k:
        raise ValueError(f"sequence length {T} must divide into tiles {blk_q}/{blk_k}")
    n_q, n_k = T // blk_q, T // blk_k

    kernel = functools.partial(
        _flash_kernel, blk_q=blk_q, blk_k=blk_k, n_k=n_k, causal=causal, scale=scale
    )
    out = pl.pallas_call(
        kernel,
        grid=(B * H, n_q, n_k),
        in_specs=[
            pl.BlockSpec((1, blk_q, Dp), lambda bh, qi, kb: (bh, qi, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, blk_k, Dp), lambda bh, qi, kb: (bh, kb, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, blk_k, Dp), lambda bh, qi, kb: (bh, kb, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (1, blk_q, Dp), lambda bh, qi, kb: (bh, qi, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((B * H, T, Dp), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((blk_q, Dp), jnp.float32),
            pltpu.VMEM((blk_q, 1), jnp.float32),
            pltpu.VMEM((blk_q, 1), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf)

    out = out[..., :D].reshape(B, H, T, D)
    return jnp.moveaxis(out, 1, 2)                          # (B, T, H, D)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(
    q,
    k,
    v,
    causal: bool = True,
    blk_q: int = 128,
    blk_k: int = 128,
    interpret: Optional[bool] = None,
):
    """Flash attention over (B, T, H, D); Pallas on TPU, interpreter elsewhere.

    ``interpret=None`` auto-selects: compiled kernel on TPU backends, the
    Pallas interpreter on CPU (slow but exact — for tests and dry runs).
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _flash_forward(q, k, v, causal, blk_q, blk_k, interpret)


def _fwd(q, k, v, causal, blk_q, blk_k, interpret):
    return flash_attention(q, k, v, causal, blk_q, blk_k, interpret), (q, k, v)


def _bwd(causal, blk_q, blk_k, interpret, residuals, g):
    """Chunked recompute backward: scan over query chunks, softmax-vjp each
    (blk, T) score slab, accumulate dK/dV — peak memory O(T·blk)."""
    q, k, v = residuals
    B, T, H, D = q.shape
    scale = 1.0 / (D ** 0.5)
    C = min(blk_q, T)
    n_c = T // C

    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    gf = g.astype(jnp.float32)

    q_chunks = jnp.moveaxis(qf.reshape(B, n_c, C, H, D), 1, 0)   # (n_c,B,C,H,D)
    g_chunks = jnp.moveaxis(gf.reshape(B, n_c, C, H, D), 1, 0)
    starts = jnp.arange(n_c) * C

    def body(carry, inp):
        dk, dv = carry
        q_c, g_c, q0 = inp
        s = jnp.einsum("bqhd,bkhd->bhqk", q_c, kf) * scale        # (B,H,C,T)
        if causal:
            qpos = q0 + jnp.arange(C)
            kpos = jnp.arange(T)
            s = jnp.where((qpos[:, None] >= kpos[None, :])[None, None], s, NEG_INF)
        p = jax.nn.softmax(s, axis=-1)
        dp = jnp.einsum("bqhd,bkhd->bhqk", g_c, vf)
        ds = p * (dp - (dp * p).sum(axis=-1, keepdims=True))      # softmax vjp
        dq_c = jnp.einsum("bhqk,bkhd->bqhd", ds, kf) * scale
        dk = dk + jnp.einsum("bhqk,bqhd->bkhd", ds, q_c) * scale
        dv = dv + jnp.einsum("bhqk,bqhd->bkhd", p, g_c)
        return (dk, dv), dq_c

    (dk, dv), dq_chunks = jax.lax.scan(
        body, (jnp.zeros_like(kf), jnp.zeros_like(vf)), (q_chunks, g_chunks, starts)
    )
    dq = jnp.moveaxis(dq_chunks, 0, 1).reshape(B, T, H, D)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


flash_attention.defvjp(_fwd, _bwd)


# ---------------------------------------------------------------------------
# masked flash attention: key masks + observed-age ALiBi + window eviction
# ---------------------------------------------------------------------------


def _masked_flash_kernel(
    q_ref, k_ref, v_ref, cq_ref, ck_ref, mk_ref, slope_ref,
    o_ref, acc_ref, m_ref, l_ref,
    *, blk_q, blk_k, n_k, window, scale,
):
    """Like _flash_kernel, plus per-key validity streamed from (B, T) rows:

    age[q, k]  = counts[q] - counts[k]       (observed-step age)
    valid      = key_mask[k] & causal & 0 <= age < window,  OR  q == k
    score      = q·k·scale − slope·age   (NEG_INF where invalid)

    Invalid probabilities are zeroed explicitly so tiles whose every entry
    is invalid cannot pollute the running denominator (exp(NEG_INF −
    NEG_INF) = 1 would otherwise leak in before the first valid tile).
    """
    pl = _pl()
    qi = pl.program_id(1)
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    live = kb * blk_k < (qi + 1) * blk_q  # strictly-future key tiles: no-op

    @pl.when(live)
    def _():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        c_q = cq_ref[0, 0].astype(jnp.float32)               # (blk_q,)
        c_k = ck_ref[0, 0].astype(jnp.float32)               # (blk_k,)
        m_k = mk_ref[0, 0].astype(jnp.float32)
        slope = slope_ref[0, 0, 0]

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale                                            # (blk_q, blk_k)
        qpos = qi * blk_q + jax.lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 0)
        kpos = kb * blk_k + jax.lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 1)
        age = c_q[:, None] - c_k[None, :]
        valid = (
            (m_k[None, :] > 0)
            & (qpos >= kpos)
            & (age >= 0)
            & (age < window)
        )
        valid = valid | (qpos == kpos)                        # self always visible
        s = jnp.where(valid, s - slope * age, NEG_INF)

        m_prev, l_prev, acc_prev = m_ref[:], l_ref[:], acc_ref[:]
        m_blk = s.max(axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_blk)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new) * valid.astype(jnp.float32)
        l_ref[:] = l_prev * alpha + p.sum(axis=-1, keepdims=True)
        acc_ref[:] = acc_prev * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        m_ref[:] = m_new

    @pl.when(kb == n_k - 1)
    def _():
        o_ref[0] = (acc_ref[:] / jnp.maximum(l_ref[:], 1e-30)).astype(o_ref.dtype)


def _masked_scores(q_c, kf, c_q, counts, key_mask, slopes, window, q0, scale, k0=0):
    """THE seq-mode attention semantics, as one score construction shared
    by every execution (einsum reference, Pallas kernel backward, masked
    ring shard): (B, H, C, T) biased+masked scores for a query chunk at
    global position ``q0`` against keys at global position ``k0``."""
    C = q_c.shape[1]
    T = kf.shape[1]
    # fp32 accumulation out of the MXU regardless of input dtype: bf16
    # operands keep the matmul at bf16 rate, scores/softmax stay accurate
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q_c, kf, preferred_element_type=jnp.float32
    ) * scale
    age = c_q[:, :, None] - counts[:, None, :]                # (B, C, T)
    qpos = q0 + jnp.arange(C)
    kpos = k0 + jnp.arange(T)
    valid = (
        (key_mask[:, None, :] > 0)
        & (qpos[:, None] >= kpos[None, :])[None]
        & (age >= 0)
        & (age < window)
    )
    valid = valid | (qpos[:, None] == kpos[None, :])[None]
    s = s - slopes[None, :, None, None] * age[:, None]
    s = jnp.where(valid[:, None], s, NEG_INF)
    return s, valid


def _masked_flash_forward(q, k, v, key_mask, slopes, window, blk_q, blk_k, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, T, H, D = q.shape
    scale = 1.0 / (D ** 0.5)
    counts = jnp.cumsum(key_mask.astype(jnp.float32), axis=1)  # observed count

    blk_q, blk_k, Tp = effective_blocks(T, blk_q, blk_k)

    def fold(x):
        x = jnp.moveaxis(x, 2, 1).reshape(B * H, T, D)
        pads = ((0, 0), (0, Tp - T), (0, (-D) % _LANE))
        return jnp.pad(x, pads)

    qf, kf, vf = fold(q), fold(k), fold(v)
    Dp = qf.shape[-1]
    n_q, n_k = Tp // blk_q, Tp // blk_k

    # padded key rows: mask 0 (invisible), counts edge-padded (finite ages).
    # Rows ride as (B, 1, Tp) so their VMEM blocks are (1, 1, blk): the TPU
    # tiling rule wants the block's last two dims divisible by (8, 128) or
    # equal to the array dims — (1, blk) against a (B, Tp) array is neither
    # (round-1 bench failure on the real chip; the interpreter accepted it).
    mask_p = jnp.pad(key_mask.astype(jnp.float32), ((0, 0), (0, Tp - T)))[:, None, :]
    counts_p = jnp.pad(counts, ((0, 0), (0, Tp - T)), mode="edge")[:, None, :]
    slopes_col = jnp.tile(slopes.astype(jnp.float32)[None, :], (B, 1)).reshape(B * H, 1, 1)

    kernel = functools.partial(
        _masked_flash_kernel,
        blk_q=blk_q, blk_k=blk_k, n_k=n_k, window=float(window), scale=scale,
    )
    out = pl.pallas_call(
        kernel,
        grid=(B * H, n_q, n_k),
        in_specs=[
            pl.BlockSpec((1, blk_q, Dp), lambda bh, qi, kb: (bh, qi, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, blk_k, Dp), lambda bh, qi, kb: (bh, kb, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, blk_k, Dp), lambda bh, qi, kb: (bh, kb, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, blk_q), lambda bh, qi, kb: (bh // H, 0, qi), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, blk_k), lambda bh, qi, kb: (bh // H, 0, kb), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, blk_k), lambda bh, qi, kb: (bh // H, 0, kb), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, 1), lambda bh, qi, kb: (bh, 0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (1, blk_q, Dp), lambda bh, qi, kb: (bh, qi, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((B * H, Tp, Dp), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((blk_q, Dp), jnp.float32),
            pltpu.VMEM((blk_q, 1), jnp.float32),
            pltpu.VMEM((blk_q, 1), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf, counts_p, counts_p, mask_p, slopes_col)

    out = out[:, :T, :D].reshape(B, H, T, D)
    return jnp.moveaxis(out, 1, 2)                            # (B, T, H, D)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8))
def masked_flash_attention(
    q, k, v, key_mask, slopes,
    window: int = 1 << 30,
    blk_q: int = 128,
    blk_k: int = 128,
    interpret: Optional[bool] = None,
):
    """Causal flash attention with per-key masks, observed-age ALiBi bias
    and window eviction — the transformer seq-mode attention semantics
    (models/transformer.py CachedSelfAttention) as one Pallas kernel.

    q/k/v: (B, T, H, D); key_mask: (B, T) 1.0 = observed; slopes: (H,).
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _masked_flash_forward(q, k, v, key_mask, slopes, window, blk_q, blk_k, interpret)


def _masked_fwd(q, k, v, key_mask, slopes, window, blk_q, blk_k, interpret):
    out = masked_flash_attention(q, k, v, key_mask, slopes, window, blk_q, blk_k, interpret)
    return out, (q, k, v, key_mask, slopes)


def _masked_bwd(window, blk_q, blk_k, interpret, residuals, g):
    """Chunked recompute backward with the same masked/biased scores."""
    q, k, v, key_mask, slopes = residuals
    B, T, H, D = q.shape
    scale = 1.0 / (D ** 0.5)
    C = min(blk_q, T)
    while T % C:
        C -= 1
    n_c = T // C

    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    counts = jnp.cumsum(key_mask.astype(jnp.float32), axis=1)
    slopes_f = slopes.astype(jnp.float32)

    q_chunks = jnp.moveaxis(qf.reshape(B, n_c, C, H, D), 1, 0)
    g_chunks = jnp.moveaxis(gf.reshape(B, n_c, C, H, D), 1, 0)
    c_chunks = jnp.moveaxis(counts.reshape(B, n_c, C), 1, 0)
    starts = jnp.arange(n_c) * C

    def body(carry, inp):
        dk, dv = carry
        q_c, g_c, c_q, q0 = inp
        s, valid = _masked_scores(q_c, kf, c_q, counts, key_mask, slopes_f, window, q0, scale)
        p = jax.nn.softmax(s, axis=-1) * valid[:, None].astype(jnp.float32)
        dp = jnp.einsum("bqhd,bkhd->bhqk", g_c, vf)
        ds = p * (dp - (dp * p).sum(axis=-1, keepdims=True))
        dq_c = jnp.einsum("bhqk,bkhd->bqhd", ds, kf) * scale
        dk = dk + jnp.einsum("bhqk,bqhd->bkhd", ds, q_c) * scale
        dv = dv + jnp.einsum("bhqk,bqhd->bkhd", p, g_c)
        return (dk, dv), dq_c

    (dk, dv), dq_chunks = jax.lax.scan(
        body, (jnp.zeros_like(kf), jnp.zeros_like(vf)),
        (q_chunks, g_chunks, c_chunks, starts),
    )
    dq = jnp.moveaxis(dq_chunks, 0, 1).reshape(B, T, H, D)
    return (
        dq.astype(q.dtype),
        dk.astype(k.dtype),
        dv.astype(v.dtype),
        jnp.zeros_like(key_mask),
        jnp.zeros_like(slopes),
    )


masked_flash_attention.defvjp(_masked_fwd, _masked_bwd)


def masked_attention_reference(q, k, v, key_mask, slopes, window: int = 1 << 30):
    """Exact einsum counterpart of masked_flash_attention — also the
    production einsum branch (models/transformer.py CachedSelfAttention
    seq mode).  q/k/v stay in their input dtype (bf16 operands keep both
    matmuls at MXU bf16 rate); scores and softmax are fp32 via the
    einsum's accumulation dtype."""
    B, T, H, D = q.shape
    counts = jnp.cumsum(key_mask.astype(jnp.float32), axis=1)
    s, valid = _masked_scores(
        q, k, counts, counts,
        key_mask, slopes.astype(jnp.float32), window, 0, 1.0 / (D ** 0.5),
    )
    attn = (jax.nn.softmax(s, axis=-1) * valid[:, None]).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", attn, v)
