"""Policy-gradient loss core (pure function of net outputs + batch).

Semantics parity with reference handyrl/train.py:190-268 (compute_loss /
compose_losses): clipped importance sampling (rho/c capped at 1), optional
two-player zero-sum value symmetrization, outcome bootstrap beyond episode
end, separate policy/value target algorithms, entropy regularization with
progress-based decay.

Everything here is jax-traceable and shape-static: it runs inside the one
jitted training step (parallel/train_step.py).  Model-dependent forward
prediction is NOT here — this consumes its outputs.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from .targets import compute_target


def _huber(x, delta: float = 1.0):
    """Smooth-L1 (torch F.smooth_l1_loss semantics, beta=1)."""
    absx = jnp.abs(x)
    return jnp.where(absx < delta, 0.5 * x * x / delta, absx - 0.5 * delta)


def entropy_from_logits(logits):
    """Categorical entropy over the last axis; safe with -1e32 legal masks."""
    ls = jax.nn.log_softmax(logits, axis=-1)
    p = jnp.exp(ls)
    return -(p * ls).sum(axis=-1)


def compute_loss_from_outputs(
    outputs: Dict[str, jnp.ndarray],
    batch: Dict[str, Any],
    args: Dict[str, Any],
) -> Tuple[Dict[str, jnp.ndarray], jnp.ndarray]:
    """Compute losses given already-trimmed outputs/batch (burn-in removed).

    outputs['policy'] must already be turn-masked and legal-action-masked
    (see parallel/train_step.forward_prediction).

    Returns (losses dict incl. 'total', data count = turn mask sum).
    """
    actions = batch["action"]          # (B, T, P, 1) int32
    emasks = batch["episode_mask"]     # (B, T, 1, 1)
    tmasks = batch["turn_mask"]        # (B, T, P, 1)
    omasks = batch["observation_mask"]  # (B, T, P, 1)

    clip_rho, clip_c = 1.0, 1.0

    log_behavior = jnp.log(jnp.clip(batch["selected_prob"], 1e-16, 1.0)) * emasks
    log_pi = jax.nn.log_softmax(outputs["policy"], axis=-1)
    log_target = jnp.take_along_axis(log_pi, actions, axis=-1) * emasks

    log_rhos = jax.lax.stop_gradient(log_target) - log_behavior
    rhos = jnp.exp(log_rhos)
    clipped_rhos = jnp.clip(rhos, 0.0, clip_rho)
    cs = jnp.clip(rhos, 0.0, clip_c)

    outputs_nograd = {k: jax.lax.stop_gradient(v) for k, v in outputs.items()}
    value_target_masks = omasks

    if "value" in outputs_nograd:
        values_nograd = outputs_nograd["value"]
        if args["turn_based_training"] and values_nograd.shape[2] == 2:
            # Two-player zero-sum: each player's value estimate is averaged
            # with the negation of the opponent's (train.py:244-248).
            values_opp = -jnp.flip(values_nograd, axis=2)
            omasks_opp = jnp.flip(omasks, axis=2)
            values_nograd = (values_nograd * omasks + values_opp * omasks_opp) / (
                omasks + omasks_opp + 1e-8
            )
            value_target_masks = jnp.clip(omasks + omasks_opp, 0.0, 1.0)
        # Beyond episode end the target value is the final outcome.
        outputs_nograd["value"] = values_nograd * emasks + batch["outcome"] * (1 - emasks)

    lmb, gamma = args["lambda"], args["gamma"]
    value_args = (outputs_nograd.get("value"), batch["outcome"], None, lmb, 1.0, clipped_rhos, cs, value_target_masks)
    return_args = (outputs_nograd.get("return"), batch["return"], batch["reward"], lmb, gamma, clipped_rhos, cs, omasks)

    targets, advantages = {}, {}
    targets["value"], advantages["value"] = compute_target(args["value_target"], *value_args)
    targets["return"], advantages["return"] = compute_target(args["value_target"], *return_args)
    if args["policy_target"] != args["value_target"]:
        _, advantages["value"] = compute_target(args["policy_target"], *value_args)
        _, advantages["return"] = compute_target(args["policy_target"], *return_args)

    total_advantages = clipped_rhos * (advantages["value"] + advantages["return"])

    # -- compose (train.py:190-216) ---------------------------------------
    losses: Dict[str, jnp.ndarray] = {}
    dcnt = tmasks.sum()

    losses["p"] = (-log_target * jax.lax.stop_gradient(total_advantages) * tmasks).sum()
    if "value" in outputs:
        losses["v"] = (((outputs["value"] - targets["value"]) ** 2) * omasks).sum() / 2
    if "return" in outputs:
        losses["r"] = (_huber(outputs["return"] - targets["return"]) * omasks).sum()

    entropy = entropy_from_logits(outputs["policy"]) * tmasks.sum(axis=-1)  # (B, T, P)
    losses["ent"] = entropy.sum()

    # progress is (B, T, 1): broadcasts over the player axis of entropy.
    progress_decay = 1 - batch["progress"] * (1 - args["entropy_regularization_decay"])
    entropy_loss = (entropy * progress_decay).sum() * -args["entropy_regularization"]

    base = losses["p"] + losses.get("v", 0.0) + losses.get("r", 0.0)
    losses["total"] = base + entropy_loss

    return losses, dcnt
