"""Ring attention: sequence-parallel exact attention over an ``sp`` mesh axis.

Long-context support beyond the reference (which scales sequence length
*down* via windows + burn-in, SURVEY.md §5.7; train.py:93-107): here the
time axis shards across devices and exact attention is computed blockwise
— each device holds its Q shard, while K/V shards rotate around the ring
via ``ppermute`` (one ICI hop per step), merged with a streaming
(flash-style) softmax.  Memory per device is O(T/n) and the K/V transfer
overlaps compute, so context length scales linearly with the mesh's
``sp`` size.

Layout: ``(B, T, H, D)`` — batch, time, heads, head dim.  Works standalone
under ``shard_map`` (``ring_attention_shard``) or through the convenience
wrapper ``ring_self_attention`` which builds the shard_map over a mesh
with ``sp`` (and optionally ``dp``) axes.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

try:
    from jax import shard_map  # jax >= 0.8
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

NEG_INF = -1e30


def _block_attention(q, k, v, q_off, k_off, scale, causal):
    """One Q-shard x K/V-block attention with running-softmax stats.

    q: (B, Tq, H, D); k, v: (B, Tk, H, D).
    Returns (o, m, l): unnormalized output (B, Tq, H, D), row max (B, H, Tq),
    row sum (B, H, Tq).
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32) * scale
    if causal:
        qpos = q_off + jnp.arange(q.shape[1])
        kpos = k_off + jnp.arange(k.shape[1])
        mask = qpos[:, None] >= kpos[None, :]
        s = jnp.where(mask[None, None], s, NEG_INF)
    m = s.max(axis=-1)                                   # (B, H, Tq)
    p = jnp.exp(s - m[..., None])
    l = p.sum(axis=-1)                                   # (B, H, Tq)
    o = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)
    return o, m, l


def _ring_loop(q, k, v, extras, axis_name: str, scores_fn, vary_axes=()):
    """Shared ring mechanics: each participant holds contiguous time
    shards of equal length (shard i owns positions [i*T_loc, (i+1)*T_loc));
    K/V (and any ``extras`` keyed to the K shard) rotate to the next device
    every step via ppermute, so after n steps every Q shard has seen every
    K/V shard; blocks merge through a streaming (flash-style) softmax.

    ``scores_fn(qf, kf, extras, q0, k0) -> (B, H, Tq, Tk)`` builds the
    (masked/biased) scores for one block — the only part that differs
    between the causal and the production masked semantics.
    ``vary_axes`` lists any additional manual mesh axes in scope (e.g. a
    'dp' batch axis) so the accumulators carry the right varying type.
    """
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    B, T_loc, H, D = q.shape
    qf = q.astype(jnp.float32)

    # accumulators start replicated but become device-varying inside the
    # ring loop; marking them keeps shard_map's VMA typing happy with the
    # carry.  jax.lax.pvary is deprecated in favor of pcast(..., to=varying).
    # Compat ladder (newest first): pcast (jax >= 0.8), pvary (0.5-0.7),
    # identity on older jax (e.g. 0.4.37) — those shard_maps have no
    # varying-in-manual-axes type system, so there is nothing to mark and
    # the loop's semantics are unchanged (golden-pinned against the einsum
    # references across all three branches by tests/test_parallel.py).
    vary = (axis_name,) + tuple(a for a in vary_axes if a)
    if hasattr(jax.lax, "pcast"):
        _mark = lambda x: jax.lax.pcast(x, vary, to="varying")
    elif hasattr(jax.lax, "pvary"):
        _mark = lambda x: jax.lax.pvary(x, vary)
    else:  # pre-VMA jax: no varying types, marking is a no-op
        _mark = lambda x: x
    o = _mark(jnp.zeros((B, T_loc, H, D), jnp.float32))
    m = _mark(jnp.full((B, H, T_loc), NEG_INF, jnp.float32))
    l = _mark(jnp.zeros((B, H, T_loc), jnp.float32))
    perm = [(j, (j + 1) % n) for j in range(n)]

    def body(i, carry):
        o, m, l, k, v, extras = carry
        k_idx = (idx - i) % n  # owner of the K/V block currently held
        s = scores_fn(qf, k.astype(jnp.float32), extras, idx * T_loc, k_idx * T_loc)
        m_blk = s.max(axis=-1)                           # (B, H, Tq)
        p = jnp.exp(s - m_blk[..., None])
        l_blk = p.sum(axis=-1)
        o_blk = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)

        # NOTE on fully-invalid blocks (every score NEG_INF): m_blk is
        # NEG_INF and p collapses to exp(0)=1 garbage, but ring step 0
        # processes the query's OWN shard where self-visibility (causal
        # diagonal / the masked 'self always visible' rule) guarantees a
        # finite m — so for every later all-invalid block beta is
        # exp(NEG_INF - finite) = 0 and the garbage never lands.
        m_new = jnp.maximum(m, m_blk)
        alpha = jnp.exp(m - m_new)                       # rescale old accum
        beta = jnp.exp(m_blk - m_new)                    # rescale new block
        l = l * alpha + l_blk * beta
        scale_old = jnp.moveaxis(alpha, 1, 2)[..., None]  # (B, Tq, H, 1)
        scale_new = jnp.moveaxis(beta, 1, 2)[..., None]
        o = o * scale_old + o_blk.astype(jnp.float32) * scale_new
        k = jax.lax.ppermute(k, axis_name, perm)
        v = jax.lax.ppermute(v, axis_name, perm)
        extras = tuple(jax.lax.ppermute(e, axis_name, perm) for e in extras)
        return o, m_new, l, k, v, extras

    o, m, l, _, _, _ = jax.lax.fori_loop(0, n, body, (o, m, l, k, v, extras))
    l = jnp.maximum(l, 1e-30)                            # fully-masked rows -> 0
    out = o / jnp.moveaxis(l, 1, 2)[..., None]
    return out.astype(q.dtype)


def ring_attention_shard(q, k, v, axis_name: str, causal: bool = True, vary_axes=()):
    """Per-shard (plain causal/full) ring attention body; call inside
    shard_map."""
    scale = 1.0 / (q.shape[-1] ** 0.5)

    def scores(qf, kf, extras, q0, k0):
        s = jnp.einsum(
            "bqhd,bkhd->bhqk", qf, kf, preferred_element_type=jnp.float32
        ) * scale
        if causal:
            qpos = q0 + jnp.arange(qf.shape[1])
            kpos = k0 + jnp.arange(kf.shape[1])
            s = jnp.where((qpos[:, None] >= kpos[None, :])[None, None], s, NEG_INF)
        return s

    return _ring_loop(q, k, v, (), axis_name, scores, vary_axes)


def ring_self_attention(
    q,
    k,
    v,
    mesh: Mesh,
    causal: bool = True,
    seq_axis: str = "sp",
    batch_axis: Optional[str] = "dp",
):
    """Sequence-parallel attention over ``mesh``: shards T over ``seq_axis``
    (and B over ``batch_axis`` when present in the mesh)."""
    if seq_axis not in mesh.shape or mesh.shape[seq_axis] == 1:
        # no sequence sharding: plain blockwise attention on each device
        o, m, l = _block_attention(
            q.astype(jnp.float32), k.astype(jnp.float32), v, 0, 0, 1.0 / (q.shape[-1] ** 0.5), causal
        )
        return (o / jnp.moveaxis(jnp.maximum(l, 1e-30), 1, 2)[..., None]).astype(q.dtype)

    b_axis = batch_axis if batch_axis in mesh.shape else None
    spec = P(b_axis, seq_axis, None, None)
    fn = shard_map(
        functools.partial(
            ring_attention_shard, axis_name=seq_axis, causal=causal, vary_axes=(b_axis,)
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(q, k, v)


def masked_ring_attention_shard(
    q, k, v, key_mask, counts, slopes, axis_name: str,
    window: float = float(1 << 30), vary_axes=(),
):
    """Ring attention with the transformer seq-mode semantics: per-key
    observation masks, ALiBi bias over *observed-step* ages, ring-buffer
    eviction of keys older than ``window`` observed steps, self always
    visible — scores built by flash_attention._masked_scores, the single
    shared semantics definition.

    ``counts`` is the GLOBAL observed-count cumsum (computed over the full
    T before sharding — ages are differences of global counts, so each
    shard only needs its own slice).  key_mask/counts (B, T_loc) rotate
    around the ring with their K/V shard.
    """
    from .flash_attention import _masked_scores  # circular at module level

    scale = 1.0 / (q.shape[-1] ** 0.5)
    c_q = counts  # this shard's queries' observed counts (B, T_loc)
    slopes_f = slopes.astype(jnp.float32)

    def scores(qf, kf, extras, q0, k0):
        mask_k, c_k = extras
        s, _ = _masked_scores(
            qf, kf, c_q, c_k, mask_k, slopes_f, window, q0, scale, k0=k0
        )
        return s

    # key_mask/counts are sharded shard_map inputs — already device-varying
    return _ring_loop(q, k, v, (key_mask, counts), axis_name, scores, vary_axes)


def masked_ring_self_attention(
    q, k, v, key_mask, slopes,
    mesh: Mesh,
    window: int = 1 << 30,
    seq_axis: str = "sp",
    batch_axis: Optional[str] = "dp",
):
    """Sequence-parallel masked attention over ``mesh``: the transformer's
    training attention (flash_attention.masked_attention_reference
    semantics) with T sharded over ``seq_axis`` — long windows whose
    K/V no longer fit one chip ride the ICI ring instead.

    q/k/v (B, T, H, D); key_mask (B, T); slopes (H,).  The global
    observed-count cumsum is taken here, before sharding.
    """
    if seq_axis not in mesh.shape or mesh.shape[seq_axis] == 1:
        from .flash_attention import masked_attention_reference

        return masked_attention_reference(q, k, v, key_mask, slopes, window=window)
    counts = jnp.cumsum(key_mask.astype(jnp.float32), axis=1)

    b_axis = batch_axis if batch_axis in mesh.shape else None
    spec4 = P(b_axis, seq_axis, None, None)
    spec2 = P(b_axis, seq_axis)
    fn = shard_map(
        functools.partial(
            masked_ring_attention_shard,
            axis_name=seq_axis,
            window=float(window),
            vary_axes=(b_axis,),
        ),
        mesh=mesh,
        in_specs=(spec4, spec4, spec4, spec2, spec2, P(None)),
        out_specs=spec4,
    )
    return fn(q, k, v, key_mask, counts, slopes)


def full_attention_reference(q, k, v, causal: bool = True):
    """Naive O(T^2) attention for golden tests."""
    scale = 1.0 / (q.shape[-1] ** 0.5)
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32)) * scale
    if causal:
        T = q.shape[1]
        mask = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(mask[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32)).astype(q.dtype)
