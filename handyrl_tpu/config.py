"""Configuration loading and validation.

Keeps the reference's config.yaml schema (env_args / train_args /
worker_args, reference config.yaml:2-38, docs/parameters.md) so existing
configs port unchanged, and layers defaults + validation on top (the
reference has no validation layer).  TPU-specific knobs live under
``train_args`` with safe defaults:

* ``mesh``: axis-name -> size dict for the device mesh ({'dp': -1} means
  "all devices data-parallel").
* ``inference_batch_size``: max cross-environment batch for the actor-side
  TPU inference engine.
* ``num_actors`` alias: ``worker.num_parallel``.
"""

from __future__ import annotations

import copy
from typing import Any, Dict

import yaml

DEFAULT_TRAIN_ARGS: Dict[str, Any] = {
    "turn_based_training": True,
    "observation": False,
    "gamma": 0.8,
    "forward_steps": 16,
    "burn_in_steps": 0,
    "compress_steps": 4,
    "entropy_regularization": 1.0e-1,
    "entropy_regularization_decay": 0.1,
    "update_episodes": 200,
    "batch_size": 128,
    "minimum_episodes": 400,
    "maximum_episodes": 100000,
    "epochs": -1,
    "num_batchers": 2,
    "eval_rate": 0.1,
    "worker": {
        "num_parallel": 6,
        "entry_port": 9999,
        "data_port": 9998,
        # liveness ping cadence on the remote actor plane, both directions
        # (server -> gathers from a dedicated thread, gathers -> server);
        # a peer silent for ~3 intervals is presumed dead.  0 disables
        # heartbeats AND the silence deadline (pre-fault-tolerance wire
        # behavior, for debugging only)
        "heartbeat_interval": 10.0,
        # max stall (no byte of progress) on gather RPC send/receive: a
        # WAN blackhole surfaces as TimeoutError -> teardown -> rejoin,
        # never a hang, while a big params blob trickling over a slow
        # link stays alive as long as bytes flow
        "socket_timeout": 60.0,
        # entry-handshake deadline: a client that connects and stalls is
        # dropped so the single entry thread keeps serving later joins
        "entry_timeout": 10.0,
    },
    "lambda": 0.7,
    "policy_target": "TD",
    "value_target": "TD",
    "eval": {"opponent": ["random"]},
    "seed": 0,
    # 0 = fresh start; N > 0 = resume from models/{N}.ckpt (digest-checked
    # against models/MANIFEST.json, refusing corrupt files); -1 = AUTO:
    # resume from the newest manifest entry that verifies, falling back to
    # older verified snapshots — the knob a preemptible-TPU launcher sets
    # once and never touches again
    "restart_epoch": 0,
    # epoch snapshots ({N}.ckpt) kept on disk; older ones are GC'd at each
    # save (latest.ckpt / state.ckpt always survive).  0 = keep all
    "keep_checkpoints": 100,
    # shm batcher supervision (runtime/shm_batch.py): respawn a dead
    # batcher child up to this many times, then degrade loudly to the
    # threaded pipeline; also degrade if the ring moves nothing for
    # batcher_stall_timeout seconds after a death (a SIGKILL can take a
    # multiprocessing queue lock with it)
    "batcher_max_restarts": 3,
    "batcher_stall_timeout": 60.0,
    # --- self-healing run plane (docs/fault_tolerance.md) ---------------
    # divergence sentinel: finite-checks of loss/grad-norm are fused into
    # the compiled train step; a bad step's update is SKIPPED (never
    # applied), and sentinel_rollback_after consecutive bad steps (in-step
    # nonfinite flags + host-side loss-spike EMA detections) roll the train
    # state back to the newest VERIFIED manifest checkpoint with re-seeded
    # sampling RNG.  false = bit-identical pre-sentinel step
    "sentinel": True,
    "sentinel_rollback_after": 8,
    # host EMA spike detector: a step whose |loss|/datum exceeds
    # sentinel_spike_factor x the EMA counts as bad (PaLM-style loss-spike
    # handling); the EMA ignores bad steps so divergence can't drag it up
    "sentinel_spike_factor": 10.0,
    "sentinel_loss_ema_decay": 0.9,
    # plane watchdog (device-rollout runs): a rollout thread that dies or
    # makes no progress for plane_stall_timeout seconds is restarted up to
    # plane_max_restarts times; past the budget a split-plane run degrades
    # split -> fused loudly.  plane_param_lag_bound > 0 additionally treats
    # actor params lagging more than that many updates as a stall (0 = off)
    "plane_stall_timeout": 120.0,
    "plane_max_restarts": 2,
    "plane_param_lag_bound": 0,
    # preemption-safe drain: on SIGTERM/SIGINT the run stops cleanly,
    # writes a final manifest-verified checkpoint within this budget, and
    # exits 75 (EX_TEMPFAIL) so a launcher relaunches with restart_epoch -1
    "drain_deadline_seconds": 60.0,
    # --- TPU-native additions -------------------------------------------
    "mesh": {"dp": -1},
    # multi-host learner plane (parallel/distributed.py): set
    # coordinator_address ("host:port" of process 0) + num_processes (+
    # process_id or PROCESS_ID env) to span hosts with jax.distributed.
    # initialization_timeout bounds startup against a dead/mis-addressed
    # coordinator (loud error, never a hang); the heartbeat/collective
    # knobs drive the cross-host health plane (parallel/health.py): a
    # lost or wedged peer is detected within heartbeat_timeout (or
    # collective_timeout for a silent wedge), the coordinator drain-saves
    # a verified checkpoint, and every survivor exits 75 for a
    # restart_epoch: -1 relaunch instead of hanging in a dead collective
    "distributed": {
        "coordinator_address": None,
        "num_processes": 1,
        "process_id": None,
        "initialization_timeout": 300.0,
        "heartbeat_interval": 5.0,
        "heartbeat_timeout": 30.0,
        "collective_timeout": 300.0,
        # health plane's TCP port on the coordinator host (0 = derive:
        # coordinator port + 1)
        "health_port": 0,
        # pod-slice topology (docs/performance.md §Pod-slice topology):
        # 'learner' processes join the jax.distributed collective and run
        # the cadenced train loop; 'actor' processes stay OUTSIDE the
        # collective (their loss must be degradable, not a collective
        # wedge) and stream rollout records to the learner's plane
        # gateway over DCN, polling versioned params back
        "role": "learner",
        # plane gateway's TCP port on the coordinator host (0 = derive:
        # health port + 1); carries param publishes + record transfers
        # for distributed.role: actor processes
        "plane_port": 0,
        # dedicated actor-host processes expected to connect to the plane
        # gateway (0 = no cross-host actor tier; rung-1 per-process device
        # planes only).  Informational for sizing/metrics — a lost actor
        # host degrades throughput, it never gates the run
        "actor_hosts": 0,
    },
    "inference_batch_size": 64,
    "prefetch_batches": 2,
    # batch-assembly plane: 'shm' (default) forks num_batchers PROCESSES
    # that write columnar batches into shared-memory ring slots — GIL-free,
    # zero-copy on the consumer side (runtime/shm_batch.py); 'device'
    # uploads host-born episodes ONCE into device ring buffers and
    # samples/assembles training windows ON DEVICE (runtime/device_batch.py
    # + DeviceEpisodeStage — make_batch and the per-update observation H2D
    # re-upload leave the hot loop; single-process, ff mode needs
    # turn_based_training: false, turn mode needs observation: true);
    # 'thread' keeps the in-process threaded batchers (the portable
    # fallback, also used automatically when a richer plane cannot start)
    "batch_pipeline": "shm",
    # shared-memory ring depth, in slots of one (B, T, P, ...) batch each;
    # clamped up to 2*fused_steps + 2 so the double-buffered device-put can
    # keep two fused groups in flight while the children keep filling
    "shm_slots": 6,
    # batch_pipeline: device geometry — episodes queue over this many ring
    # lanes (rounded up to a mesh-dp multiple), each slots steps deep, and
    # upload in (chunk, lanes) blocks.  Keep lanes*chunk well below
    # minimum_episodes x the typical episode length or the first flush
    # waits on generation
    "device_stage_lanes": 8,
    "device_stage_slots": 1024,
    "device_stage_chunk": 64,
    # k SGD updates fused under one lax.scan per device call (amortizes
    # per-call dispatch for small models); 1 = one jit call per update.
    # Semantics are identical: lr is already held constant within an epoch.
    "fused_steps": 1,
    # N > 0: generate self-play episodes fully ON DEVICE, N parallel games
    # per jit call (envs exposing a vector twin, e.g. TicTacToe). Workers
    # then skew toward evaluation; 0 = host actors only.
    "device_rollout_games": 0,
    # true: keep the self-play data on device end to end — rollout records
    # are ingested into device ring buffers and training batches are
    # sampled + assembled + stepped in one dispatch (runtime/
    # device_replay.py).  Needs device_rollout_games > 0; two window
    # modes picked by turn_based_training (see docs/parameters.md).
    "device_replay": False,
    # N > 0: play N batched net-vs-baseline eval matches ON DEVICE at
    # every epoch boundary (runtime/device_eval.py) — the per-epoch
    # win-rate curve host eval workers starve on slow hosts.  Opponent
    # follows eval.opponent when it is random/rulebase (envs without a
    # rule_based_action_all device twin fall back to random).
    "device_eval_games": 0,
    # device-plane topology: 'fused' (default) runs self-play and training
    # time-sliced on ONE mesh; 'split' partitions the devices into a
    # learner mesh (train_args.mesh over the leading devices) and an actor
    # mesh (the trailing actor_chips devices) so both planes run at full
    # duty CONCURRENTLY — params flow actor-ward every
    # param_refresh_updates learner steps, trajectories learner-ward
    # (runtime/plane.py).  Needs device_rollout_games > 0 and >= 2 devices
    "plane": "fused",
    # devices carved off for the actor plane under plane: split
    "actor_chips": 1,
    # learner steps between cross-mesh param refreshes of the actor plane
    # (plane: split): the actor's params are at most this stale — the
    # plane_param_lag metric surfaces the realized lag
    "param_refresh_updates": 8,
    # ring length in steps per lane for device_replay
    "device_replay_slots": 1024,
    # game steps advanced per rollout dispatch in the device_replay loop
    "device_replay_k_steps": 32,
    # --- inference serving plane (docs/serving.md) ----------------------
    # `main.py --serve` (or ServingServer embedded): continuous-batching
    # inference over the framed-socket transport, multi-model routing and
    # zero-downtime hot-swap on new verified checkpoints
    "serving": {
        # TCP port the serving front listens on (0 = ephemeral, for tests)
        "port": 9997,
        # resident snapshot engines beyond which the LRU non-latest engine
        # is retired (drained, never dropped); the latest is always pinned
        "max_models": 4,
        # default per-request latency budget: a request with no explicit
        # slo_ms must complete within this or be shed/expired (not imposed
        # under shed_policy: none)
        "slo_ms": 200.0,
        # 'deadline' sheds on predicted SLO violation (queue waves x EMA
        # batch time), 'queue' sheds only at queue_bound, 'none' never
        # sheds and imposes no default deadline (every admitted request
        # completes — drain semantics; explicit request slo_ms still holds)
        "shed_policy": "deadline",
        # power-of-two bucket cap per device batch (engine max_batch)
        "max_batch": 64,
        # straggler wait once the first request of a batch arrived
        "max_wait_ms": 2.0,
        # bucket sizes compiled at engine build / before a hot-swap flip;
        # the first post-swap request must never pay an XLA compile
        "warm_buckets": [1, 8],
        # queued-request bound per engine (both shed policies enforce it)
        "queue_bound": 1024,
        # silent-client reaping deadline on the server hub (0 = keep
        # idle connections forever; request/reply clients may be bursty)
        "recv_timeout": 0.0,
        # seconds between checkpoint-manifest polls for auto hot-swap on
        # a new verified snapshot (0 = swap only on explicit request)
        "watch_interval": 0.0,
        # seconds between serve_* health records appended to metrics_path
        # by the standalone server (0 = off)
        "stats_interval": 30.0,
        # server-resident recurrent sessions (docs/serving.md §Fleet tier):
        # device-resident hidden states pinned per open session before the
        # LRU spills to host (0 disables the session cache entirely —
        # open_session frames become bad_request, ship-state still works)
        "session_capacity": 1024,
        # host-side spill ring beyond session_capacity: evicted sessions
        # park here as numpy and re-upload on next touch (counted as
        # session_restored); beyond this the oldest spill is dropped and
        # its next touch is an affinity miss (fresh initial state)
        "session_spill": 4096,
        # engine param residency: 'float32' (exact) or 'int8' (per-channel
        # symmetric weight-only quantization, fp32 scales, dequantize
        # fused into the compiled apply — models/quantize.py).  Applied
        # at engine build, so ModelRouter engines, fleet replicas, and
        # frozen league opponents all inherit it; win-rate parity is
        # MEASURED by the lowprec bench stage, never assumed
        "weight_dtype": "float32",
        # replay-episode calibration batches sampled at publish when
        # weight_dtype is int8: the router replays stored observations
        # through the fp32 and int8 engines and logs the measured output
        # deviation (0 = skip the calibration record)
        "calibration_batches": 4,
    },
    # --- fleet serving tier (docs/serving.md §Fleet tier) ----------------
    # `main.py --fleet`: a front-end entry port proxying rid-pipelined
    # client frames across N `--serve` (or `--edge`) replicas — balance by
    # polled shed-rate/queue-depth, session affinity to the replica holding
    # the hidden state, loud replica_lost failover + backoff rejoin, and
    # replica-by-replica fleet-wide hot-swap
    "fleet": {
        # TCP entry port the router listens on (0 = ephemeral, for tests)
        "port": 9996,
        # backend replicas: "host:port" strings or {host, port, tags}
        # dicts; tag "edge" marks feed-forward-only artifact capacity
        # (skipped by stateful routes and swap propagation)
        "replicas": [],
        # seconds between stats-frame polls feeding the load scores
        "stats_poll_s": 2.0,
        # transient-fault budget for that poll (utils/retry.py): up to
        # poll_retry_attempts retries with exponential backoff starting
        # at poll_retry_backoff_s before a failing poll may declare the
        # replica lost — one EINTR/ECONNRESET never costs a replica_lost
        "poll_retry_attempts": 3,
        "poll_retry_backoff_s": 0.1,
        # per-replica stall deadline: a replica silent this long with
        # proxied requests pending is declared lost (bounded failover);
        # 0 disables (failover then only on connection loss)
        "replica_stall_s": 30.0,
        # lost-replica rejoin backoff: starts at rejoin_backoff_s, doubles
        # to rejoin_backoff_max_s, retries forever (PR 2 discipline)
        "rejoin_backoff_s": 1.0,
        "rejoin_backoff_max_s": 30.0,
        # seconds between fleet_* health records appended to metrics_path
        # (0 = off)
        "stats_interval": 30.0,
        # planned-retire budget: seal -> drain in-flight -> export the
        # SessionCache -> import on the successor must finish inside this,
        # else the retire proceeds lossy (sessions re-open as counted
        # affinity misses — degraded loudly, never a hang)
        "migrate_timeout_s": 30.0,
        # elastic fleet (docs/serving.md §Elastic fleet): replica count
        # driven by the windowed shed rate / queue depth the balancer
        # already polls.  Spawned replicas join warm-then-admit (never
        # routed to before their engine is published and warmed); retires
        # go through the zero-loss session-migration path
        "autoscale": {
            "enabled": False,
            # replica-count bounds (non-edge replicas; config-registered
            # replicas are the operator's floor — never auto-retired)
            "min_replicas": 1,
            "max_replicas": 4,
            # seconds between autoscale decisions
            "interval_s": 1.0,
            # scale UP when the windowed shed rate exceeds this SLO...
            "shed_slo": 0.01,
            # ...or mean queue depth per replica exceeds depth_high;
            # scale DOWN only once depth falls under depth_low with zero
            # sheds for scale_down_after_s straight (hysteresis)
            "depth_high": 64.0,
            "depth_low": 1.0,
            "scale_down_after_s": 30.0,
            # minimum seconds between any two scale actions
            "cooldown_s": 10.0,
            # a spawned replica that is not warm (admitted) within this
            # is marked lost and cycles through the rejoin backoff
            "warm_timeout_s": 120.0,
        },
        # CPU edge replica (`main.py --edge`): port, request threads, and
        # the frozen artifact it serves (CLI path argument overrides)
        "edge_port": 9995,
        "edge_workers": 2,
        "edge_model": "",
    },
    # --- league training plane (docs/league.md) -------------------------
    # `main.py --league` (handyrl_tpu/league): population-based training —
    # a persistent League of frozen snapshots + anchors backed by the
    # checkpoint manifest, PFSP matchmaking over a per-ordered-pair payoff
    # ledger, ModelRouter-resident opponent engines, and a gated promotion
    # that freezes the candidate into the population
    "league": {
        # opponent sampling over the frozen population (AlphaStar PFSP):
        # 'var' weights p(1-p) (focus near-peers), 'hard' weights (1-p)^2
        # (focus the hardest), 'even' is uniform; p = candidate win rate
        "pfsp_weighting": "var",
        # fraction of league generation matches played latest-vs-latest
        # (pure self-play keeps the candidate from overfitting the pool)
        "selfplay_rate": 0.2,
        # promotion gate: the candidate freezes into the population only
        # once every active opponent has >= promote_games recorded games
        # AND the candidate's aggregate win points across the pool reach
        # promote_winrate (win points = wins + draws/2, wp_func convention)
        "promote_winrate": 0.55,
        "promote_games": 8,
        # frozen members kept active for matchmaking (oldest non-anchor
        # members retire from the pool first; their snapshots and payoff
        # books persist).  The anchor always stays active
        "max_population": 16,
    },
    # --- data flywheel (docs/serving.md §Data flywheel) ------------------
    # quality-guarded production loop: the serving tier assembles served
    # traffic into complete training episodes (harvest), the learner
    # pulls them into its EpisodeStore alongside/instead of self-play,
    # and promotions of new snapshots into serving are gated on LIVE win
    # rate with an auto-rollback quality sentinel behind the gate
    "flywheel": {
        "enabled": False,
        # fraction of each epoch's update_episodes budget filled from
        # harvested traffic (the rest stays self-play); 1.0 = train on
        # served traffic only, 0.0 = quality plane without harvest ingest
        "harvest_fraction": 0.5,
        # drop harvested episodes generated >= this many model epochs
        # behind the learner's current epoch (staleness bound)
        "staleness_epochs": 4,
        # where the learner's ingest loop dials the serving tier; port 0
        # follows serving.port
        "harvest_host": "127.0.0.1",
        "harvest_port": 0,
        # ingest poll cadence / per-poll episode cap
        "harvest_poll_s": 1.0,
        "harvest_max_pull": 64,
        # server-side harvest hygiene: an open episode idle past the TTL
        # is dropped (counted truncated); at most max_open concurrent
        # open episodes (the oldest sheds first)
        "harvest_ttl_s": 600.0,
        "harvest_max_open": 256,
        # promotion gate: a fresh snapshot is staged as a shadow
        # candidate on shadow_fraction of default-route traffic and the
        # served `latest` flips only once its live win points over
        # promote_games reported games clear promote_winrate; gating off
        # = every fresh snapshot flips immediately (the PR 13 behavior)
        "gate_promotions": True,
        "promote_winrate": 0.55,
        "promote_games": 16,
        "shadow_fraction": 0.25,
        # quality sentinel behind the gate: a PROMOTED snapshot whose
        # live win-point EMA (window quality_window games) degrades more
        # than demote_drop below the incumbent's bar is demoted
        # serving-side and a verified rollback signal reaches training
        "quality_window": 32,
        "demote_drop": 0.15,
    },
    # --- observability plane (docs/observability.md) --------------------
    # structured span tracing (utils/trace.py): ring-buffered in-process
    # spans over the hot-path seams (dispatch, batch waits, cadence
    # broadcasts, heartbeats, serving lifecycle, epoch-boundary work),
    # flushed to trace.jsonl with the metrics.jsonl tail discipline and
    # exportable to chrome://tracing via scripts/trace_export.py.  OFF by
    # default and provably free: with enabled: false the hot path is
    # bit-identical (one attribute check per seam) — pinned by the obs
    # sanitizer suite
    "trace": {
        "enabled": False,
        # sink path; multi-process ranks N > 0 derive path.rankN.jsonl
        "path": "trace.jsonl",
        # bounded in-process span ring: a full ring DROPS (counted in the
        # trace_dropped metric), never blocks a dispatch
        "ring_size": 4096,
        # background flusher cadence, seconds
        "flush_interval": 0.5,
        # also enter a jax.profiler.TraceAnnotation per span so host spans
        # land inside XLA device profiles (profile_dir captures)
        "annotate_device": True,
    },
    "observability": {
        # multi-process runs: followers piggyback per-epoch metric
        # snapshots on health-plane heartbeats so the coordinator's
        # metrics.jsonl carries rank_* aggregates for EVERY rank (a
        # wedged-but-heartbeating follower is visible as a stale rank
        # report before the collective watchdog's bound)
        "rank_metrics": True,
    },
    # N > 0: when an env's vector twin is autovec-lifted (envs/autovec.py
    # __autovec__), play N random step-parity games between the numpy
    # rules and the lifted device env at Learner startup and refuse to
    # train on a divergent lift.  0 = trust the lift (the parity suite
    # covers bundled rules)
    "autovec_verify_games": 0,
    "metrics_path": "metrics.jsonl",
    "model_dir": "models",
    "battle_port": 9876,
    "profile_dir": None,
    # whole-window attention training for transformer models (models that
    # set supports_seq); turn off to force the step-scan path
    "seq_forward": True,
    # seq-mode attention implementation ('attn_mode' is an accepted
    # alias): 'auto' (Pallas masked flash attention when the window is
    # >= flash_min_t, einsum shorter — on TPU compiled, on CPU via the
    # exact Pallas interpreter; other backends fall back to einsum),
    # 'flash', 'einsum', or 'ring' (sequence-parallel masked ring
    # attention — needs an 'sp' mesh axis)
    "seq_attention": "auto",
    # auto-mode crossover: windows shorter than this use the exact einsum
    # path (the O(T^2) term is tiny and XLA-fusable at short T; the
    # Pallas kernel pays fixed launch/block overhead)
    "flash_min_t": 128,
    # flash kernel tile sizes (query/key rows per VMEM block): power-of-two
    # multiples of 8, clamped to the 128-lane tile inside the kernel.  128
    # is the measured sweet spot; smaller tiles trade MXU utilization for
    # less VMEM per program
    "blk_q": 128,
    "blk_k": 128,
    # recompute ladder for the transformer seq path: 'none' (store every
    # activation), 'attn' (recompute each attention sublayer in the
    # backward), 'block' (recompute whole attention+FFN blocks — the lever
    # that fits T1024 x d1536 in HBM), or 'auto' ('block' for T >= 512 on
    # TPU, else 'none').  For RNN scan training the ladder collapses to
    # on/off over the scan body (the historical remat: auto|true|false)
    "remat": "auto",
    # feed-forward models with burn_in_steps 0 slice the training
    # observation to the live prefix of the T axis — numerically identical,
    # skips compute on end-of-episode padding; disable when debugging
    # shape/recompile issues (parallel/train_step.py _ff_compact)
    "compact_padding": True,
    # fully unroll the RNN training scan over T: 'auto' = on for
    # single-device CPU (XLA:CPU runs while-loop bodies without its fast
    # kernel runtime), off for TPU and multi-device meshes (unrolled
    # bodies explode SPMD-partitioner compile time)
    "unroll": "auto",
    # 'bfloat16' runs the forward/backward compute in bf16 (MXU rate)
    # with fp32 master weights; 'float32' is exact
    "compute_dtype": "float32",
    # quantize observation planes to int8 at episode finalize: the actor
    # wire blocks, shm ring slots, and device replay rings then carry
    # int8 obs (4x fewer bytes) and dequantize on device inside the
    # compiled sample/train programs.  Static per-plane scale/zero-point
    # come from env metadata (env.obs_int8_spec(); default scale 1.0 /
    # zero-point 0 — EXACT for 0/1-occupancy planes, which is every
    # bundled env).  models/quantize.py
    "obs_int8": False,
    # multiplies the reference lr schedule (3e-8 x data-count EMA,
    # train.py:328-332) -- 1.0 is exact parity.  The schedule assumes
    # GPU-scale update counts; raise it when the update budget is small
    # (e.g. CI soaks on a slow host).
    "lr_scale": 1.0,
}

DEFAULT_WORKER_ARGS: Dict[str, Any] = {
    "server_address": "",
    "num_parallel": 8,
    "entry_port": 9999,
    # on a severed/stalled connection the worker machine tears its session
    # down (no actor thread survives) and re-enters through the entry port
    # with exponential backoff; rejoin: false restores join-once behavior
    "rejoin": True,
    "rejoin_backoff": 1.0,
    "rejoin_backoff_max": 60.0,
    # bound on consecutive failed sessions before giving up (-1 = forever,
    # the right default for a fleet behind a supervisor)
    "max_rejoins": -1,
    # how long each entry attempt keeps retrying the TCP connect (server
    # still booting / restarting) before counting as a failed session
    "entry_retry_seconds": 60.0,
}

VALID_TARGETS = ("MC", "TD", "UPGO", "VTRACE")


def effective_shm_slots(train: Dict[str, Any]) -> int:
    """The ring depth the shm batch plane ACTUALLY allocates: ``shm_slots``
    clamped up so the double-buffered device-put can keep two fused groups
    in flight while the children keep filling.  Single source of truth —
    ``validate_args`` checks ``num_batchers`` against it and
    ``ShmBatchPipeline`` allocates exactly it; change the consumer's
    buffering depth in one place only."""
    return max(
        int(train.get("shm_slots", 6)),
        2 * int(train.get("fused_steps", 1)) + 2,
        3,
    )


def _deep_merge(base: Dict[str, Any], override: Dict[str, Any]) -> Dict[str, Any]:
    out = copy.deepcopy(base)
    for key, value in (override or {}).items():
        if isinstance(value, dict) and isinstance(out.get(key), dict):
            out[key] = _deep_merge(out[key], value)
        else:
            out[key] = value
    return out


def validate_args(args: Dict[str, Any]) -> Dict[str, Any]:
    train = args["train_args"]
    for key in ("policy_target", "value_target"):
        if train[key] not in VALID_TARGETS:
            raise ValueError(f"{key}={train[key]!r} not one of {VALID_TARGETS}")
    for key in ("forward_steps", "batch_size", "update_episodes", "compress_steps"):
        if train[key] <= 0:
            raise ValueError(f"train_args.{key} must be positive, got {train[key]}")
    if train["burn_in_steps"] < 0:
        raise ValueError("train_args.burn_in_steps must be >= 0")
    if train["restart_epoch"] < -1:
        raise ValueError(
            "train_args.restart_epoch must be >= -1 (-1 = auto-resume from "
            "the newest verified snapshot)"
        )
    if train["keep_checkpoints"] < 0:
        raise ValueError("train_args.keep_checkpoints must be >= 0 (0 = keep all)")
    if train["batcher_max_restarts"] < 0:
        raise ValueError("train_args.batcher_max_restarts must be >= 0")
    if train["batcher_stall_timeout"] <= 0:
        raise ValueError("train_args.batcher_stall_timeout must be > 0")
    if train["sentinel_rollback_after"] < 1:
        raise ValueError("train_args.sentinel_rollback_after must be >= 1")
    if train["sentinel_spike_factor"] <= 1.0:
        raise ValueError(
            "train_args.sentinel_spike_factor must be > 1 (a spike is a "
            "multiple of the loss EMA)"
        )
    if not 0.0 < train["sentinel_loss_ema_decay"] < 1.0:
        raise ValueError("train_args.sentinel_loss_ema_decay must be in (0, 1)")
    if train["plane_stall_timeout"] <= 0:
        raise ValueError("train_args.plane_stall_timeout must be > 0")
    if train["plane_max_restarts"] < 0:
        raise ValueError("train_args.plane_max_restarts must be >= 0")
    if train["plane_param_lag_bound"] < 0:
        raise ValueError("train_args.plane_param_lag_bound must be >= 0 (0 = off)")
    if train["drain_deadline_seconds"] <= 0:
        raise ValueError("train_args.drain_deadline_seconds must be > 0")
    dist = train["distributed"]
    if dist["coordinator_address"] is not None:
        # both the init pre-flight (parallel/distributed.py) and the health
        # plane (parallel/health.py) parse host:port out of this — a
        # missing port must fail HERE with a named knob, not as a bare
        # int() traceback inside a socket helper
        _host, _, _port = str(dist["coordinator_address"]).rpartition(":")
        if not _host or not _port.isdigit() or not 1 <= int(_port) <= 65535:
            raise ValueError(
                f"train_args.distributed.coordinator_address="
                f"{dist['coordinator_address']!r} must be 'host:port' with a "
                "TCP port (the address of process 0)"
            )
    if int(dist["num_processes"]) < 1:
        raise ValueError("train_args.distributed.num_processes must be >= 1")
    if dist["process_id"] is not None and int(dist["process_id"]) < 0:
        raise ValueError("train_args.distributed.process_id must be >= 0")
    if float(dist["initialization_timeout"]) <= 0:
        raise ValueError(
            "train_args.distributed.initialization_timeout must be > 0 "
            "(it bounds jax.distributed.initialize against a dead or "
            "mis-addressed coordinator — 0 would restore the indefinite "
            "startup hang)"
        )
    if float(dist["heartbeat_interval"]) < 0:
        raise ValueError(
            "train_args.distributed.heartbeat_interval must be >= 0 "
            "(0 disables the cross-host health plane)"
        )
    if float(dist["heartbeat_timeout"]) <= 0:
        raise ValueError("train_args.distributed.heartbeat_timeout must be > 0")
    if (
        float(dist["heartbeat_interval"]) > 0
        and float(dist["heartbeat_timeout"]) <= 2 * float(dist["heartbeat_interval"])
    ):
        raise ValueError(
            "train_args.distributed.heartbeat_timeout must exceed 2x "
            "heartbeat_interval — a single delayed beat must not count a "
            "live host as lost"
        )
    if float(dist["collective_timeout"]) < 0:
        raise ValueError(
            "train_args.distributed.collective_timeout must be >= 0 "
            "(0 disables the collective watchdog)"
        )
    if not isinstance(dist["health_port"], int) or not 0 <= dist["health_port"] <= 65535:
        raise ValueError(
            f"train_args.distributed.health_port={dist['health_port']!r} "
            "must be a TCP port (0 = coordinator port + 1)"
        )
    if (
        dist["health_port"] == 0
        and dist["coordinator_address"] is not None
        and float(dist["heartbeat_interval"]) > 0  # plane enabled at all
        and int(str(dist["coordinator_address"]).rpartition(":")[2]) >= 65535
    ):
        raise ValueError(
            "train_args.distributed.health_port derives as coordinator "
            "port + 1 = 65536, which is not a TCP port — set "
            "distributed.health_port explicitly"
        )
    # pod-slice topology knobs (docs/performance.md §Pod-slice topology).
    # The device data plane IS supported multi-process now (per-process
    # rings/rollout feed the collective train step through the
    # make_array_from_process_local_data seam, every device dispatch
    # gated on the coordinator cadence, RNGs rank-decorrelated) — so the
    # old blanket rejections became the composition checks below: what
    # must actually hold is that the per-process SHARDS divide evenly
    if str(dist["role"]) not in ("learner", "actor"):
        raise ValueError(
            f"train_args.distributed.role={dist['role']!r} not one of "
            "('learner', 'actor') — learners join the jax.distributed "
            "collective; actor hosts stream records to the plane gateway"
        )
    if not isinstance(dist["plane_port"], int) or not 0 <= dist["plane_port"] <= 65535:
        raise ValueError(
            f"train_args.distributed.plane_port={dist['plane_port']!r} "
            "must be a TCP port (0 = health port + 1)"
        )
    if int(dist["actor_hosts"]) < 0:
        raise ValueError("train_args.distributed.actor_hosts must be >= 0")
    if (int(dist["actor_hosts"]) > 0 or str(dist["role"]) == "actor") and not dist[
        "coordinator_address"
    ]:
        raise ValueError(
            "train_args.distributed.actor_hosts/role: actor need "
            "distributed.coordinator_address — the plane gateway binds on "
            "(and actor hosts dial) the coordinator host"
        )
    if str(dist["role"]) == "actor" and train["device_rollout_games"] <= 0:
        raise ValueError(
            "train_args.distributed.role: actor needs device_rollout_games "
            "> 0 — a dedicated actor host generates with the on-device "
            "streaming rollout (host self-play already has the worker tier)"
        )
    if (
        dist["plane_port"] == 0
        and dist["coordinator_address"] is not None
        and (int(dist["actor_hosts"]) > 0 or str(dist["role"]) == "actor")
        and (
            dist["health_port"]
            or int(str(dist["coordinator_address"]).rpartition(":")[2]) + 1
        )
        >= 65535
    ):
        raise ValueError(
            "train_args.distributed.plane_port derives as health port + 1 "
            "= 65536, which is not a TCP port — set "
            "distributed.plane_port explicitly"
        )
    # the distributed plane only ACTIVATES with a coordinator_address
    # (init_distributed returns 0 without one — num_processes alone may
    # just be a fleet template), so the shard-divisibility checks key
    # on both
    if int(dist["num_processes"]) > 1 and dist["coordinator_address"]:
        nprocs = int(dist["num_processes"])
        if int(train["batch_size"]) % nprocs != 0:
            raise ValueError(
                f"train_args.batch_size={train['batch_size']} must divide "
                f"evenly across distributed.num_processes={nprocs} — each "
                "process assembles batch_size/num_processes local rows for "
                "the collective train step"
            )
        if train["device_rollout_games"] > 0 and (
            int(train["device_rollout_games"]) % nprocs != 0
        ):
            raise ValueError(
                f"train_args.device_rollout_games="
                f"{train['device_rollout_games']} must divide evenly across "
                f"distributed.num_processes={nprocs} — each process runs "
                "device_rollout_games/num_processes lanes on its local "
                "actor devices (the per-mesh lane divisibility is checked "
                "at Learner startup where the local device count is known)"
            )
    if train["worker"]["heartbeat_interval"] < 0:
        raise ValueError("train_args.worker.heartbeat_interval must be >= 0 (0 = off)")
    for key in ("socket_timeout", "entry_timeout"):
        if train["worker"][key] <= 0:
            raise ValueError(f"train_args.worker.{key} must be > 0")
    if train["fused_steps"] < 1:
        raise ValueError("train_args.fused_steps must be >= 1")
    if train["batch_pipeline"] not in ("shm", "thread", "device"):
        raise ValueError(
            f"train_args.batch_pipeline={train['batch_pipeline']!r} "
            "not one of ('shm', 'thread', 'device')"
        )
    if int(train["shm_slots"]) < 2:
        raise ValueError("train_args.shm_slots must be >= 2")
    if int(train["num_batchers"]) < 0:
        raise ValueError(
            "train_args.num_batchers must be >= 0 (0 = in-process threaded "
            "batchers; the shm plane needs at least 1 process)"
        )
    # the ring depth the shm plane is GUARANTEED to allocate on every
    # platform: the runtime may clamp fused_steps down to 1 (multi-device
    # CPU meshes execute fused scans pathologically — trainer.py), which
    # shrinks the 2*fused+2 enlargement with it, so only the fused=1 floor
    # can be promised at config time
    floor_slots = effective_shm_slots(dict(train, fused_steps=1))
    if (
        train["batch_pipeline"] in ("shm", "device")  # device falls back to shm
        and int(train["num_batchers"]) > floor_slots
    ):
        # a child beyond the ring depth would never be dealt a slot: it
        # spins forever contributing nothing — fail loudly at startup
        # instead of deep inside shm_batch setup (same spirit as the
        # plane: split validations)
        raise ValueError(
            f"train_args.num_batchers={train['num_batchers']} exceeds the "
            f"guaranteed shm ring depth {floor_slots} (shm_slots="
            f"{train['shm_slots']}; fused_steps can be clamped to 1 at "
            "runtime, so its ring enlargement does not count): each batcher "
            "process needs at least one ring slot to hold — raise shm_slots "
            "or lower num_batchers"
        )
    if train["batch_pipeline"] == "device":
        if train["device_replay"]:
            raise ValueError(
                "train_args.batch_pipeline: device is redundant under "
                "device_replay: true (that path never materializes host "
                "episodes, so there is nothing for the stage to upload)"
            )
        if int(train["device_stage_lanes"]) < 1:
            raise ValueError("train_args.device_stage_lanes must be >= 1")
        if int(train["device_stage_chunk"]) < 1:
            raise ValueError("train_args.device_stage_chunk must be >= 1")
        min_slots = train["burn_in_steps"] + train["forward_steps"]
        if int(train["device_stage_slots"]) <= min_slots:
            raise ValueError(
                "train_args.device_stage_slots must exceed burn_in_steps + "
                f"forward_steps = {min_slots}"
            )
    if train["device_rollout_games"] < 0:
        raise ValueError("train_args.device_rollout_games must be >= 0")
    if train["device_eval_games"] < 0:
        raise ValueError("train_args.device_eval_games must be >= 0")
    if train["device_replay"]:
        if train["device_rollout_games"] <= 0:
            raise ValueError(
                "train_args.device_replay needs device_rollout_games > 0 "
                "(the lane count of the streaming rollout it feeds from)"
            )
        # the remaining constraints (env hooks, feed-forward net, burn-in,
        # turn_based_training) are checked by DeviceReplay at Learner
        # startup, where the env/net are known
        if train["device_replay_slots"] <= train["forward_steps"]:
            raise ValueError("train_args.device_replay_slots must exceed forward_steps")
        if train["device_replay_k_steps"] < 1:
            raise ValueError("train_args.device_replay_k_steps must be >= 1")
    if train["plane"] not in ("fused", "split"):
        raise ValueError(
            f"train_args.plane={train['plane']!r} not one of ('fused', 'split')"
        )
    if int(train["actor_chips"]) < 1:
        raise ValueError("train_args.actor_chips must be >= 1")
    if int(train["param_refresh_updates"]) < 1:
        raise ValueError("train_args.param_refresh_updates must be >= 1")
    if train["plane"] == "split" and train["device_rollout_games"] <= 0:
        raise ValueError(
            "train_args.plane: split needs device_rollout_games > 0 (the "
            "actor plane generates with the on-device streaming rollout; "
            "host actors don't occupy a device plane)"
        )
    # observation: true with device_rollout_games is validated per-env at
    # Learner startup: streaming vector envs with an observe_mask hook
    # (Geister) record observer views; turn-player-only envs must refuse
    if not 0.0 <= train["eval_rate"] <= 1.0:
        raise ValueError("train_args.eval_rate must be in [0, 1]")
    serving = train["serving"]
    if serving["shed_policy"] not in ("deadline", "queue", "none"):
        raise ValueError(
            f"train_args.serving.shed_policy={serving['shed_policy']!r} "
            "not one of ('deadline', 'queue', 'none')"
        )
    if int(serving["max_models"]) < 1:
        raise ValueError("train_args.serving.max_models must be >= 1")
    if float(serving["slo_ms"]) <= 0:
        raise ValueError("train_args.serving.slo_ms must be > 0")
    if int(serving["max_batch"]) < 1:
        raise ValueError("train_args.serving.max_batch must be >= 1")
    if float(serving["max_wait_ms"]) < 0:
        raise ValueError("train_args.serving.max_wait_ms must be >= 0")
    if int(serving["queue_bound"]) < 1:
        raise ValueError("train_args.serving.queue_bound must be >= 1")
    buckets = serving["warm_buckets"]
    if not isinstance(buckets, (list, tuple)) or not buckets:
        raise ValueError(
            "train_args.serving.warm_buckets must be a non-empty list of "
            "bucket sizes"
        )
    for b in buckets:
        if not isinstance(b, int) or b < 1 or (b & (b - 1)):
            raise ValueError(
                f"train_args.serving.warm_buckets entries must be powers of "
                f"two >= 1 (the engine's compiled batch shapes), got {b!r}"
            )
        if b > int(serving["max_batch"]):
            raise ValueError(
                f"train_args.serving.warm_buckets entry {b} exceeds "
                f"serving.max_batch {serving['max_batch']} — it would warm a "
                "shape the engine never dispatches"
            )
    for key in ("recv_timeout", "watch_interval", "stats_interval"):
        if float(serving[key]) < 0:
            raise ValueError(f"train_args.serving.{key} must be >= 0 (0 = off)")
    if not isinstance(serving["port"], int) or not 0 <= serving["port"] <= 65535:
        raise ValueError(
            f"train_args.serving.port={serving['port']!r} must be a TCP port "
            "(0 = ephemeral)"
        )
    for key in ("session_capacity", "session_spill"):
        if int(serving[key]) < 0:
            raise ValueError(
                f"train_args.serving.{key} must be >= 0 "
                "(session_capacity 0 disables the session cache)"
            )
    if serving["weight_dtype"] not in ("float32", "int8"):
        raise ValueError(
            f"train_args.serving.weight_dtype={serving['weight_dtype']!r} "
            "not one of ('float32', 'int8')"
        )
    if int(serving["calibration_batches"]) < 0:
        raise ValueError(
            "train_args.serving.calibration_batches must be >= 0 (0 = skip "
            "the publish-time calibration record)"
        )
    if not isinstance(train["obs_int8"], bool):
        raise ValueError(
            f"train_args.obs_int8={train['obs_int8']!r} must be a bool "
            "(int8 observation planes on the wire/rings)"
        )
    fleet = train["fleet"]
    for key in ("port", "edge_port"):
        if not isinstance(fleet[key], int) or not 0 <= fleet[key] <= 65535:
            raise ValueError(
                f"train_args.fleet.{key}={fleet[key]!r} must be a TCP port "
                "(0 = ephemeral)"
            )
    if not isinstance(fleet["replicas"], (list, tuple)):
        raise ValueError(
            "train_args.fleet.replicas must be a list of 'host:port' strings "
            "or {host, port, tags} dicts"
        )
    for entry in fleet["replicas"]:
        if isinstance(entry, str):
            host, sep, port = entry.rpartition(":")
            if not sep or not port.isdigit():
                raise ValueError(
                    f"train_args.fleet.replicas entry {entry!r} is not "
                    "'host:port'"
                )
        elif isinstance(entry, dict):
            if "host" not in entry or "port" not in entry:
                raise ValueError(
                    f"train_args.fleet.replicas entry {entry!r} needs "
                    "'host' and 'port' keys"
                )
        else:
            raise ValueError(
                f"train_args.fleet.replicas entry {entry!r} must be a "
                "'host:port' string or a dict"
            )
    if int(fleet["poll_retry_attempts"]) < 0:
        raise ValueError(
            "train_args.fleet.poll_retry_attempts must be >= 0 (0 = no "
            "retry, the pre-flywheel fail-at-once behavior)"
        )
    if float(fleet["poll_retry_backoff_s"]) <= 0:
        raise ValueError("train_args.fleet.poll_retry_backoff_s must be > 0")
    if float(fleet["stats_poll_s"]) <= 0:
        raise ValueError(
            "train_args.fleet.stats_poll_s must be > 0 (it feeds the load "
            "scores the router balances by)"
        )
    if float(fleet["replica_stall_s"]) < 0:
        raise ValueError(
            "train_args.fleet.replica_stall_s must be >= 0 (0 disables the "
            "stall deadline; failover then only on connection loss)"
        )
    if float(fleet["rejoin_backoff_s"]) <= 0:
        raise ValueError("train_args.fleet.rejoin_backoff_s must be > 0")
    if float(fleet["rejoin_backoff_max_s"]) < float(fleet["rejoin_backoff_s"]):
        raise ValueError(
            "train_args.fleet.rejoin_backoff_max_s must be >= "
            "rejoin_backoff_s (it is the backoff's cap)"
        )
    if float(fleet["stats_interval"]) < 0:
        raise ValueError("train_args.fleet.stats_interval must be >= 0 (0 = off)")
    if float(fleet["migrate_timeout_s"]) <= 0:
        raise ValueError(
            "train_args.fleet.migrate_timeout_s must be > 0 (the planned-"
            "retire drain/export/import budget)"
        )
    if int(fleet["edge_workers"]) < 1:
        raise ValueError("train_args.fleet.edge_workers must be >= 1")
    autoscale = fleet["autoscale"]
    if not isinstance(autoscale["enabled"], bool):
        raise ValueError(
            f"train_args.fleet.autoscale.enabled={autoscale['enabled']!r} "
            "must be a bool"
        )
    if int(autoscale["min_replicas"]) < 1:
        raise ValueError(
            "train_args.fleet.autoscale.min_replicas must be >= 1 (a fleet "
            "scaled to zero cannot serve)"
        )
    if int(autoscale["max_replicas"]) < int(autoscale["min_replicas"]):
        raise ValueError(
            "train_args.fleet.autoscale.max_replicas must be >= min_replicas"
        )
    for key in ("interval_s", "warm_timeout_s"):
        if float(autoscale[key]) <= 0:
            raise ValueError(f"train_args.fleet.autoscale.{key} must be > 0")
    if not 0.0 <= float(autoscale["shed_slo"]) <= 1.0:
        raise ValueError(
            "train_args.fleet.autoscale.shed_slo must be in [0, 1] (a shed "
            "RATE: sheds over requests in the window)"
        )
    if float(autoscale["depth_low"]) < 0:
        raise ValueError("train_args.fleet.autoscale.depth_low must be >= 0")
    if float(autoscale["depth_high"]) <= float(autoscale["depth_low"]):
        raise ValueError(
            "train_args.fleet.autoscale.depth_high must be > depth_low "
            "(the hysteresis band between scale-up and scale-down)"
        )
    for key in ("scale_down_after_s", "cooldown_s"):
        if float(autoscale[key]) < 0:
            raise ValueError(f"train_args.fleet.autoscale.{key} must be >= 0")
    league = train["league"]
    if league["pfsp_weighting"] not in ("var", "hard", "even"):
        raise ValueError(
            f"train_args.league.pfsp_weighting={league['pfsp_weighting']!r} "
            "not one of ('var', 'hard', 'even')"
        )
    if not 0.0 <= float(league["selfplay_rate"]) <= 1.0:
        raise ValueError("train_args.league.selfplay_rate must be in [0, 1]")
    if not 0.0 < float(league["promote_winrate"]) < 1.0:
        raise ValueError(
            "train_args.league.promote_winrate must be in (0, 1) — it is a "
            "win-points bar over the active population"
        )
    if int(league["promote_games"]) < 1:
        raise ValueError("train_args.league.promote_games must be >= 1")
    if int(league["max_population"]) < 2:
        raise ValueError(
            "train_args.league.max_population must be >= 2 (the anchor "
            "plus at least one frozen member)"
        )
    fly = train["flywheel"]
    if not isinstance(fly["enabled"], bool):
        raise ValueError(
            f"train_args.flywheel.enabled={fly['enabled']!r} must be a bool"
        )
    for key in ("harvest_fraction", "shadow_fraction"):
        if not 0.0 <= float(fly[key]) <= 1.0:
            raise ValueError(f"train_args.flywheel.{key} must be in [0, 1]")
    if not 0.0 < float(fly["promote_winrate"]) < 1.0:
        raise ValueError(
            "train_args.flywheel.promote_winrate must be in (0, 1) — it is "
            "a live win-points bar, not a guarantee"
        )
    if not 0.0 < float(fly["demote_drop"]) < 1.0:
        raise ValueError(
            "train_args.flywheel.demote_drop must be in (0, 1) — the live "
            "win-point EMA drop that trips the quality sentinel"
        )
    if int(fly["staleness_epochs"]) < 1:
        raise ValueError(
            "train_args.flywheel.staleness_epochs must be >= 1 (0 would "
            "drop every harvested episode as stale)"
        )
    for key in ("promote_games", "quality_window", "harvest_max_pull",
                "harvest_max_open"):
        if int(fly[key]) < 1:
            raise ValueError(f"train_args.flywheel.{key} must be >= 1")
    for key in ("harvest_poll_s", "harvest_ttl_s"):
        if float(fly[key]) <= 0:
            raise ValueError(f"train_args.flywheel.{key} must be > 0")
    if not isinstance(fly["gate_promotions"], bool):
        raise ValueError(
            f"train_args.flywheel.gate_promotions="
            f"{fly['gate_promotions']!r} must be a bool"
        )
    if not isinstance(fly["harvest_port"], int) or not (
        0 <= fly["harvest_port"] <= 65535
    ):
        raise ValueError(
            f"train_args.flywheel.harvest_port={fly['harvest_port']!r} must "
            "be a TCP port in [0, 65535] (0 = follow serving.port)"
        )
    if int(train["autovec_verify_games"]) < 0:
        raise ValueError("train_args.autovec_verify_games must be >= 0 (0 = off)")
    tr = train["trace"]
    if not isinstance(tr["enabled"], bool):
        raise ValueError(
            f"train_args.trace.enabled={tr['enabled']!r} must be a bool"
        )
    if tr["enabled"] and not str(tr["path"] or "").strip():
        raise ValueError(
            "train_args.trace.path must name a file when trace.enabled is "
            "true (writability is probed at startup by trace.configure)"
        )
    if int(tr["ring_size"]) < 1:
        raise ValueError("train_args.trace.ring_size must be >= 1")
    if float(tr["flush_interval"]) <= 0:
        raise ValueError("train_args.trace.flush_interval must be > 0")
    if not isinstance(tr["annotate_device"], bool):
        raise ValueError(
            f"train_args.trace.annotate_device={tr['annotate_device']!r} "
            "must be a bool"
        )
    obs = train["observability"]
    if not isinstance(obs["rank_metrics"], bool):
        raise ValueError(
            f"train_args.observability.rank_metrics="
            f"{obs['rank_metrics']!r} must be a bool"
        )
    if train["seq_attention"] not in ("auto", "flash", "einsum", "ring"):
        raise ValueError(
            f"train_args.seq_attention={train['seq_attention']!r} "
            "not one of ('auto', 'flash', 'einsum', 'ring')"
        )
    if int(train["flash_min_t"]) < 1:
        raise ValueError("train_args.flash_min_t must be >= 1")
    for key in ("blk_q", "blk_k"):
        b = int(train[key])
        if b < 8 or (b & (b - 1)):
            raise ValueError(
                f"train_args.{key} must be a power of two >= 8 (8 sublanes x "
                f"the 128-lane tile rule — pallas_guide 'Tiling Constraints'), "
                f"got {train[key]}; the kernel clamps blocks above 128 down "
                "to the lane tile"
            )
    rv = train["remat"]
    # isinstance(bool) first: tuple membership would accept the ints 0/1
    # via ==, which resolve_seq_remat (isinstance-based) would then read
    # as 'auto' — one config value must not mean two things
    if not (isinstance(rv, bool) or rv in ("auto", "none", "attn", "block")):
        raise ValueError(
            f"train_args.remat={rv!r} not one of "
            "('auto', true, false, 'none', 'attn', 'block')"
        )
    uv = train["unroll"]
    if not (isinstance(uv, bool) or uv in ("auto", None)):
        raise ValueError(
            f"train_args.unroll={uv!r} not one of ('auto', true, false)"
        )
    if not isinstance(train["compact_padding"], bool):
        raise ValueError(
            f"train_args.compact_padding={train['compact_padding']!r} "
            "must be a bool"
        )
    mesh = train["mesh"]
    if not isinstance(mesh, dict) or not mesh:
        raise ValueError("train_args.mesh must be a non-empty axis->size dict")
    for ax, size in mesh.items():
        if not isinstance(size, int) or size == 0 or size < -1:
            raise ValueError(
                f"train_args.mesh[{ax!r}]={size!r}: axis sizes are positive "
                "ints or -1 (fill remaining devices)"
            )
    if sum(1 for s in mesh.values() if s == -1) > 1:
        raise ValueError(
            "train_args.mesh: at most one axis may be -1 (fill) — "
            f"got {mesh}"
        )
    if train["seq_attention"] == "ring" and train["remat"] in ("attn", "block", True):
        raise ValueError(
            "train_args.remat ladder is unsupported with seq_attention: "
            "'ring' — the ring already partitions activation memory over "
            "'sp' (each device holds one T/sp shard), and jax.checkpoint "
            "around the shard_map ring loop fails its scan-carry "
            "replication typing; use remat: none or auto"
        )
    if train["seq_attention"] == "ring":
        sp = mesh.get("sp", 1)
        if sp != -1 and sp < 2:
            raise ValueError(
                "train_args.seq_attention: 'ring' needs an 'sp' mesh axis of "
                f"size >= 2 (or -1), got mesh {mesh}"
            )
        T = train["burn_in_steps"] + train["forward_steps"]
        if sp > 0 and T % sp:
            raise ValueError(
                f"train_args.seq_attention: 'ring' window {T} (burn_in_steps "
                f"+ forward_steps) must be divisible by mesh sp={sp}"
            )
    if train["compute_dtype"] not in ("float32", "bfloat16"):
        raise ValueError(
            f"train_args.compute_dtype={train['compute_dtype']!r} "
            "not one of ('float32', 'bfloat16')"
        )
    if train["lr_scale"] <= 0:
        raise ValueError(f"train_args.lr_scale must be > 0, got {train['lr_scale']}")
    worker_args = args.get("worker_args", {})
    if worker_args and float(worker_args.get("entry_retry_seconds", 60.0)) <= 0:
        raise ValueError("worker_args.entry_retry_seconds must be > 0")
    if "env" not in args.get("env_args", {}):
        raise ValueError("env_args.env is required")
    return args


def normalize_args(raw: Dict[str, Any]) -> Dict[str, Any]:
    """Apply defaults to a raw config dict and validate."""
    train_raw = dict(raw.get("train_args", {}) or {})
    # 'attn_mode' is the documented alias for 'seq_attention' (the knob
    # predates the auto-pick policy); an explicit attn_mode wins, and
    # setting both to DIFFERENT values is a config contradiction
    if "attn_mode" in train_raw:
        mode = train_raw.pop("attn_mode")
        if train_raw.get("seq_attention", mode) != mode:
            raise ValueError(
                f"train_args.attn_mode={mode!r} contradicts "
                f"train_args.seq_attention={train_raw['seq_attention']!r} "
                "(attn_mode is an alias; set one)"
            )
        train_raw["seq_attention"] = mode
    args = {
        "env_args": copy.deepcopy(raw.get("env_args", {})),
        "train_args": _deep_merge(DEFAULT_TRAIN_ARGS, train_raw),
        "worker_args": _deep_merge(DEFAULT_WORKER_ARGS, raw.get("worker_args", {})),
    }
    return validate_args(args)


def load_config(path: str = "config.yaml") -> Dict[str, Any]:
    with open(path) as f:
        raw = yaml.safe_load(f) or {}
    return normalize_args(raw)
