"""Model-driven and scripted agents for match play and evaluation.

The acting API consumed by the match executors (runtime/evaluation.py) and
the network battle client (runtime/battle.py) is three methods:

    reset(env, show=False)
    action(env, player, show=False) -> int
    observe(env, player, show=False) -> value estimate (or None)

Capability parity with the reference agent zoo (handyrl/agent.py:13-113)
with a different construction: every model-backed agent is an ensemble —
a single checkpoint is the one-member case — and action selection is
vectorized numpy (masked logits + Gumbel-max sampling) rather than
per-action python loops.  A "model" is anything exposing ``inference`` /
``init_hidden``: a jitted InferenceModel, a BatchedInferenceClient sharing
the actor-plane engine across threads, an ExportedModel, or the
zero-output RandomModel.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import numpy as np

_ILLEGAL = np.float32(-1e32)


def masked_policy_logits(policy: np.ndarray, legal: Sequence[int]) -> np.ndarray:
    """Return logits with every illegal entry pushed to -1e32."""
    out = np.full(np.shape(policy), _ILLEGAL, np.float32)
    idx = np.asarray(legal, np.int64)
    out[idx] = np.asarray(policy, np.float32)[idx]
    return out


def sample_logits(
    logits: np.ndarray, temperature: float, rng: np.random.Generator
) -> int:
    """Pick an action index from masked logits.

    ``temperature == 0`` is argmax.  Otherwise Gumbel-max on
    ``logits / temperature`` — distributionally identical to softmax
    sampling, with no normalization pass and no underflow on the -1e32
    illegal entries."""
    if temperature <= 0.0:
        return int(np.argmax(logits))
    gumbel = rng.gumbel(size=np.shape(logits)).astype(np.float32)
    return int(np.argmax(logits / np.float32(temperature) + gumbel))


def mean_pool_outputs(member_outs: Sequence[Dict[str, Any]]) -> Dict[str, np.ndarray]:
    """Mean-pool every non-hidden output across ensemble members
    (reference EnsembleAgent semantics, agent.py:92-107).  Shared by the
    acting ensemble here and the serving plane's ensemble routes — one
    definition of 'ensemble output', so they cannot silently diverge."""
    keys = {
        k
        for out in member_outs
        for k, v in out.items()
        if k != "hidden" and v is not None
    }
    return {
        k: np.mean(
            [
                np.asarray(out[k], np.float32)
                for out in member_outs
                if out.get(k) is not None
            ],
            axis=0,
        )
        for k in keys
    }


def _scalar(x) -> Optional[float]:
    return None if x is None else float(np.asarray(x).reshape(-1)[0])


def _display(env, prob: Optional[np.ndarray], value: Optional[float]) -> None:
    """Human-readable decision dump; envs may provide their own renderer."""
    if hasattr(env, "print_outputs"):
        env.print_outputs(prob, value)
        return
    if value is not None:
        print(f"v = {value:.4f}")
    if prob is not None:
        print("p =", np.round(prob * 1000).astype(np.int64))


class RandomAgent:
    """Uniform over legal actions; the value estimate is a flat zero."""

    def __init__(self, seed: Optional[int] = None):
        self._rng = np.random.default_rng(seed)

    def reset(self, env, show: bool = False) -> None:
        pass

    def action(self, env, player: int, show: bool = False) -> int:
        return int(self._rng.choice(np.asarray(env.legal_actions(player))))

    def observe(self, env, player: int, show: bool = False):
        return [0.0]


class RuleBasedAgent(RandomAgent):
    """Environment-scripted policy where the env provides one, else random."""

    def __init__(self, key: Optional[str] = None, seed: Optional[int] = None):
        super().__init__(seed)
        self.key = key

    def action(self, env, player: int, show: bool = False) -> int:
        rule = getattr(env, "rule_based_action", None)
        if rule is None:
            return super().action(env, player, show)
        return rule(player, key=self.key)


class Agent:
    """Model-backed agent: ensemble forward -> masked logits -> selection.

    ``models`` may be a single model or a list; outputs are mean-pooled
    across members (reference EnsembleAgent semantics, agent.py:92-107)
    and each member carries its own recurrent state.
    """

    def __init__(
        self,
        models,
        temperature: float = 0.0,
        observation: bool = True,
        seed: Optional[int] = None,
    ):
        self.models: List[Any] = (
            list(models) if isinstance(models, (list, tuple)) else [models]
        )
        self.temperature = float(temperature)
        self.observation = observation
        self._rng = np.random.default_rng(seed)
        self._hidden: List[Any] = [None] * len(self.models)

    @property
    def model(self):
        """The first (or only) ensemble member."""
        return self.models[0]

    def reset(self, env, show: bool = False) -> None:
        self._hidden = [m.init_hidden() for m in self.models]

    def _forward(self, obs) -> Dict[str, np.ndarray]:
        """One inference per member; mean-pool everything but hidden state."""
        member_outs = []
        for i, m in enumerate(self.models):
            out = m.inference(obs, self._hidden[i])
            self._hidden[i] = out.get("hidden")
            member_outs.append(out)
        return mean_pool_outputs(member_outs)

    def action(self, env, player: int, show: bool = False) -> int:
        outputs = self._forward(env.observation(player))
        logits = masked_policy_logits(
            np.reshape(outputs["policy"], -1), env.legal_actions(player)
        )
        if show:
            exp = np.exp(logits - logits.max())
            _display(env, exp / exp.sum(), _scalar(outputs.get("value")))
        return sample_logits(logits, self.temperature, self._rng)

    def observe(self, env, player: int, show: bool = False):
        if not self.observation:
            return None
        outputs = self._forward(env.observation(player))
        value = outputs.get("value")
        if show:
            _display(env, None, _scalar(value))
        return value


class EnsembleAgent(Agent):
    """Mean-pooled multi-checkpoint agent (Agent already pools lists)."""

    def __init__(self, models, temperature: float = 0.0, observation: bool = True):
        super().__init__(list(models), temperature, observation)


class SoftAgent(Agent):
    """Softmax-sampling agent at temperature 1 (agent.py:110-112)."""

    def __init__(self, model):
        super().__init__(model, temperature=1.0)
