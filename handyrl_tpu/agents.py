"""Agent zoo for evaluation and match play.

Capability parity with reference handyrl/agent.py:13-113: random,
rule-based, greedy/temperature model agents, ensembles and the T=1.0 soft
agent.  Models are anything with the ``inference``/``init_hidden`` API —
an InferenceModel, a BatchedInferenceClient sharing the actor-side engine,
a RandomModel, or an ensemble thereof.
"""

from __future__ import annotations

import random
from typing import Any, Dict, List, Optional

import numpy as np

from .utils import softmax


class RandomAgent:
    """Uniform over legal actions (agent.py:13-22)."""

    def reset(self, env, show: bool = False):
        pass

    def action(self, env, player: int, show: bool = False) -> int:
        return random.choice(env.legal_actions(player))

    def observe(self, env, player: int, show: bool = False):
        return [0.0]


class RuleBasedAgent(RandomAgent):
    """Delegates to the environment's scripted policy (agent.py:25-33)."""

    def __init__(self, key: Optional[str] = None):
        self.key = key

    def action(self, env, player: int, show: bool = False) -> int:
        if hasattr(env, "rule_based_action"):
            return env.rule_based_action(player, key=self.key)
        return random.choice(env.legal_actions(player))


def print_outputs(env, prob, v) -> None:
    if hasattr(env, "print_outputs"):
        env.print_outputs(prob, v)
    else:
        if v is not None:
            print("v = %f" % v)
        if prob is not None:
            print("p = %s" % (prob * 1000).astype(int))


class Agent:
    """Greedy (or temperature-sampled) model agent with hidden-state carry.

    Parity with reference Agent (agent.py:36-89): ``reset`` re-seeds the
    hidden state, ``action`` masks illegal actions and picks argmax (T=0)
    or samples p^(1/T), ``observe`` returns the value estimate for
    non-acting observation steps.
    """

    def __init__(self, model, temperature: float = 0.0, observation: bool = True):
        self.model = model
        self.hidden = None
        self.temperature = temperature
        self.observation = observation

    def reset(self, env, show: bool = False):
        self.hidden = self.model.init_hidden()

    def plan(self, obs) -> Dict[str, Any]:
        outputs = self.model.inference(obs, self.hidden)
        self.hidden = outputs.get("hidden")
        return outputs

    def action(self, env, player: int, show: bool = False) -> int:
        outputs = self.plan(env.observation(player))
        actions = env.legal_actions(player)
        p = np.asarray(outputs["policy"], dtype=np.float32)
        mask = np.ones_like(p) * 1e32
        mask[actions] = 0.0
        p = p - mask

        if show:
            v = outputs.get("value")
            print_outputs(env, softmax(p), None if v is None else float(np.reshape(v, -1)[0]))

        if self.temperature == 0:
            ap_list = sorted([(a, p[a]) for a in actions], key=lambda x: -x[1])
            return ap_list[0][0]
        prob = softmax(p / self.temperature)
        return int(random.choices(np.arange(len(p)), weights=prob)[0])

    def observe(self, env, player: int, show: bool = False):
        v = None
        if self.observation:
            outputs = self.plan(env.observation(player))
            v = outputs.get("value")
            if show:
                print_outputs(env, None, None if v is None else float(np.reshape(v, -1)[0]))
        return v


class EnsembleAgent(Agent):
    """Mean-pools outputs of several models (agent.py:92-107)."""

    def __init__(self, models, temperature: float = 0.0, observation: bool = True):
        super().__init__(models[0], temperature, observation)
        self.models = models

    def reset(self, env, show: bool = False):
        self.hidden = [model.init_hidden() for model in self.models]

    def plan(self, obs) -> Dict[str, Any]:
        outputs = {}
        for i, model in enumerate(self.models):
            o = model.inference(obs, self.hidden[i])
            self.hidden[i] = o.get("hidden")
            for k, v in o.items():
                if k == "hidden" or v is None:
                    continue
                outputs[k] = outputs.get(k, 0) + np.asarray(v) / len(self.models)
        return outputs


class SoftAgent(Agent):
    """Temperature-1 sampling agent (agent.py:110-112)."""

    def __init__(self, model):
        super().__init__(model, temperature=1.0)
