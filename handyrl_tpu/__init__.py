"""handyrl_tpu — a TPU-native distributed reinforcement learning framework.

A from-scratch JAX/XLA/Flax re-design with the capabilities of HandyRL
(reference: /root/reference, DeNA's HandyRL, MIT license): IMPALA-style
learner/worker self-play training for turn-based, simultaneous-move,
multi-player and imperfect-information games, with off-policy corrected
policy-gradient targets (MC / TD(lambda) / UPGO / V-Trace).

Architecture differences from the reference (TPU-first, not a port):

* Compute path is pure-functional JAX: the whole training update
  (forward, loss, target scans, optimizer) is ONE jitted function
  sharded over a ``jax.sharding.Mesh`` (data-parallel by default, with
  optional model axes), instead of torch ``nn.DataParallel``.
* Actor-side inference is batched across environments onto the TPU via
  an inference engine, instead of batch-1 per-process CPU inference.
* Game logic is pure numpy (no framework dependency in ``envs/``);
  neural nets live in ``models/`` as Flax modules.
* RL target recursions (reference handyrl/losses.py) are
  time-reversed ``jax.lax.scan``s, compiled and fused by XLA.
* Fixed-shape ``(B, T, P, ...)`` batches always (XLA-friendly), where the
  reference only pads short windows.
"""

__version__ = "0.1.0"
