"""Host-facing inference wrappers: numpy in / numpy out, jitted apply.

Replaces the reference's ModelWrapper/RandomModel (handyrl/model.py:33-74).
Key difference: ``apply`` is jitted once per (module, batch-shape) and runs
on the accelerator; hosts speak numpy pytrees at the boundary.  The
batched-across-environments path (see runtime/inference_engine.py) is the
TPU-first replacement for the reference's per-process batch-1 CPU
inference.
"""

from __future__ import annotations

import functools
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from ..utils import tree_map


@functools.lru_cache(maxsize=None)
def jitted_apply(module):
    """One compiled apply per module *value* (linen modules hash by config),
    so swapping parameters — e.g. each training epoch — never recompiles."""
    return jax.jit(module.apply)


def fetch_outputs(outputs) -> Dict[str, Any]:
    """Bring async device outputs to the host as a numpy pytree.

    The explicit fetch half of the serving plane's dispatch/fetch split:
    ``inference_batch_async`` enqueues the program (called under the
    per-device dispatch locks), and THIS runs outside them, so the locks
    cover only the enqueue — a second model's engine on the same device
    can dispatch while the first batch's outputs stream back.
    """
    return tree_map(np.asarray, jax.device_get(outputs))


def init_variables(module, env, seed: int = 0):
    """Initialize model variables from a sample observation of ``env``."""
    env.reset()
    obs = env.observation(env.players()[0])
    obs_b = tree_map(lambda x: jnp.asarray(x)[None], obs)
    hidden = module.initial_state((1,))
    return module.init(jax.random.PRNGKey(seed), obs_b, hidden)


class SingleInferenceMixin:
    """Single-sample ``inference`` on top of a batched ``inference_batch``:
    add the leading batch axis, run, strip it again (model.py:50-60)."""

    def inference(self, obs, hidden=None) -> Dict[str, Any]:
        obs_b = tree_map(lambda x: np.asarray(x)[None], obs)
        hidden_b = tree_map(lambda x: np.asarray(x)[None], hidden) if hidden is not None else None
        outputs = self.inference_batch(obs_b, hidden_b)
        return tree_map(lambda x: x[0], outputs)


class InferenceModel(SingleInferenceMixin):
    """A (module, variables) pair exposing batched and single inference.

    API kept compatible with the reference wrapper (model.py:50-60):
    ``inference(obs, hidden)`` is single-sample numpy->numpy;
    ``inference_batch`` takes/returns batch-leading pytrees.
    """

    def __init__(self, module, variables):
        self.module = module
        self.variables = variables

    @property
    def _apply(self):
        return jitted_apply(self.module)

    def init_hidden(self, batch_dims=()):
        hidden = self.module.initial_state(tuple(batch_dims))
        return None if hidden is None else tree_map(np.asarray, hidden)

    def inference_batch_async(self, obs, hidden=None):
        """Enqueue one batched apply and return the ASYNC device outputs
        (no host sync).  Callers that need numpy pass the result through
        ``fetch_outputs`` — the serving plane dispatches this under
        ``dispatch_serialized`` and fetches outside the device locks."""
        return self._apply(self.variables, obs, hidden)

    def inference_batch(self, obs, hidden=None) -> Dict[str, Any]:
        outputs = self._apply(self.variables, obs, hidden)
        return jax.device_get(outputs)


def build_inference_model(module, params, weight_dtype: str = "float32"):
    """THE engine-build seam for ``serving.weight_dtype``: every place
    that wraps a published/loaded param tree into an engine model
    (ModelRouter.publish, its cold-resolve path, the bench's serving
    stages) goes through here, so the int8 rung reaches the serving
    plane, the fleet replicas, and the frozen league opponents from one
    switch.  Lazy import keeps the fp32 path free of the quantize
    module."""
    if weight_dtype == "int8":
        from .quantize import QuantizedInferenceModel

        return QuantizedInferenceModel(module, {"params": params})
    if weight_dtype not in (None, "float32"):
        raise ValueError(
            f"weight_dtype must be 'float32' or 'int8', got {weight_dtype!r}"
        )
    return InferenceModel(module, {"params": params})


class RandomModel:
    """Zero-logit stand-in (uniform policy over legal actions, zero value).

    Role of reference RandomModel (model.py:65-74): served as model_id 0 so
    early evaluation opponents are well-defined.
    """

    def __init__(self, output_spec: Dict[str, Any]):
        self._outputs = {
            k: np.zeros(shape, dtype) for k, (shape, dtype) in output_spec.items() if k != "hidden"
        }

    @classmethod
    def from_model(cls, model: InferenceModel, obs) -> "RandomModel":
        out = model.inference(obs, model.init_hidden())
        spec = {
            k: (v.shape, v.dtype)
            for k, v in out.items()
            if k != "hidden" and v is not None
        }
        return cls(spec)

    def init_hidden(self, batch_dims=()):
        return None

    def inference(self, obs, hidden=None, **kwargs):
        return {k: v.copy() for k, v in self._outputs.items()}
